"""Interleaved best-of-2 sanitizer-on vs off over the 1k-run soak shape.

The bobrarace overhead measurement recorded in
bobrapet_tpu/analysis/racedetect.py's module docstring — rerun after
any change to the tracked-wrapper hot path and update those numbers.

Run: JAX_PLATFORMS=cpu python bench_race_overhead.py
"""
import gc
import os
import sys
import time

os.environ.setdefault("BOBRA_SOAK", "1")
# match the soak suite's _gc_posture fixture (manager GC posture) —
# default thresholds thrash on the soak's live-object population
gc.set_threshold(100_000, 50, 50)
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))

import test_scale_soak as soak  # noqa: E402

from bobrapet_tpu.analysis.racedetect import sanitize_races  # noqa: E402

N = soak.N_RUNS
STEPS = soak.STEPS_PER_RUN


def one_trial() -> float:
    rt = soak._soak_rt()
    t0 = time.perf_counter()
    runs = [rt.run_story("soak", inputs={"i": i}) for i in range(N)]
    soak.drain(rt)
    wall = time.perf_counter() - t0
    ok = sum(1 for r in runs if rt.run_phase(r) == "Succeeded")
    assert ok == N, f"{ok}/{N} succeeded"
    return N * STEPS / wall


def main() -> None:
    results = {"off": [], "on": []}
    # interleave so box drift hits both arms equally; best-of-2 per arm
    for trial in ("off", "on", "off", "on"):
        if trial == "on":
            with sanitize_races() as det:
                sps = one_trial()
            det.assert_clean()
        else:
            sps = one_trial()
        results[trial].append(sps)
        print(f"{trial}: {sps:.1f} steps/s", flush=True)
    best_off = max(results["off"])
    best_on = max(results["on"])
    print(f"\nbest off: {best_off:.1f} steps/s")
    print(f"best on:  {best_on:.1f} steps/s")
    print(f"ratio on/off: {best_on / best_off:.3f}")


if __name__ == "__main__":
    main()
