"""Compute plane tests on the virtual 8-device CPU mesh.

Kernels run in interpret mode; sharding/collectives run on the forced
8-device CPU backend (conftest sets XLA_FLAGS) — the multi-chip paths
compile and execute exactly as they would across a slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import dataclasses
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bobrapet_tpu.models.llama import (
    forward,
    greedy_generate,
    init_cache,
    init_params,
    llama_tiny,
)
from bobrapet_tpu.ops.attention import attention_reference, flash_attention
from bobrapet_tpu.ops.rmsnorm import rmsnorm_pallas, rmsnorm_reference
from bobrapet_tpu.ops.rope import apply_rope, rope_frequencies
from bobrapet_tpu.parallel.mesh import build_mesh
from bobrapet_tpu.parallel.ring_attention import ring_attention
from bobrapet_tpu.parallel.sharding import llama_param_specs, shard_params


def test_eight_cpu_devices():
    assert len(jax.devices()) == 8


class TestRMSNorm:
    def test_pallas_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 128))
        w = jax.random.normal(jax.random.PRNGKey(1), (128,)) * 0.1 + 1.0
        ref = rmsnorm_reference(x, w)
        out = rmsnorm_pallas(x, w, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_uneven_rows_fall_back_to_single_block(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 7, 128))
        w = jnp.ones((128,))
        out = rmsnorm_pallas(x, w, block_rows=256, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(rmsnorm_reference(x, w)), rtol=1e-5, atol=1e-5
        )


class TestRope:
    def test_rotation_preserves_norm(self):
        freqs = rope_frequencies(64, 128)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 64))
        y = apply_rope(x, freqs)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            rtol=1e-4,
        )

    def test_positions_offset(self):
        freqs = rope_frequencies(64, 128)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 2, 64))
        a = apply_rope(x, freqs)  # positions 0..3
        pos = jnp.arange(4)[None, :]
        b = apply_rope(x, freqs, positions=pos)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
        shifted = apply_rope(x, freqs, positions=pos + 10)
        assert not np.allclose(np.asarray(a), np.asarray(shifted))


class TestFlashAttention:
    @pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
    def test_matches_reference_causal(self, hq, hkv):
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (2, 256, hq, 64))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 256, hkv, 64))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 256, hkv, 64))
        ref = attention_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_non_causal(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 2, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 2, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 128, 2, 32))
        ref = attention_reference(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_decode_offset_reference(self):
        # 1 query token attending over 16-token prefix
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 4, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 4, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 4, 32))
        full = attention_reference(q, k, v, causal=True, q_offset=15)
        # position 15 sees all 16 keys -> equals non-causal
        nc = attention_reference(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(full), np.asarray(nc), rtol=1e-5)


class TestLlama:
    def test_forward_shapes_and_determinism(self):
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits, _ = forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        logits2, _ = forward(params, tokens, cfg)
        np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))

    def test_cached_decode_matches_full_forward(self):
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, cfg.vocab_size)
        full_logits, _ = forward(params, tokens, cfg)

        cache = init_cache(cfg, 1, capacity=32)
        prefill, cache = forward(
            params, tokens[:, :8], cfg, cache=cache,
            positions=jnp.arange(8)[None, :],
        )
        np.testing.assert_allclose(
            np.asarray(prefill), np.asarray(full_logits[:, :8]), rtol=2e-3, atol=2e-3
        )
        # decode the remaining 4 tokens one at a time
        outs = []
        for i in range(8, 12):
            step_logits, cache = forward(
                params, tokens[:, i : i + 1], cfg, cache=cache,
                positions=jnp.array([[i]]),
            )
            outs.append(step_logits)
        decode = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(decode), np.asarray(full_logits[:, 8:]), rtol=2e-3, atol=2e-3
        )

    def test_greedy_generate(self):
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        toks = greedy_generate(params, prompt, cfg, max_new_tokens=5)
        assert toks.shape == (2, 5)
        assert int(toks.min()) >= 0 and int(toks.max()) < cfg.vocab_size

    def test_param_count_8b_in_range(self):
        from bobrapet_tpu.models.llama import llama3_8b

        n = llama3_8b().param_count
        assert 7.5e9 < n < 8.5e9


class TestSharding:
    def test_build_mesh_axes(self):
        mesh = build_mesh({"data": 2, "model": 4})
        assert mesh.shape == {"data": 2, "model": 4}
        # explicit multi-axis grants are honored verbatim now (the old
        # implicit first-axis fill silently doubled the data axis — the
        # mis-sizing the build_mesh hardening removed); the smaller
        # grant shrinks to a device prefix instead
        mesh2 = build_mesh({"data": 1, "model": 4})
        assert mesh2.shape == {"data": 1, "model": 4}
        # the single-axis convenience fill is kept
        mesh3 = build_mesh({"data": 1})
        assert mesh3.shape == {"data": 8}

    def test_sharded_forward_matches_single_device(self):
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
        ref, _ = forward(params, tokens, cfg)

        mesh = build_mesh({"data": 2, "model": 4})
        sharded = shard_params(params, mesh)
        tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P("data")))

        @jax.jit
        def run(p, t):
            logits, _ = forward(p, t, cfg)
            return logits

        out = run(sharded, tok_sharded)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)

    def test_param_specs_cover_tree(self):
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        mesh = build_mesh({"data": 2, "model": 4})
        specs = llama_param_specs(params, mesh)
        p_leaves = jax.tree_util.tree_leaves(params)
        s_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        assert len(p_leaves) == len(s_leaves)


class TestTrainStep:
    def test_train_step_with_remat_and_ring(self):
        import optax
        from bobrapet_tpu.parallel.train import (
            init_sharded_train_state,
            make_token_batch,
            make_train_step,
        )

        cfg = llama_tiny(vocab_size=128, max_seq_len=64)
        devs = np.array(jax.devices()).reshape(2, 1, 2, 2)
        mesh = Mesh(devs, ("data", "fsdp", "model", "seq"))
        with mesh:
            params, opt_state, opt = init_sharded_train_state(
                jax.random.PRNGKey(0), cfg, mesh, optax.adamw(1e-3)
            )
            step = make_train_step(cfg, mesh, optimizer=opt, remat=True)
            tokens = make_token_batch(jax.random.PRNGKey(1), cfg, 4, 32, mesh)
            params, opt_state, loss = step(params, opt_state, tokens)
        assert np.isfinite(float(loss))

    def test_ulysses_train_step_matches_ring(self):
        """Both context-parallel strategies compute identical attention,
        so one train step from the same state must produce the same
        loss — and ulysses' backward must compile under the full
        sharded step (this is its only full-train coverage)."""
        import optax
        from bobrapet_tpu.parallel.train import (
            init_sharded_train_state,
            make_token_batch,
            make_train_step,
        )

        cfg = llama_tiny(vocab_size=128, max_seq_len=64)
        devs = np.array(jax.devices()).reshape(1, 2, 2, 2)
        mesh = Mesh(devs, ("data", "fsdp", "model", "seq"))
        losses = {}
        for strategy in ("ring", "ulysses"):
            with mesh:
                params, opt_state, opt = init_sharded_train_state(
                    jax.random.PRNGKey(0), cfg, mesh, optax.adamw(1e-3)
                )
                step = make_train_step(cfg, mesh, optimizer=opt,
                                       seq_parallel=strategy)
                tokens = make_token_batch(jax.random.PRNGKey(1), cfg, 4, 32, mesh)
                _, _, loss = step(params, opt_state, tokens)
                losses[strategy] = float(loss)
        assert np.isfinite(losses["ring"])
        assert losses["ulysses"] == pytest.approx(losses["ring"], rel=1e-5)

    def test_ulysses_strategy_requires_divisible_heads(self):
        """The misconfiguration fails at BUILD time, before a caller
        initializes expensive sharded state."""
        from bobrapet_tpu.parallel.train import make_train_step

        cfg = llama_tiny()  # n_heads=4, not divisible by seq=8
        devs = np.array(jax.devices()).reshape(1, 1, 1, 8)
        mesh = Mesh(devs, ("data", "fsdp", "model", "seq"))
        with pytest.raises(ValueError, match="divisible"):
            make_train_step(cfg, mesh, seq_parallel="ulysses")

    def test_seq_parallel_contradiction_rejected(self):
        from bobrapet_tpu.parallel.train import make_train_step

        cfg = llama_tiny()
        devs = np.array(jax.devices()).reshape(1, 1, 1, 8)
        mesh = Mesh(devs, ("data", "fsdp", "model", "seq"))
        with pytest.raises(ValueError, match="contradicts"):
            make_train_step(cfg, mesh, use_ring_attention=False,
                            seq_parallel="ulysses")

    def test_token_batch_sequence_sharding_flag(self):
        from bobrapet_tpu.parallel.train import make_token_batch
        from jax.sharding import PartitionSpec

        cfg = llama_tiny()
        devs = np.array(jax.devices()).reshape(2, 4)
        mesh = Mesh(devs, ("data", "seq"))
        t = make_token_batch(jax.random.PRNGKey(0), cfg, 2, 31, mesh, sequence_sharded=True)

        def axes(spec):
            # older jax reports singleton axes as 1-tuples
            # (PartitionSpec(('data',), 'seq')); normalize before comparing
            return tuple(
                (a,) if isinstance(a, str) else tuple(a) for a in spec
            )

        assert axes(t.sharding.spec) == axes(PartitionSpec("data", "seq"))

    def test_generate_capacity_guard(self):
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="exceeds cache capacity"):
            greedy_generate(params, prompt, cfg, max_new_tokens=8, cache_capacity=16)


class TestRingAttention:
    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
    def test_matches_reference_over_8_shards(self, hq, hkv):
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        S = 64  # 8 tokens per device
        q = jax.random.normal(jax.random.PRNGKey(0), (2, S, hq, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, S, hkv, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, S, hkv, 32))
        ref = attention_reference(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh, axis_name="seq", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_non_causal(self):
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 2, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 2, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 2, 16))
        ref = attention_reference(q, k, v, causal=False)
        out = ring_attention(q, k, v, mesh, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_ring_inside_llama_forward(self):
        from bobrapet_tpu.parallel.ring_attention import make_ring_attn_fn

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0, cfg.vocab_size)
        ref, _ = forward(params, tokens, cfg)
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        attn = make_ring_attn_fn(mesh, "seq")
        out, _ = forward(params, tokens, cfg, attn_fn=attn)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


class TestUlyssesAttention:
    """The all-to-all sequence-parallel strategy (DeepSpeed-Ulysses
    pattern): one head-scatter all-to-all, dense local attention over
    the full sequence, one gather back. Complement to ring attention
    for meshes where n_heads >= axis size."""

    @pytest.mark.parametrize("hq,hkv", [(8, 8), (8, 4), (8, 2), (16, 8)])
    def test_matches_reference_over_8_shards(self, hq, hkv):
        from bobrapet_tpu.parallel.ulysses import ulysses_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        S = 64
        q = jax.random.normal(jax.random.PRNGKey(0), (2, S, hq, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, S, hkv, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, S, hkv, 32))
        ref = attention_reference(q, k, v, causal=True)
        out = ulysses_attention(q, k, v, mesh, axis_name="seq", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_non_causal(self):
        from bobrapet_tpu.parallel.ulysses import ulysses_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 8, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 8, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8, 16))
        ref = attention_reference(q, k, v, causal=False)
        out = ulysses_attention(q, k, v, mesh, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_head_divisibility_guard(self):
        from bobrapet_tpu.parallel.ulysses import ulysses_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 4, 16))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh)

    def test_matches_ring_attention(self):
        """The two long-context strategies agree on the same shards."""
        from bobrapet_tpu.parallel.ulysses import ulysses_attention

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("seq",))
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 8, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 4, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 4, 32))
        ring = ring_attention(q, k, v, mesh, axis_name="seq", causal=True)
        uly = ulysses_attention(q, k, v, mesh, axis_name="seq", causal=True)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                                   rtol=2e-4, atol=2e-4)

    def test_ulysses_inside_llama_forward(self):
        from bobrapet_tpu.parallel.ulysses import make_ulysses_attn_fn

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 64), 0,
                                    cfg.vocab_size)
        ref, _ = forward(params, tokens, cfg)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
        attn = make_ulysses_attn_fn(mesh, "seq")
        out, _ = forward(params, tokens, cfg, attn_fn=attn)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)


class TestInt8Quantization:
    """Weight-only int8 decode (BASELINE: the 8B single-chip path needs
    int8; per-output-channel absmax keeps column error independent)."""

    def test_roundtrip_error_bounded(self):
        from bobrapet_tpu.models import quant

        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
        q = quant.quantize_array(w)
        assert q["q"].dtype == jnp.int8
        back = quant.dequantize_array(q)
        # absmax/127 per column bounds the element error at scale/2
        col_scale = np.asarray(q["scale"])
        err = np.abs(np.asarray(back) - np.asarray(w))
        assert (err <= col_scale[None, :] * 0.51).all()

    def test_tree_halves_and_preserves_structure(self):
        from bobrapet_tpu.models import quant

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        qp = quant.quantize_params(params)
        # embed stays exact; matmul weights are int8
        assert qp["embed"]["weight"].dtype == params["embed"]["weight"].dtype
        assert qp["layers"][0]["attn"]["wq"]["q"].dtype == jnp.int8
        assert qp["layers"][0]["attn_norm"]["weight"].ndim == 1  # untouched
        # ~4x smaller matmul weights dominate the fp32 tiny tree
        assert quant.tree_bytes(qp) < 0.5 * quant.tree_bytes(params)
        deq = quant.dequantize_params(qp)
        ref_tree = jax.tree_util.tree_structure(params)
        assert jax.tree_util.tree_structure(deq) == ref_tree

    def test_quantized_forward_close_and_decode_agrees(self):
        from bobrapet_tpu.models import quant

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        qp = quant.quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref, _ = forward(params, tokens, cfg)

        # the forward consumes the int8 tree NATIVELY (scales applied
        # after each matmul) — no dequantized weight ever materializes
        out = jax.jit(lambda qp, t: forward(qp, t, cfg)[0])(qp, tokens)
        # logits track closely relative to their spread
        spread = float(jnp.std(ref))
        assert float(jnp.max(jnp.abs(out - ref))) < 0.12 * spread * 10
        # greedy argmax agrees on the vast majority of positions
        agree = jnp.mean(
            (jnp.argmax(out, -1) == jnp.argmax(ref, -1)).astype(jnp.float32)
        )
        assert float(agree) >= 0.9, float(agree)

    def test_quantized_greedy_generate(self):
        from bobrapet_tpu.models import quant

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        qp = quant.quantize_params(params)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    cfg.vocab_size)

        toks = jax.jit(lambda qp, p: greedy_generate(
            qp, p, cfg=cfg, max_new_tokens=4, cache_capacity=16))(qp, prompt)
        assert toks.shape == (1, 4)

    def test_quantize_dequantize_requantize_fixpoint(self):
        """The stored scale is what divided the weight (ADVICE r2):
        quantizing the dequantized view with the same scale reproduces
        q exactly — no drift from an f32-vs-stored-dtype mismatch."""
        from bobrapet_tpu.models import quant

        w = (jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
             ).astype(jnp.bfloat16)
        q1 = quant.quantize_array(w)
        back = quant.dequantize_array(q1)
        q2 = quant.quantize_array(back)
        np.testing.assert_array_equal(np.asarray(q1["q"]), np.asarray(q2["q"]))
        np.testing.assert_array_equal(
            np.asarray(q1["scale"], dtype=np.float32),
            np.asarray(q2["scale"], dtype=np.float32),
        )

    @pytest.mark.parametrize("fs,tp", [(4, 2), (2, 4)])
    def test_int8_composes_with_tensor_parallel(self, fs, tp):
        """VERDICT r2 #5: int8 x TP — the quantized tree shards over the
        model axis (scales on the weight's output axis), and the sharded
        quantized forward matches the single-device quantized forward.

        The (fsdp=2, model=4) shape puts a 4-wide model axis over
        llama_tiny's 2 KV heads: jax 0.4's SPMD partitioner
        mis-partitions that non-divisible GQA head axis (padded KV
        shards leak into attention — the bf16 UNquantized sharded
        forward diverges identically: 93% of logits mismatch, max abs
        diff ~3.3, so this is an upstream partitioner defect, not a
        quantization bug). Version-gated until a jax upgrade; the
        divisible (fsdp=4, model=2) shape proves int8 x TP on every
        version."""
        if tp > 2 and tuple(
            int(x) for x in jax.__version__.split(".")[:2]
        ) < (0, 5):
            pytest.skip(
                "jax 0.4 SPMD mis-partitions GQA KV heads (2) over a "
                "4-wide model axis (bf16 and int8 alike: 93% logit "
                "mismatch, max abs diff ~3.3)"
            )
        from bobrapet_tpu.models import quant
        from bobrapet_tpu.parallel.sharding import llama_param_specs, shard_params

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        qp = quant.quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        ref = jax.jit(lambda qp, t: forward(qp, t, cfg)[0])(qp, tokens)

        mesh = Mesh(np.array(jax.devices()).reshape(fs, tp), ("fsdp", "model"))
        sharded = shard_params(qp, mesh)
        # int8 payload carries the weight's spec; the scale shards on
        # the OUTPUT axis (column-parallel wq -> scale on model)
        wq = sharded["layers"][0]["attn"]["wq"]
        assert wq["q"].dtype == jnp.int8
        assert wq["q"].sharding.spec == llama_param_specs(params, mesh)[
            "layers"][0]["attn"]["wq"]
        assert tuple(wq["scale"].sharding.spec) == ("model",)
        # row-parallel wo: scale on fsdp (the output axis)
        wo = sharded["layers"][0]["attn"]["wo"]
        assert tuple(wo["scale"].sharding.spec) == ("fsdp",)
        # per-chip int8 bytes: |W|/(fsdp*model) — TP and int8 compose
        local_q = wq["q"].addressable_shards[0].data
        assert local_q.size == wq["q"].size // 8

        with mesh:
            out = jax.jit(lambda qp, t: forward(qp, t, cfg)[0])(sharded, tokens)
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32),
            np.asarray(ref, dtype=np.float32),
            rtol=2e-3, atol=2e-3,
        )

    def test_int8_tp_greedy_generate(self):
        """The 8B serving shape end-to-end: quantized + model-sharded
        greedy decode produces identical tokens to unsharded decode."""
        from bobrapet_tpu.models import quant
        from bobrapet_tpu.parallel.sharding import shard_params

        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        qp = quant.quantize_params(params)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                    cfg.vocab_size)
        ref = jax.jit(lambda qp, p: greedy_generate(
            qp, p, cfg=cfg, max_new_tokens=4, cache_capacity=16))(qp, prompt)

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("model",))
        sharded = shard_params(qp, mesh)
        with mesh:
            toks = jax.jit(lambda qp, p: greedy_generate(
                qp, p, cfg=cfg, max_new_tokens=4, cache_capacity=16))(
                sharded, prompt)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))


class TestSpeculativeDecoding:
    """Draft-propose + target-verify decode: greedy speculative output
    must be TOKEN-IDENTICAL to target-only greedy — acceptance rate
    only moves speed, never content."""

    def _spec(self, target, draft, cfg, dcfg, prompt, n, k):
        from bobrapet_tpu.models.speculative import speculative_generate

        return jax.jit(
            lambda tp, dp, p: speculative_generate(
                tp, dp, p, cfg, dcfg, max_new_tokens=n, k=k)
        )(target, draft, prompt)

    def test_identical_to_target_greedy_with_weak_draft(self):
        cfg = llama_tiny()
        dcfg = llama_tiny()
        target = init_params(jax.random.PRNGKey(0), cfg)
        draft = init_params(jax.random.PRNGKey(7), dcfg)  # unrelated model
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0,
                                    cfg.vocab_size)
        want = jax.jit(lambda p, t: greedy_generate(
            p, t, cfg=cfg, max_new_tokens=10, cache_capacity=64))(
            target, prompt)

        res = self._spec(target, draft, cfg, dcfg, prompt, 10, 4)
        np.testing.assert_array_equal(np.asarray(res.tokens),
                                      np.asarray(want)[0])
        assert int(res.rounds) >= 1
        assert int(res.drafted) == int(res.rounds) * 4

    def test_perfect_draft_accepts_everything(self):
        """Draft == target: every proposal matches, so the loop commits
        k+1 tokens per round (the ideal acceptance ceiling)."""
        cfg = llama_tiny()
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0,
                                    cfg.vocab_size)
        n, k = 12, 3
        want = jax.jit(lambda p, t: greedy_generate(
            p, t, cfg=cfg, max_new_tokens=n, cache_capacity=64))(
            params, prompt)
        res = self._spec(params, params, cfg, cfg, prompt, n, k)
        np.testing.assert_array_equal(np.asarray(res.tokens),
                                      np.asarray(want)[0])
        assert int(res.accepted) == int(res.drafted)
        # ceil((n-1)/(k+1)) rounds after the prefill-committed token
        assert int(res.rounds) == -(-(n - 1) // (k + 1))

    def test_smaller_draft_architecture(self):
        """The draft may be a genuinely smaller model (fewer layers) —
        outputs still match the target exactly."""
        cfg = llama_tiny()
        dcfg = llama_tiny()
        dcfg = dataclasses.replace(dcfg, n_layers=1, ffn_hidden=128)
        target = init_params(jax.random.PRNGKey(0), cfg)
        draft = init_params(jax.random.PRNGKey(3), dcfg)
        prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 9), 0,
                                    cfg.vocab_size)
        want = jax.jit(lambda p, t: greedy_generate(
            p, t, cfg=cfg, max_new_tokens=7, cache_capacity=64))(
            target, prompt)
        res = self._spec(target, draft, cfg, dcfg, prompt, 7, 2)
        np.testing.assert_array_equal(np.asarray(res.tokens),
                                      np.asarray(want)[0])
