"""Per-controller dispatch: pool isolation, keyed serialization, and
pump parity (ISSUE 1 tentpole).

The reference gives every controller its own worker pool sized by
``controller.Options.MaxConcurrentReconciles`` (cmd/main.go:650-769);
these tests pin the properties that replacement must preserve:

- a blocked controller cannot head-of-line-block its peers;
- ``controllers.max-concurrent-reconciles`` (and the per-controller
  ``controllers.<name>.max-concurrent-reconciles`` override) is
  actually consumed: N distinct keys reconcile concurrently;
- one KEY never overlaps itself, and an event arriving mid-reconcile
  triggers exactly one follow-up run (workqueue dirty semantics);
- the ManualClock test pump is unchanged: serial, deterministic,
  virtual-time-advancing.
"""

from __future__ import annotations

import threading
import time

import pytest

from bobrapet_tpu.config.operator import OperatorConfig, parse_config
from bobrapet_tpu.controllers.manager import Clock, ControllerManager, ManualClock
from bobrapet_tpu.core.store import ResourceStore


def wait_for(cond, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    """Lockdep for the dispatcher suite (see test_concurrency.py)."""
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace over the dispatcher suite: pools, dirty/active sets,
    failure counters and timer heaps are all tracked (see
    test_concurrency.py for the contract)."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


def make_manager(**per_controller) -> ControllerManager:
    m = ControllerManager(ResourceStore(), clock=Clock())
    cfg = OperatorConfig()
    cfg.controllers.max_concurrent_reconciles = 1
    cfg.controllers.per_controller = dict(per_controller)
    m.apply_config(cfg)
    return m


class TestPoolIsolation:
    def test_blocked_controller_does_not_starve_peers(self):
        """Controller 'slow' parks on an event while 'fast' must keep
        draining its own queue — the exact head-of-line-blocking the
        single-dispatcher design suffered."""
        release = threading.Event()
        slow_started = threading.Event()
        fast_done: list[str] = []

        def slow(ns, name):
            slow_started.set()
            assert release.wait(10.0)
            return None

        def fast(ns, name):
            fast_done.append(name)
            return None

        m = make_manager()
        m.register("slow", slow, watches={})
        m.register("fast", fast, watches={})
        m.start()
        try:
            m.enqueue("slow", "default", "blocker")
            assert wait_for(slow_started.is_set)
            for i in range(10):
                m.enqueue("fast", "default", f"k{i}")
            assert wait_for(lambda: len(fast_done) == 10), fast_done
            assert not release.is_set()  # slow is STILL parked
        finally:
            release.set()
            m.stop()

    def test_config_width_runs_n_distinct_keys_concurrently(self):
        """With controllers.max-concurrent-reconciles=N, N reconciles of
        distinct keys overlap (a barrier only opens once N arrive)."""
        n = 4
        barrier = threading.Barrier(n, timeout=10.0)
        peak = []

        def fanout(ns, name):
            barrier.wait()  # deadlocks unless n run CONCURRENTLY
            peak.append(name)
            return None

        m = ControllerManager(ResourceStore(), clock=Clock())
        cfg = parse_config({"controllers.max-concurrent-reconciles": str(n)})
        m.apply_config(cfg)
        m.register("fanout", fanout, watches={})
        m.start()
        try:
            for i in range(n):
                m.enqueue("fanout", "default", f"k{i}")
            assert wait_for(lambda: len(peak) == n)
        finally:
            m.stop()

    def test_per_controller_override_key_wins(self):
        """controllers.<name>.max-concurrent-reconciles overrides the
        global default for that controller only."""
        cfg = parse_config({
            "controllers.max-concurrent-reconciles": "1",
            "controllers.wide.max-concurrent-reconciles": "3",
        })
        assert cfg.controllers.per_controller == {"wide": 3}

        barrier = threading.Barrier(3, timeout=10.0)
        wide_done: list[str] = []
        narrow_overlap = []
        narrow_in_flight = threading.Semaphore(0)
        narrow_running = []

        def wide(ns, name):
            barrier.wait()
            wide_done.append(name)
            return None

        def narrow(ns, name):
            narrow_running.append(name)
            if len(narrow_running) > 1:
                narrow_overlap.append(name)
            time.sleep(0.02)
            narrow_running.remove(name)
            narrow_in_flight.release()
            return None

        m = ControllerManager(ResourceStore(), clock=Clock())
        m.apply_config(cfg)
        m.register("wide", wide, watches={})
        m.register("narrow", narrow, watches={})
        m.start()
        try:
            for i in range(3):
                m.enqueue("wide", "default", f"w{i}")
                m.enqueue("narrow", "default", f"n{i}")
            assert wait_for(lambda: len(wide_done) == 3)
            for _ in range(3):
                assert narrow_in_flight.acquire(timeout=10.0)
            # the width-1 pool never ran two keys at once
            assert narrow_overlap == []
        finally:
            m.stop()

    def test_live_reload_grows_pool(self):
        """apply_config mid-flight widens a pool: a second batch that
        needs 3-way concurrency passes after the reload."""
        m = make_manager()
        barrier = threading.Barrier(3, timeout=10.0)
        done = []

        def fn(ns, name):
            barrier.wait()
            done.append(name)
            return None

        m.register("growme", fn, watches={})
        cfg = OperatorConfig()
        cfg.controllers.per_controller = {"growme": 3}
        m.apply_config(cfg)
        m.start()
        try:
            for i in range(3):
                m.enqueue("growme", "default", f"g{i}")
            assert wait_for(lambda: len(done) == 3)
        finally:
            m.stop()


class TestKeyedSerialization:
    def test_same_key_never_overlaps_and_dirty_runs_once(self):
        """An event for a key that is mid-reconcile must not start a
        second reconcile of that key; it must schedule EXACTLY one
        follow-up run after the in-flight one completes."""
        in_flight = []
        overlaps = []
        runs = []
        first_entered = threading.Event()
        release_first = threading.Event()
        lock = threading.Lock()

        def fn(ns, name):
            with lock:
                if in_flight:
                    overlaps.append(name)
                in_flight.append(name)
                runs.append(time.monotonic())
            if len(runs) == 1:
                first_entered.set()
                assert release_first.wait(10.0)
            with lock:
                in_flight.remove(name)
            return None

        m = make_manager(serial=4)  # width > 1: serialization must be keyed
        m.register("serial", fn, watches={})
        m.start()
        try:
            m.enqueue("serial", "default", "hot")
            assert wait_for(first_entered.is_set)
            # three events land mid-reconcile: dedupe to ONE follow-up
            m.enqueue("serial", "default", "hot")
            m.enqueue("serial", "default", "hot")
            m.enqueue("serial", "default", "hot")
            time.sleep(0.05)
            assert len(runs) == 1  # nothing overlapped the in-flight run
            release_first.set()
            assert wait_for(lambda: len(runs) == 2)
            time.sleep(0.2)  # settle: no third run may appear
            assert len(runs) == 2, runs
            assert overlaps == []
        finally:
            release_first.set()
            m.stop()

    def test_distinct_keys_of_one_controller_do_overlap(self):
        """Sanity inverse: the serialization is per-KEY, not per-pool."""
        barrier = threading.Barrier(2, timeout=10.0)
        done = []

        def fn(ns, name):
            barrier.wait()
            done.append(name)
            return None

        m = make_manager(pair=2)
        m.register("pair", fn, watches={})
        m.start()
        try:
            m.enqueue("pair", "default", "a")
            m.enqueue("pair", "default", "b")
            assert wait_for(lambda: sorted(done) == ["a", "b"])
        finally:
            m.stop()


class TestPumpParity:
    """run_until_quiet / ManualClock behavior is unchanged: serial,
    deterministic, virtual-time-advancing (the envtest analogue)."""

    def test_pump_is_serial_and_fifo(self):
        order = []
        active = []

        def a(ns, name):
            assert not active, "pump must be strictly serial"
            active.append(1)
            order.append(("a", name))
            active.pop()
            return None

        def b(ns, name):
            assert not active
            active.append(1)
            order.append(("b", name))
            active.pop()
            return None

        m = ControllerManager(ResourceStore(), clock=ManualClock())
        # wide pools configured — the PUMP must stay serial regardless
        cfg = OperatorConfig()
        cfg.controllers.max_concurrent_reconciles = 8
        m.apply_config(cfg)
        m.register("a", a, watches={})
        m.register("b", b, watches={})
        m.enqueue("a", "default", "1")
        m.enqueue("b", "default", "2")
        m.enqueue("a", "default", "3")
        assert m.run_until_quiet() == 3
        # global FIFO across controllers, exactly as the old dispatcher
        assert order == [("a", "1"), ("b", "2"), ("a", "3")]

    def test_pump_advances_virtual_time_through_timers(self):
        clock = ManualClock(start=1000.0)
        m = ControllerManager(ResourceStore(), clock=clock)
        ticks = []

        def fn(ns, name):
            ticks.append(clock.now())
            return 60.0 if len(ticks) < 3 else None  # requeue twice

        m.register("timer", fn, watches={})
        m.enqueue("timer", "default", "t")
        assert m.run_until_quiet() == 3
        assert ticks == [1000.0, 1060.0, 1120.0]

    def test_pump_backoff_on_failure_requeues(self):
        m = ControllerManager(ResourceStore(), clock=ManualClock())
        attempts = []

        def flaky(ns, name):
            attempts.append(name)
            if len(attempts) < 3:
                raise RuntimeError("transient")
            return None

        m.register("flaky", flaky, watches={})
        m.enqueue("flaky", "default", "x")
        assert m.run_until_quiet() == 3
        assert len(attempts) == 3

    def test_pump_dedupes_queued_keys(self):
        m = ControllerManager(ResourceStore(), clock=ManualClock())
        runs = []
        m.register("dedupe", lambda ns, name: runs.append(name), watches={})
        for _ in range(5):
            m.enqueue("dedupe", "default", "same")
        assert m.run_until_quiet() == 1
        assert runs == ["same"]


class TestRuntimeWiring:
    def test_runtime_manager_follows_configmap_reload(self):
        """The per-controller key flows ConfigMap -> OperatorConfigManager
        -> ControllerManager.apply_config live."""
        from bobrapet_tpu.core.object import new_resource
        from bobrapet_tpu.runtime import Runtime

        rt = Runtime()
        assert rt.manager._default_max_concurrent == 4  # ControllerTuning default
        rt.store.create(new_resource(
            "ConfigMap", "operator-config", "bobrapet-system",
            spec={"data": {
                "controllers.max-concurrent-reconciles": "2",
                "controllers.steprun.max-concurrent-reconciles": "8",
            }},
        ))
        assert rt.manager._default_max_concurrent == 2
        assert rt.manager._per_controller_max == {"steprun": 8}
        assert rt.manager._pools["steprun"].target == 8
        assert rt.manager._pools["storyrun"].target == 2


class TestSchedulingGateUnderConcurrency:
    def test_queue_cap_holds_with_concurrent_storyrun_workers(self):
        """Cross-run queue caps are check-then-launch: with several
        StoryRun workers live, the cap must never be breached (the
        DAG serializes the gate+launch window under _sched_lock)."""
        import threading as _threading

        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.config.operator import QueueConfig
        from bobrapet_tpu.runtime import Runtime
        from bobrapet_tpu.sdk import register_engram

        rt = Runtime(clock=Clock(), executor_mode="threaded")
        rt.config_manager.config.scheduling.queues["capq"] = QueueConfig(
            name="capq", max_concurrent=2
        )
        peak = [0]
        active = [0]
        lock = _threading.Lock()

        @register_engram("gate.work")
        def work(ctx):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.02)
            with lock:
                active[0] -= 1
            return {"ok": 1}

        rt.apply(make_engram_template("gate-tpl", entrypoint="gate.work"))
        rt.apply(make_engram("gate-worker", "gate-tpl"))
        rt.apply(make_story("capped", steps=[
            {"name": "w", "ref": {"name": "gate-worker"}},
        ], policy={"queue": "capq"}))
        rt.start()
        try:
            runs = [rt.run_story("capped") for _ in range(10)]
            assert wait_for(
                lambda: all(rt.run_phase(r) == "Succeeded" for r in runs),
                timeout=60.0,
            ), [rt.run_phase(r) for r in runs]
        finally:
            rt.stop()
        assert peak[0] <= 2, f"queue cap breached: peak concurrency {peak[0]}"
