"""Cross-shard stale-scope recovery (the PR-6-vintage lost-work race).

During a rebalance drain a dependent StepRun could resolve
``steps.<sib>.output`` from a StoryRun status view that lagged the
sibling's output patch and fail the run terminally ("cannot index
NoneType with .i" in the churn soak). The fix resolves missing outputs
from the AUTHORITATIVE StepRun state, and requeues (bounded) when even
that lags — these tests pin all three legs: heal, requeue, exhaust.
The churn-soak assert in test_shard_e2e stays the live detector.
"""

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.controllers.steprun import STALE_SCOPE_RETRY_CAP
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.sdk import register_engram


def _setup(rt):
    rt.apply(make_engram_template("w-tpl", entrypoint="stale-impl"))
    rt.apply(make_engram("worker", "w-tpl"))

    @register_engram("stale-impl")
    def impl(ctx):
        return {"i": ctx.inputs.get("v", 5)}

    rt.apply(make_story("dep-story", steps=[
        {"name": "s1", "ref": {"name": "worker"}, "with": {"v": 5}},
        {"name": "s2", "ref": {"name": "worker"},
         "with": {"v": "{{ steps.s1.output.i }}"}},
    ]))


def _steprun_of(rt, run, step_id):
    for sr in rt.store.list("StepRun"):
        if (
            (sr.spec.get("storyRunRef") or {}).get("name") == run
            and sr.spec.get("stepId") == step_id
        ):
            return sr
    return None


def _drive_to_s2(rt):
    """Run s1 to completion, launch s2, and return its StepRun name."""
    run = rt.run_story("dep-story")
    for _ in range(8):
        rt.storyrun_controller.reconcile("default", run)
        s1 = _steprun_of(rt, run, "s1")
        if s1 is not None:
            rt.steprun_controller.reconcile("default", s1.meta.name)
            if rt.store.get(
                "StepRun", "default", s1.meta.name
            ).status.get("phase") == "Succeeded":
                break
    rt.storyrun_controller.reconcile("default", run)
    s2 = _steprun_of(rt, run, "s2")
    assert s2 is not None, "s2 never launched"
    return run, _steprun_of(rt, run, "s1").meta.name, s2.meta.name


def _blank_view_output(rt, run):
    """Simulate the lagging replica view: the StoryRun's stepStates say
    s1 Succeeded but carry no output (the output patch 'in flight')."""
    def lag(r):
        r.status["stepStates"]["s1"]["output"] = None

    rt.store.mutate("StoryRun", "default", run, lag)


class TestStaleScopeRecovery:
    def test_heals_from_authoritative_steprun(self, rt):
        _setup(rt)
        run, _s1, s2 = _drive_to_s2(rt)
        _blank_view_output(rt, run)
        before = metrics.steprun_stale_scope.value("healed")
        # the dependent's reconcile must resolve s1's output from the
        # authoritative StepRun and dispatch — not fail the run
        for _ in range(4):
            rt.steprun_controller.reconcile("default", s2)
        status = rt.store.get("StepRun", "default", s2).status
        assert status.get("phase") == "Succeeded", status
        assert status.get("output") == {"i": 5}
        assert metrics.steprun_stale_scope.value("healed") == before + 1

    def test_requeues_when_even_the_steprun_lags(self, rt):
        _setup(rt)
        run, s1, s2 = _drive_to_s2(rt)
        _blank_view_output(rt, run)
        # blank the authoritative output too: nothing to heal from yet
        rt.store.patch_status(
            "StepRun", "default", s1, lambda st: st.update({"output": None})
        )
        delay = rt.steprun_controller.reconcile("default", s2)
        assert delay is not None and delay > 0  # requeued, not failed
        status = rt.store.get("StepRun", "default", s2).status
        assert status.get("phase") != "Failed"
        assert status.get("staleScopeRetries") == 1
        # the output surfaces -> next reconcile launches and clears the
        # retry ledger
        rt.store.patch_status(
            "StepRun", "default", s1,
            lambda st: st.update({"output": {"i": 5}}),
        )
        for _ in range(4):
            rt.steprun_controller.reconcile("default", s2)
        status = rt.store.get("StepRun", "default", s2).status
        assert status.get("phase") == "Succeeded"
        assert "staleScopeRetries" not in status

    def test_exhaustion_fails_loudly(self, rt):
        """A scope still stale past the cap is a genuinely lost output:
        the run must fail with a message naming the starved sibling —
        the requeue must not paper over real lost work forever."""
        _setup(rt)
        run, s1, s2 = _drive_to_s2(rt)
        _blank_view_output(rt, run)
        rt.store.patch_status(
            "StepRun", "default", s1, lambda st: st.update({"output": None})
        )
        rt.store.patch_status(
            "StepRun", "default", s2,
            lambda st: st.update(
                {"staleScopeRetries": STALE_SCOPE_RETRY_CAP}
            ),
        )
        rt.steprun_controller.reconcile("default", s2)
        status = rt.store.get("StepRun", "default", s2).status
        assert status.get("phase") == "Failed"
        assert "stale" in (status.get("error") or {}).get("message", "")

    def test_genuine_template_errors_stay_terminal(self, rt):
        """An outputless sibling that did NOT succeed is not a lagging
        view — indexing its None output is a genuine evaluation error
        and must stay terminal, not loop on the requeue."""
        _setup(rt)
        run, s1, s2 = _drive_to_s2(rt)
        _blank_view_output(rt, run)
        rt.store.patch_status(
            "StepRun", "default", s1, lambda st: st.update({"output": None})
        )

        def fail_sib(r):
            r.status["stepStates"]["s1"]["phase"] = "Failed"

        rt.store.mutate("StoryRun", "default", run, fail_sib)
        rt.steprun_controller.reconcile("default", s2)
        status = rt.store.get("StepRun", "default", s2).status
        assert status.get("phase") == "Failed"
        assert "input template evaluation failed" in (
            (status.get("error") or {}).get("message", "")
        )
