"""Store service units: wire codec, group-committed journal, durable
store recovery, and the socket service + client shim — all in-process
(threads over a tmp Unix socket), so tier-1 covers the full RPC surface
without subprocess spawn cost. The real multi-process contract lives in
tests/test_proc_soak.py.
"""

from __future__ import annotations

import json
import os
import socket
import tempfile
import threading
import time

import pytest

from bobrapet_tpu.core.object import ObjectMeta, Resource, new_resource
from bobrapet_tpu.core.store import (
    AdmissionDenied,
    Conflict,
    NotFound,
    ResourceStore,
    StoreError,
)
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.store_service import (
    DurableResourceStore,
    Journal,
    StoreClient,
    StoreService,
    make_store,
)
from bobrapet_tpu.store_service.backend import ENV_BACKEND, ENV_SOCKET
from bobrapet_tpu.store_service.journal import dump_recovered, load_state
from bobrapet_tpu.store_service.wire import FrameConn, recv_frame, send_frame


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace over the new process-boundary shims: the service's
    session/gate registries and the client's pending-call tables are
    @guarded_state — this suite runs them with the sanitizer armed."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


def _res(name: str, kind: str = "Story", ns: str = "default", **spec) -> Resource:
    return Resource(kind=kind, meta=ObjectMeta(namespace=ns, name=name),
                    spec=spec or {"v": 1})


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

class TestWire:
    def test_roundtrip_and_clean_eof(self):
        a, b = socket.socketpair()
        try:
            send_frame(a, {"op": "ping", "k": [1, 2, {"x": "y"}]})
            assert recv_frame(b) == {"op": "ping", "k": [1, 2, {"x": "y"}]}
            a.close()
            assert recv_frame(b) is None  # clean EOF, not an exception
        finally:
            b.close()

    def test_oversized_frame_rejected_by_sender(self):
        a, b = socket.socketpair()
        try:
            with pytest.raises(ValueError):
                send_frame(a, {"blob": "x" * (64 * 1024 * 1024)})
        finally:
            a.close()
            b.close()

    def test_frameconn_serializes_concurrent_senders(self):
        a, b = socket.socketpair()
        conn = FrameConn(a)
        try:
            threads = [
                threading.Thread(
                    target=lambda i=i: [conn.send({"i": i, "pad": "p" * 512})
                                        for _ in range(50)]
                )
                for i in range(4)
            ]
            got = []

            def reader():
                while len(got) < 200:
                    frame = recv_frame(b)
                    assert frame is not None
                    got.append(frame)

            rt = threading.Thread(target=reader)
            rt.start()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            rt.join(timeout=10.0)
            # interleaved senders never torn: every frame parsed whole
            assert len(got) == 200
        finally:
            conn.close()
            b.close()


# ---------------------------------------------------------------------------
# journal: group commit + durability
# ---------------------------------------------------------------------------

class TestJournal:
    def test_append_then_wait_durable(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"), fsync_batch=8)
        try:
            seqs = [j.append({"n": i}) for i in range(20)]
            j.wait_durable(seqs[-1], timeout=10.0)
            assert j.durable_seq >= seqs[-1]
        finally:
            j.close()
        lines = (tmp_path / "j.jsonl").read_bytes().splitlines()
        assert [json.loads(ln)["n"] for ln in lines] == list(range(20))

    def test_batch_of_one_is_per_record_fsync(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"), fsync_batch=1)
        try:
            for i in range(5):
                j.wait_durable(j.append({"n": i}), timeout=10.0)
        finally:
            j.close()

    def test_live_retune_and_close_drains(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"), fsync_batch=64)
        j.set_fsync_batch(2)
        assert j.fsync_batch == 2
        last = 0
        for i in range(10):
            last = j.append({"n": i})
        j.close()  # must drain pending before the worker exits
        assert j.durable_seq >= last
        assert len((tmp_path / "j.jsonl").read_bytes().splitlines()) == 10

    def test_live_fsync_failure_fails_loud_not_silently_durable(
        self, tmp_path, monkeypatch
    ):
        """A genuine I/O failure (ENOSPC/EIO analog) on the LIVE file
        must never advance _durable: waiters and appenders get errors,
        never an ack for a record the journal lost."""
        import bobrapet_tpu.store_service.journal as journal_mod

        j = Journal(str(tmp_path / "j.jsonl"), fsync_batch=8)
        try:
            seq0 = j.append({"n": 0})
            j.wait_durable(seq0, timeout=10.0)

            def broken_fsync(fd):
                raise OSError(28, "No space left on device")

            monkeypatch.setattr(journal_mod.os, "fsync", broken_fsync)
            seq1 = j.append({"n": 1})
            with pytest.raises(RuntimeError, match="journal write failed"):
                j.wait_durable(seq1, timeout=10.0)
            assert j.durable_seq < seq1  # the lost batch was NOT acked
            with pytest.raises(RuntimeError, match="journal write failed"):
                j.append({"n": 2})
        finally:
            monkeypatch.undo()
            j.close()


class TestDurableStore:
    def _store(self, d, **kw) -> DurableResourceStore:
        kw.setdefault("fsync_batch", 4)
        return DurableResourceStore(str(d), **kw)

    def test_recovery_replays_objects_and_exact_rv(self, tmp_path):
        s = self._store(tmp_path)
        s.create(_res("a", v=1))
        s.create(_res("b"))
        s.mutate("Story", "default", "a", lambda r: r.spec.__setitem__("v", 2))
        s.delete("Story", "default", "b")
        rv = s._rv_counter
        s.close()

        s2 = self._store(tmp_path)
        try:
            assert s2._rv_counter == rv  # exact, incl. the delete bump
            assert s2.get("Story", "default", "a").spec["v"] == 2
            assert s2.try_get("Story", "default", "b") is None
            # recovered store keeps journaling: new commits survive too
            s2.create(_res("c"))
        finally:
            s2.close()
        objs, rv3, replayed, _ = load_state(str(tmp_path))
        assert ("Story", "default", "c") in objs
        assert rv3 == rv + 1
        assert replayed >= 1

    def test_dump_matches_offline_recovery_bytes(self, tmp_path):
        s = self._store(tmp_path)
        try:
            for i in range(25):
                s.create(_res(f"r{i}", v=i))
            s.mutate("Story", "default", "r3",
                     lambda r: r.spec.__setitem__("v", 99))
            s.delete("Story", "default", "r7")
            d0 = s.dump()
        finally:
            s.close()
        assert d0 == dump_recovered(str(tmp_path))

    def test_snapshot_truncates_journal_and_preserves_bytes(self, tmp_path):
        s = self._store(tmp_path, snapshot_every=10)
        try:
            for i in range(25):  # crosses the snapshot threshold twice
                s.create(_res(f"s{i}", v=i))
            d0 = s.dump()
        finally:
            s.close()
        # compaction actually happened: journal holds the tail, not all 25
        journal_lines = (tmp_path / "journal.jsonl").read_bytes().splitlines()
        assert 0 < len(journal_lines) < 25
        assert (tmp_path / "snapshot.json").exists()
        assert dump_recovered(str(tmp_path)) == d0

    def test_torn_tail_tolerated(self, tmp_path):
        s = self._store(tmp_path)
        s.create(_res("whole"))
        d0 = s.dump()
        s.close()
        with open(tmp_path / "journal.jsonl", "ab") as fh:
            fh.write(b'{"op": "put", "key": ["Sto')  # crash mid-write
        assert dump_recovered(str(tmp_path)) == d0

    def test_journal_metrics_registered(self):
        assert metrics.store_journal_append_latency is not None
        assert metrics.store_journal_fsync_batch is not None
        assert metrics.store_journal_snapshot_duration is not None
        assert metrics.store_journal_replay_rate is not None


# ---------------------------------------------------------------------------
# service + client over a real socket (in-process threads)
# ---------------------------------------------------------------------------

@pytest.fixture()
def served():
    d = tempfile.mkdtemp(prefix="bobra-svc-")
    sock = os.path.join(d, "s.sock")
    store = ResourceStore()
    service = StoreService(store, sock).start()
    clients = []

    def connect() -> StoreClient:
        c = StoreClient(sock)
        clients.append(c)
        return c

    yield store, connect
    for c in clients:
        c.close()
    service.close()


class TestServiceClient:
    def test_crud_conflict_notfound(self, served):
        _, connect = served
        c = connect()
        created = c.create(_res("a", v=1))
        assert created.meta.resource_version == 1
        stale = created
        c.mutate("Story", "default", "a", lambda r: r.spec.__setitem__("v", 2))
        stale.spec["v"] = 7
        with pytest.raises(Conflict):
            c.update(stale)
        with pytest.raises(NotFound):
            c.get("Story", "default", "missing")
        with pytest.raises(NotFound):
            c.delete("Story", "default", "missing")
        c.delete("Story", "default", "a")
        assert len(c) == 0

    def test_watch_events_and_resync(self, served):
        _, connect = served
        c = connect()
        events = []
        cond = threading.Condition()

        def on_ev(ev):
            with cond:
                events.append((ev.type, ev.resource.meta.name))
                cond.notify_all()

        c.watch(on_ev, kinds=["Story"])
        c.create(_res("w1"))
        with cond:
            cond.wait_for(lambda: ("ADDED", "w1") in events, timeout=10.0)
        c.resync()
        with cond:
            cond.wait_for(lambda: ("MODIFIED", "w1") in events, timeout=10.0)
        assert ("ADDED", "w1") in events and ("MODIFIED", "w1") in events

    def test_client_side_admission_chain(self, served):
        _, connect = served
        c = connect()

        def default_v(r):
            r.spec.setdefault("v", 42)

        def deny_neg(new, old):
            if new.spec.get("v", 0) < 0:
                raise AdmissionDenied("v must be >= 0")

        c.register_defaulter("Story", default_v)
        c.register_validator("Story", deny_neg)
        got = c.create(Resource(kind="Story",
                                meta=ObjectMeta(namespace="default", name="adm"),
                                spec={}))
        assert got.spec["v"] == 42  # defaulted client-side, then shipped
        with pytest.raises(AdmissionDenied):
            c.create(_res("bad", v=-1))

    def test_cross_client_gate_and_session_death_rollback(self, served):
        _, connect = served
        c1, c2 = connect(), connect()
        lock1, res1 = c1.scheduling_gate()
        lock2, res2 = c2.scheduling_gate()
        with lock1:
            res1[("q", "default")] = 2
        with lock2:
            assert res2.get(("q", "default"), 0) == 2  # one gate, all shards
            res2[("q", "default")] = 5  # net +3 owned by c2's session
        c2.close()  # kill -9 analog: session dies holding reservations

        def rolled_back() -> bool:
            with lock1:
                return res1.get(("q", "default"), 0) == 2

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not rolled_back():
            time.sleep(0.02)
        assert rolled_back(), "dead session's net delta was not rolled back"

    def test_gate_survives_client_killed_while_waiting(self, served):
        """kill -9 analog for a client whose gate_acquire is BLOCKED:
        its stranded server-side acquire thread must never take (and
        keep) ownership for the dead sid — the gate has to stay
        acquirable bus-wide afterwards."""
        _, connect = served
        c1, c2, c3 = connect(), connect(), connect()
        lock1, _ = c1.scheduling_gate()
        lock2, _ = c2.scheduling_gate()
        lock3, _ = c3.scheduling_gate()
        lock1.acquire()
        try:
            waiter_done = threading.Event()

            def blocked_acquire():
                try:
                    lock2.acquire()
                except StoreError:
                    pass  # expected: session died mid-acquire
                waiter_done.set()

            t = threading.Thread(target=blocked_acquire, daemon=True)
            t.start()
            time.sleep(0.3)  # let gate_acquire reach the service and block
            c2.close()  # die while waiting for the gate
            time.sleep(0.2)  # let the service tear the session down
        finally:
            lock1.release()

        acquired = threading.Event()

        def third():
            lock3.acquire()
            acquired.set()
            lock3.release()

        t3 = threading.Thread(target=third, daemon=True)
        t3.start()
        assert acquired.wait(10.0), "gate wedged by client killed mid-acquire"
        assert waiter_done.wait(10.0)
        t3.join(timeout=5.0)

    def test_client_survives_outage_longer_than_deadline(self):
        """A store-service restart SLOWER than reconnect_deadline must
        not brick the client: calls during the outage fail, but the
        client keeps redialing and heals once the service returns."""
        d = tempfile.mkdtemp(prefix="bobra-svc-outage-")
        sock = os.path.join(d, "s.sock")
        service = StoreService(ResourceStore(), sock).start()
        c = StoreClient(sock, reconnect_deadline=0.2)
        try:
            c.create(_res("pre"))
            service.close()
            time.sleep(0.6)  # outage 3x the reconnect deadline
            service2 = StoreService(ResourceStore(), sock).start()
            try:
                deadline = time.monotonic() + 10.0
                while True:
                    try:
                        c.create(_res("post"))
                        break
                    except StoreError:
                        assert time.monotonic() < deadline, (
                            "client never recovered after slow restart"
                        )
                        time.sleep(0.05)
                assert c.get("Story", "default", "post").meta.name == "post"
            finally:
                service2.close()
        finally:
            c.close()

    def test_oversized_response_fails_call_not_session(
        self, served, monkeypatch
    ):
        """A response above the frame cap must fail just that call with
        a StoreError — not tear down the session (watch stream and all
        in-flight requests) the way a real socket death does."""
        from bobrapet_tpu.store_service import wire

        _, connect = served
        c = connect()
        for i in range(50):
            c.create(_res(f"wide{i}", v=i))
        time.sleep(0.2)  # drain small watch frames before lowering the cap
        monkeypatch.setattr(wire, "MAX_FRAME", 4096)
        with pytest.raises(StoreError, match="frame cap"):
            c.list("Story", "default")
        # session survived: single-object traffic still flows
        assert c.get("Story", "default", "wide7").spec["v"] == 7

    def test_list_count_kinds_rv(self, served):
        _, connect = served
        c = connect()
        for i in range(4):
            c.create(_res(f"l{i}", kind="Engram"))
        assert {r.meta.name for r in c.list("Engram", "default")} == {
            "l0", "l1", "l2", "l3"}
        assert c.count("Engram", "default") == 4
        assert c.list_keys("Engram", "default") == [
            ("default", f"l{i}") for i in range(4)]
        assert "Engram" in c.kinds()
        assert c._rv_counter == 4

    def test_local_index_fallback(self, served):
        _, connect = served
        c = connect()
        c.add_index("Engram", "byTpl",
                    lambda r: [r.spec.get("tpl")] if r.spec.get("tpl") else [])
        c.create(_res("i1", kind="Engram", tpl="t-a"))
        c.create(_res("i2", kind="Engram", tpl="t-b"))
        c.create(_res("i3", kind="Engram", tpl="t-a"))
        got = {r.meta.name for r in c.list("Engram", "default",
                                           index=("byTpl", "t-a"))}
        assert got == {"i1", "i3"}

    def test_durable_service_dump_remote(self):
        d = tempfile.mkdtemp(prefix="bobra-svc-dur-")
        sock = os.path.join(d, "s.sock")
        store = DurableResourceStore(os.path.join(d, "data"), fsync_batch=2)
        service = StoreService(store, sock).start()
        c = StoreClient(sock)
        try:
            c.create(_res("dur1", v=1))
            c.create(_res("dur2", v=2))
            c.snapshot_remote()
            c.create(_res("dur3", v=3))
            d0 = c.dump_remote()
        finally:
            c.close()
            service.close()
            store.close()
        assert d0 == dump_recovered(os.path.join(d, "data"))


# ---------------------------------------------------------------------------
# backend seam + config knobs
# ---------------------------------------------------------------------------

class TestBackendSeam:
    def test_inproc_is_default(self, monkeypatch):
        monkeypatch.delenv(ENV_BACKEND, raising=False)
        s = make_store()
        assert isinstance(s, ResourceStore)
        assert not isinstance(s, DurableResourceStore)

    def test_service_requires_socket(self, monkeypatch):
        from bobrapet_tpu.core.store import StoreError

        monkeypatch.delenv(ENV_SOCKET, raising=False)
        with pytest.raises(StoreError):
            make_store("service")

    def test_env_selects_service(self, served, monkeypatch):
        _, connect = served
        ref = connect()  # keeps the fixture socket path
        monkeypatch.setenv(ENV_BACKEND, "service")
        monkeypatch.setenv(ENV_SOCKET, ref.socket_path)
        c = make_store()
        try:
            assert isinstance(c, StoreClient)
        finally:
            c.close()


class TestConfigKnobs:
    def test_validation_rejects_bad_values(self):
        from bobrapet_tpu.config.operator import OperatorConfig

        cfg = OperatorConfig()
        cfg.store.journal_fsync_batch = 0
        errs = cfg.validate()
        assert any("store.journal-fsync-batch" in e for e in errs)
        cfg = OperatorConfig()
        cfg.store.snapshot_every_records = 0
        assert any("store.snapshot-every-records" in e for e in cfg.validate())

    def test_dotted_keys_apply(self):
        from bobrapet_tpu.config.operator import OperatorConfig, parse_config

        cfg = parse_config({
            "store.journal-fsync-batch": "16",
            "store.snapshot-every-records": "500",
        })
        assert isinstance(cfg, OperatorConfig)
        assert cfg.store.journal_fsync_batch == 16
        assert cfg.store.snapshot_every_records == 500

    def test_live_reload_retunes_journal(self, tmp_path):
        s = DurableResourceStore(str(tmp_path), fsync_batch=64)
        try:
            s._journal.set_fsync_batch(4)
            assert s._journal.fsync_batch == 4
            s.create(_res("tuned"))
        finally:
            s.close()
