"""GOOD corpus for cow-discipline: nothing here may be flagged."""


def read_view(store):
    sr = store.get_view("StepRun", "ns", "a")
    return sr.status.get("phase")


def copy_then_mutate(store):
    sr = store.get_view("StepRun", "ns", "a").deepcopy()
    sr.status["phase"] = "Running"  # OK: chain broken by deepcopy()
    return sr


def rebind_clears_taint(store):
    sr = store.get_view("StepRun", "ns", "a")
    sr = {"status": {}}
    sr["status"]["phase"] = "Running"  # OK: rebound to a fresh dict
    return sr


def write_through_store(store):
    def patch(r):
        r.status["phase"] = "Running"  # OK: mutate() hands out a copy

    store.mutate("StepRun", "ns", "a", patch)


def dump_is_fresh(cached_parse, Step, spec):
    parsed = cached_parse(Step, spec)
    d = parsed.to_dict()
    d["name"] = "local-copy"  # OK: to_dict() is a new tree
    return d
