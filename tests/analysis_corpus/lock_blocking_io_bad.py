"""BAD corpus for lock-blocking-io: every pattern here must be flagged."""

import os
import threading
import time

_lock = threading.Lock()


class Recorder:
    def __init__(self, store):
        self._lock = threading.Lock()
        self.store = store

    def sweep_sleep(self):
        with self._lock:
            time.sleep(0.5)  # BAD: sleep under lock

    def sweep_store(self):
        with self._lock:
            return self.store.list("StepRun")  # BAD: store traffic under lock

    def sweep_view(self):
        with self._lock:
            return self.store.list_views("StepRun")  # BAD: store lock edge

    def _journal(self, payload):
        with open("/tmp/journal", "w") as f:  # blocking helper
            f.write(payload)

    def sweep_indirect(self):
        with self._lock:
            self._journal("x")  # BAD: same-file helper does file I/O

    def sweep_socket(self, sock):
        with self._lock:
            return sock.recv(4096)  # BAD: socket under lock

    def sweep_event(self, ev):
        with self._lock:
            ev.wait(1.0)  # BAD: Event.wait blocks the lock (no release)


def module_level(payload):
    with _lock:
        os.replace("/tmp/a", "/tmp/b")  # BAD: filesystem under module lock


class RpcClient:
    """Self-receiver interprocedural resolution: a blocking method of
    THIS class called as ``self.get(...)`` is followed (the good
    corpus pins that ``other.get(...)`` is not)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._done = threading.Event()

    def get(self, key):
        self._done.wait()
        return key

    def blocking_under_lock(self):
        with self._lock:
            return self.get("k")  # BAD: self.get blocks via Event.wait
