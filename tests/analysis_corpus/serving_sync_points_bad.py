"""serving-sync-points bad fixture: every tagged line must flag."""

import jax
import numpy as np


def commit_horizon(rec):
    jax.block_until_ready(rec["last"])  # BAD
    payload = jax.device_get(rec["outs"])  # BAD
    return payload


def sample_metrics(arr):
    host = np.asarray(arr)  # BAD
    return host.mean()


class Engine:
    def drain(self, toks):
        toks.block_until_ready()  # BAD
        # annotation present but no reason given — still a finding
        return jax.device_get(toks)  # sync-point:   # BAD
