"""BAD corpus for enum-literal-drift (fed to the checker under a
bobrapet_tpu/ pseudo-path; as a real tests/ file it would be exempt)."""


def compare_phase(sr):
    return sr.status.get("phase") == "Running"  # BAD: Phase.RUNNING


def compare_exit(state):
    if state.exit_class in ("retry", "rateLimited"):  # BAD: ExitClass members
        return True
    return False


def stamp_phase(status):
    status["phase"] = "Succeeded"  # BAD: keyed store of Phase value


def build_status():
    return {"phase": "Failed", "exitClass": "terminal"}  # BAD: both keys
