"""BAD corpus for shared-state-discipline: every tagged line must be
flagged. Never imported — parsed by tests/test_analysis.py only."""

import threading
from collections import defaultdict, deque

from bobrapet_tpu.analysis.racedetect import guarded_state


class Registry:
    """Owns a lock, mutates its containers without it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._order = []
        self._seen = set()
        self._recent = deque()
        self._buckets = defaultdict(set)

    def put(self, key, value):
        self._items[key] = value  # BAD: subscript assign, no lock

    def bump(self, key):
        self._items[key] += 1  # BAD: augmented assign, no lock

    def forget(self, key):
        del self._items[key]  # BAD: delete, no lock

    def push(self, item):
        self._order.append(item)  # BAD: list mutator, no lock

    def tag(self, key, label):
        self._seen.add((key, label))  # BAD: set mutator, no lock

    def note(self, item):
        self._recent.appendleft(item)  # BAD: deque mutator, no lock

    def retire(self, bucket, key):
        # inner containers inherit the outer attribute's discipline
        self._buckets[bucket].discard(key)  # BAD: through-subscript mutation

    def deferred(self):
        with self._lock:
            def later():
                self._order.append("late")  # BAD: closure outlives the lock
            return later

    def _sweep(self):
        self._items.clear()  # BAD: helper with no in-class call sites

    def _cycle_a(self):
        self._seen.discard("a")  # BAD: mutual recursion, no locked entry
        self._cycle_b()

    def _cycle_b(self):
        self._seen.discard("b")  # BAD: mutual recursion, no locked entry
        self._cycle_a()


@guarded_state("declared", "ghost")
class Drifted:  # BAD: declares 'ghost' but __init__ assigns no such container
    def __init__(self):
        self._lock = threading.Lock()
        self.declared = {}
        self.missing = []  # BAD: container undeclared in guarded_state
