"""GOOD corpus for metrics-drift."""

from bobrapet_tpu.observability.metrics import REGISTRY, metrics


def emit_known():
    metrics.steprun_total.inc("Succeeded")  # OK: registered family
    metrics.reconcile_queue_depth.set(3, "steprun")  # OK


def adhoc_prefixed():
    # OK: ad-hoc registration is allowed when it stays in the namespace
    return REGISTRY.counter("bobrapet_corpus_demo_total", "demo")


def registry_admin():
    REGISTRY.reset()  # OK: registry management, not an emission
    return metrics.REGISTRY if hasattr(metrics, "REGISTRY") else None
