"""BAD corpus for config-key-drift (checked against the REAL registry
in config/operator.py): unregistered dotted keys in key positions."""

CONFIG_MAP_DATA = {
    "data": {
        "fleet.bogus-knob": "1",  # BAD: no such key in the table
        "dataplane.writer-max-batch-size": "64",  # BAD: near-miss of a real key
    }
}


def read_unknown(config):
    return config.get("controllers.max-reconcile-width")  # BAD: unregistered
