"""GOOD corpus for shared-state-discipline: nothing here may be
flagged. Never imported — parsed by tests/test_analysis.py only."""

import threading
from collections import deque

from bobrapet_tpu.analysis.racedetect import guarded_state


@guarded_state("_items", "_order")
class DisciplinedRegistry:
    """Every mutation lock-held, lexically or through a *_locked chain;
    the guarded_state declaration matches the discovered containers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._order = deque()
        self._items["boot"] = 1  # __init__ is pre-publication
        self.capacity = 8  # scalar attrs are out of scope

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._order.append(key)

    def evict(self):
        with self._lock:
            self._evict_locked()

    def _evict_locked(self):
        # excused transitively: its only call site holds the lock
        while len(self._order) > self.capacity:
            self._trim_one_locked()

    def _trim_one_locked(self):
        # two-level chain plus self-recursion: the fixed point proves
        # every path here enters under the lock
        key = self._order.popleft()
        self._items.pop(key, None)
        if key in self._items:
            self._trim_one_locked()

    def snapshot(self):
        with self._lock:
            return dict(self._items)


class InitCallee:
    """A mutating helper called only from __init__ is pre-publication."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}
        self._seed()

    def _seed(self):
        self._state["ready"] = False

    def ready(self):
        with self._lock:
            self._state["ready"] = True


class NoLock:
    """No lock attribute: the discipline does not apply (the class is
    single-threaded by construction or externally synchronized — the
    runtime sanitizer, not this checker, judges that claim)."""

    def __init__(self):
        self._cache = {}

    def put(self, k, v):
        self._cache[k] = v
