"""serving-sync-points good fixture: zero findings expected."""

import jax
import jax.numpy as jnp


def commit_horizon(rec):
    # the engine's one intended round-trip per horizon, reviewed
    jax.block_until_ready(rec["last"])  # sync-point: per-horizon commit
    payload = jax.device_get(rec["outs"])  # sync-point: commit payload
    return payload


def patch_lane(dev, trow):
    # jnp.asarray is an UPLOAD (host->device), not a sync — never flagged
    return {**dev, "tables": jnp.asarray(trow)}


def enqueue(fn, *args):
    # plain dispatch without a sync is the steady state
    return fn(*args)
