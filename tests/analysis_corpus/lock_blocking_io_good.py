"""GOOD corpus for lock-blocking-io: nothing here may be flagged."""

import threading
import time


class Recorder:
    def __init__(self, store):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.store = store
        self._pending = []

    def sweep(self):
        # snapshot under the lock, act after release — the fixed
        # recorder pattern
        with self._lock:
            pending = list(self._pending)
            self._pending.clear()
        for key in pending:
            self.store.list("StepRun", namespace=key)
        time.sleep(0.01)

    def wait_for_work(self):
        with self._lock:
            self._cond.wait(timeout=1.0)  # OK: Condition.wait releases

    def deferred_def(self):
        with self._lock:
            def flush():
                time.sleep(1.0)  # OK: defined under lock, not run

            self._pending.append(flush)


class RpcClient:
    """A class whose OWN ``get`` blocks must not poison unrelated
    ``dict.get`` calls under a lock: interprocedural resolution only
    follows bare names and self/cls methods, never other receivers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handlers = {}
        self._done = threading.Event()

    def get(self, key):
        self._done.wait()  # genuinely blocking RPC-style method
        return key

    def handlers_for(self, kind):
        with self._lock:
            # OK: dict.get on a non-self receiver, not RpcClient.get
            return list(self._handlers.get(kind, []))
