"""BAD corpus for metrics-drift (checked against the REAL inventory in
observability/metrics.py)."""

from bobrapet_tpu.observability.metrics import REGISTRY, metrics


def emit_unknown():
    metrics.totally_unregistered_family.inc("x")  # BAD: not in inventory


def rogue_unprefixed():
    return REGISTRY.counter("my_adhoc_total", "no namespace")  # BAD: prefix
