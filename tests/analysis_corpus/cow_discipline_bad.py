"""BAD corpus for cow-discipline: every mutation here must be flagged."""


def mutate_view(store):
    sr = store.get_view("StepRun", "ns", "a")
    sr.status["phase"] = "Poisoned"  # BAD: assignment into a view


def mutate_try_view(store):
    sr = store.try_get_view("StepRun", "ns", "a")
    if sr is not None:
        sr.spec.update({"k": "v"})  # BAD: mutating method on a view


def mutate_list_views(store):
    for obj in store.list_views("StepRun"):
        obj.meta.labels["touched"] = "yes"  # BAD: loop var from list_views


def mutate_parsed(cached_parse, Step, spec):
    parsed = cached_parse(Step, spec)
    parsed.with_["k"] = "v"  # BAD: shared parse mutated


def mutate_event(ev, store):
    sr = ev.resource
    del sr.status["phase"]  # BAD: watch payloads are shared


def mutate_alias(store):
    view = store.get_view("StepRun", "ns", "a")
    alias = view
    alias.status["x"] = 1  # BAD: taint propagates through alias
