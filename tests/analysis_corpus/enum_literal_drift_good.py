"""GOOD corpus for enum-literal-drift."""

from bobrapet_tpu.api.enums import ExitClass, Phase


def compare_phase(sr):
    return sr.status.get("phase") == Phase.RUNNING  # OK: enum member


def stamp_phase(status):
    status["phase"] = str(Phase.SUCCEEDED)  # OK: serialized enum


def build_status():
    return {"phase": Phase.FAILED.value, "exitClass": ExitClass.TERMINAL.value}


def unrelated_literals(doc):
    # OK: 'Running' compared against something with no phase hint
    return doc.title == "Running"


def kube_vocabulary(pod):
    # would be BAD in repo code (and is, in cluster/: suppressed with a
    # justification) — here the hint word is absent so it's not flagged
    return pod.state == "Whatever"
