"""GOOD corpus for config-key-drift: registered keys + dynamic families
+ dotted strings in non-key positions."""

CONFIG_MAP_DATA = {
    "data": {
        "fleet.preemption-retry-cap": "5",  # OK: registered
        "dataplane.writer-max-batch": "64",  # OK: registered
        "controllers.steprun.max-concurrent-reconciles": "8",  # OK: dynamic family
        "scheduling.queue.gpu.max-concurrent": "2",  # OK: dynamic family
    }
}


def read_known(config):
    return config.get("templating.evaluation-timeout")  # OK: registered


def span_name(tracer):
    # OK: dotted string as a call argument is NOT a config key position
    return tracer.start_span("engram.work")
