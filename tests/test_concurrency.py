"""Threaded-executor + store-concurrency hardening.

The reference's race coverage is architectural (optimistic concurrency,
SDK-vs-controller status races, steprun_sdk_race_test.go); this suite is
its analogue for the in-process control plane's LIVE mode: a dispatcher
thread, a threaded gang executor (one thread per host), and concurrent
store writers. Also carries the dehydrate/hydrate round-trip fuzz
(reference: pkg/storage/manager_fuzz_test.go).
"""

import random
import string
import threading
import time

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.controllers.jobs import JOB_KIND, LocalGangExecutor, make_job
from bobrapet_tpu.controllers.manager import Clock
from bobrapet_tpu.core.store import ResourceStore
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram


def wait_for(cond, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    """Lockdep for the whole module: every repo lock created while these
    threaded tests run is instrumented; an acquisition-order cycle
    (potential deadlock) fails the suite at module teardown."""
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace for the whole module: every @guarded_state container
    created by these tests is swapped for a tracked wrapper; an
    unordered, unlocked conflicting access pair fails the suite at
    teardown unless justified in bobrarace-baseline.json."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


@pytest.fixture
def live_rt():
    """Runtime in live mode: real clock, dispatcher thread, threaded
    gang executor."""
    rt = Runtime(clock=Clock(), executor_mode="threaded")
    rt.start()
    yield rt
    rt.stop()


class TestClaimArbitration:
    def test_two_executors_run_each_job_exactly_once(self):
        """Two executor instances watching one store must arbitrate via
        the claim: every job executes on exactly one of them (the old
        id(self)%100000 identity could collide and double-run)."""
        store = ResourceStore()
        ran: list[str] = []
        lock = threading.Lock()

        @register_engram("claims.count")
        def count(ctx):
            with lock:
                ran.append(ctx.env.get("JOB_NAME", ctx.step_run))
            return {"ok": True}

        ex1 = LocalGangExecutor(store, mode="sync")
        ex2 = LocalGangExecutor(store, mode="sync")
        assert ex1.executor_id != ex2.executor_id
        for i in range(12):
            store.create(make_job(
                f"job-{i}", "default", f"sr-{i}",
                entrypoint="claims.count",
                env={"JOB_NAME": f"job-{i}"},
            ))
        jobs = store.list(JOB_KIND, "default")
        assert all(j.status.get("phase") in ("Succeeded", "Failed") for j in jobs)
        assert sorted(ran) == sorted(f"job-{i}" for i in range(12))
        claimed_by = {j.status["executor"] for j in jobs}
        assert claimed_by <= {ex1.executor_id, ex2.executor_id}

    def test_executor_identity_is_collision_free_across_instances(self):
        store = ResourceStore()
        ids = {LocalGangExecutor(store, mode="sync").executor_id for _ in range(20)}
        assert len(ids) == 20


class TestThreadedExecutor:
    def _setup(self, rt, entrypoint, name="worker"):
        rt.apply(make_engram_template(f"{name}-tpl", entrypoint=entrypoint))
        rt.apply(make_engram(name, f"{name}-tpl"))

    def test_threaded_story_end_to_end(self, live_rt):
        """A 3-step DAG completes in live mode: dispatcher thread +
        per-host gang threads, no pump() determinism to hide races."""
        done = []

        @register_engram("live.step")
        def step(ctx):
            done.append(ctx.step)
            return {"at": ctx.step}

        self._setup(live_rt, "live.step")
        live_rt.apply(make_story("live", steps=[
            {"name": "a", "ref": {"name": "worker"}},
            {"name": "b", "ref": {"name": "worker"}, "needs": ["a"]},
            {"name": "c", "ref": {"name": "worker"}, "needs": ["a"]},
        ]))
        run = live_rt.run_story("live")
        assert wait_for(lambda: live_rt.run_phase(run) == "Succeeded"), (
            live_rt.run_phase(run), done,
        )
        assert sorted(done) == ["a", "b", "c"]

    def test_threaded_multihost_gang(self, live_rt):
        """All hosts of a gang run as real threads; every TPU_WORKER_ID
        appears exactly once."""
        seen: list[int] = []
        lock = threading.Lock()

        @register_engram("live.gang")
        def gang(ctx):
            with lock:
                seen.append(ctx.host_id)
            return {"hosts": ctx.num_hosts}

        self._setup(live_rt, "live.gang")
        live_rt.apply(make_story("gang", steps=[
            {"name": "train", "ref": {"name": "worker"}, "tpu": {"hosts": 4}},
        ]))
        run = live_rt.run_story("gang")
        assert wait_for(lambda: live_rt.run_phase(run) == "Succeeded")
        assert sorted(seen) == [0, 1, 2, 3]

    def test_deadline_kills_hung_host(self, live_rt):
        """A host that ignores its deadline is killed by the executor's
        join-timeout and recorded as EXIT_TIMEOUT (kubelet's
        activeDeadlineSeconds role)."""
        release = threading.Event()

        @register_engram("live.hang")
        def hang(ctx):
            release.wait(20.0)
            return {}

        self._setup(live_rt, "live.hang")
        live_rt.apply(make_story("hung", steps=[
            {"name": "h", "ref": {"name": "worker"},
             "execution": {"timeout": "1s", "retry": {"maxRetries": 0}}},
        ]))
        run = live_rt.run_story("hung")
        try:
            assert wait_for(lambda: live_rt.run_phase(run) == "Failed", timeout=30)
            r = live_rt.store.get("StoryRun", "default", run)
            state = r.status["stepStates"]["h"]
            # 124 = timeout, classified retryable (reference:
            # classifyExitCode:4815); budget 0 makes it final here
            assert state["exitCode"] == 124, state
            assert state["exitClass"] == "retry", state
        finally:
            release.set()

    def test_cancel_mid_gang_reaches_running_hosts(self, live_rt):
        """Graceful cancel deletes the Job; the executor must propagate
        that to in-flight host threads (cancel event -> cooperative
        check_deadline raises), not leak them as daemons."""
        started = threading.Event()
        observed_cancel = threading.Event()

        @register_engram("live.cancelable")
        def cancelable(ctx):
            started.set()
            for _ in range(600):
                ctx.check_deadline()
                time.sleep(0.05)
            return {}

        self._setup(live_rt, "live.cancelable")
        live_rt.apply(make_story("cancelme", steps=[
            {"name": "long", "ref": {"name": "worker"}},
        ]))
        run = live_rt.run_story("cancelme")
        assert wait_for(started.is_set, timeout=15)

        def request_cancel(r):
            r.spec["cancelRequested"] = True

        live_rt.store.mutate("StoryRun", "default", run, request_cancel)
        assert wait_for(lambda: live_rt.run_phase(run) == "Finished", timeout=30)
        r = live_rt.store.get("StoryRun", "default", run)
        assert r.status["reason"] == "Canceled"
        # the gang thread observed the cancel (did not run to completion)
        ex = live_rt.job_executor
        assert wait_for(lambda: not ex._cancels, timeout=10)

    def test_parallel_stories_under_load(self, live_rt):
        """Many concurrent runs with fan-out complete without lost
        updates (store conflict retries under a live dispatcher)."""

        @register_engram("live.load")
        def load(ctx):
            return {"step": ctx.step}

        self._setup(live_rt, "live.load")
        live_rt.apply(make_story("fan", steps=[
            {"name": "root", "ref": {"name": "worker"}},
            {"name": "l", "ref": {"name": "worker"}, "needs": ["root"]},
            {"name": "r", "ref": {"name": "worker"}, "needs": ["root"]},
            {"name": "join", "ref": {"name": "worker"}, "needs": ["l", "r"]},
        ]))
        runs = [live_rt.run_story("fan") for _ in range(8)]
        for run in runs:
            assert wait_for(lambda r=run: live_rt.run_phase(r) == "Succeeded"), (
                run, live_rt.run_phase(run),
            )


class TestStoreConflictRetries:
    def test_concurrent_mutates_all_land(self):
        """N threads incrementing one status counter via mutate: the
        optimistic-concurrency retry loop must not lose any update."""
        from bobrapet_tpu.core.object import new_resource

        store = ResourceStore()
        store.create(new_resource("Job", "ctr", "default", spec={}))

        def bump(r):
            r.status["n"] = int(r.status.get("n", 0)) + 1

        def worker():
            for _ in range(25):
                store.mutate("Job", "default", "ctr", bump, status_only=True)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert store.get("Job", "default", "ctr").status["n"] == 8 * 25


# ---------------------------------------------------------------------------
# dehydrate/hydrate fuzz (reference: pkg/storage/manager_fuzz_test.go)
# ---------------------------------------------------------------------------


def _random_value(rng: random.Random, depth: int = 0):
    kinds = ["str", "int", "float", "bool", "none", "bigstr"]
    if depth < 4:
        kinds += ["list", "dict", "dict", "list"]
    kind = rng.choice(kinds)
    if kind == "str":
        return "".join(rng.choices(string.printable, k=rng.randint(0, 40)))
    if kind == "bigstr":
        return rng.choice(string.ascii_letters) * rng.randint(100, 5000)
    if kind == "int":
        return rng.randint(-(2**40), 2**40)
    if kind == "float":
        return rng.uniform(-1e9, 1e9)
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "none":
        return None
    if kind == "list":
        return [_random_value(rng, depth + 1) for _ in range(rng.randint(0, 5))]
    return {
        f"k{i}-{rng.randint(0, 999)}": _random_value(rng, depth + 1)
        for i in range(rng.randint(0, 5))
    }


class TestDehydrateHydrateFuzz:
    @pytest.mark.parametrize("seed", range(40))
    def test_roundtrip(self, seed):
        from bobrapet_tpu.storage import MemoryStore, StorageManager

        rng = random.Random(seed)
        mgr = StorageManager(
            MemoryStore(), max_inline_size=rng.choice([16, 64, 256, 1024])
        )
        value = _random_value(rng)
        prefix = "runs/default/fuzz/steps/s/output"
        out = mgr.dehydrate(value, prefix)
        back = mgr.hydrate(out, allowed_prefixes=["runs/default/fuzz"])
        assert back == value
        # hydrate is idempotent on already-hydrated values
        assert mgr.hydrate(back, allowed_prefixes=["runs/default/fuzz"]) == value

    @pytest.mark.parametrize("seed", range(40, 60))
    def test_roundtrip_through_native_ssd(self, seed, tmp_path):
        from bobrapet_tpu.storage import StorageManager
        from bobrapet_tpu.storage.ssd import SSDStore

        rng = random.Random(seed)
        store = SSDStore(str(tmp_path / "cache"))
        mgr = StorageManager(store, max_inline_size=rng.choice([32, 128, 512]))
        value = _random_value(rng)
        out = mgr.dehydrate(value, "runs/default/fz/steps/s/output")
        back = mgr.hydrate(out, allowed_prefixes=["runs/default/fz"])
        assert back == value
        store.close()


class TestLeaseLeaderElection:
    """TTL lease on the coordination bus (VERDICT r2 #6): renew/steal
    semantics with CAS through the store, flock nowhere in the path."""

    def _electors(self, duration=15.0):
        from bobrapet_tpu.controllers.manager import ManualClock
        from bobrapet_tpu.core.store import ResourceStore
        from bobrapet_tpu.utils.leader import LeaseLeaderElector

        clock = ManualClock()
        store = ResourceStore()
        a = LeaseLeaderElector(store, identity="a", clock=clock,
                               lease_duration=duration)
        b = LeaseLeaderElector(store, identity="b", clock=clock,
                               lease_duration=duration)
        return clock, store, a, b

    def test_standby_takes_over_on_holder_death(self):
        clock, store, a, b = self._electors()
        assert a.try_acquire()
        assert a.is_leader
        # the standby keeps losing while the holder renews
        assert not b.try_acquire()
        clock.advance(10.0)
        assert a.heartbeat()
        clock.advance(10.0)
        assert not b.try_acquire()  # renewTime is fresh
        # holder dies (stops renewing); TTL expires -> standby steals
        clock.advance(16.0)
        assert b.try_acquire()
        assert b.is_leader
        assert b.holder() == "b"
        lease = store.get("Lease", "bobrapet-system", "bobrapet-manager")
        assert lease.spec["leaseTransitions"] == 1
        # the dead holder's next heartbeat observes lost leadership
        assert not a.heartbeat()
        assert not a.is_leader

    def test_release_hands_over_immediately(self):
        clock, store, a, b = self._electors()
        assert a.try_acquire()
        a.release()
        assert not a.is_leader
        # no TTL wait needed after a clean release
        assert b.try_acquire()
        assert b.holder() == "b"

    def test_two_runtimes_failover(self):
        """Two manager replicas on the shared bus: the standby's
        controllers only start after it wins the election."""
        from bobrapet_tpu.controllers.manager import ManualClock
        from bobrapet_tpu.core.store import ResourceStore
        from bobrapet_tpu.utils.leader import LeaseLeaderElector

        clock = ManualClock()
        shared = ResourceStore()  # the coordination bus both point at
        primary = LeaseLeaderElector(shared, identity="replica-1", clock=clock)
        standby = LeaseLeaderElector(shared, identity="replica-2", clock=clock)
        assert primary.try_acquire()
        assert not standby.try_acquire()
        # primary crashes; standby polls until the TTL lapses
        for _ in range(3):
            assert not standby.try_acquire()
            clock.advance(6.0)
        assert standby.try_acquire()  # 18s > 15s TTL
        # the new leader runs a Runtime and the control plane works
        from bobrapet_tpu.runtime import Runtime
        from bobrapet_tpu.sdk import register_engram

        rt = Runtime()
        rt.apply(make_engram_template("lead-tpl", entrypoint="lead-impl"))
        rt.apply(make_engram("lead", "lead-tpl"))

        @register_engram("lead-impl")
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("after-failover",
                            steps=[{"name": "s", "ref": {"name": "lead"}}]))
        run = rt.run_story("after-failover")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"

    def test_kube_lease_elector_against_fake_cluster(self):
        """The reference's mechanism (coordination.k8s.io Lease through
        the API server) over the stdlib client + FakeCluster."""
        from bobrapet_tpu.cluster import FakeCluster
        from bobrapet_tpu.controllers.manager import ManualClock
        from bobrapet_tpu.utils.leader import KubeLeaseElector

        clock = ManualClock()
        cluster = FakeCluster(clock=clock)
        a = KubeLeaseElector(cluster, identity="pod-a", clock=clock)
        b = KubeLeaseElector(cluster, identity="pod-b", clock=clock)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert b.holder() == "pod-a"
        clock.advance(10.0)
        assert a.heartbeat()
        clock.advance(16.0)
        assert b.try_acquire()
        lease = cluster.get("coordination.k8s.io/v1", "Lease",
                            "bobrapet-system", "bobrapet-manager")
        assert lease["spec"]["holderIdentity"] == "pod-b"
        assert lease["spec"]["leaseTransitions"] == 1
        assert not a.heartbeat()


class TestThreadedClusterBackend:
    """Live-mode cluster backend: real clock, dispatcher thread, and the
    FakeKubelet running pods on their own threads — the flat cluster
    event dispatch and bus reflection must hold under real concurrency
    (the race the serialized _dispatching flag fix targets)."""

    @pytest.fixture
    def live_cluster_rt(self):
        rt = Runtime(clock=Clock(), executor_mode="threaded",
                     executor_backend="cluster")
        rt.start()
        yield rt
        rt.stop()

    def test_threaded_cluster_story_end_to_end(self, live_cluster_rt):
        rt = live_cluster_rt
        done = []
        lock = threading.Lock()

        @register_engram("live.cluster.step")
        def step(ctx):
            with lock:
                done.append(ctx.step)
            return {"at": ctx.step}

        rt.apply(make_engram_template("cw-tpl", entrypoint="live.cluster.step"))
        rt.apply(make_engram("cw", "cw-tpl"))
        rt.apply(make_story("live-cluster", steps=[
            {"name": "a", "ref": {"name": "cw"}},
            {"name": "b", "ref": {"name": "cw"}, "needs": ["a"]},
            {"name": "c", "ref": {"name": "cw"}, "needs": ["a"]},
        ]))
        run = rt.run_story("live-cluster")
        assert wait_for(lambda: rt.run_phase(run) == "Succeeded",
                        timeout=30.0), (rt.run_phase(run), done)
        assert sorted(done) == ["a", "b", "c"]
        # the work demonstrably ran as cluster pods
        pods = rt.cluster.list("v1", "Pod", "default")
        assert len(pods) == 3
        assert all(p["status"]["phase"] == "Succeeded" for p in pods)

    def test_threaded_cluster_parallel_fanout(self, live_cluster_rt):
        rt = live_cluster_rt
        seen = []
        lock = threading.Lock()

        @register_engram("live.cluster.fan")
        def fan(ctx):
            with lock:
                seen.append(ctx.inputs.get("shard"))
            return {"shard": ctx.inputs.get("shard")}

        rt.apply(make_engram_template("cf-tpl", entrypoint="live.cluster.fan"))
        rt.apply(make_engram("cf", "cf-tpl"))
        rt.apply(make_story("fan-cluster", steps=[
            {"name": "split", "type": "parallel", "with": {"steps": [
                {"name": f"b{i}", "ref": {"name": "cf"}, "with": {"shard": i}}
                for i in range(6)
            ]}},
        ]))
        run = rt.run_story("fan-cluster")
        assert wait_for(lambda: rt.run_phase(run) == "Succeeded",
                        timeout=30.0), rt.run_phase(run)
        assert sorted(seen) == list(range(6))


class TestSoak:
    """Heavy interleaving: many concurrent stories / streams, checking
    nothing deadlocks, drops, or cross-contaminates."""

    def test_twenty_concurrent_stories_on_threaded_cluster(self):
        rt = Runtime(clock=Clock(), executor_mode="threaded",
                     executor_backend="cluster")
        rt.start()
        try:
            results = {}
            lock = threading.Lock()

            @register_engram("soak.echo")
            def echo(ctx):
                with lock:
                    results[ctx.story_run] = ctx.inputs.get("i")
                return {"i": ctx.inputs.get("i")}

            rt.apply(make_engram_template("soak-tpl", entrypoint="soak.echo"))
            rt.apply(make_engram("soak", "soak-tpl"))
            rt.apply(make_story("soak-story", steps=[
                {"name": "one", "ref": {"name": "soak"},
                 "with": {"i": "{{ inputs.i }}"}},
                {"name": "two", "ref": {"name": "soak"},
                 "with": {"i": "{{ steps.one.output.i }}"}, "needs": ["one"]},
            ], output={"i": "{{ steps.two.output.i }}"}))
            runs = [rt.run_story("soak-story", inputs={"i": i},
                                 name=f"soak-run-{i}")
                    for i in range(20)]
            # 120s: ~3s standalone, but late in a full tier-1 run on a
            # 2-core box a straggler can brush a 60s cutoff (observed
            # once with every printed phase already Succeeded)
            assert wait_for(
                lambda: all(rt.run_phase(r) == "Succeeded" for r in runs),
                timeout=120.0,
            ), [rt.run_phase(r) for r in runs]
            for i, r in enumerate(runs):
                assert rt.run_output(r) == {"i": i}  # no cross-talk
            # the engram-side record agrees: each run saw only its input
            assert {results[r] for r in runs if r in results} == set(range(20))
            # every pod retired cleanly on the fake cluster
            pods = rt.cluster.list("v1", "Pod", "default")
            assert len(pods) == 40
            assert all(p["status"]["phase"] == "Succeeded" for p in pods)
        finally:
            rt.stop()

    def test_native_hub_many_concurrent_streams(self):
        """16 independent credit-controlled streams through ONE native
        hub event loop: per-stream ordering and completeness hold."""
        pytest.importorskip("ctypes")
        from bobrapet_tpu.dataplane import StreamConsumer, StreamProducer
        from bobrapet_tpu.dataplane.native import make_hub

        hub = make_hub()
        hub.start()
        try:
            settings = {
                "flowControl": {"mode": "credits",
                                "initialCredits": {"messages": 8},
                                "ackEvery": {"messages": 1}},
                "backpressure": {"buffer": {"maxMessages": 16}},
            }
            n_streams, n_msgs = 16, 100
            received = {s: [] for s in range(n_streams)}
            done = [threading.Event() for _ in range(n_streams)]

            def drain(s):
                c = StreamConsumer(hub.endpoint, f"soak/r/s{s}",
                                   settings=settings, decode_json=True)
                for m in c:
                    received[s].append(m["i"])
                done[s].set()

            for s in range(n_streams):
                threading.Thread(target=drain, args=(s,), daemon=True).start()

            def produce(s):
                p = StreamProducer(hub.endpoint, f"soak/r/s{s}",
                                   settings=settings)
                for i in range(n_msgs):
                    p.send({"i": i}, timeout=30.0)
                p.close()

            producers = [threading.Thread(target=produce, args=(s,),
                                          daemon=True)
                         for s in range(n_streams)]
            for t in producers:
                t.start()
            for t in producers:
                t.join(60)
                assert not t.is_alive()
            for s in range(n_streams):
                assert done[s].wait(30), s
                assert received[s] == list(range(n_msgs)), s
        finally:
            hub.stop()
