"""Fleet health & preemption-recovery subsystem: unit layer.

Covers the health registry (suspicion scoring, decay, quarantine
escalation), cordon-aware SlicePool allocation (exclusion +
fragmentation + NoCapacity-not-misshape), PREEMPTED exit
classification, the fleet.* config family, the checkpoint-resume env
contract, retry-delay determinism satellites, and the webhook cert
fallback-dir hardening.
"""

from __future__ import annotations

import os
import stat

import pytest

from bobrapet_tpu.api.enums import BackoffStrategy, ExitClass
from bobrapet_tpu.api.shared import RetryPolicy
from bobrapet_tpu.config.operator import FleetConfig, parse_config
from bobrapet_tpu.controllers.manager import ManualClock
from bobrapet_tpu.controllers.retry import classify_exit_code, compute_retry_delay
from bobrapet_tpu.fleet import FleetHealthRegistry, grant_cells, host_cells
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.parallel.placement import NoCapacity, SlicePool, parse_topology


def _registry(clock, **overrides):
    cfg = FleetConfig(**overrides)
    return FleetHealthRegistry(config=lambda: cfg, clock=clock)


class TestHealthRegistry:
    def test_preemption_quarantines_immediately(self):
        clock = ManualClock()
        reg = _registry(clock, quarantine_seconds=100.0)
        reg.report_preemption("p", [(0, 0), (0, 1)], key="e1")
        assert reg.is_quarantined("p", (0, 0))
        assert reg.quarantined_cells("p") == {(0, 0), (0, 1)}
        assert metrics.fleet_quarantined_cells.value("p") == 2

    def test_event_key_dedupes_across_reporters(self):
        clock = ManualClock()
        reg = _registry(clock)
        assert reg.report_preemption("p", [(0, 0)], key="job-1")
        assert not reg.report_preemption("p", [(0, 0)], key="job-1")
        assert metrics.fleet_preemptions.value("p") == 1

    def test_quarantine_decays_out(self):
        clock = ManualClock()
        reg = _registry(clock, quarantine_seconds=50.0)
        reg.report_preemption("p", [(1, 1)], key="e")
        clock.advance(51.0)
        assert not reg.is_quarantined("p", (1, 1))
        assert reg.quarantined_cells("p") == set()
        assert metrics.fleet_quarantined_cells.value("p") == 0

    def test_repeat_offender_quarantine_escalates(self):
        clock = ManualClock()
        reg = _registry(clock, quarantine_seconds=50.0,
                        max_quarantine_multiplier=8.0)
        reg.report_preemption("p", [(2, 2)], key="a")  # strike 1: 50s
        clock.advance(51.0)
        assert not reg.is_quarantined("p", (2, 2))
        reg.report_preemption("p", [(2, 2)], key="b")  # strike 2: 100s
        clock.advance(51.0)
        assert reg.is_quarantined("p", (2, 2))
        clock.advance(50.0)
        assert not reg.is_quarantined("p", (2, 2))

    def test_suspicion_accumulates_to_threshold(self):
        clock = ManualClock()
        reg = _registry(clock, suspicion_threshold=2.0,
                        suspicion_half_life_seconds=1000.0)
        reg.report_suspect("p", [(3, 3)], weight=1.0)
        assert not reg.is_quarantined("p", (3, 3))
        reg.report_suspect("p", [(3, 3)], weight=1.0)
        assert reg.is_quarantined("p", (3, 3))

    def test_suspicion_decays_below_threshold(self):
        clock = ManualClock()
        reg = _registry(clock, suspicion_threshold=2.0,
                        suspicion_half_life_seconds=10.0)
        reg.report_suspect("p", [(4, 4)], weight=1.5)
        clock.advance(20.0)  # two half-lives: 1.5 -> 0.375
        assert reg.suspicion("p", (4, 4)) == pytest.approx(0.375)
        reg.report_suspect("p", [(4, 4)], weight=1.0)
        assert not reg.is_quarantined("p", (4, 4))

    def test_healthy_report_never_shortens_quarantine(self):
        clock = ManualClock()
        reg = _registry(clock, quarantine_seconds=100.0)
        reg.report_preemption("p", [(5, 5)], key="e")
        reg.report_healthy("p", [(5, 5)])
        assert reg.is_quarantined("p", (5, 5))


class TestGrantCellMapping:
    GRANT = {"topology": "2x4", "origin": [1, 0], "hosts": 2, "pool": "p"}

    def test_grant_cells_cover_block(self):
        cells = grant_cells(self.GRANT)
        assert len(cells) == 8
        assert cells[0] == (1, 0) and cells[-1] == (2, 3)

    def test_host_cells_partition_block(self):
        h0 = host_cells(self.GRANT, 0)
        h1 = host_cells(self.GRANT, 1)
        assert len(h0) == len(h1) == 4
        assert not set(h0) & set(h1)
        assert set(h0) | set(h1) == set(grant_cells(self.GRANT))

    def test_unknown_host_means_whole_block(self):
        assert host_cells(self.GRANT, None) == grant_cells(self.GRANT)


class TestCordonAwarePool:
    def test_cordoned_cells_excluded_from_grants(self):
        pool = SlicePool("p", "2x2")
        pool.set_cordoned({(0, 0)})
        with pytest.raises(NoCapacity):
            pool.allocate(want_topology="2x2")
        # a block that avoids the cordon still fits
        g = pool.allocate(want_topology="1x2")
        assert tuple(g.origin) == (1, 0)

    def test_grant_around_quarantine_stays_contiguous_and_shaped(self):
        """Exclusion must never produce a mis-shaped or fragmented
        grant: what comes back is exactly the requested block, placed
        on non-cordoned cells."""
        pool = SlicePool("p", "4x4", chips_per_host=2)
        pool.set_cordoned({(1, 1), (1, 2)})  # hole in the middle
        g = pool.allocate(want_topology="2x4")
        assert parse_topology(g.topology) == (2, 4)
        cells = {
            (g.origin[0] + i, g.origin[1] + j)
            for i in range(2) for j in range(4)
        }
        assert not cells & {(1, 1), (1, 2)}
        assert len(cells) == 8

    def test_fragmented_free_capacity_raises_no_capacity(self):
        """Free chips exist but no contiguous block: NoCapacity, never
        a smaller/mis-shaped grant."""
        pool = SlicePool("p", "4x1")
        pool.set_cordoned({(1, 0), (3, 0)})  # free cells 0 and 2, split
        assert pool.schedulable_chips() == 2
        with pytest.raises(NoCapacity):
            pool.allocate(want_topology="2x1")
        g = pool.allocate(want_topology="1x1")  # single cells still fit
        assert parse_topology(g.topology) == (1, 1)

    def test_cordon_release_and_resync(self):
        pool = SlicePool("p", "2x2")
        pool.set_cordoned({(0, 0), (0, 1), (1, 0), (1, 1)})
        with pytest.raises(NoCapacity):
            pool.allocate(want_topology="1x1")
        pool.set_cordoned(set())  # quarantine decayed -> full sync drops it
        g = pool.allocate(want_topology="2x2")
        assert g.hosts >= 1

    def test_release_still_works_for_cordoned_grant_cells(self):
        pool = SlicePool("p", "2x2")
        g = pool.allocate(want_topology="2x2")
        pool.set_cordoned({(0, 0)})  # cordon lands under a live grant
        pool.release(g.slice_id)
        assert pool.free_chips() == 4
        assert pool.schedulable_chips() == 3


class TestPreemptedClassification:
    def test_sigterm_with_node_condition_is_preempted(self):
        assert classify_exit_code(143, preempted=True) is ExitClass.PREEMPTED
        assert classify_exit_code(137, preempted=True) is ExitClass.PREEMPTED

    def test_any_nonzero_death_on_reclaimed_node_is_preempted(self):
        assert classify_exit_code(1, preempted=True) is ExitClass.PREEMPTED
        assert classify_exit_code(124, preempted=True) is ExitClass.PREEMPTED

    def test_success_and_unknown_win_over_the_flag(self):
        assert classify_exit_code(0, preempted=True) is ExitClass.SUCCESS
        assert classify_exit_code(None, preempted=True) is ExitClass.UNKNOWN

    def test_without_flag_sigterm_stays_plain_retry(self):
        assert classify_exit_code(143) is ExitClass.RETRY

    def test_preempted_class_budget_semantics(self):
        assert ExitClass.PREEMPTED.is_retryable
        assert not ExitClass.PREEMPTED.consumes_retry_budget


class TestFleetConfig:
    def test_dotted_keys_parse(self):
        cfg = parse_config({
            "fleet.preemption-retry-cap": "7",
            "fleet.redrive-delay": "2s",
            "fleet.quarantine": "10m",
            "fleet.suspicion-threshold": "3.5",
            "fleet.suspicion-half-life": "5m",
            "fleet.heartbeat-timeout": "90s",
            "fleet.fail-fast": "false",
            "fleet.max-quarantine-multiplier": "4",
        })
        f = cfg.fleet
        assert f.preemption_retry_cap == 7
        assert f.redrive_delay_seconds == 2.0
        assert f.quarantine_seconds == 600.0
        assert f.suspicion_threshold == 3.5
        assert f.suspicion_half_life_seconds == 300.0
        assert f.heartbeat_timeout_seconds == 90.0
        assert f.fail_fast is False
        assert f.max_quarantine_multiplier == 4.0

    def test_invalid_values_keep_defaults(self):
        cfg = parse_config({"fleet.preemption-retry-cap": "banana"})
        assert cfg.fleet.preemption_retry_cap == FleetConfig().preemption_retry_cap

    def test_validation_rejects_bad_tree(self):
        cfg = FleetConfig(preemption_retry_cap=-1)
        from bobrapet_tpu.config.operator import OperatorConfig

        errs = OperatorConfig(fleet=cfg).validate()
        assert any("fleet.preemption-retry-cap" in e for e in errs)

    def test_live_reload_through_configmap(self, rt):
        """fleet.* keys reload like controllers.*/dataplane.* — via the
        operator ConfigMap resource, no restart."""
        from bobrapet_tpu.core.object import new_resource

        assert rt.config_manager.config.fleet.preemption_retry_cap == 5
        rt.store.create(new_resource(
            "ConfigMap", "operator-config", "bobrapet-system",
            {"data": {"fleet.preemption-retry-cap": "2",
                      "fleet.quarantine": "42s"}},
        ))
        assert rt.config_manager.config.fleet.preemption_retry_cap == 2
        assert rt.config_manager.config.fleet.quarantine_seconds == 42.0
        # the fleet manager reads the same live tree
        assert rt.fleet.cfg.preemption_retry_cap == 2


class TestGKEFleetWiring:
    def test_materializer_honors_fleet_knobs(self):
        from bobrapet_tpu.gke import GKEMaterializer

        cfg = FleetConfig(gke_spot=True, termination_grace_seconds=45.0)
        m = GKEMaterializer.from_fleet_config(cfg)
        assert m.spot is True
        assert m.termination_grace_seconds == 45
        off = GKEMaterializer.from_fleet_config(
            FleetConfig(termination_grace_seconds=0.0)
        )
        assert off.termination_grace_seconds is None

    def test_spot_and_grace_keys_parse(self):
        cfg = parse_config({"fleet.gke-spot": "true",
                            "fleet.termination-grace": "90s"})
        assert cfg.fleet.gke_spot is True
        assert cfg.fleet.termination_grace_seconds == 90.0

    def test_gang_manifest_carries_spot_and_grace(self):
        from bobrapet_tpu.gke import GKEMaterializer
        from bobrapet_tpu.controllers.jobs import make_job

        job = make_job(
            "j1", "default", "sr1", entrypoint="e", env={}, hosts=2,
            slice_grant={"sliceId": "p-s1", "pool": "p", "topology": "2x2",
                         "hosts": 2, "origin": [0, 0], "meshAxes": {}},
        )
        m = GKEMaterializer.from_fleet_config(
            FleetConfig(gke_spot=True, termination_grace_seconds=45.0)
        )
        k8s_job = [x for x in m.materialize_job(job) if x["kind"] == "Job"][0]
        pod = k8s_job["spec"]["template"]["spec"]
        assert pod["terminationGracePeriodSeconds"] == 45
        assert pod["nodeSelector"]["cloud.google.com/gke-spot"] == "true"
        assert any(t["key"] == "cloud.google.com/gke-spot"
                   for t in pod["tolerations"])


class TestResumeEnvContract:
    def test_resume_fields_render(self):
        from bobrapet_tpu.sdk import contract

        env = contract.build_env(
            namespace="ns", story="s", story_run="r", step="fit",
            step_run="sr", checkpoint_prefix="runs/ns/r/steps/fit/model-ckpt",
            resume_step=12, preemption_attempt=2,
        )
        assert env[contract.ENV_CHECKPOINT_PREFIX] == "runs/ns/r/steps/fit/model-ckpt"
        assert env[contract.ENV_RESUME_STEP] == "12"
        assert env[contract.ENV_PREEMPTION_ATTEMPT] == "2"

    def test_fresh_launch_omits_resume(self):
        from bobrapet_tpu.sdk import contract

        env = contract.build_env(
            namespace="ns", story="s", story_run="r", step="fit",
            step_run="sr", checkpoint_prefix="p",
        )
        assert contract.ENV_RESUME_STEP not in env
        assert contract.ENV_PREEMPTION_ATTEMPT not in env

    def test_context_reads_resume_fields(self):
        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext

        ctx = EngramContext({
            contract.ENV_CHECKPOINT_PREFIX: "explicit/prefix",
            contract.ENV_RESUME_STEP: "7",
            contract.ENV_PREEMPTION_ATTEMPT: "1",
        })
        assert ctx.checkpoint_prefix == "explicit/prefix"
        assert ctx.resume_step == 7
        assert ctx.preemption_attempt == 1

    def test_context_prefix_defaults_to_canonical(self):
        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext

        ctx = EngramContext({
            contract.ENV_NAMESPACE: "ns",
            contract.ENV_STORY_RUN: "r",
            contract.ENV_STEP: "fit",
        })
        assert ctx.checkpoint_prefix == "runs/ns/r/steps/fit/model-ckpt"
        assert ctx.resume_step is None


class TestRetryDelaySatellites:
    """ISSUE 3 satellite: compute_retry_delay was only exercised
    indirectly — pin down seeded-jitter determinism and the backoff-cap
    boundary."""

    def test_seeded_jitter_is_deterministic(self):
        import random

        policy = RetryPolicy(delay="10s", max_delay="300s", jitter=20,
                             backoff=BackoffStrategy.EXPONENTIAL)
        a = compute_retry_delay(policy, attempt=3, rng=random.Random(42))
        b = compute_retry_delay(policy, attempt=3, rng=random.Random(42))
        c = compute_retry_delay(policy, attempt=3, rng=random.Random(43))
        assert a == b
        assert a != c  # different seed actually moves the draw

    def test_jitter_stays_within_pct_band(self):
        import random

        policy = RetryPolicy(delay="10s", max_delay="1000s", jitter=25)
        base = 10.0 * 2 ** 2  # attempt 3 exponential
        for seed in range(50):
            d = compute_retry_delay(policy, attempt=3, rng=random.Random(seed))
            assert base * 0.75 <= d <= base * 1.25

    def test_cap_boundary_exact_hit(self):
        # exponential 5 * 2^5 = 160 == max_delay: no clamping distortion
        policy = RetryPolicy(delay="5s", max_delay="160s", jitter=0)
        assert compute_retry_delay(policy, attempt=6) == 160.0
        # one attempt later the cap clamps
        assert compute_retry_delay(policy, attempt=7) == 160.0

    def test_cap_applies_before_jitter(self):
        """Jitter is applied to the capped delay, so a +pct draw can
        exceed max_delay by at most the jitter band — never by the
        uncapped exponential."""
        import random

        policy = RetryPolicy(delay="100s", max_delay="100s", jitter=10)
        for seed in range(20):
            d = compute_retry_delay(policy, attempt=10, rng=random.Random(seed))
            assert 90.0 <= d <= 110.0

    def test_linear_and_constant_strategies(self):
        lin = RetryPolicy(delay="7s", max_delay="300s", jitter=0,
                          backoff=BackoffStrategy.LINEAR)
        assert compute_retry_delay(lin, attempt=4) == 28.0
        const = RetryPolicy(delay="7s", max_delay="300s", jitter=0,
                            backoff=BackoffStrategy.CONSTANT)
        assert compute_retry_delay(const, attempt=4) == 7.0

    def test_rate_limited_floor(self):
        policy = RetryPolicy(delay="1s", max_delay="300s", jitter=0)
        assert compute_retry_delay(policy, attempt=1, rate_limited=True) == 30.0

    def test_zero_jitter_no_rng_needed(self):
        policy = RetryPolicy(delay="5s", max_delay="300s", jitter=0)
        assert compute_retry_delay(policy, attempt=1) == 5.0


class TestSecureCertFallbackDir:
    """ISSUE 3 satellite (advisor r5): the webhook cert fallback dir
    must be per-user 0700, never a predictable world-accessible path."""

    def test_creates_per_user_0700_dir(self, tmp_path):
        from bobrapet_tpu.cluster.certs import secure_fallback_cert_dir

        path = secure_fallback_cert_dir(base=str(tmp_path))
        assert os.path.isdir(path)
        assert str(os.getuid()) in os.path.basename(path)
        assert stat.S_IMODE(os.lstat(path).st_mode) == 0o700

    def test_world_writable_dir_drops_key_material(self, tmp_path):
        from bobrapet_tpu.cluster.certs import secure_fallback_cert_dir

        uid = os.getuid()
        loose = tmp_path / f"bobrapet-webhook-certs-{uid}"
        loose.mkdir(mode=0o777)
        os.chmod(loose, 0o777)  # mkdir is umask-filtered; force it
        (loose / "tls.key").write_text("PLANTED")
        (loose / "ca.key").write_text("PLANTED")
        (loose / "tls.crt").write_text("cert stays")
        path = secure_fallback_cert_dir(base=str(tmp_path))
        assert path == str(loose)
        assert not os.path.exists(loose / "tls.key")
        assert not os.path.exists(loose / "ca.key")
        assert os.path.exists(loose / "tls.crt")
        assert stat.S_IMODE(os.lstat(path).st_mode) == 0o700

    def test_symlink_fallback_refused(self, tmp_path):
        from bobrapet_tpu.cluster.certs import CertError, secure_fallback_cert_dir

        uid = os.getuid()
        real = tmp_path / "elsewhere"
        real.mkdir()
        os.symlink(real, tmp_path / f"bobrapet-webhook-certs-{uid}")
        with pytest.raises(CertError):
            secure_fallback_cert_dir(base=str(tmp_path))

    def test_private_dir_reused_untouched(self, tmp_path):
        from bobrapet_tpu.cluster.certs import secure_fallback_cert_dir

        first = secure_fallback_cert_dir(base=str(tmp_path))
        with open(os.path.join(first, "tls.key"), "w") as f:
            f.write("mine")
        second = secure_fallback_cert_dir(base=str(tmp_path))
        assert first == second
        with open(os.path.join(first, "tls.key")) as f:
            assert f.read() == "mine"
