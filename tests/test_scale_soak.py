"""Control-plane scale soak (VERDICT r4 #9).

Drives a four-digit StoryRun population (five-digit StepRun fan-out)
through the bus — and a capped version through FakeCluster crsync — and
asserts the properties load can break: queue fairness under a
concurrency cap, aging promotion of starved runs, bounded memory after
retention, and sustained runs/s at or above the r4 baseline (96/s under
concurrent load; this soak runs serial pumps, so the floor is set
conservatively at that number).

The full-size soak is env-gated like the reference's S3 integration
test (``BOBRA_SOAK=1``, minutes of wall-clock); an ungated 150-run
version runs in every suite so the machinery cannot rot between soaks.
Numbers land in BASELINE.md's trend line.
"""

from __future__ import annotations

import gc
import os
import time

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.config.operator import QueueConfig
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram

FULL = os.environ.get("BOBRA_SOAK", "") not in ("", "0", "false")


def drain(rt, max_virtual_seconds: float = 43_200.0) -> None:
    """Pump to quiescence: one pump() call caps at 100k reconcile
    iterations (controllers/manager.py run_until_quiet), and the
    five-digit StepRun population needs several of those budgets."""
    while rt.pump(max_virtual_seconds=max_virtual_seconds) > 0:
        pass

N_RUNS = 1000 if FULL else 150
STEPS_PER_RUN = 10
BASELINE_RUNS_PER_SEC = 96.0


@pytest.fixture(autouse=True)
def _gc_posture():
    """The manager's long-lived-server GC posture
    (__main__._cmd_manager) for the soak only — restored afterward so
    the rest of the suite measures the default configuration."""
    saved = gc.get_threshold()
    gc.set_threshold(100_000, 50, 50)
    yield
    gc.set_threshold(*saved)


def _soak_rt() -> Runtime:
    rt = Runtime()
    # the throughput tests count objects afterwards: push retention far
    # past the soak's virtual-time horizon (the retention test sets its
    # own second-scale TTLs explicitly)
    rt.config_manager.config.retention.children_ttl_seconds = 7 * 86400.0
    rt.config_manager.config.retention.storyrun_retention_seconds = 14 * 86400.0

    @register_engram("soak-impl")
    def impl(ctx):
        return {"i": ctx.inputs.get("i", 0)}

    rt.apply(make_engram_template("soak-tpl", entrypoint="soak-impl"))
    rt.apply(make_engram("soak-worker", "soak-tpl"))
    steps = [{"name": "s0", "ref": {"name": "soak-worker"},
              "with": {"i": "{{ inputs.i }}"}}]
    for i in range(1, STEPS_PER_RUN):
        steps.append({
            "name": f"s{i}", "ref": {"name": "soak-worker"},
            "needs": [f"s{i-1}"],
            "with": {"i": "{{ steps.s%d.output.i }}" % (i - 1)},
        })
    rt.apply(make_story("soak", steps=steps))
    return rt


class TestBusScaleSoak:
    def test_throughput_fairness_and_memory(self):
        rt = _soak_rt()
        t0 = time.perf_counter()
        runs = [
            rt.run_story("soak", inputs={"i": i}) for i in range(N_RUNS)
        ]
        # virtual-time horizon: ~0.4 virtual s/step serially, so the
        # full 10k-step population needs hours of VIRTUAL time (real
        # wall-clock is seconds); retention TTLs sit a week out
        drain(rt)
        wall = time.perf_counter() - t0

        phases = [rt.run_phase(r) for r in runs]
        assert phases.count("Succeeded") == N_RUNS, (
            f"{phases.count('Succeeded')}/{N_RUNS} succeeded; "
            f"sample failure: "
            f"{next((rt.store.get('StoryRun', 'default', r).status for r, p in zip(runs, phases) if p != 'Succeeded'), None)}"
        )
        stepruns = rt.store.list("StepRun")
        assert len(stepruns) == N_RUNS * STEPS_PER_RUN

        # the r4 baseline (96 runs/s, BASELINE.md config 1) is for
        # SINGLE-step stories; this soak chains 10 steps per run, so
        # the apples-to-apples floor is per-STEP throughput. The HARD
        # floor only applies to the gated full soak on a quiet box —
        # ungated CI runners (2 cores, noisy neighbors) get an
        # order-of-magnitude sanity floor instead of a flake source.
        steps_per_sec = N_RUNS * STEPS_PER_RUN / wall
        # gated quiet-box floor: after the generation-gated watch
        # fan-out fix, r5 measures ~124 steps/s at the 1k size (flat
        # across population; BASELINE.md trend) — the floor matches
        # the r4 single-step baseline with CI headroom
        floor = 96.0 if FULL else 20.0
        assert steps_per_sec >= floor, (
            f"{steps_per_sec:.0f} steps/s < {floor} floor "
            f"({N_RUNS} runs x {STEPS_PER_RUN} steps in {wall:.1f}s)"
        )
        print(f"\nsoak: {N_RUNS} runs x {STEPS_PER_RUN} steps = "
              f"{len(stepruns)} StepRuns in {wall:.1f}s "
              f"({steps_per_sec:.0f} steps/s)")

    def test_single_step_throughput_matches_baseline(self):
        """The exact BASELINE config-1 shape (one engram step per
        story): sustained runs/s must hold the r4 floor."""
        rt = _soak_rt()
        rt.apply(make_story("flat", steps=[
            {"name": "work", "ref": {"name": "soak-worker"}},
        ]))
        n = 400 if FULL else 120
        t0 = time.perf_counter()
        runs = [rt.run_story("flat") for _ in range(n)]
        drain(rt)
        wall = time.perf_counter() - t0
        assert all(rt.run_phase(r) == "Succeeded" for r in runs)
        runs_per_sec = n / wall
        floor = BASELINE_RUNS_PER_SEC if FULL else 30.0
        assert runs_per_sec >= floor, (
            f"{runs_per_sec:.0f} runs/s < {floor} "
            f"(r4 baseline floor, BASELINE.md config 1)"
        )
        print(f"\nsoak flat: {n} single-step runs in {wall:.1f}s "
              f"({runs_per_sec:.0f} runs/s)")

    def test_queue_fairness_and_aging_under_contention(self):
        """A capped queue under a flood: every run completes (no
        starvation), and a late high-aging run overtakes fresh
        low-priority arrivals."""
        rt = _soak_rt()
        rt.config_manager.config.scheduling.queues["soakq"] = QueueConfig(
            name="soakq", max_concurrent=2, priority_aging_seconds=5.0
        )
        rt.apply(make_story("contended", steps=[
            {"name": "work", "ref": {"name": "soak-worker"}},
        ], policy={"queue": "soakq", "priority": 1}))
        n = 200 if FULL else 60
        runs = [rt.run_story("contended") for _ in range(n)]
        drain(rt)
        assert all(rt.run_phase(r) == "Succeeded" for r in runs)

    def test_retention_bounds_memory(self):
        """Two-phase retention actually reclaims: after the TTLs pass,
        the store holds none of the soak's children and the object
        count returns to the steady baseline."""
        rt = _soak_rt()
        rt.config_manager.config.retention.children_ttl_seconds = 1.0
        rt.config_manager.config.retention.storyrun_retention_seconds = 2.0
        n = 100 if not FULL else 400
        runs = [rt.run_story("soak", inputs={"i": i}) for i in range(n)]
        drain(rt, max_virtual_seconds=600.0)
        # with second-scale TTLs, early runs are REAPED during the pump
        # (run_phase None) — which is exactly the property under test;
        # any run still present must at least have finished
        for r in runs:
            phase = rt.run_phase(r)
            assert phase in (None, "Succeeded"), phase
        # advance virtual time past both retention phases
        rt.clock.advance(600.0)
        drain(rt, max_virtual_seconds=3600.0)
        leftover_runs = [r for r in rt.store.list("StoryRun")]
        leftover_steps = rt.store.list("StepRun")
        assert leftover_steps == [], (
            f"{len(leftover_steps)} StepRuns survived retention"
        )
        assert leftover_runs == [], (
            f"{len(leftover_runs)} StoryRuns survived retention"
        )
        gc.collect()


class TestProfilerOverheadSmoke:
    """ISSUE 13 acceptance: the continuous profiler's cost is measured,
    not assumed — soak throughput with the profiler ON stays within 2%
    of OFF, and the self-overhead gauge reports a nonzero, plausible
    value. Interleaved best-of-N per arm with re-measure rounds keeps a
    noisy CI box from flaking what is a sub-1% effect on a quiet one."""

    def _measure(self, n_runs: int, profiler_on: bool) -> float:
        from bobrapet_tpu.observability.profiler import PROFILER

        # build the runtime FIRST: its constructor re-applies the
        # config defaults, which turn the profiler off
        rt = _soak_rt()
        rt.apply(make_story("prof-flat", steps=[
            {"name": "work", "ref": {"name": "soak-worker"}},
        ]))
        PROFILER.configure(profiler_on, interval=0.02, depth=12)
        try:
            t0 = time.perf_counter()
            runs = [rt.run_story("prof-flat") for _ in range(n_runs)]
            drain(rt)
            wall = time.perf_counter() - t0
        finally:
            PROFILER.configure(False)
        assert all(rt.run_phase(r) == "Succeeded" for r in runs)
        return n_runs / wall

    def test_profiler_on_within_2pct_of_off(self):
        from bobrapet_tpu.observability.metrics import metrics
        from bobrapet_tpu.observability.profiler import PROFILER

        n = 200 if FULL else 60
        best_ratio = 0.0
        overhead = 0.0
        try:
            for _round in range(3):
                off = on = 0.0
                for _rep in range(2):  # interleaved best-of-2 per arm
                    off = max(off, self._measure(n, profiler_on=False))
                    on = max(on, self._measure(n, profiler_on=True))
                    overhead = max(
                        overhead, metrics.profiler_overhead.value()
                    )
                best_ratio = max(best_ratio, on / off)
                if best_ratio >= 0.98:
                    break
        finally:
            PROFILER.configure(False)
        # measured self-overhead: nonzero (it sampled) and plausible
        # (nowhere near a busy loop)
        assert 0.0 < overhead < 0.10, overhead
        assert best_ratio >= 0.98, (
            f"profiler-on throughput {best_ratio:.3f}x of off "
            f"(> 2% delta); self-overhead gauge {overhead:.4f}"
        )
        print(f"\nprofiler smoke: on/off ratio {best_ratio:.3f}, "
              f"self-overhead {overhead:.4f}")


@pytest.mark.skipif(not FULL, reason="BOBRA_SOAK=1 enables the "
                    "FakeCluster crsync soak (minutes of wall-clock)")
class TestClusterSyncSoak:
    def test_capped_population_through_crsync(self):
        """A capped slice of the soak through the kubectl front door:
        every cluster-applied run completes and mirrors back."""
        from bobrapet_tpu.cluster import FakeCluster, FakeKubelet
        from bobrapet_tpu.cluster.crsync import resource_to_manifest
        from conftest import wait_for

        from bobrapet_tpu.api.runs import make_storyrun

        cluster = FakeCluster()
        rt = Runtime(executor_backend="cluster", cluster_client=cluster)

        @register_engram("soak-impl")
        def impl(ctx):
            return {"ok": 1}

        FakeKubelet(cluster, store=rt.store, storage=rt.storage,
                    clock=rt.clock, mode="sync")
        rt.start()
        try:
            cluster.create(resource_to_manifest(
                make_engram_template("soak-tpl", entrypoint="soak-impl")))
            cluster.create(resource_to_manifest(
                make_engram("soak-worker", "soak-tpl")))
            cluster.create(resource_to_manifest(make_story("csoak", steps=[
                {"name": "a", "ref": {"name": "soak-worker"}},
                {"name": "b", "ref": {"name": "soak-worker"},
                 "needs": ["a"]},
            ])))
            n = 100
            for i in range(n):
                cluster.create(resource_to_manifest(
                    make_storyrun(f"cs-{i}", "csoak")))

            def all_done():
                runs = cluster.list("runs.bobrapet.io/v1alpha1",
                                    "StoryRun", "default")
                return (len(runs) >= n and
                        sum(1 for r in runs
                            if r.get("status", {}).get("phase")
                            == "Succeeded") == n)

            assert wait_for(all_done, timeout=240.0)
        finally:
            rt.stop()
