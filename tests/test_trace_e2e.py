"""ISSUE 8 acceptance: ONE stitched trace across the whole run.

A story with a parallel TPU fan-out, an executeStory handoff, and a
realtime serving step must yield a single queryable trace — admission
-> DAG scheduling -> gang placement -> Job dispatch -> SDK execution
-> serving first token — with every span sharing the StoryRun's
traceId across the process-boundary stitch (status-persisted context
riding the env contract) and the executeStory handoff edge, plus
TTFT/TPOT histograms populated and visible in ``REGISTRY.expose()``.
"""

from __future__ import annotations

import json

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.observability import REGISTRY
from bobrapet_tpu.observability.tracing import (
    InMemorySpanExporter,
    Tracer,
    TracingConfig,
)
from bobrapet_tpu.parallel.placement import SlicePool
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram


class TestStitchedTrace:
    def test_one_trace_admission_to_first_token(self, monkeypatch):
        from bobrapet_tpu.observability import tracing as tracing_mod

        exporter = InMemorySpanExporter()
        tracer = Tracer(TracingConfig(enabled=True), exporter=exporter)
        # controllers/SDK/engine resolve the module TRACER at call time
        monkeypatch.setattr(tracing_mod, "TRACER", tracer)
        rt = Runtime(tracer=tracer)
        rt.placer.add_pool(SlicePool("trace-pool", "4x4", chips_per_host=4))

        @register_engram("trace-e2e-worker")
        def impl(ctx):  # noqa: ARG001
            return {"ok": True}

        rt.apply(make_engram_template("te-w-tpl", entrypoint="trace-e2e-worker"))
        rt.apply(make_engram("te-worker", "te-w-tpl"))
        # realtime serving step: deployment-mode engram (the WorkloadSim
        # plays kubelet; the model server itself is driven below through
        # the same env contract the deployment would receive)
        rt.apply(make_engram_template(
            "te-s-tpl", image="serve:1",
            entrypoint="bobrapet_tpu.serving.engram:serve",
            supportedModes=["deployment"],
        ))
        rt.apply(make_engram("te-server", "te-s-tpl"))
        rt.apply(make_story("te-sub", steps=[
            {"name": "inner", "ref": {"name": "te-worker"}},
        ]))
        rt.apply(make_story("te-main", steps=[
            {"name": "fan", "type": "parallel", "with": {"steps": [
                {"name": "b1", "ref": {"name": "te-worker"},
                 "tpu": {"topology": "2x2"}},
                {"name": "b2", "ref": {"name": "te-worker"},
                 "tpu": {"topology": "2x2"}},
            ]}},
            {"name": "sub", "type": "executeStory", "needs": ["fan"],
             "with": {"storyRef": {"name": "te-sub"}}},
            {"name": "generate", "ref": {"name": "te-server"},
             "needs": ["fan", "sub"]},
        ], policy={"queue": "trace-pool"}))

        run = rt.run_story("te-main", inputs={})
        rt.pump()

        srun = rt.store.get("StoryRun", "default", run)
        # the serving topology stays live; everything batch is done
        assert srun.status["phase"] == "Running"
        trace = srun.status["trace"]
        tid = trace["traceId"]

        # --- executeStory handoff: the child run RESUMES the trace ----
        children = [
            r for r in rt.store.list("StoryRun", "default")
            if r.meta.labels.get("bobrapet.io/story-run") == run
        ]
        assert children, "sub-story child run missing"
        assert children[0].status["trace"]["traceId"] == tid

        # --- realtime step: trace persisted + carried on the env ------
        gen_sr = next(
            sr for sr in rt.store.list("StepRun", "default")
            if sr.spec.get("stepId") == "generate"
        )
        assert gen_sr.status["phase"] == "Running"
        assert gen_sr.status["trace"]["traceId"] == tid
        dep = next(
            d for d in rt.store.list("Deployment", "default")
            if d.meta.labels.get("bobrapet.io/step-run") == gen_sr.meta.name
        )
        tc = json.loads(dep.spec["env"]["BOBRA_TRACEPARENT"])
        assert tc["traceId"] == tid

        # --- serving side: drive the engine exactly as the deployment's
        # worker would (env-contract trace context), to first token ----
        import jax

        from bobrapet_tpu.models import llama
        from bobrapet_tpu.serving import PagedConfig, ServingEngine

        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        eng.slo_step = "generate"
        eng.trace_context = tc
        eng.submit(list(range(1, 9)), max_new_tokens=4, tenant="acme")
        eng.run()

        # --- ONE trace, every hop ------------------------------------
        stitched = [s for s in exporter.spans if s.trace_id == tid]
        names = {s.name for s in stitched}
        for expected in (
            "storyrun.run",        # admission
            "dag.reconcile",       # scheduling decision
            "step.execute",        # launch
            "slice.place_group",   # batched gang placement
            "steprun.dispatch",    # Job/gang dispatch
            "sdk.step",            # worker-side execution
            "steprun.realtime",    # dataplane/serving step stitch point
            "serving.request",     # request lifecycle to first token
        ):
            assert expected in names, f"missing {expected} in {sorted(names)}"

        req_span = next(s for s in stitched if s.name == "serving.request")
        assert any(name == "first_token" for _, name in req_span.events)
        assert "ttftSeconds" in req_span.attributes
        assert req_span.attributes["tenant"] == "acme"

        # --- SLO histograms populated and exposed --------------------
        page = REGISTRY.expose()
        assert 'bobrapet_serving_ttft_seconds_count{step="generate",tenant="acme"}' in page
        assert 'bobrapet_serving_queue_wait_seconds_count{step="generate",tenant="acme"}' in page
        assert 'bobrapet_serving_tpot_seconds_count{step="generate",tenant="acme"}' in page
        assert 'bobrapet_serving_e2e_latency_seconds_count{step="generate",tenant="acme"}' in page
        # within-threshold counters make burn rates computable
        assert 'bobrapet_serving_slo_total{slo="ttft"' in page
        assert 'bobrapet_serving_slo_total{slo="tpot"' in page

    def test_per_request_trace_wins_under_ambient_span(self, monkeypatch):
        """The serve loop runs inside the gang host's sdk.step span in
        production — a caller-supplied per-request trace must still win
        (the request span is detached from the thread-local parent)."""
        from bobrapet_tpu.observability import tracing as tracing_mod

        exporter = InMemorySpanExporter()
        tracer = Tracer(TracingConfig(enabled=True), exporter=exporter)
        monkeypatch.setattr(tracing_mod, "TRACER", tracer)

        import jax

        from bobrapet_tpu.models import llama
        from bobrapet_tpu.serving import PagedConfig, ServingEngine

        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        eng.trace_context = {"traceId": "99" * 16, "spanId": "88" * 8}
        caller_tid = "ab" * 16
        with tracer.start_span("sdk.step", run="amb-run", namespace="ns"):
            eng.submit(list(range(1, 9)), max_new_tokens=2,
                       trace={"traceId": caller_tid, "spanId": "cd" * 8})
            eng.submit(list(range(1, 9)), max_new_tokens=2)
            eng.run()
        req_spans = [s for s in exporter.spans if s.name == "serving.request"]
        tids = {s.trace_id for s in req_spans}
        # per-request override wins; the engine-level context covers the
        # rest — neither is swallowed by the ambient sdk.step span
        assert caller_tid in tids
        assert "99" * 16 in tids

        # untrusted tenant labels are cardinality-capped
        labels = {eng._bound_tenant(f"uuid-{i}") for i in range(200)}
        assert "other" in labels
        assert len(labels) <= ServingEngine.MAX_TENANT_LABELS + 1

    def test_trace_disabled_costs_nothing_and_stitches_nothing(self):
        rt = Runtime()  # default tracer follows telemetry.enabled=False
        assert not rt.tracer.config.enabled

        @register_engram("trace-e2e-dark")
        def impl(ctx):  # noqa: ARG001
            return {}

        rt.apply(make_engram_template("td-tpl", entrypoint="trace-e2e-dark"))
        rt.apply(make_engram("td-worker", "td-tpl"))
        rt.apply(make_story("td-story", steps=[
            {"name": "s", "ref": {"name": "td-worker"}},
        ]))
        run = rt.run_story("td-story", inputs={})
        rt.pump()
        srun = rt.store.get("StoryRun", "default", run)
        assert srun.status["phase"] == "Succeeded"
        # span-dark: no trace minted anywhere
        assert "trace" not in srun.status
