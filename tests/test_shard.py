"""Sharded control plane — unit coverage.

The fast, deterministic legs: ring math (stability, balance, minimal
movement), lease fencing (a paused-and-resumed stale leader must not
publish), ShardMap admission (the fence is enforced at the bus, not by
publisher discipline), router verdicts (own/park/drop + watch-delivery
interest), the double-reconcile detector's ledger, and the new config
keys. End-to-end multi-manager behaviour lives in test_shard_e2e.py.
"""

from __future__ import annotations

import pytest

from bobrapet_tpu.api.runs import STEP_RUN_KIND, STORY_RUN_KIND
from bobrapet_tpu.config.operator import OperatorConfig, _apply_dotted
from bobrapet_tpu.controllers.manager import ManualClock
from bobrapet_tpu.core.object import new_resource
from bobrapet_tpu.core.store import AdmissionDenied, ResourceStore
from bobrapet_tpu.shard import (
    ADMIT_DROP,
    ADMIT_OWN,
    ADMIT_PARK,
    DoubleReconcileDetector,
    HashRing,
    SHARD_MAP_KIND,
    SHARD_MAP_NAME,
    SHARD_NAMESPACE,
    ShardMapPublisher,
    ShardRouter,
    register_shard_admission,
)
from bobrapet_tpu.shard.map import SHARD_LEASE_NAME
from bobrapet_tpu.shard.router import LABEL_STORY_RUN
from bobrapet_tpu.utils.hashing import stable_uint64
from bobrapet_tpu.utils.leader import LEASE_KIND, LeaseLeaderElector


# ---------------------------------------------------------------------------
# hashing + ring
# ---------------------------------------------------------------------------


def test_stable_uint64_is_process_stable():
    # sha256-derived: the exact value is part of the contract (two
    # managers in different processes must agree on every ring position)
    assert stable_uint64("vnode:0:0") == stable_uint64("vnode:0:0")
    assert stable_uint64("a") != stable_uint64("b")
    v = stable_uint64("bobrapet")
    assert 0 <= v < 2 ** 64
    # pin one value so an accidental algorithm change cannot silently
    # remap every resident run across a fleet upgrade
    import hashlib

    expect = int.from_bytes(hashlib.sha256(b"bobrapet").digest()[:8], "big")
    assert v == expect


def test_ring_deterministic_across_instances():
    a = HashRing(["0", "1", "2"])
    b = HashRing(["2", "1", "0"])  # order-independent
    assert a == b
    for i in range(200):
        assert a.owner(f"ns/run-{i}") == b.owner(f"ns/run-{i}")


def test_ring_balance_four_members():
    ring = HashRing([str(i) for i in range(4)])
    counts = {m: 0 for m in ring.members}
    n = 4000
    for i in range(n):
        counts[ring.owner(f"default/run-{i}")] += 1
    largest, smallest = max(counts.values()), min(counts.values())
    # 64 vnodes keeps the spread well under 2x (docstring promises ~1.4)
    assert largest / smallest < 2.0, counts
    for m, c in counts.items():
        assert c > 0, f"member {m} owns nothing"


def test_ring_minimal_movement_on_join():
    keys = [f"default/run-{i}" for i in range(2000)]
    before = HashRing(["0", "1", "2", "3"])
    after = HashRing(["0", "1", "2", "3", "4"])
    moved = before.moved_keys(after, keys)
    # consistent hashing: ~1/5 of the keyspace moves (all to the
    # joiner); tolerate 2x sampling noise, reject rehash-the-world
    assert len(moved) < len(keys) * 0.4, len(moved)
    for k in moved:
        assert after.owner(k) == "4", "keys may only move TO the joiner"


def test_ring_single_member_owns_everything():
    ring = HashRing(["solo"])
    for i in range(50):
        assert ring.owner(f"ns/r{i}") == "solo"
        assert ring.owns("solo", f"ns/r{i}")
    with pytest.raises(ValueError):
        HashRing([])


# ---------------------------------------------------------------------------
# lease fencing
# ---------------------------------------------------------------------------


def _elector(store, clock, ident, duration=10.0):
    return LeaseLeaderElector(
        store, name=SHARD_LEASE_NAME, namespace=SHARD_NAMESPACE,
        lease_duration=duration, identity=ident, clock=clock,
    )


def test_fence_token_monotonic_across_steals():
    store = ResourceStore()
    clock = ManualClock()
    a = _elector(store, clock, "a")
    b = _elector(store, clock, "b")
    assert a.try_acquire() and a.fence_token == 1
    assert not b.try_acquire()  # lease held
    clock.advance(11.0)  # past lease_duration: a's lease expires
    assert b.try_acquire() and b.fence_token == 2
    assert b.validate_fence()
    # a still THINKS it leads (no heartbeat since): the fresh-read
    # check must say otherwise
    assert a.is_leader  # cached flag, deliberately stale
    assert not a.validate_fence()


def test_stale_leader_cannot_renew_back_in():
    store = ResourceStore()
    clock = ManualClock()
    a = _elector(store, clock, "a")
    b = _elector(store, clock, "b")
    assert a.try_acquire()
    clock.advance(11.0)
    assert b.try_acquire()
    # the resumed stale leader heartbeats: same holder name is NOT
    # enough — the fence epoch moved on, so it must lose
    clock.advance(11.0)  # b's lease is expired too: a could steal...
    assert a.try_acquire()
    assert a.fence_token == 3  # ...but only via a fresh acquisition
    assert not b.validate_fence()


def test_stale_leader_map_publish_rejected_at_admission():
    store = ResourceStore()
    clock = ManualClock()
    register_shard_admission(store)
    a = _elector(store, clock, "a")
    b = _elector(store, clock, "b")
    assert a.try_acquire()
    pub_a = ShardMapPublisher(store, a)
    assert pub_a.publish(["0", "1"]) is not None

    clock.advance(11.0)
    assert b.try_acquire()  # a is now a stale leader (paused + resumed)
    pub_b = ShardMapPublisher(store, b)
    assert pub_b.publish(["0", "1", "2"]) is not None

    # a's pre-check already refuses (fresh lease read) ...
    assert pub_a.publish(["0"]) is None
    # ... and even a write that skips the pre-check dies at admission
    with pytest.raises(AdmissionDenied, match="fenced out"):
        def stale_write(r):
            r.spec["members"] = ["0"]
            r.spec["epoch"] = int(r.spec["epoch"]) + 1
            r.spec["fence"] = a.fence_token  # stale token
        store.mutate(SHARD_MAP_KIND, SHARD_NAMESPACE, SHARD_MAP_NAME,
                     stale_write)
    # the surviving map is b's
    m = store.get(SHARD_MAP_KIND, SHARD_NAMESPACE, SHARD_MAP_NAME)
    assert m.spec["members"] == ["0", "1", "2"]
    assert m.spec["fence"] == b.fence_token


def test_map_epoch_must_increase():
    store = ResourceStore()
    register_shard_admission(store)
    store.create(new_resource(
        SHARD_MAP_KIND, SHARD_MAP_NAME, SHARD_NAMESPACE,
        {"members": ["0"], "epoch": 5, "fence": 1},
    ))
    with pytest.raises(AdmissionDenied, match="epoch must increase"):
        def bad(r):
            r.spec["members"] = ["0", "1"]  # change without an epoch bump
        store.mutate(SHARD_MAP_KIND, SHARD_NAMESPACE, SHARD_MAP_NAME, bad)
    with pytest.raises(AdmissionDenied, match="non-empty list"):
        store.create(new_resource(
            SHARD_MAP_KIND, "other-map", SHARD_NAMESPACE,
            {"members": [], "epoch": 1},
        ))


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------


def _two_shard_routers(store):
    return (ShardRouter(store, "0", shard_count=2),
            ShardRouter(store, "1", shard_count=2))


def _owned_run(router, prefix="default/r"):
    """A run key this router owns under its active ring."""
    for i in range(500):
        ns_name = f"{prefix}{i}"
        if router.owns_root(ns_name):
            return ns_name.split("/", 1)[1]
    raise AssertionError("no owned key found in 500 candidates")


def test_router_partitions_storyrun_keys():
    store = ResourceStore()
    r0, r1 = _two_shard_routers(store)
    mine = theirs = 0
    for i in range(200):
        v0, _ = r0.classify("storyrun", "default", f"r{i}")
        v1, _ = r1.classify("storyrun", "default", f"r{i}")
        # exactly one shard owns every run, the other drops it
        assert {v0, v1} == {ADMIT_OWN, ADMIT_DROP}
        mine += v0 == ADMIT_OWN
        theirs += v1 == ADMIT_OWN
    assert mine and theirs
    # non-family controllers always run everywhere
    assert r0.classify("shard", SHARD_NAMESPACE, SHARD_MAP_NAME)[0] == ADMIT_OWN
    assert r1.classify("cluster", "x", "y")[0] == ADMIT_OWN


def test_router_steprun_follows_parent_run():
    store = ResourceStore()
    r0, r1 = _two_shard_routers(store)
    run = _owned_run(r0)
    sr = new_resource(STEP_RUN_KIND, f"{run}-step-a", "default",
                      {"storyRunRef": {"name": run}})
    store.create(sr)
    assert r0.classify("steprun", "default", sr.meta.name)[0] == ADMIT_OWN
    assert r1.classify("steprun", "default", sr.meta.name)[0] == ADMIT_DROP
    # delivery interest mirrors the gate
    assert r0.wants(sr) and not r1.wants(sr)


def test_router_child_storyrun_delivers_to_parent_shard():
    store = ResourceStore()
    r0, r1 = _two_shard_routers(store)
    parent = _owned_run(r0)
    # a child owned by shard 1 whose parent lives on shard 0
    child_name = None
    for i in range(500):
        cand = f"{parent}-sub-{i}"
        if r1.owns_run("default", cand):
            child_name = cand
            break
    assert child_name is not None
    child = new_resource(STORY_RUN_KIND, child_name, "default",
                         {"storyRef": {"name": "s"}},
                         labels={LABEL_STORY_RUN: parent})
    # both the owner (to run it) and the parent's shard (to observe
    # completion) must see its events
    assert r1.wants(child) and r0.wants(child)
    # the reconcile gate stays exclusive: only the owner runs it
    assert r1.classify("storyrun", "default", child_name)[0] == ADMIT_OWN
    assert r0.classify("storyrun", "default", child_name)[0] == ADMIT_DROP


def test_router_rebalance_park_and_promote():
    store = ResourceStore()
    r0 = ShardRouter(store, "0", shard_count=1)
    assert r0.classify("storyrun", "default", "r1")[0] == ADMIT_OWN
    # a second member joins: keys moving 0 -> 1 must PARK on the gainer
    # and DROP on the loser only after the barrier
    r0.begin_rebalance(["0", "1"], epoch=1, started_at=0.0)
    assert r0.rebalancing
    two = HashRing(["0", "1"])
    moving = next(f"r{i}" for i in range(500)
                  if two.owner(f"default/r{i}") == "1")
    staying = next(f"r{i}" for i in range(500)
                   if two.owner(f"default/r{i}") == "0")
    # loser keeps draining... new work for the moving family is refused
    assert r0.classify("storyrun", "default", moving)[0] == ADMIT_DROP
    assert r0.classify("storyrun", "default", staying)[0] == ADMIT_OWN
    # ...while a router for the GAINER parks it until the promote
    r1 = ShardRouter(store, "1", shard_count=1)
    r1.begin_rebalance(["0", "1"], epoch=1, started_at=0.0)
    assert r1.classify("storyrun", "default", moving)[0] == ADMIT_PARK
    old_n, new_n, _ = r1.promote()
    assert (old_n, new_n) == (1, 2)
    assert r1.classify("storyrun", "default", moving)[0] == ADMIT_OWN


def test_router_stale_epoch_rebalance_ignored():
    store = ResourceStore()
    r = ShardRouter(store, "0", shard_count=2)
    r.begin_rebalance(["0", "1", "2"], epoch=3, started_at=0.0)
    r.begin_rebalance(["0"], epoch=2, started_at=1.0)  # stale: ignored
    assert r.pending_epoch == 3
    r.promote()
    assert r.active_epoch == 3
    assert r.members() == ("0", "1", "2")


def test_router_bootstrap_count_reload():
    store = ResourceStore()
    r = ShardRouter(store, "0", shard_count=1)
    assert r.set_bootstrap_count(4)
    assert r.members() == ("0", "1", "2", "3")
    # once a published map has promoted, the static count is advisory
    r.begin_rebalance(["0", "1"], epoch=1, started_at=0.0)
    r.promote()
    assert not r.set_bootstrap_count(8)
    assert r.members() == ("0", "1")


# ---------------------------------------------------------------------------
# double-reconcile detector
# ---------------------------------------------------------------------------


def test_detector_flags_cross_shard_overlap_only():
    det = DoubleReconcileDetector()
    det._started("0", "default/r1", "storyrun", "default", "r1")
    # same family on the SAME shard (storyrun + steprun pools) is legal
    det._started("0", "default/r1", "steprun", "default", "r1-s0")
    assert not det.violations
    # a second shard entering the same family is the invariant breach
    det._started("1", "default/r1", "steprun", "default", "r1-s1")
    assert len(det.violations) == 1
    v = det.violations[0]
    assert v.root == "default/r1" and set(v.shards) == {"0", "1"}
    with pytest.raises(AssertionError):
        det.assert_clean()


def test_detector_finish_balances_ledger():
    det = DoubleReconcileDetector()
    det._started("0", "default/r2", "storyrun", "default", "r2")
    det._finished("0", "default/r2")
    # after the finish, another shard may legally take the family over
    det._started("1", "default/r2", "storyrun", "default", "r2")
    det._finished("1", "default/r2")
    det.assert_clean()
    assert det.processed == {"0": 1, "1": 1}


# ---------------------------------------------------------------------------
# coordinator self-fence
# ---------------------------------------------------------------------------


def test_coordinator_self_fences_on_stale_renewal():
    """The member-side half of the fencing contract: once this
    member's own renewal is stale past member_ttl/2 the gate parks all
    family work (the leader may declare it dead at any moment and hand
    its families to survivors), and the next landed renewal reopens
    it — with the parked-gauge entry released, not leaked."""
    from bobrapet_tpu.shard import ShardCoordinator

    store = ResourceStore()
    clock = ManualClock()
    router = ShardRouter(store, "0", shard_count=1)
    coord = ShardCoordinator(store, router, manager=None, clock=clock,
                             heartbeat_interval=2.0, member_ttl=6.0)
    coord._beat(clock.now())  # first renewal lands
    assert coord.gate("storyrun", "default", "r1") is None  # admitted
    clock.advance(3.1)  # stale past member_ttl/2 with no renewal
    delay = coord.gate("storyrun", "default", "r1")
    assert delay is not None and delay >= 0  # parked, never dropped
    assert ("storyrun", "default", "r1") in router.parked
    # non-family controllers (the shard controller itself, cluster
    # reconcilers) are never fenced — they are what recovers us
    assert coord.gate("shard", SHARD_NAMESPACE, SHARD_MAP_NAME) is None
    coord._beat(clock.now())  # a renewal lands: fence lifts
    assert coord.gate("storyrun", "default", "r1") is None
    assert ("storyrun", "default", "r1") not in router.parked


def test_sharded_runtimes_share_the_scheduling_gate():
    """Named-queue caps are bus-global admission invariants: the
    check-then-reserve window must serialize across every manager on
    the bus (store.scheduling_gate), or N shards could each admit one
    step over a cap in the same instant. The GLOBAL cap's reservation
    bucket stays per-engine — it is shard-local dispatch capacity."""
    from bobrapet_tpu.shard import ShardedControlPlane

    cp = ShardedControlPlane(shards=2)  # built, never started
    try:
        d0, d1 = (rt.dag for rt in cp.runtimes.values())
        assert d0._sched_lock is d1._sched_lock
        assert d0._sched_reserved is d1._sched_reserved
        assert d0._global_bucket != d1._global_bucket
    finally:
        cp.stop()


def test_coordinator_beat_records_renewal_success():
    from bobrapet_tpu.shard import ShardCoordinator
    from bobrapet_tpu.shard.map import SHARD_MEMBER_KIND

    store = ResourceStore()
    clock = ManualClock()
    router = ShardRouter(store, "0", shard_count=1)
    coord = ShardCoordinator(store, router, manager=None, clock=clock,
                             heartbeat_interval=2.0, member_ttl=6.0)
    clock.advance(5.0)
    coord._beat(clock.now())
    m = store.get(SHARD_MEMBER_KIND, SHARD_NAMESPACE, "0")
    assert m.spec["renewTime"] == pytest.approx(clock.now())
    assert coord._last_renew_ok == pytest.approx(clock.now())
    assert not coord._self_fenced()


# ---------------------------------------------------------------------------
# config keys
# ---------------------------------------------------------------------------


def test_shard_config_keys_apply_and_validate():
    cfg = OperatorConfig()
    assert _apply_dotted(cfg, "controllers.shard-count", "4")
    assert _apply_dotted(cfg, "controllers.shard-id", "3")
    assert _apply_dotted(cfg, "scheduling.queue-probe-interval", "250ms")
    assert cfg.controllers.shard_count == 4
    assert cfg.controllers.shard_id == 3
    assert cfg.scheduling.queue_probe_interval == pytest.approx(0.25)
    assert not cfg.validate()

    cfg.controllers.shard_id = 4  # out of [0, shard-count)
    errs = cfg.validate()
    assert any("shard-id" in e for e in errs)
    cfg.controllers.shard_id = 0
    cfg.controllers.shard_count = 0
    errs = cfg.validate()
    assert any("shard-count" in e for e in errs)

    cfg.controllers.shard_count = 2
    cfg.scheduling.queue_probe_interval = 0.0  # hot-loop foot-gun
    errs = cfg.validate()
    assert any("queue-probe-interval" in e for e in errs)


def test_runtime_rejects_unknown_shard_options_before_filter_install():
    """A shard_options typo must raise BEFORE the construction bracket
    installs this shard's watch predicate as the store default — a
    dead shard's filter would silently misbind the next Runtime's
    watchers on the shared bus."""
    from bobrapet_tpu.runtime import Runtime

    store = ResourceStore()
    with pytest.raises(TypeError, match="unknown shard_options"):
        Runtime(store=store, shard_id="0", shard_options={"vnode": 32})
    assert store._default_watch_filter is None
