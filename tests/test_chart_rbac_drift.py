"""Chart <-> code RBAC drift (ISSUE 4 satellite).

`controllers/rbac.manager_cluster_rules()` derives the ClusterRole the
manager needs from code-level registrations: CRD groups from the schema
registry, workload kinds from what the materializer emits / executors
watch, the election Lease from the elector. The chart's
`serviceaccount.yaml` is the hand-maintained mirror. Like the
webhook-drift suite (test_chart_webhook_drift.py), this renders the
chart template and diffs the grants both ways, so registering a new CRD
group or teaching the executor a new workload kind without widening the
chart (or widening the chart beyond what code uses — a least-privilege
regression) fails here instead of shipping a manager that cannot (or
can over-) reach the cluster.

The run-scoped identity allowlist is asserted separately: the verbs the
runner sanitizer may ever grant (`SAFE_VERBS`) must not exceed what the
manager itself holds on the namespaced kinds it creates Role objects
for — a run could otherwise be granted more than its creator has.
"""

from __future__ import annotations

import os

import pytest
import yaml

from bobrapet_tpu.controllers.rbac import (
    SAFE_VERBS,
    manager_cluster_rules,
)

CHART = os.path.join(
    os.path.dirname(__file__), "..",
    "deploy", "chart", "bobrapet-tpu", "templates", "serviceaccount.yaml",
)


def render_chart() -> list[dict]:
    """Poor-man's helm template, same approach as the webhook suite."""
    with open(CHART) as f:
        text = f.read()
    text = "\n".join(
        line for line in text.splitlines()
        if not line.strip().startswith("{{-")
    )
    text = (
        text.replace("{{ .Release.Name }}", "rel")
        .replace("{{ .Release.Namespace }}", "ns")
    )
    return [d for d in yaml.safe_load_all(text) if d]


def normalize(rules: list[dict]) -> set[tuple]:
    """(group, resource, verb) triples — the flat grant set, immune to
    how rules happen to be batched into list entries."""
    out = set()
    for rule in rules:
        for g in rule.get("apiGroups") or [""]:
            for r in rule.get("resources") or []:
                for v in rule.get("verbs") or []:
                    out.add((g, r, v))
    return out


@pytest.fixture(scope="module")
def chart_docs():
    return render_chart()


@pytest.fixture(scope="module")
def chart_cluster_role(chart_docs):
    roles = [d for d in chart_docs if d["kind"] == "ClusterRole"]
    assert len(roles) == 1, "expected exactly one manager ClusterRole"
    return roles[0]


class TestChartRBACDrift:
    def test_identity_object_kinds_present(self, chart_docs):
        kinds = {d["kind"] for d in chart_docs}
        assert kinds == {
            "ServiceAccount", "Role", "RoleBinding",
            "ClusterRole", "ClusterRoleBinding",
        }

    def test_cluster_role_matches_code_derived_rules(self, chart_cluster_role):
        chart = normalize(chart_cluster_role["rules"])
        code = normalize(manager_cluster_rules())
        assert chart == code, (
            f"manager ClusterRole drifted:\n"
            f"  chart-only (over-grant / stale): {sorted(chart - code)}\n"
            f"  code-only (manager will get Forbidden): {sorted(code - chart)}\n"
            f"update deploy/chart/bobrapet-tpu/templates/serviceaccount.yaml "
            f"or controllers/rbac.manager_cluster_rules()"
        )

    def test_pods_stay_read_only(self, chart_cluster_role):
        """Least-privilege pin: exit-code extraction reads pods; nothing
        may ever write them through the manager identity."""
        grants = normalize(chart_cluster_role["rules"])
        pod_verbs = {v for (g, r, v) in grants if r == "pods"}
        assert pod_verbs == {"get", "list", "watch"}

    def test_no_wildcard_outside_crd_groups(self, chart_cluster_role):
        crd_groups = {
            g for rule in manager_cluster_rules()
            for g in rule["apiGroups"]
            if "*" in rule["resources"]
        }
        for rule in chart_cluster_role["rules"]:
            if any("*" in r for r in rule.get("resources") or []):
                assert set(rule["apiGroups"]) <= crd_groups, (
                    f"wildcard resources outside the CRD groups: {rule}"
                )

    def test_leader_election_role_scoped_to_leases(self, chart_docs):
        role = next(d for d in chart_docs if d["kind"] == "Role")
        grants = normalize(role["rules"])
        assert {r for (_, r, _) in grants} == {"leases"}

    def test_runner_allowlist_within_manager_grants(self, chart_cluster_role):
        """sanitize_rules() can never mint a run-scoped Role whose verbs
        exceed the manager's own CRD-group grants (the objects the
        runner touches are CRD kinds + core kinds the manager manages)."""
        grants = normalize(chart_cluster_role["rules"])
        manager_verbs = {v for (g, r, v) in grants if r == "*"}
        assert SAFE_VERBS <= manager_verbs, (
            f"runner allowlist verbs {sorted(SAFE_VERBS - manager_verbs)} "
            f"exceed the manager's own grants"
        )
