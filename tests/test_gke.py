"""GKE materialization layer: SliceGrant + bus resources → manifests.

Golden-structure tests per BASELINE configs 2/3/5 (VERDICT r1 missing
#3): `google.com/tpu` limits, gke-tpu nodeSelectors, Indexed Job
completion-index → TPU_WORKER_ID, headless Service hostnames,
JobSet wrapping, Deployment/Service for realtime, and an end-to-end
run where the locally-executed Job bus resource materializes into
`kubectl apply`-able YAML.
"""

import pytest
import yaml

from bobrapet_tpu.gke import (
    GKEMaterializer,
    materialize_deployment,
    materialize_gang_job,
    to_yaml,
)
from bobrapet_tpu.gke.materialize import (
    COMPLETION_INDEX_ANNOTATION,
    NODE_SELECTOR_ACCELERATOR,
    NODE_SELECTOR_TOPOLOGY,
    TPU_RESOURCE,
)
from bobrapet_tpu.parallel.placement import SlicePool


def _by_kind(manifests):
    out = {}
    for m in manifests:
        out.setdefault(m["kind"], []).append(m)
    return out


def _container(job):
    return job["spec"]["template"]["spec"]["containers"][0]


def _env_dict(container):
    plain = {}
    refs = {}
    for e in container["env"]:
        if "value" in e:
            plain[e["name"]] = e["value"]
        else:
            refs[e["name"]] = e["valueFrom"]
    return plain, refs


def _grant_for(topology, chips_per_host, accelerator):
    pool = SlicePool("pool", topology, chips_per_host=chips_per_host,
                     accelerator=accelerator)
    return pool.allocate(want_topology=topology).to_dict()


class TestGangJob:
    def test_config2_v5e4_single_host(self):
        """BASELINE config 2: Llama engram on single-host v5e-4 (2x2)."""
        grant = _grant_for("2x2", 4, "tpu-v5-lite-podslice")
        manifests = materialize_gang_job(
            name="run1-generate", namespace="prod",
            image="bobrapet/llama:latest",
            env={"BOBRA_STEP": "generate"}, grant=grant,
        )
        kinds = _by_kind(manifests)
        job = kinds["Job"][0]
        svc = kinds["Service"][0]

        assert job["apiVersion"] == "batch/v1"
        assert job["spec"]["completions"] == 1
        assert job["spec"]["parallelism"] == 1
        assert job["spec"]["completionMode"] == "Indexed"
        c = _container(job)
        assert c["resources"]["limits"][TPU_RESOURCE] == "4"
        assert c["resources"]["requests"][TPU_RESOURCE] == "4"
        sel = job["spec"]["template"]["spec"]["nodeSelector"]
        assert sel[NODE_SELECTOR_ACCELERATOR] == "tpu-v5-lite-podslice"
        assert sel[NODE_SELECTOR_TOPOLOGY] == "2x2"
        assert svc["spec"]["clusterIP"] == "None"

    def test_config3_v5e16_multi_host(self):
        """BASELINE config 3: gang-scheduled fan-out on v5e-16 (4x4, 4 hosts)."""
        grant = _grant_for("4x4", 4, "tpu-v5-lite-podslice")
        assert grant["hosts"] == 4
        manifests = materialize_gang_job(
            name="run1-train", namespace="prod", image="img",
            env={}, grant=grant,
        )
        kinds = _by_kind(manifests)
        job = kinds["Job"][0]
        assert job["spec"]["completions"] == 4
        assert job["spec"]["parallelism"] == 4
        c = _container(job)
        assert c["resources"]["limits"][TPU_RESOURCE] == "4"  # 16 chips / 4 hosts
        plain, refs = _env_dict(c)
        # worker identity from the completion index (downward API)
        assert refs["TPU_WORKER_ID"]["fieldRef"]["fieldPath"] == (
            f"metadata.annotations['{COMPLETION_INDEX_ANNOTATION}']"
        )
        hostnames = plain["TPU_WORKER_HOSTNAMES"].split(",")
        assert hostnames == [
            f"run1-train-{i}.run1-train-workers" for i in range(4)
        ]
        assert plain["BOBRA_COORDINATOR_ADDRESS"].startswith(
            "run1-train-0.run1-train-workers:"
        )
        assert plain["BOBRA_TPU_HOSTS"] == "4"
        # pods join the headless service via subdomain
        assert job["spec"]["template"]["spec"]["subdomain"] == "run1-train-workers"

    def test_config5_v5p32(self):
        """BASELINE config 5: RAG generate leg on v5p-32 (2x4x4, 8 hosts)."""
        grant = _grant_for("2x4x4", 4, "tpu-v5p-slice")
        assert grant["hosts"] == 8
        manifests = materialize_gang_job(
            name="rag-generate", namespace="prod", image="img",
            env={}, grant=grant,
        )
        job = _by_kind(manifests)["Job"][0]
        assert job["spec"]["completions"] == 8
        c = _container(job)
        assert c["resources"]["limits"][TPU_RESOURCE] == "4"
        sel = job["spec"]["template"]["spec"]["nodeSelector"]
        assert sel[NODE_SELECTOR_TOPOLOGY] == "2x4x4"
        assert sel[NODE_SELECTOR_ACCELERATOR] == "tpu-v5p-slice"

    def test_config1_cpu_only_plain_job(self):
        """BASELINE config 1: no grant → plain single-pod Job, no TPU fields."""
        manifests = materialize_gang_job(
            name="solo", namespace="default", image="img",
            env={"BOBRA_STEP": "only"}, grant=None, timeout_seconds=60,
        )
        assert len(manifests) == 1
        job = manifests[0]
        assert job["kind"] == "Job"
        assert "completionMode" not in job["spec"]
        assert job["spec"]["activeDeadlineSeconds"] == 60
        spec = job["spec"]["template"]["spec"]
        assert "nodeSelector" not in spec
        assert "resources" not in _container(job) or TPU_RESOURCE not in (
            _container(job).get("resources", {}).get("limits", {})
        )

    def test_jobset_wrapper(self):
        grant = _grant_for("4x4", 4, "tpu-v5-lite-podslice")
        manifests = materialize_gang_job(
            name="js", namespace="default", image="img", env={},
            grant=grant, jobset=True,
        )
        kinds = _by_kind(manifests)
        js = kinds["JobSet"][0]
        assert js["apiVersion"] == "jobset.x-k8s.io/v1alpha2"
        rj = js["spec"]["replicatedJobs"][0]
        assert rj["template"]["spec"]["completionMode"] == "Indexed"
        assert "ttlSecondsAfterFinished" not in rj["template"]["spec"]
        assert js["spec"]["failurePolicy"]["maxRestarts"] == 0

    def test_uneven_hosts_rejected(self):
        grant = _grant_for("4x4", 4, "tpu-v5-lite-podslice")
        grant["hosts"] = 3  # 16 chips over 3 hosts
        with pytest.raises(ValueError, match="do not divide"):
            materialize_gang_job(
                name="bad", namespace="default", image="img", env={}, grant=grant,
            )


class TestDeployment:
    def test_realtime_deployment_and_service(self):
        manifests = materialize_deployment(
            name="run1-stream-rt", namespace="prod", image="img",
            env={"BOBRA_STEP": "stream"}, port=50051,
            selector={"bobrapet.io/step-run": "run1-stream"},
            readiness_path="/healthz",
        )
        kinds = _by_kind(manifests)
        dep = kinds["Deployment"][0]
        svc = kinds["Service"][0]
        assert dep["spec"]["selector"]["matchLabels"] == {
            "bobrapet.io/step-run": "run1-stream"
        }
        c = dep["spec"]["template"]["spec"]["containers"][0]
        assert c["readinessProbe"]["httpGet"]["path"] == "/healthz"
        assert svc["spec"]["ports"][0]["port"] == 50051


class TestEndToEnd:
    def test_local_job_materializes_to_applyable_yaml(self, rt):
        """The job the local executor ran is exactly what GKE would get:
        capture the bus Job from a TPU story and materialize it."""
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.controllers.jobs import JOB_KIND
        from bobrapet_tpu.sdk import register_engram

        rt.placer.add_pool(
            SlicePool("v5e-pool", "4x4", chips_per_host=4,
                      accelerator="tpu-v5-lite-podslice")
        )
        rt.apply(make_engram_template("w-tpl", entrypoint="gke-e2e-impl"))
        rt.apply(make_engram("worker", "w-tpl"))

        @register_engram("gke-e2e-impl")
        def impl(ctx):
            return {}

        rt.apply(make_story("tpu-story", steps=[
            {"name": "train", "ref": {"name": "worker"},
             "tpu": {"topology": "2x4", "meshAxes": {"data": 2, "model": 4}}},
        ], policy={"queue": "v5e-pool"}))
        run = rt.run_story("tpu-story")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"

        jobs = [j for j in rt.store.list(JOB_KIND, "default")
                if j.spec.get("sliceGrant")]
        assert jobs, "TPU story produced no Job bus resource with a grant"
        manifests = GKEMaterializer().materialize_job(jobs[0])

        kinds = _by_kind(manifests)
        job = kinds["Job"][0]
        assert job["spec"]["completions"] == 2  # 8 chips / 4 per host
        c = _container(job)
        assert c["resources"]["limits"][TPU_RESOURCE] == "4"
        plain, refs = _env_dict(c)
        assert plain["BOBRA_MESH_AXES"] == '{"data":2,"model":4}'
        assert "TPU_WORKER_ID" in refs
        # the local env contract facts survived into the manifest
        assert plain["BOBRA_STEP"] == "train"

        # kubectl-appliable: multi-doc YAML round-trips
        docs = [d for d in yaml.safe_load_all(to_yaml(manifests)) if d]
        assert [d["kind"] for d in docs] == [m["kind"] for m in manifests]
        for d in docs:
            assert d["metadata"]["name"]
            assert d["apiVersion"]

    def test_runtime_export_gke_manifests(self, rt):
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.sdk import register_engram

        rt.apply(make_engram_template("x-tpl", entrypoint="gke-export-impl"))
        rt.apply(make_engram("worker", "x-tpl"))

        @register_engram("gke-export-impl")
        def impl(ctx):
            return {}

        rt.apply(make_story("s", steps=[
            {"name": "a", "ref": {"name": "worker"}},
        ]))
        run = rt.run_story("s")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        manifests = rt.export_gke_manifests()
        assert any(m["kind"] == "Job" for m in manifests)

    def test_impulse_workload_exports_sa_and_secrets(self, rt):
        """Impulse listeners export with their service account, secrets,
        and StatefulSet mode preserved."""
        from bobrapet_tpu.api.catalog import (
            make_engram_template,
            make_impulse_template,
        )
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.impulse import make_impulse
        from bobrapet_tpu.api.story import make_story

        rt.apply(make_engram_template("i-tpl", entrypoint="gke-impulse-impl"))
        rt.apply(make_engram("worker", "i-tpl"))
        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        rt.apply(make_impulse_template("webhook-tpl", image="impulse-img",
                                       supportedModes=["deployment", "statefulset"]))
        imp = make_impulse("hook", "webhook-tpl", story="s")
        imp.spec["workload"] = {"mode": "statefulset"}
        imp.spec["secrets"] = {"apikey": "hook-api-secret"}
        rt.apply(imp)
        rt.pump()

        manifests = rt.export_gke_manifests()
        stss = [m for m in manifests if m["kind"] == "StatefulSet"]
        assert stss, f"no StatefulSet exported; kinds={[m['kind'] for m in manifests]}"
        sts = stss[0]
        pod_spec = sts["spec"]["template"]["spec"]
        assert pod_spec["serviceAccountName"] == "hook-impulse-sa"
        assert sts["spec"]["serviceName"]
        vols = {v["name"]: v for v in pod_spec["volumes"]}
        assert vols["secret-apikey"]["secret"]["secretName"] == "hook-api-secret"
        c = pod_spec["containers"][0]
        env = {e["name"]: e.get("value") for e in c["env"]}
        assert env["BOBRA_SECRET_APIKEY_PATH"] == "/var/run/bobrapet/secrets/apikey"


class TestJobSetNaming:
    def test_jobset_hostnames_use_child_job_name(self):
        from bobrapet_tpu.gke.materialize import JOBSET_REPLICATED_JOB

        pool = SlicePool("p", "4x4", chips_per_host=4,
                         accelerator="tpu-v5-lite-podslice")
        grant = pool.allocate(want_topology="4x4").to_dict()
        manifests = materialize_gang_job(
            name="js", namespace="default", image="img", env={},
            grant=grant, jobset=True,
        )
        js = _by_kind(manifests)["JobSet"][0]
        pod = js["spec"]["replicatedJobs"][0]["template"]["spec"]["template"]
        env = {e["name"]: e.get("value") for e in pod["spec"]["containers"][0]["env"]}
        child = f"js-{JOBSET_REPLICATED_JOB}-0"
        assert env["TPU_WORKER_HOSTNAMES"].split(",")[0] == f"{child}-0.js-workers"
        assert env["BOBRA_COORDINATOR_ADDRESS"].startswith(f"{child}-0.js-workers:")
        svc = _by_kind(manifests)["Service"][0]
        assert svc["spec"]["publishNotReadyAddresses"] is True
