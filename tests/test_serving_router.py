"""Disaggregated prefill/decode serving: roles, router, KV handoff.

The contract under test everywhere: a disaggregated pool (prefill-role
engines exporting paged-KV through the SharedPrefixRegistry, decode
engines adopting via scatter, a prefix-aware router in front) emits
BYTE-IDENTICAL output to one unified engine serving the same requests —
across greedy and sampled streams, through the in-memory registry AND
the slice-local SSD tier (the test_serving_kv_persistence pattern), and
across live role reloads mid-stream. Plus the routing policy itself:
longest-matching-chain affinity, least-loaded fallback on a registry
miss, per-pool queue visibility, and the bench's router-hit-rate floor
pinned as a fast unit test so the headline win cannot silently rot.
"""

import jax
import numpy as np
import pytest

from bobrapet_tpu.config.operator import OperatorConfig, ServingConfig
from bobrapet_tpu.models import llama
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.serving import (
    PagedConfig,
    ServingEngine,
    ServingRouter,
    SharedPrefixRegistry,
)
from bobrapet_tpu.storage.store import SliceLocalSSDStore


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _pcfg(**over):
    kw = dict(max_slots=4, block_size=16, num_blocks=128,
              max_blocks_per_seq=8)
    kw.update(over)
    return PagedConfig(**kw)


def _prompts(cfg, n=6, seed=0, shared_blocks=3, tail=9):
    """n prompts sharing a ``shared_blocks``-block system prefix with
    unique tails (the prefix-heavy shape)."""
    rng = np.random.default_rng(seed)
    system = rng.integers(0, cfg.vocab_size, 16 * shared_blocks).tolist()
    return [system + rng.integers(0, cfg.vocab_size, tail + i).tolist()
            for i in range(n)]


def _unified_reference(model, prompts, max_new=8, temps=None):
    cfg, params = model
    eng = ServingEngine(params, cfg, _pcfg())
    for i, p in enumerate(prompts):
        eng.submit(list(p), max_new_tokens=max_new,
                   temperature=(temps[i] if temps else 0.0))
    return {r.rid: r.output for r in eng.run()}


def _disagg(model, reg, n_decode=1, prefill_threshold=0, **pf_over):
    cfg, params = model
    pf = ServingEngine(params, cfg, _pcfg(**pf_over), prefix_shared=reg,
                       role="prefill")
    decs = {
        f"d{i}": ServingEngine(params, cfg, _pcfg(), prefix_shared=reg,
                               role="decode")
        for i in range(n_decode)
    }
    router = ServingRouter({"pf": pf, **decs}, registry=reg,
                           prefill_threshold=prefill_threshold)
    return router, pf, decs


class TestLongestMatch:
    """Satellite: the explicit SharedPrefixRegistry.longest_match API
    (the router's probe — only exact chain-hash adoption existed)."""

    def test_depth_counts_leading_chain_blocks(self, model):
        cfg, params = model
        reg = SharedPrefixRegistry()
        eng = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        prompt = _prompts(cfg, n=1)[0]  # 3 full blocks + tail
        eng.submit(list(prompt), max_new_tokens=4)
        eng.run()
        assert reg.longest_match("bogus-scope", prompt, 16) == 0
        scope = eng.blocks.scope
        assert reg.longest_match(scope, prompt, 16) == 3
        # a diverging second block breaks the chain after one block
        forked = prompt[:16] + [(prompt[16] + 1) % cfg.vocab_size] \
            + prompt[17:]
        assert reg.longest_match(scope, forked, 16) == 1
        # salt scopes chains exactly like register/match_prefix
        assert reg.longest_match(scope, prompt, 16, salt=1) == 0

    def test_query_touches_lru(self, model):
        """A probed chain is a chain worth keeping: longest_match must
        refresh recency so the router's hot prompts survive eviction."""
        reg = SharedPrefixRegistry(max_entries=2)
        reg.put("s", b"a", {"k": np.zeros(1)})
        reg.put("s", b"b", {"k": np.zeros(1)})
        # touch "a" via the probe path, then insert a third entry:
        # "b" (now LRU) must be the one evicted
        assert reg.longest_match_hashes("s", [b"a"]) == 1
        reg.put("s", b"c", {"k": np.zeros(1)})
        assert reg.get("s", b"a") is not None
        assert reg.get("s", b"b") is None

    def test_partial_match_depth_metric_recorded(self):
        reg = SharedPrefixRegistry()
        reg.put("s", b"a", {"k": np.zeros(1)})
        n0 = metrics.serving_prefix_match_depth.count()
        s0 = metrics.serving_prefix_match_depth.sum()
        reg.longest_match_hashes("s", [b"a", b"missing"])
        assert metrics.serving_prefix_match_depth.count() == n0 + 1
        assert metrics.serving_prefix_match_depth.sum() == s0 + 1.0


class TestEngineRoles:
    def test_prefill_role_retires_at_first_token(self, model):
        cfg, params = model
        reg = SharedPrefixRegistry()
        eng = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg,
                            role="prefill")
        prompt = _prompts(cfg, n=1)[0]
        eng.submit(list(prompt), max_new_tokens=8)
        done = eng.run()
        assert len(done) == 1 and done[0].prefilled
        assert len(done[0].output) == 1  # the product: KV export + t0
        assert len(reg) >= 3  # full prompt blocks exported

    def test_prefill_role_eos_and_budget_complete_normally(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), role="prefill")
        prompt = _prompts(cfg, n=1)[0]
        eng.submit(list(prompt), max_new_tokens=1)  # budget at t0
        req = eng.run()[0]
        assert req.done and not req.prefilled

    def test_prefill_role_requires_prefix_caching(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="prefix_caching"):
            ServingEngine(params, cfg, _pcfg(prefix_caching=False),
                          role="prefill")
        eng = ServingEngine(params, cfg, _pcfg(prefix_caching=False))
        with pytest.raises(ValueError, match="prefix_caching"):
            eng.set_role("prefill")

    def test_bad_role_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="role"):
            ServingEngine(params, cfg, _pcfg(), role="verifier")
        eng = ServingEngine(params, cfg, _pcfg())
        with pytest.raises(ValueError, match="role"):
            eng.set_role("verifier")

    def test_submit_handoff_contract_validation(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg())
        with pytest.raises(ValueError, match="preseeded"):
            eng.submit([1, 2, 3], max_new_tokens=2, output=[5, 6])
        with pytest.raises(ValueError, match="rid"):
            eng.submit([1, 2, 3], max_new_tokens=2, rid=-1)
        # a pinned rid advances the engine's counter past it
        rid = eng.submit([1, 2, 3], max_new_tokens=2, rid=7)
        assert rid == 7
        assert eng.submit([1, 2, 3], max_new_tokens=2) == 8


class TestRouterPolicy:
    def test_prefix_hit_routes_to_deepest_chain_engine(self, model):
        """The engine already holding the chain wins over load."""
        cfg, params = model
        reg = SharedPrefixRegistry()
        router, _pf, decs = _disagg(model, reg, n_decode=2,
                                    prefill_threshold=10_000)
        prompts = _prompts(cfg, n=3)
        # seed: first request lands somewhere least-loaded and
        # registers the chain locally there
        r0 = router.submit(list(prompts[0]), max_new_tokens=4)
        router.run()
        owner = next(name for name, eng in decs.items()
                     if eng.blocks.longest_local_match(prompts[1]) > 0)
        # load the OTHER engine so least-loaded would pick it...
        # (rid=999 keeps this direct-to-engine traffic out of the
        # router's rid space — the router must ignore it at harvest)
        other = next(n for n in decs if n != owner)
        rng = np.random.default_rng(9)
        decs[other].submit(
            rng.integers(0, cfg.vocab_size, 8).tolist(),
            max_new_tokens=64, rid=999)
        # ...but the chain owner must win on affinity (budget > one
        # decode horizon so the request is still resident post-step)
        r1 = router.submit(list(prompts[1]), max_new_tokens=64)
        router.step()
        assert router.outcomes[r1] == "prefix-hit"
        assert any(s is not None and s.request.rid == r1
                   for s in decs[owner].slots) or any(
            q.rid == r1 for q in decs[owner].pending)
        router.run()
        assert router.outcomes[r0] == "miss"  # first ever: cold chain
        assert all(r.rid != 999 for r in router.finished)

    def test_registry_miss_falls_back_least_loaded(self, model):
        cfg, params = model
        reg = SharedPrefixRegistry()
        router, _pf, decs = _disagg(model, reg, n_decode=2,
                                    prefill_threshold=10_000)
        rng = np.random.default_rng(3)
        # nothing registered anywhere: every routing is a miss, and the
        # two decode engines share the load about evenly
        rids = [router.submit(rng.integers(0, cfg.vocab_size, 8).tolist(),
                              max_new_tokens=4) for _ in range(6)]
        router.run()
        assert all(router.outcomes[r] == "miss" for r in rids)
        served = [len([r for r in eng.finished]) for eng in decs.values()]
        assert min(served) >= 1  # least-loaded spread, not one hot spot

    def test_affinity_off_is_pure_least_loaded(self, model):
        cfg, params = model
        reg = SharedPrefixRegistry()
        router, _pf, _decs = _disagg(model, reg, n_decode=1,
                                     prefill_threshold=10_000)
        router.set_prefix_affinity(False)
        prompts = _prompts(cfg, n=2)
        for p in prompts:
            router.submit(list(p), max_new_tokens=4)
        router.run()
        assert all(o == "miss" for o in router.outcomes.values())

    def test_hit_rate_floor_on_prefix_heavy_leg(self, model):
        """CI floor for the bench's headline router-hit-rate: on a
        prefix-heavy workload the disaggregated router must route at
        least half the decode admissions by prefix chain (the bench
        asserts >= 0.5 on the same shape; this pins it fast)."""
        cfg, params = model
        reg = SharedPrefixRegistry()
        router, _pf, _decs = _disagg(model, reg, prefill_threshold=32)
        prompts = _prompts(cfg, n=6)
        for p in prompts:
            router.submit(list(p), max_new_tokens=4)
        fin = router.run()
        assert len(fin) == 6
        handoffs = [r for r in fin if r.kv_handoff_s is not None]
        assert len(handoffs) == 6  # every long went through the pool
        hits = [r for r in handoffs
                if router.outcomes[r.rid] == "prefix-hit"]
        assert len(hits) / len(handoffs) >= 0.5
        assert router.hit_rate >= 0.5

    def test_pool_queue_metrics_emitted(self, model):
        cfg, params = model
        reg = SharedPrefixRegistry()
        router, _pf, _decs = _disagg(model, reg, prefill_threshold=0)
        w0 = {p: metrics.serving_pool_wait.count(p)
              for p in ("prefill", "decode")}
        k0 = metrics.serving_kv_handoff.count()
        for p in _prompts(cfg, n=3):
            router.submit(list(p), max_new_tokens=4)
        router.run()
        # both pools admitted work (handoffs ride the decode pool)
        assert metrics.serving_pool_wait.count("prefill") > w0["prefill"]
        assert metrics.serving_pool_wait.count("decode") > w0["decode"]
        assert metrics.serving_pool_depth.value("prefill") == 0.0
        assert metrics.serving_pool_depth.value("decode") == 0.0
        assert metrics.serving_kv_handoff.count() == k0 + 3


class TestHandoffAccounting:
    """The PR-8 SLO plane must see a routed request ONCE, end to end —
    not as two short requests split at the handoff."""

    def test_slo_plane_counts_routed_request_once(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, n=3, seed=21)
        c0 = metrics.serving_requests.value("completed")
        e0 = metrics.serving_e2e_latency.count("", "")
        t0 = metrics.serving_ttft.count("", "")
        q0 = metrics.serving_queue_wait.count("", "")
        reg = SharedPrefixRegistry()
        router, _pf, decs = _disagg(model, reg)
        for p in prompts:
            router.submit(list(p), max_new_tokens=8)
        fin = router.run()
        # one completion / e2e / ttft / queue-wait observation per
        # USER request — the prefill leg is a continuation, not a
        # completion, and the decode leg must not re-observe TTFT
        assert metrics.serving_requests.value("completed") == c0 + 3
        assert metrics.serving_e2e_latency.count("", "") == e0 + 3
        assert metrics.serving_ttft.count("", "") == t0 + 3
        assert metrics.serving_queue_wait.count("", "") == q0 + 3
        # the decode-side request carries the ORIGINAL submit clock, so
        # its e2e spans the whole request (>= the handoff latency)
        for r in fin:
            assert r.kv_handoff_s is not None
            assert (r.finished_at - r.submitted_at) >= r.kv_handoff_s


class TestHandoffParity:
    """Decode output byte-identical to the unified reference across
    the prefill->decode KV handoff."""

    def test_greedy_handoff_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, n=6, seed=11)
        ref = _unified_reference(model, prompts)
        reg = SharedPrefixRegistry()
        router, pf, _decs = _disagg(model, reg)
        for p in prompts:
            router.submit(list(p), max_new_tokens=8)
        got = {r.rid: r.output for r in router.run()}
        assert got == ref
        # and the decode side really adopted instead of re-prefilling
        assert sum(d.blocks.shared_hits for d in _decs.values()) >= 3

    def test_sampled_handoff_byte_identical(self, model):
        """rid pinning keeps sampled streams a pure function of
        (seed, rid, index) ACROSS the engine switch."""
        cfg, _ = model
        prompts = _prompts(cfg, n=4, seed=12)
        temps = [0.0, 0.8, 1.1, 0.7]
        ref = _unified_reference(model, prompts, temps=temps)
        reg = SharedPrefixRegistry()
        router, _pf, _decs = _disagg(model, reg)
        for i, p in enumerate(prompts):
            router.submit(list(p), max_new_tokens=8, temperature=temps[i])
        assert {r.rid: r.output for r in router.run()} == ref

    def test_handoff_through_ssd_tier_byte_identical(self, model, tmp_path):
        """The PR-10 pattern extended: the registry's memory LRU is too
        small to hold the chain, so the handoff adoption reads back
        through the slice-local SSD tier — output still byte-identical
        and the handoff latency still recorded per request."""
        cfg, _ = model
        prompts = _prompts(cfg, n=4, seed=13)
        ref = _unified_reference(model, prompts)
        tier = SliceLocalSSDStore(str(tmp_path / "tier"))
        reg = SharedPrefixRegistry(max_entries=1)  # evicts ~everything
        reg.attach_spill(tier)
        router, _pf, _decs = _disagg(model, reg)
        for p in prompts:
            router.submit(list(p), max_new_tokens=8)
        fin = router.run()
        assert {r.rid: r.output for r in fin} == ref
        assert len(tier.list("kv/")) >= 3  # the chain went through disk
        assert all(r.kv_handoff_s is not None and r.kv_handoff_s >= 0
                   for r in fin)

    def test_handoff_fast_path_skips_suffix_prefill(self, model):
        """A block-aligned prompt's handoff needs ZERO prefill
        dispatches on the decode side: the adopted chain covers every
        cached position and the already-sampled first token is the next
        decode input."""
        cfg, params = model
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, cfg.vocab_size, 64).tolist()  # 4 blocks
        ref = _unified_reference(model, [prompt])
        reg = SharedPrefixRegistry()
        router, _pf, decs = _disagg(model, reg)
        router.submit(list(prompt), max_new_tokens=8)
        fin = router.run()
        dec = next(iter(decs.values()))
        assert dec.phase_seconds["prefill"] == 0.0  # no suffix forward
        assert {r.rid: r.output for r in fin} == ref


class TestRoleReload:
    def test_demoted_prefill_engine_drains_unified(self, model):
        """serving.role reload mid-stream: a prefill engine demoted to
        unified keeps decoding its in-flight requests to completion —
        nothing dropped, nothing stuck, outputs byte-identical."""
        cfg, _ = model
        prompts = _prompts(cfg, n=4, seed=14)
        ref = _unified_reference(model, prompts)
        reg = SharedPrefixRegistry()
        router, pf, _decs = _disagg(model, reg)
        for p in prompts:
            router.submit(list(p), max_new_tokens=8)
        router.step()  # work in flight on the prefill engine
        pf.set_role("unified")  # live demotion
        fin = router.run()
        assert {r.rid: r.output for r in fin} == ref
        assert len(fin) == 4

    def test_empty_prefill_pool_reroutes_queued_work(self, model):
        """Demotion with requests still QUEUED for the prefill pool:
        they drain through the decode pool instead of deadlocking."""
        cfg, _ = model
        prompts = _prompts(cfg, n=3, seed=15)
        ref = _unified_reference(model, prompts)
        reg = SharedPrefixRegistry()
        router, pf, _decs = _disagg(model, reg)
        for p in prompts:
            router.submit(list(p), max_new_tokens=8)
        pf.set_role("unified")  # before ANY step: queue still full
        fin = router.run()
        assert {r.rid: r.output for r in fin} == ref

    def test_apply_tuning_applies_role_and_router_knobs(self, model):
        """The live-reload path: serving.role retunes engines (step-
        pinned roles survive), serving.router-* retunes live routers."""
        from bobrapet_tpu.serving import engram

        cfg, params = model
        reg = SharedPrefixRegistry()
        eng = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        pinned = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg,
                               role="prefill")
        pinned._engram_pinned = frozenset(["role"])
        router = ServingRouter({"a": eng, "b": pinned}, registry=reg)
        # engines built outside build_engine join the reload set here
        engram._LIVE_ENGINES.add(eng)
        engram._LIVE_ENGINES.add(pinned)
        try:
            engram.apply_tuning(ServingConfig(
                role="decode", router_prefill_threshold=128,
                router_prefix_affinity=False))
            assert eng.role == "decode"
            assert pinned.role == "prefill"  # step-pinned survives
            assert router.prefill_threshold == 128
            assert router.prefix_affinity is False
        finally:
            engram.apply_tuning(ServingConfig())
        assert eng.role == "unified"
        assert router.prefill_threshold == 0


class TestConfigKeys:
    def test_serving_role_and_router_keys_parse_and_validate(self):
        from bobrapet_tpu.config.operator import _apply_dotted

        cfg = OperatorConfig()
        assert _apply_dotted(cfg, "serving.role", "prefill")
        assert cfg.serving.role == "prefill"
        assert _apply_dotted(cfg, "serving.router-prefill-threshold", "256")
        assert cfg.serving.router_prefill_threshold == 256
        assert _apply_dotted(cfg, "serving.router-prefix-affinity", "false")
        assert cfg.serving.router_prefix_affinity is False
        assert not cfg.validate()

    def test_validation_rejects_bad_values(self):
        cfg = OperatorConfig()
        cfg.serving.role = "verifier"
        assert any("serving.role" in e for e in cfg.validate())
        cfg = OperatorConfig()
        cfg.serving.router_prefill_threshold = -1
        assert any("router-prefill-threshold" in e for e in cfg.validate())

    def test_build_engine_role_step_key(self, model, tmp_path):
        """The step `role` key pins the engine role; prefill without
        prefix caching fails loudly when explicit, degrades when the
        role came from the global knob."""
        from bobrapet_tpu.serving.engram import build_engine

        class Ctx:
            config = {"model": "tiny", "role": "prefill",
                      "prefixShared": True}
            storage = None
            step = "s"
            trace_context = None

        eng = build_engine(Ctx())
        assert eng.role == "prefill"
        assert "role" in eng._engram_pinned

        class Bad(Ctx):
            config = {"model": "tiny", "role": "prefill",
                      "paging": {"prefixCaching": False}}

        with pytest.raises(ValueError, match="prefixCaching"):
            build_engine(Bad())

        class NoShare(Ctx):
            # explicit prefill with sharing off: the engine's product
            # (exported blocks) would go nowhere — config contradiction
            config = {"model": "tiny", "role": "prefill",
                      "prefixShared": False}

        with pytest.raises(ValueError, match="prefix sharing"):
            build_engine(NoShare())


class TestRouterStreamSurface:
    def test_router_duck_types_stream_server_surface(self, model):
        """StreamServer drives a router exactly like an engine."""
        from bobrapet_tpu.serving.service import StreamServer

        cfg, _ = model
        reg = SharedPrefixRegistry()
        router, _pf, _decs = _disagg(model, reg)
        prompts = _prompts(cfg, n=3, seed=16)
        ref = _unified_reference(model, prompts)

        msgs = [{"id": i, "prompt": p, "maxNewTokens": 8}
                for i, p in enumerate(prompts)]
        out = []

        class Producer:
            def send(self, payload, **kw):
                out.append(payload)

            def close(self):
                pass

        server = StreamServer(router, iter(msgs), Producer(),
                              trace_context={"traceId": "t" * 32})
        served = server.run()
        assert served == 3
        got = {m["id"]: m["tokens"] for m in out}
        assert got == {i: ref[i] for i in range(3)}
