"""Packaging/entrypoint: CRD schema export, manager CLI, serving.

(reference: cmd/main.go flags/health/metrics serving :113-151,:445-483,
:941; generated CRD YAML config/crd/bases/ — SURVEY layer 6.)
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from bobrapet_tpu.api.schemas import all_crd_manifests, crd_manifest, _registry


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestCRDGeneration:
    def test_all_twelve_kinds(self):
        manifests = all_crd_manifests()
        assert len(manifests) == 12
        kinds = {m["spec"]["names"]["kind"] for m in manifests}
        assert kinds == {
            "Story", "Engram", "Impulse", "StoryRun", "StepRun",
            "StoryTrigger", "EffectClaim", "EngramTemplate",
            "ImpulseTemplate", "Transport", "TransportBinding",
            "ReferenceGrant",
        }

    def test_story_schema_structure(self):
        entry = next(e for e in _registry() if e.kind == "Story")
        m = crd_manifest(entry)
        assert m["metadata"]["name"] == "stories.bobrapet.io"
        version = m["spec"]["versions"][0]
        assert version["subresources"] == {"status": {}}
        spec_schema = version["schema"]["openAPIV3Schema"]["properties"]["spec"]
        steps = spec_schema["properties"]["steps"]
        assert steps["type"] == "array"
        step_props = steps["items"]["properties"]
        # snake_py -> camelYaml, trailing-underscore keywords unmangled
        assert "if" in step_props and "with" in step_props
        assert "allowFailure" in step_props
        assert step_props["type"]["enum"]  # StepType enum rendered
        # nested dataclass expansion (TPUPolicy)
        assert "accelerator" in step_props["tpu"]["properties"]

    def test_cluster_scoped_kinds(self):
        scopes = {e.kind: e.scope for e in _registry()}
        assert scopes["EngramTemplate"] == "Cluster"
        assert scopes["Transport"] == "Cluster"
        assert scopes["StoryRun"] == "Namespaced"

    def test_status_left_open(self):
        for m in all_crd_manifests():
            status = m["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
                "properties"]["status"]
            assert status.get("x-kubernetes-preserve-unknown-fields") is True

    def test_checked_in_crds_current(self):
        """deploy/crds must match the generator (the reference keeps
        generated CRD YAML committed and CI-checked)."""
        import yaml

        for entry, manifest in zip(_registry(), all_crd_manifests()):
            path = os.path.join(
                "deploy", "crds", f"{entry.group}_{entry.plural}.yaml"
            )
            assert os.path.exists(path), f"{path} missing — run make crds"
            with open(path) as f:
                on_disk = yaml.safe_load(f)
            assert on_disk == manifest, f"{path} stale — run make crds"


class TestManagerCLI:
    def test_export_crds_cli(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "bobrapet_tpu", "export-crds",
             "--out", str(tmp_path / "crds")],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stderr
        files = os.listdir(tmp_path / "crds")
        assert len(files) == 12

    def test_manager_serves_health_and_metrics(self, tmp_path):
        port = _free_port()
        token_file = tmp_path / "token"
        token_file.write_text("s3cret")
        proc = subprocess.Popen(
            [sys.executable, "-m", "bobrapet_tpu", "manager",
             "--metrics-bind-address", f"127.0.0.1:{port}",
             "--metrics-token-file", str(token_file),
             "--persist-dir", str(tmp_path / "store")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            deadline = time.monotonic() + 60
            last_err = None
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/readyz", timeout=1
                    ) as resp:
                        assert resp.status == 200
                        break
                except (urllib.error.URLError, ConnectionError, OSError) as e:
                    last_err = e
                    time.sleep(0.2)
            else:
                raise AssertionError(f"manager never ready: {last_err}")

            # metrics guarded by the bearer token
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                )
            assert exc.value.code == 403
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics",
                headers={"Authorization": "Bearer s3cret"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                page = resp.read().decode()
            assert "bobrapet_reconcile_total" in page

            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=20) == 0
        finally:
            if proc.poll() is None:
                proc.kill()


class TestLeaderElection:
    """flock-based lease (reference: cmd/main.go --leader-elect)."""

    def test_exclusive_acquisition_and_handover(self, tmp_path):
        from bobrapet_tpu.utils.leader import FileLeaderElector

        lease = str(tmp_path / "leader.lock")
        a = FileLeaderElector(lease)
        b = FileLeaderElector(lease)
        assert a.try_acquire() is True
        assert a.is_leader
        assert b.try_acquire() is False  # held exclusively
        assert b.holder() == a.identity
        a.release()
        assert b.try_acquire() is True  # handover after release
        b.release()

    def test_acquire_blocks_until_leadership(self, tmp_path):
        import threading

        from bobrapet_tpu.utils.leader import FileLeaderElector

        lease = str(tmp_path / "leader.lock")
        a = FileLeaderElector(lease)
        assert a.try_acquire()
        b = FileLeaderElector(lease)
        won = threading.Event()

        def contend():
            if b.acquire(poll_interval=0.05):
                won.set()

        t = threading.Thread(target=contend, daemon=True)
        t.start()
        assert not won.wait(0.3)  # still held by a
        a.release()
        assert won.wait(5)
        b.release()

    def test_lock_survives_across_processes(self, tmp_path):
        """The lease is a real kernel flock, not an in-process latch."""
        import subprocess
        import sys

        from bobrapet_tpu.utils.leader import FileLeaderElector

        lease = str(tmp_path / "leader.lock")
        a = FileLeaderElector(lease)
        assert a.try_acquire()
        probe = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, '.');"
             "from bobrapet_tpu.utils.leader import FileLeaderElector;"
             f"print(FileLeaderElector({lease!r}).try_acquire())"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert probe.stdout.strip() == "False", probe.stderr
        a.release()


class TestHelmChart:
    """Chart parity (VERDICT r2 #9): templates render cleanly through
    the no-helm subset renderer; ServiceMonitor/NetworkPolicy/shared-CA
    gate on values; rendered docs are valid Kubernetes-shaped YAML."""

    CHART = os.path.join(os.path.dirname(__file__), "..", "deploy", "chart",
                         "bobrapet-tpu")

    def _render(self, **values):
        from bobrapet_tpu.gke.chart import render_chart_manifests

        return render_chart_manifests(self.CHART, values=values or None)

    def test_default_render_is_valid_and_complete(self):
        docs = self._render()
        kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
        assert ("Deployment", "bobrapet-manager") in kinds
        assert ("Deployment", "bobravoz-hub") in kinds
        assert ("Service", "bobravoz-hub") in kinds
        assert ("ServiceAccount", "bobrapet-manager") in kinds
        assert ("Role", "bobrapet-leader-election") in kinds
        assert ("PersistentVolumeClaim", "bobrapet-store") in kinds
        for d in docs:
            assert d.get("apiVersion") and d.get("kind")
            assert d["metadata"].get("name")
        # defaults exclude the gated extras
        assert not [k for k, _ in kinds if k in
                    ("ServiceMonitor", "NetworkPolicy", "Certificate")]
        # manager args wired from values
        mgr = next(d for d in docs
                   if (d["kind"], d["metadata"]["name"]) == ("Deployment", "bobrapet-manager"))
        args = mgr["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--leader-elect" in args
        assert "--persist-dir=/var/lib/bobrapet/store" in args
        # stock-cluster default: one replica over RWO (HA is opt-in:
        # replicas 2 + accessMode ReadWriteMany on an RWX class)
        assert mgr["spec"]["replicas"] == 1
        pvc = next(d for d in docs if d["kind"] == "PersistentVolumeClaim")
        assert pvc["spec"]["accessModes"] == ["ReadWriteOnce"]
        ha = self._render(replicas=2,
                          persistence={"accessMode": "ReadWriteMany"})
        pvc = next(d for d in ha if d["kind"] == "PersistentVolumeClaim")
        assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]

    def test_gated_monitoring_and_security_render(self):
        docs = self._render(
            metrics={"serviceMonitor": True, "networkPolicy": True},
            certManager={"enabled": True},
            hub={"tls": True},
        )
        kinds = {d["kind"] for d in docs}
        assert {"ServiceMonitor", "NetworkPolicy", "Certificate",
                "ClusterIssuer", "Issuer"} <= kinds
        # TLS hub mounts the cert-manager secret and passes --tls-dir
        hub = next(d for d in docs
                   if (d["kind"], d["metadata"]["name"]) == ("Deployment", "bobravoz-hub"))
        c = hub["spec"]["template"]["spec"]["containers"][0]
        assert "--tls-dir=/var/run/bobrapet/tls" in c["args"]
        assert hub["spec"]["template"]["spec"]["volumes"][0]["secret"][
            "secretName"] == "bobrapet-hub-tls"

    def test_webhooks_without_certmanager_render_nothing(self):
        """webhooks.enabled without certManager.enabled must not render
        ANY webhook artifact: a failurePolicy=Fail configuration whose
        serving cert can never be issued would block every CR write in
        the cluster (the chart subset renderer has no `fail`, so the
        guard is render-to-nothing + this test)."""
        docs = self._render(webhooks={"enabled": True})
        kinds = {d["kind"] for d in docs}
        assert "ValidatingWebhookConfiguration" not in kinds
        assert "MutatingWebhookConfiguration" not in kinds
        mgr = next(d for d in docs
                   if (d["kind"], d["metadata"]["name"]) ==
                   ("Deployment", "bobrapet-manager"))
        args = mgr["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--serve-webhooks" not in args
        assert not any(
            v.get("secret") for v in
            mgr["spec"]["template"]["spec"]["volumes"] or []
        )

    def test_webhook_serving_render_matches_registered_chain(self):
        """The chart's static webhook list cannot drift from what the
        manager actually registers: rendered resources == the
        programmatic webhook_configurations() coverage."""
        docs = self._render(
            certManager={"enabled": True}, webhooks={"enabled": True},
        )
        by_kind = {d["kind"]: d for d in docs}
        assert "MutatingWebhookConfiguration" in by_kind
        assert "ValidatingWebhookConfiguration" in by_kind
        svc = next(d for d in docs
                   if (d["kind"], d["metadata"]["name"]) ==
                   ("Service", "bobrapet-webhook-service"))
        assert svc["spec"]["ports"][0]["targetPort"] == 9443

        from bobrapet_tpu.cluster.admission import webhook_configurations
        from bobrapet_tpu.runtime import Runtime

        rt = Runtime()
        programmatic = webhook_configurations(
            rt.store, "https://x:9443", "CA"
        )
        for cfg_kind in ("MutatingWebhookConfiguration",
                        "ValidatingWebhookConfiguration"):
            want = {
                r
                for c in programmatic if c["kind"] == cfg_kind
                for w in c["webhooks"] for rule in w["rules"]
                for r in rule["resources"]
            }
            got = {
                r
                for w in by_kind[cfg_kind]["webhooks"]
                for rule in w["rules"] for r in rule["resources"]
            }
            assert got == want, (cfg_kind, got ^ want)
            # every chart hook routes to a path the server actually
            # serves, through the in-cluster Service
            from bobrapet_tpu.cluster.admission import _PATH_TO_KIND

            for w in by_kind[cfg_kind]["webhooks"]:
                path = w["clientConfig"]["service"]["path"]
                assert path in _PATH_TO_KIND, path
                assert w["clientConfig"]["service"]["name"] == (
                    "bobrapet-webhook-service")

        # manager args + cert mount wired
        mgr = next(d for d in docs
                   if (d["kind"], d["metadata"]["name"]) ==
                   ("Deployment", "bobrapet-manager"))
        args = mgr["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--serve-webhooks" in args
        assert "--webhook-certs-dir=/var/run/webhook-certs" in args
        assert "--skip-webhook-registration" in args
        vols = mgr["spec"]["template"]["spec"]["volumes"]
        assert any(
            v.get("secret", {}).get("secretName") ==
            "bobrapet-webhook-server-cert" for v in vols
        )

    def test_disabled_persistence_drops_pvc_and_flag(self):
        docs = self._render(persistence={"enabled": False})
        assert not [d for d in docs if d["kind"] == "PersistentVolumeClaim"]
        mgr = next(d for d in docs
                   if (d["kind"], d["metadata"]["name"]) == ("Deployment", "bobrapet-manager"))
        args = mgr["spec"]["template"]["spec"]["containers"][0]["args"]
        assert not [a for a in args if a.startswith("--persist-dir")]

    def test_export_chart_cli(self, tmp_path):
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, "-m", "bobrapet_tpu", "export-chart",
             "--out", str(tmp_path / "rendered")],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        files = os.listdir(tmp_path / "rendered")
        assert "deployment.yaml" in files

    def test_make_test_e2e_smoke(self):
        """The gated e2e target runs green in this environment (falls
        back to the no-container packaging smoke without docker)."""
        import subprocess

        out = subprocess.run(
            ["make", "test-e2e"],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.join(os.path.dirname(__file__), ".."),
        )
        assert out.returncode == 0, out.stderr + out.stdout
        assert "OK" in out.stdout


class TestSamples:
    """Admission-valid sample CRs for every kind (the reference ships
    empty spec templates in config/samples; these are real)."""

    def test_definition_samples_admit_through_webhooks(self):
        from bobrapet_tpu.api.samples import definition_samples
        from bobrapet_tpu.runtime import Runtime

        rt = Runtime()  # webhooks ENABLED
        for r in definition_samples():
            rt.apply(r)  # raises AdmissionDenied on any invalid sample
        rt.pump()
        story = rt.store.get("Story", "default", "rag")
        assert story.status["validationStatus"] == "valid"

    def test_export_covers_every_kind(self, tmp_path):
        import yaml

        from bobrapet_tpu.api.samples import export_samples

        paths = export_samples(str(tmp_path))
        kinds = set()
        for p in paths:
            with open(p) as f:
                doc = yaml.safe_load(f)
            assert doc["apiVersion"].endswith("/v1alpha1")
            assert doc["spec"]
            kinds.add(doc["kind"])
        assert kinds == {
            "Story", "Engram", "Impulse", "StoryRun", "StepRun",
            "StoryTrigger", "EffectClaim", "EngramTemplate",
            "ImpulseTemplate", "Transport", "TransportBinding",
            "ReferenceGrant",
        }

    def test_checked_in_samples_current(self):
        """deploy/samples must match a fresh export (definition kinds:
        exact; harvested kinds: same file names)."""
        import subprocess
        import sys

        repo = os.path.join(os.path.dirname(__file__), "..")
        out = subprocess.run(
            [sys.executable, "-m", "bobrapet_tpu", "export-samples",
             "--out", "deploy/samples"],
            capture_output=True, text=True, timeout=300, cwd=repo,
        )
        assert out.returncode == 0, out.stderr
        # porcelain status catches modified AND untracked (a bare git
        # diff is blind to brand-new sample files)
        diff = subprocess.run(
            ["git", "status", "--porcelain", "--", "deploy/samples"],
            capture_output=True, text=True, cwd=repo,
        )
        assert diff.stdout.strip() == "", (
            f"checked-in samples stale:\n{diff.stdout}"
        )
