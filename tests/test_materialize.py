"""Materialize subsystem: offloaded-data policy fail / inject / controller.

(reference: internal/controller/runs/materialize.go,
templating_policy.go, offloaded_refs.go test coverage model)

The controller policy delegates condition evaluation over offloaded
step output to a dedicated materialize StepRun whose input ships with
storage refs intact; the SDK hydrates in-pod and reports the result.
"""

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.enums import OffloadedDataPolicy
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.controllers.materialize import (
    DEFAULT_MATERIALIZE_ENGRAM,
    MATERIALIZE_ANNOTATION,
    materialize_name,
)
from bobrapet_tpu.core.object import new_resource
from bobrapet_tpu.sdk import register_engram


BIG = "x" * 100_000  # exceeds the 16KiB inline limit -> SDK offloads


def _setup(rt, policy):
    rt.config_manager.config.templating.offloaded_data_policy = policy
    ran = []
    rt.apply(make_engram_template("prod-tpl", entrypoint="prod-impl"))
    rt.apply(make_engram("producer", "prod-tpl"))
    rt.apply(make_engram_template("cons-tpl", entrypoint="cons-impl"))
    rt.apply(make_engram("consumer", "cons-tpl"))

    @register_engram("prod-impl")
    def produce(ctx):
        return {"blob": BIG, "flag": "go"}

    @register_engram("cons-impl")
    def consume(ctx):
        ran.append(ctx.step)
        return {"done": True}

    return ran


def _story(condition):
    return make_story("mat", steps=[
        {"name": "big", "ref": {"name": "producer"}},
        {"name": "gated", "ref": {"name": "consumer"}, "needs": ["big"],
         "if": condition},
    ])


class TestPolicies:
    def test_fail_policy_fails_step(self, rt):
        _setup(rt, OffloadedDataPolicy.FAIL)
        rt.apply(_story("{{ steps.big.output.blob }}"))
        run = rt.run_story("mat")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Failed"
        assert r.status["stepStates"]["gated"]["reason"] == "OffloadedDataPolicy"

    def test_inject_policy_hydrates_in_controller(self, rt):
        ran = _setup(rt, OffloadedDataPolicy.INJECT)
        rt.apply(_story("{{ steps.big.output.blob }}"))
        run = rt.run_story("mat")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        assert ran == ["gated"]

    def test_controller_policy_runs_materialize_delegate(self, rt):
        ran = _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.apply(_story("{{ steps.big.output.blob }}"))
        run = rt.run_story("mat")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        assert ran == ["gated"]
        # the delegate StepRun exists, annotated, owned by the run,
        # bound to the managed engram, and its input kept storage refs
        mat = rt.store.get("StepRun", "default", materialize_name(run, "gated"))
        assert mat.meta.annotations[MATERIALIZE_ANNOTATION] == "true"
        assert mat.spec["engramRef"]["name"] == DEFAULT_MATERIALIZE_ENGRAM
        blob = mat.spec["input"]["scope"]["steps"]["big"]["output"]["blob"]
        assert "storageRef" in blob
        assert mat.status["output"]["result"] is True

    def test_controller_policy_false_condition_skips(self, rt):
        ran = _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.apply(_story("{{ steps.big.output.blob == 'nope' }}"))
        run = rt.run_story("mat")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        assert ran == []
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["stepStates"]["gated"]["phase"] == "Skipped"
        assert r.status["stepStates"]["gated"]["reason"] == "ConditionFalse"

    def test_spoofed_delegate_refused(self, rt):
        _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.apply(_story("{{ steps.big.output.blob }}"))
        # plant a foreign StepRun at the deterministic delegate name
        from bobrapet_tpu.api.runs import make_storyrun

        run_name = "mat-run-spoof"
        rt.store.create(new_resource(
            "StepRun", materialize_name(run_name, "gated"), "default",
            spec={"stepId": "gated#materialize",
                  "storyRunRef": {"name": "some-other-run"},
                  "engramRef": {"name": "consumer"}},
        ))
        rt.store.create(make_storyrun(run_name, "mat", {}, "default"))
        rt.pump()
        r = rt.store.get("StoryRun", "default", run_name)
        assert r.status["phase"] == "Failed"
        assert r.status["stepStates"]["gated"]["reason"] == "OffloadedDataPolicy"
        assert "not owned" in r.status["stepStates"]["gated"]["message"]

    def test_builtin_survives_registry_clear(self):
        from bobrapet_tpu.sdk.registry import clear_registry, get_engram

        clear_registry()
        assert get_engram("bobrapet.materialize") is not None

    def test_wait_until_over_offloaded_data_controller_policy(self, rt):
        """A wait primitive polling offloaded output under the controller
        policy resolves through the materialize delegate."""
        _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.apply(make_story("mat-wait", steps=[
            {"name": "big", "ref": {"name": "producer"}},
            {"name": "w", "type": "wait", "needs": ["big"],
             "with": {"until": "{{ steps.big.output.flag == 'go' }}",
                      "timeout": "5m", "pollInterval": "1s"}},
        ]))
        run = rt.run_story("mat-wait")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Succeeded"
        assert r.status["stepStates"]["w"]["phase"] == "Succeeded"


class TestAdviceRegressions:
    def test_delegate_inherits_scheduling_labels(self, rt):
        """The materialize delegate carries the parent run's queue and
        priority labels so it is accounted against the same queue's
        max_concurrent (reference: applySchedulingLabelsFromStoryRun)."""
        from bobrapet_tpu.controllers.step_executor import (
            LABEL_PRIORITY,
            LABEL_QUEUE,
        )

        _setup(rt, OffloadedDataPolicy.CONTROLLER)
        story = _story("{{ steps.big.output.blob }}")
        story.spec["policy"] = {"queue": "tpu-pool", "priority": 7}
        rt.apply(story)
        run = rt.run_story("mat")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        mat = rt.store.get("StepRun", "default", materialize_name(run, "gated"))
        assert mat.meta.labels[LABEL_QUEUE] == "tpu-pool"
        assert mat.meta.labels[LABEL_PRIORITY] == "7"

    def test_missing_configured_engram_fails_step(self, rt):
        """A non-default materialize engram that doesn't exist is a
        config error surfaced immediately — not an eternally-Blocked
        delegate polled at 1s (ADVICE: materialize.py:118)."""
        _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.config_manager.config.templating.materialize_engram = "no-such-engram"
        rt.apply(_story("{{ steps.big.output.blob }}"))
        run = rt.run_story("mat")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Failed"
        assert "no-such-engram" in r.status["stepStates"]["gated"]["message"]

    def test_blocked_delegate_checked_against_its_own_engram(self, rt):
        """When the configured materialize engram changes AFTER a
        delegate was created, the Blocked check must consult the
        delegate's own engramRef (whose engram vanished), not the new
        config value — else the dead delegate is polled forever."""
        from bobrapet_tpu.api.runs import make_storyrun
        from bobrapet_tpu.controllers.materialize import (
            MaterializeFailed,
            resolve_materialize,
        )

        _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.apply(make_engram_template("mat-y-tpl", entrypoint="mat-y-impl"))
        rt.apply(make_engram("mat-y", "mat-y-tpl"))
        run = rt.store.create(make_storyrun("r1", "mat", {}, "default"))
        # delegate bound to the OLD configured engram mat-x (now gone),
        # Blocked by the StepRun controller
        delegate = new_resource(
            "StepRun", materialize_name("r1", "gated"), "default",
            spec={"storyRunRef": {"name": "r1"},
                  "stepId": "gated#materialize",
                  "engramRef": {"name": "mat-x"},
                  "input": {"expression": "x", "scope": {}}},
            owners=[run.owner_ref()],
        )
        delegate.status.update({
            "phase": "Blocked",
            "conditions": [{"type": "Ready", "status": "False",
                            "reason": "ReferenceNotFound",
                            "message": "engram 'mat-x' not found"}],
        })
        rt.store.create(delegate)
        # config has moved on to healthy mat-y; the delegate is still dead
        with pytest.raises(MaterializeFailed, match="Blocked"):
            resolve_materialize(
                rt.store, run, "gated", "x", {}, engram_name="mat-y"
            )

    def test_blocked_delegate_with_live_engram_keeps_polling(self, rt):
        """Inverse: a delegate whose OWN engram is healthy must not be
        failed just because the configured name is currently broken —
        the stale Blocked condition self-heals."""
        from bobrapet_tpu.api.runs import make_storyrun
        from bobrapet_tpu.controllers.materialize import resolve_materialize

        _setup(rt, OffloadedDataPolicy.CONTROLLER)
        rt.apply(make_engram_template("mat-y-tpl", entrypoint="mat-y-impl"))
        rt.apply(make_engram("mat-y", "mat-y-tpl"))
        run = rt.store.create(make_storyrun("r2", "mat", {}, "default"))
        delegate = new_resource(
            "StepRun", materialize_name("r2", "gated"), "default",
            spec={"storyRunRef": {"name": "r2"},
                  "stepId": "gated#materialize",
                  "engramRef": {"name": "mat-y"},
                  "input": {"expression": "x", "scope": {}}},
            owners=[run.owner_ref()],
        )
        delegate.status.update({
            "phase": "Blocked",
            "conditions": [{"type": "Ready", "status": "False",
                            "reason": "ReferenceNotFound",
                            "message": "stale"}],
        })
        rt.store.create(delegate)
        assert resolve_materialize(
            rt.store, run, "gated", "x", {}, engram_name="missing-now"
        ) is None
