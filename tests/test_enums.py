"""Phase machine + enum vocabulary tests (mirrors reference pkg/enums semantics)."""

from bobrapet_tpu.api.enums import (
    BATCH_ONLY_PRIMITIVES,
    ExitClass,
    Phase,
    StepType,
    StopMode,
    StoryPattern,
    WorkloadMode,
)


def test_terminal_phases():
    terminal = {
        Phase.SUCCEEDED,
        Phase.FAILED,
        Phase.FINISHED,
        Phase.CANCELED,
        Phase.COMPENSATED,
        Phase.TIMEOUT,
        Phase.ABORTED,
        Phase.SKIPPED,
    }
    for p in Phase:
        assert p.is_terminal == (p in terminal), p


def test_nonterminal_phases_recoverable():
    for p in (Phase.PENDING, Phase.RUNNING, Phase.PAUSED, Phase.BLOCKED, Phase.SCHEDULING):
        assert not p.is_terminal


def test_stop_mode_terminal_phase():
    assert StopMode.SUCCESS.terminal_phase is Phase.SUCCEEDED
    assert StopMode.FAILURE.terminal_phase is Phase.FAILED
    assert StopMode.CANCEL.terminal_phase is Phase.FINISHED


def test_exit_class_retry_budget():
    # Unknown exit retries without consuming the budget
    assert ExitClass.UNKNOWN.is_retryable
    assert not ExitClass.UNKNOWN.consumes_retry_budget
    assert ExitClass.RETRY.is_retryable and ExitClass.RETRY.consumes_retry_budget
    assert ExitClass.RATE_LIMITED.is_retryable
    assert not ExitClass.TERMINAL.is_retryable
    assert not ExitClass.SUCCESS.is_retryable


def test_batch_only_primitives():
    assert StepType.WAIT in BATCH_ONLY_PRIMITIVES
    assert StepType.GATE in BATCH_ONLY_PRIMITIVES
    assert StepType.PARALLEL not in BATCH_ONLY_PRIMITIVES


def test_workload_realtime():
    assert not WorkloadMode.JOB.is_realtime
    assert WorkloadMode.DEPLOYMENT.is_realtime
    assert WorkloadMode.STATEFULSET.is_realtime
    assert StoryPattern.REALTIME.is_realtime


def test_enums_serialize_as_strings():
    assert str(Phase.RUNNING) == "Running"
    assert Phase("Running") is Phase.RUNNING


def test_accelerator_from_device_kind():
    from bobrapet_tpu.api.enums import (
        PEAK_BF16_FLOPS,
        AcceleratorType,
        accelerator_from_device_kind,
    )

    assert accelerator_from_device_kind("TPU v5 lite") == AcceleratorType.TPU_V5E
    assert accelerator_from_device_kind("TPU v5e") == AcceleratorType.TPU_V5E
    assert accelerator_from_device_kind("TPU v5p") == AcceleratorType.TPU_V5P
    assert accelerator_from_device_kind("TPU v5") == AcceleratorType.TPU_V5P
    assert accelerator_from_device_kind("TPU v4") == AcceleratorType.TPU_V4
    assert accelerator_from_device_kind("TPU v6e") == AcceleratorType.TPU_V6E
    assert accelerator_from_device_kind("cpu") is None
    # every TPU family has a peak-FLOPs entry for MFU
    for accel in AcceleratorType:
        if accel != AcceleratorType.CPU:
            assert accel in PEAK_BF16_FLOPS
