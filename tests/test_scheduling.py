"""Queue scheduling: priority ordering with aging + queued-step parking.

Mirrors the reference's scheduling semantics (reference:
internal/controller/runs/dag.go — enforcePriorityOrdering:1910,
effectivePriority:1948, storyRunQueuedSince:1962,
storyRunHasDemand:1981, markQueuedSteps:1999): ready steps blocked by
a scheduling gate are parked Pending with a queued reason; their
startedAt is the queue-entry time that drives priority aging; a run is
deferred while any same-queue peer with live demand has strictly
higher effective priority.
"""

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.config.operator import QueueConfig
from bobrapet_tpu.controllers.dag import (
    REASON_PRIORITY_QUEUED,
    REASON_SCHEDULING_QUEUED,
    effective_priority,
    storyrun_has_demand,
    storyrun_queued_since,
)
from bobrapet_tpu.core.object import Resource, new_resource
from bobrapet_tpu.sdk import register_engram


class TestEffectivePriority:
    def test_base_without_queue_time(self):
        assert effective_priority(3, None, 300.0, 1000.0) == 3

    def test_aging_adds_one_step_per_interval(self):
        # queued 650s with a 300s aging interval -> +2
        assert effective_priority(3, 1000.0, 300.0, 1650.0) == 5

    def test_aging_disabled(self):
        assert effective_priority(3, 1000.0, 0.0, 99999.0) == 3

    def test_negative_elapsed_ignored(self):
        assert effective_priority(3, 2000.0, 300.0, 1000.0) == 3


class TestDemandAndQueuedSince:
    def _run_with_states(self, states, phase="Succeeded") -> Resource:
        r = new_resource("StoryRun", "r", "default", spec={})
        r.status = {"phase": phase, "stepStates": states}
        return r

    def test_running_run_has_demand(self):
        assert storyrun_has_demand(self._run_with_states({}, phase="Running"))

    def test_terminal_run_without_queued_steps_has_no_demand(self):
        assert not storyrun_has_demand(self._run_with_states({}))

    def test_queued_step_is_demand(self):
        r = self._run_with_states(
            {"a": {"phase": "Pending", "reason": REASON_SCHEDULING_QUEUED,
                   "startedAt": 50.0}}
        )
        assert storyrun_has_demand(r)
        assert storyrun_queued_since(r) == 50.0

    def test_queued_since_earliest_wins(self):
        r = self._run_with_states({
            "a": {"phase": "Pending", "reason": REASON_SCHEDULING_QUEUED,
                  "startedAt": 70.0},
            "b": {"phase": "Pending", "reason": REASON_PRIORITY_QUEUED,
                  "startedAt": 30.0},
            "c": {"phase": "Running", "startedAt": 10.0},  # running, not queued
        })
        assert storyrun_queued_since(r) == 30.0

    def test_plain_pending_is_not_queued(self):
        r = self._run_with_states(
            {"a": {"phase": "Pending", "reason": "Launched", "startedAt": 5.0}}
        )
        assert storyrun_queued_since(r) is None

    def test_guard_parked_pending_run_has_no_demand(self):
        """A run parked Pending by a guard (story deleted, reference
        denied) with no step states can never launch — it must not park
        queue peers behind its priority."""
        r = new_resource("StoryRun", "r", "default", spec={})
        r.status = {"phase": "Pending", "reason": "StoryNotFound",
                    "stepStates": {}}
        assert not storyrun_has_demand(r)
        # but a freshly-admitted Pending run (no guard reason) does compete
        r.status = {"phase": "Pending", "stepStates": {}}
        assert storyrun_has_demand(r)


def _setup_story(rt, story_name, priority, queue="tpu"):
    rt.apply(make_story(story_name, steps=[
        {"name": "work", "ref": {"name": "worker"}},
    ], policy={"queue": queue, "priority": priority}))


@pytest.fixture
def contended_rt(rt):
    """Runtime with a 1-slot queue and a registered worker engram."""
    rt.config_manager.config.scheduling.queues["tpu"] = QueueConfig(
        name="tpu", max_concurrent=1, priority_aging_seconds=300.0
    )
    rt.apply(make_engram_template("worker-tpl", entrypoint="worker-impl"))
    rt.apply(make_engram("worker", "worker-tpl"))

    @register_engram("worker-impl")
    def impl(ctx):
        return {"ok": True}

    return rt


def _stepruns_of(rt, run_name):
    return [
        sr for sr in rt.store.list("StepRun")
        if sr.meta.labels.get("bobrapet.io/story-run") == run_name
    ]


class TestQueueScheduling:
    def test_scheduling_labels_stamped(self, contended_rt):
        rt = contended_rt
        _setup_story(rt, "lbl", priority=7)
        run = rt.run_story("lbl")
        rt.storyrun_controller.reconcile("default", run)
        r = rt.store.get("StoryRun", "default", run)
        assert r.meta.labels["bobrapet.io/queue"] == "tpu"
        assert r.meta.labels["bobrapet.io/priority"] == "7"

    def test_queue_limit_parks_step_with_queued_reason(self, contended_rt):
        rt = contended_rt
        _setup_story(rt, "first", priority=0)
        _setup_story(rt, "second", priority=0)
        r1 = rt.run_story("first")
        rt.storyrun_controller.reconcile("default", r1)
        assert len(_stepruns_of(rt, r1)) == 1  # occupies the only slot

        r2 = rt.run_story("second")
        rt.storyrun_controller.reconcile("default", r2)
        assert _stepruns_of(rt, r2) == []
        state = rt.store.get("StoryRun", "default", r2).status["stepStates"]["work"]
        assert state["phase"] == "Pending"
        assert state["reason"] == REASON_SCHEDULING_QUEUED
        assert state["startedAt"] == rt.clock.now()

    def test_higher_priority_peer_defers_launch(self, contended_rt):
        rt = contended_rt
        _setup_story(rt, "low", priority=1)
        _setup_story(rt, "high", priority=5)
        # low occupies the slot; high queues behind the limit
        r_low = rt.run_story("low")
        rt.storyrun_controller.reconcile("default", r_low)
        r_high = rt.run_story("high")
        rt.storyrun_controller.reconcile("default", r_high)
        high_state = rt.store.get("StoryRun", "default", r_high).status["stepStates"]["work"]
        assert high_state["reason"] == REASON_SCHEDULING_QUEUED

        # a second low-priority run must yield to high's demand
        r_low2 = rt.run_story("low")
        rt.storyrun_controller.reconcile("default", r_low2)
        low2_state = rt.store.get("StoryRun", "default", r_low2).status["stepStates"]["work"]
        assert low2_state["reason"] == REASON_PRIORITY_QUEUED

        # finish low's step -> slot frees; high launches, low2 still waits
        sr = _stepruns_of(rt, r_low)[0]
        for _ in range(5):
            rt.steprun_controller.reconcile("default", sr.meta.name)
            phase = rt.store.get("StepRun", "default", sr.meta.name).status.get("phase")
            if phase == "Succeeded":
                break
        assert phase == "Succeeded"
        rt.storyrun_controller.reconcile("default", r_low2)
        assert _stepruns_of(rt, r_low2) == []
        rt.storyrun_controller.reconcile("default", r_high)
        assert len(_stepruns_of(rt, r_high)) == 1

        # drain everything; all runs complete
        rt.pump()
        assert rt.run_phase(r_low) == "Succeeded"
        assert rt.run_phase(r_high) == "Succeeded"
        assert rt.run_phase(r_low2) == "Succeeded"

    def test_aging_lets_starved_run_overtake(self, contended_rt):
        rt = contended_rt
        _setup_story(rt, "low", priority=0)
        _setup_story(rt, "high", priority=2)
        r_hold = rt.run_story("high")  # occupies the slot
        rt.storyrun_controller.reconcile("default", r_hold)

        r_low = rt.run_story("low")
        rt.storyrun_controller.reconcile("default", r_low)
        # the running high-priority run outranks low, so the priority
        # gate (checked before the slot gate) parks it
        assert (
            rt.store.get("StoryRun", "default", r_low)
            .status["stepStates"]["work"]["reason"]
            == REASON_PRIORITY_QUEUED
        )

        # low has been queued for 3 aging intervals: effective 0+3 > 2,
        # so a newly arriving high-priority run is the one deferred
        rt.clock.advance(950.0)
        r_high2 = rt.run_story("high")
        rt.storyrun_controller.reconcile("default", r_high2)
        state = rt.store.get("StoryRun", "default", r_high2).status["stepStates"]["work"]
        assert state["reason"] == REASON_PRIORITY_QUEUED

        rt.pump()
        for r in (r_hold, r_low, r_high2):
            assert rt.run_phase(r) == "Succeeded"

    def test_failfast_reclaims_queued_steps(self, contended_rt):
        """A step parked behind a scheduling gate must be skipped by
        fail-fast like a never-started step — it must not launch once the
        failure frees capacity (regression: queued markers escaping
        _apply_skips)."""
        rt = contended_rt
        ran = []

        @register_engram("bad-impl")
        def bad(ctx):
            raise RuntimeError("boom")

        @register_engram("spy-impl")
        def spy(ctx):
            ran.append(ctx.step)
            return {}

        rt.apply(make_engram_template("bad-tpl", entrypoint="bad-impl"))
        rt.apply(make_engram("bad", "bad-tpl"))
        rt.apply(make_engram_template("spy-tpl", entrypoint="spy-impl"))
        rt.apply(make_engram("spy", "spy-tpl"))
        rt.apply(make_story("ff", steps=[
            {"name": "a", "ref": {"name": "bad"},
             "execution": {"retry": {"maxRetries": 0}}},
            {"name": "b", "ref": {"name": "spy"}},
        ], policy={"queue": "tpu", "priority": 0, "concurrency": 1}))
        run = rt.run_story("ff")
        rt.pump()
        assert rt.run_phase(run) == "Failed"
        states = rt.store.get("StoryRun", "default", run).status["stepStates"]
        assert states["b"]["phase"] == "Skipped"
        assert states["b"]["reason"] == "FailFast"
        assert ran == []

    def test_no_queue_no_priority_gate(self, contended_rt):
        rt = contended_rt
        rt.apply(make_story("plain", steps=[
            {"name": "work", "ref": {"name": "worker"}},
        ]))
        run = rt.run_story("plain")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
