"""Resource store semantics: the coordination-bus contract.

These mirror the guarantees the reference gets from kube-apiserver that
its controllers depend on (optimistic concurrency, spec/status split,
watches, finalizers, GC, indexes).
"""

import pytest

from bobrapet_tpu.core import (
    ADDED,
    DELETED,
    MODIFIED,
    AdmissionDenied,
    AlreadyExists,
    Conflict,
    EventRecorder,
    NotFound,
    ResourceStore,
    Resource,
    new_resource,
)


@pytest.fixture
def store():
    return ResourceStore()


def make(name="s1", kind="Story", ns="default", spec=None):
    return new_resource(kind, name, ns, spec or {"steps": []})


class TestCRUD:
    def test_create_assigns_identity(self, store):
        obj = store.create(make())
        assert obj.meta.uid and obj.meta.resource_version > 0
        assert obj.meta.generation == 1
        assert obj.meta.creation_timestamp > 0

    def test_create_duplicate(self, store):
        store.create(make())
        with pytest.raises(AlreadyExists):
            store.create(make())

    def test_get_returns_copy(self, store):
        store.create(make())
        a = store.get("Story", "default", "s1")
        a.spec["steps"].append({"name": "x"})
        b = store.get("Story", "default", "s1")
        assert b.spec["steps"] == []

    def test_get_missing(self, store):
        with pytest.raises(NotFound):
            store.get("Story", "default", "nope")

    def test_update_requires_fresh_rv(self, store):
        store.create(make())
        a = store.get("Story", "default", "s1")
        b = store.get("Story", "default", "s1")
        a.spec["x"] = 1
        store.update(a)
        b.spec["x"] = 2
        with pytest.raises(Conflict):
            store.update(b)

    def test_generation_bumps_only_on_spec_change(self, store):
        store.create(make())
        obj = store.get("Story", "default", "s1")
        obj.meta.labels["a"] = "b"
        obj = store.update(obj)
        assert obj.meta.generation == 1  # metadata-only change
        obj.spec["x"] = 1
        obj = store.update(obj)
        assert obj.meta.generation == 2

    def test_status_update_cannot_touch_spec(self, store):
        store.create(make())
        obj = store.get("Story", "default", "s1")
        obj.spec["hacked"] = True
        obj.status["phase"] = "Running"
        store.update_status(obj)
        cur = store.get("Story", "default", "s1")
        assert "hacked" not in cur.spec
        assert cur.status["phase"] == "Running"
        assert cur.meta.generation == 1

    def test_mutate_retries_conflicts(self, store):
        store.create(make())
        # interleave a competing write inside the mutation function once
        calls = {"n": 0}

        def bump(r):
            calls["n"] += 1
            if calls["n"] == 1:
                store.mutate("Story", "default", "s1", lambda r2: r2.spec.update(other=1))
            r.spec["mine"] = calls["n"]

        store.mutate("Story", "default", "s1", bump)
        cur = store.get("Story", "default", "s1")
        assert cur.spec["other"] == 1 and cur.spec["mine"] == 2


class TestWatch:
    def test_watch_sees_lifecycle(self, store):
        seen = []
        store.watch(lambda ev: seen.append((ev.type, ev.resource.name)))
        store.create(make())
        store.mutate("Story", "default", "s1", lambda r: r.spec.update(x=1))
        store.delete("Story", "default", "s1")
        assert seen == [(ADDED, "s1"), (MODIFIED, "s1"), (DELETED, "s1")]

    def test_watch_kind_filter(self, store):
        seen = []
        store.watch(lambda ev: seen.append(ev.resource.kind), kinds=["StepRun"])
        store.create(make())
        store.create(make(name="r1", kind="StepRun"))
        assert seen == ["StepRun"]

    def test_watcher_can_reenter_store(self, store):
        # watcher performing a write must not deadlock
        def on_event(ev):
            if ev.type == ADDED and ev.resource.kind == "Story":
                store.create(make(name="child", kind="StepRun"))

        store.watch(on_event)
        store.create(make())
        assert store.try_get("StepRun", "default", "child") is not None

    def test_unsubscribe(self, store):
        seen = []
        cancel = store.watch(lambda ev: seen.append(1))
        store.create(make())
        cancel()
        store.create(make(name="s2"))
        assert len(seen) == 1


class TestFinalizersAndGC:
    def test_finalizer_parks_deletion(self, store):
        obj = make()
        obj.meta.finalizers = ["bobrapet.io/cleanup"]
        store.create(obj)
        store.delete("Story", "default", "s1")
        cur = store.get("Story", "default", "s1")
        assert cur.meta.deletion_timestamp is not None
        # removing the finalizer completes deletion
        cur.meta.finalizers = []
        store.update(cur)
        assert store.try_get("Story", "default", "s1") is None

    def test_cascade_delete_owned_children(self, store):
        parent = store.create(make(kind="StoryRun", name="run1"))
        child = new_resource("StepRun", "run1-step-a")
        child.meta.owner_references = [parent.owner_ref()]
        store.create(child)
        unowned = store.create(make(kind="StepRun", name="stray"))
        store.delete("StoryRun", "default", "run1")
        assert store.try_get("StepRun", "default", "run1-step-a") is None
        assert store.try_get("StepRun", "default", "stray") is not None
        assert unowned is not None

    def test_cascade_respects_child_finalizers(self, store):
        parent = store.create(make(kind="StoryRun", name="run1"))
        child = new_resource("StepRun", "run1-step-a")
        child.meta.owner_references = [parent.owner_ref()]
        child.meta.finalizers = ["drain"]
        store.create(child)
        store.delete("StoryRun", "default", "run1")
        parked = store.get("StepRun", "default", "run1-step-a")
        assert parked.meta.deletion_timestamp is not None


class TestIndexes:
    def test_index_lookup(self, store):
        store.add_index(
            "StepRun", "storyRunRef", lambda r: [r.spec.get("storyRunRef", {}).get("name", "")]
        )
        store.create(
            new_resource("StepRun", "a", spec={"storyRunRef": {"name": "run1"}})
        )
        store.create(
            new_resource("StepRun", "b", spec={"storyRunRef": {"name": "run2"}})
        )
        got = store.list("StepRun", index=("storyRunRef", "run1"))
        assert [r.name for r in got] == ["a"]

    def test_index_tracks_updates_and_deletes(self, store):
        store.add_index(
            "StepRun", "phase", lambda r: [r.status.get("phase", "")]
        )
        store.create(new_resource("StepRun", "a"))
        store.mutate("StepRun", "default", "a", lambda r: r.status.update(phase="Running"), status_only=True)
        assert [r.name for r in store.list("StepRun", index=("phase", "Running"))] == ["a"]
        store.mutate("StepRun", "default", "a", lambda r: r.status.update(phase="Succeeded"), status_only=True)
        assert store.list("StepRun", index=("phase", "Running")) == []
        assert [r.name for r in store.list("StepRun", index=("phase", "Succeeded"))] == ["a"]
        store.delete("StepRun", "default", "a")
        assert store.list("StepRun", index=("phase", "Succeeded")) == []

    def test_index_backfills_existing_objects(self, store):
        store.create(new_resource("StepRun", "pre", spec={"storyRunRef": {"name": "r9"}}))
        store.add_index(
            "StepRun", "storyRunRef", lambda r: [r.spec.get("storyRunRef", {}).get("name", "")]
        )
        assert [r.name for r in store.list("StepRun", index=("storyRunRef", "r9"))] == ["pre"]

    def test_label_and_namespace_filters(self, store):
        store.create(new_resource("Story", "a", namespace="ns1", labels={"team": "x"}))
        store.create(new_resource("Story", "b", namespace="ns2", labels={"team": "x"}))
        assert len(store.list("Story", labels={"team": "x"})) == 2
        assert [r.name for r in store.list("Story", namespace="ns1")] == ["a"]


class TestAdmission:
    def test_defaulter_runs_on_create_and_update(self, store):
        def default_pattern(r: Resource):
            r.spec.setdefault("pattern", "batch")

        store.register_defaulter("Story", default_pattern)
        obj = store.create(make())
        assert obj.spec["pattern"] == "batch"

    def test_validator_denies(self, store):
        def deny_empty_steps(r: Resource, old):
            if not r.spec.get("steps"):
                raise AdmissionDenied("steps required")

        store.register_validator("Story", deny_empty_steps)
        with pytest.raises(AdmissionDenied):
            store.create(make(spec={"steps": []}))


class TestPersistence:
    def test_roundtrip(self, tmp_store_dir):
        s1 = ResourceStore(persist_dir=tmp_store_dir)
        s1.create(make(spec={"steps": [{"name": "a"}]}))
        s1.mutate("Story", "default", "s1", lambda r: r.status.update(phase="Running"), status_only=True)
        s2 = ResourceStore(persist_dir=tmp_store_dir)
        cur = s2.get("Story", "default", "s1")
        assert cur.status["phase"] == "Running"
        assert cur.spec["steps"] == [{"name": "a"}]
        # resourceVersion counter resumes past loaded values
        s2.mutate("Story", "default", "s1", lambda r: r.spec.update(x=1))
        assert s2.get("Story", "default", "s1").meta.resource_version > cur.meta.resource_version


class TestHardening:
    def test_persist_filenames_cannot_collide(self, tmp_store_dir):
        s = ResourceStore(persist_dir=tmp_store_dir)
        s.create(new_resource("Story", "b.c", namespace="a"))
        s.create(new_resource("Story", "c", namespace="a.b"))
        s2 = ResourceStore(persist_dir=tmp_store_dir)
        assert s2.try_get("Story", "a", "b.c") is not None
        assert s2.try_get("Story", "a.b", "c") is not None

    def test_persist_name_cannot_escape_dir(self, tmp_store_dir):
        import os

        s = ResourceStore(persist_dir=tmp_store_dir)
        s.create(new_resource("Story", "../../evil"))
        for root, _, files in os.walk(tmp_store_dir):
            for f in files:
                assert os.path.realpath(os.path.join(root, f)).startswith(
                    os.path.realpath(tmp_store_dir)
                )

    def test_raising_watcher_does_not_fail_write_or_starve_others(self, store):
        seen = []

        def bad(ev):
            raise RuntimeError("watcher bug")

        store.watch(bad)
        store.watch(lambda ev: seen.append(ev.type))
        obj = store.create(make())  # must not raise
        assert obj.meta.uid
        assert seen == [ADDED]

    def test_watch_events_in_commit_order_under_concurrency(self, store):
        import threading

        order = []
        store.watch(lambda ev: order.append(ev.resource.meta.resource_version))

        def writer(i):
            store.create(make(name=f"s-{i}"))

        ts = [threading.Thread(target=writer, args=(i,)) for i in range(20)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert order == sorted(order)

    def test_conflict_carries_versions(self, store):
        store.create(make())
        a = store.get("Story", "default", "s1")
        b = store.get("Story", "default", "s1")
        a.spec["x"] = 1
        store.update(a)
        b.spec["x"] = 2
        with pytest.raises(Conflict) as ei:
            store.update(b)
        assert ei.value.actual > ei.value.expected

    def test_warning_not_folded_into_normal(self, store):
        rec = EventRecorder()
        obj = store.create(make())
        rec.normal(obj, "Reconciling", "syncing")
        rec.warning(obj, "Reconciling", "syncing")
        types = [e.type for e in rec.for_object("Story", "default", "s1")]
        assert types == ["Normal", "Warning"]


class TestEventRecorder:
    def test_dedup(self, store):
        rec = EventRecorder()
        obj = store.create(make())
        for _ in range(5):
            rec.warning(obj, "RetryScheduled", "retrying step")
        evs = rec.for_object("Story", "default", "s1")
        assert len(evs) == 1 and evs[0].count == 5

    def test_distinct_messages_not_deduped(self, store):
        rec = EventRecorder()
        obj = store.create(make())
        rec.normal(obj, "Scheduled", "step a")
        rec.normal(obj, "Scheduled", "step b")
        assert len(rec.for_object("Story", "default", "s1")) == 2


class TestCheapReads:
    """store.count / store.list_keys: the copy-free reads the r5
    usage-counter and queue-cap indexes depend on — they must agree
    with list() and track status/annotation-derived index functions
    through every write path."""

    def _indexed(self):
        store = ResourceStore()
        store.add_index(
            "StepRun", "engramRef",
            lambda r: [(r.spec.get("engramRef") or {}).get("name", "")],
        )
        store.add_index(
            "StepRun", "activeByEngram",
            lambda r: (
                [] if r.status.get("phase") == "Succeeded"
                else [(r.spec.get("engramRef") or {}).get("name", "")]
            ),
        )
        for i in range(5):
            store.create(new_resource(
                "StepRun", f"sr{i}", "default",
                {"engramRef": {"name": "w" if i < 3 else "x"}},
            ))
        return store

    def test_count_matches_list_everywhere(self):
        store = self._indexed()
        for kwargs in (
            {"kind": "StepRun"},
            {"kind": "StepRun", "namespace": "default"},
            {"kind": "StepRun", "namespace": "other"},
            {"kind": "StepRun", "index": ("engramRef", "w")},
            {"kind": "StepRun", "index": ("engramRef", "missing")},
        ):
            assert store.count(**kwargs) == len(store.list(**kwargs)), kwargs

    def test_list_keys_matches_list_identities(self):
        store = self._indexed()
        keys = store.list_keys("StepRun", index=("engramRef", "w"))
        objs = store.list("StepRun", index=("engramRef", "w"))
        assert keys == [(o.meta.namespace, o.meta.name) for o in objs]
        assert keys == sorted(keys)

    def test_status_derived_index_tracks_updates(self):
        store = self._indexed()
        assert store.count("StepRun", index=("activeByEngram", "w")) == 3

        def done(r):
            r.status["phase"] = "Succeeded"

        store.mutate("StepRun", "default", "sr0", done)
        assert store.count("StepRun", index=("activeByEngram", "w")) == 2
        store.delete("StepRun", "default", "sr1")
        assert store.count("StepRun", index=("activeByEngram", "w")) == 1

    def test_unknown_index_raises_like_list(self):
        store = self._indexed()
        from bobrapet_tpu.core.store import StoreError

        with pytest.raises(StoreError):
            store.count("StepRun", index=("nope", "v"))
        with pytest.raises(StoreError):
            store.list_keys("StepRun", index=("nope", "v"))


class TestRuntimeScaleIndexes:
    """The r5 scale indexes through the real Runtime: active counts and
    uncounted-token buckets stay exact across phase flips and token
    consumption (drift here silently corrupts usage counters)."""

    def test_queue_active_and_usage_indexes(self):
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.controllers.dag import (
            ACTIVE_ALL_BUCKET,
            INDEX_STEPRUN_QUEUE_ACTIVE,
        )
        from bobrapet_tpu.controllers.resources import (
            INDEX_STORYRUN_STORY_ACTIVE,
            INDEX_STORYRUN_UNCOUNTED,
        )
        from bobrapet_tpu.runtime import Runtime
        from bobrapet_tpu.sdk import register_engram

        rt = Runtime()

        @register_engram("idx-impl")
        def impl(ctx):
            return {"ok": 1}

        rt.apply(make_engram_template("idx-tpl", entrypoint="idx-impl"))
        rt.apply(make_engram("idx-worker", "idx-tpl"))
        rt.apply(make_story("idx-story", steps=[
            {"name": "a", "ref": {"name": "idx-worker"}},
        ]))
        runs = [rt.run_story("idx-story") for _ in range(4)]
        rt.pump()
        assert all(rt.run_phase(r) == "Succeeded" for r in runs)
        # everything terminal: active buckets empty, queue-cap bucket too
        assert rt.store.count(
            "StoryRun", index=(INDEX_STORYRUN_STORY_ACTIVE, "idx-story")
        ) == 0
        assert rt.store.count(
            "StepRun", index=(INDEX_STEPRUN_QUEUE_ACTIVE, ACTIVE_ALL_BUCKET)
        ) == 0
        # token consumption drained the uncounted bucket and the Story
        # status carries the exact run count
        assert rt.store.count(
            "StoryRun", index=(INDEX_STORYRUN_UNCOUNTED, "idx-story")
        ) == 0
        story = rt.store.get("Story", "default", "idx-story")
        assert story.status.get("runsTriggered") == 4


class TestSnapshotViews:
    """Copy-on-write reads: views share the committed object; writes
    still isolate at the store boundary."""

    def _store(self):
        store = ResourceStore()
        store.create(new_resource("Job", "v1", "default",
                                  spec={"cfg": {"deep": [1, 2]}}))
        return store

    def test_view_is_the_committed_object(self):
        store = self._store()
        a = store.get_view("Job", "default", "v1")
        b = store.try_get_view("Job", "default", "v1")
        assert a is b  # no per-read copies
        assert store.get("Job", "default", "v1") is not a  # get() still isolates

    def test_views_survive_writes_unchanged(self):
        """An update replaces the committed object; a previously handed
        out view keeps its (old) content — never mutated in place."""
        store = self._store()
        old = store.get_view("Job", "default", "v1")
        old_rv = old.meta.resource_version
        store.mutate("Job", "default", "v1",
                     lambda r: r.spec.__setitem__("cfg", {"deep": [3]}))
        assert old.spec == {"cfg": {"deep": [1, 2]}}
        assert old.meta.resource_version == old_rv
        fresh = store.get_view("Job", "default", "v1")
        assert fresh is not old
        assert fresh.spec == {"cfg": {"deep": [3]}}

    def test_status_only_update_shares_spec_between_versions(self):
        """The copy-on-write core: a status write reuses the committed
        spec subtree instead of deep-copying it."""
        store = self._store()
        before = store.get_view("Job", "default", "v1")
        store.patch_status("Job", "default", "v1",
                           lambda s: s.__setitem__("phase", "Running"))
        after = store.get_view("Job", "default", "v1")
        assert after is not before
        assert after.spec is before.spec  # shared, not copied
        assert after.status.get("phase") == "Running"
        assert before.status.get("phase") is None

    def test_list_views_filters_like_list(self):
        store = self._store()
        store.create(new_resource("Job", "v2", "other", spec={},
                                  labels={"pick": "me"}))
        assert [r.meta.name for r in store.list_views("Job")] == ["v1", "v2"]
        assert [r.meta.name
                for r in store.list_views("Job", namespace="other")] == ["v2"]
        assert [r.meta.name
                for r in store.list_views("Job", labels={"pick": "me"})] == ["v2"]

    def test_watch_event_shares_committed_object(self):
        store = self._store()
        seen = []
        store.watch(lambda ev: seen.append(ev.resource), kinds=["Job"])
        store.patch_status("Job", "default", "v1",
                           lambda s: s.__setitem__("phase", "Running"))
        assert seen and seen[-1] is store.get_view("Job", "default", "v1")
