"""API type layer: dict <-> dataclass roundtrips for the 12 kinds."""

from bobrapet_tpu.api.catalog import EngramTemplateSpec, make_engram_template
from bobrapet_tpu.api.engram import EngramSpec
from bobrapet_tpu.api.enums import (
    AcceleratorType,
    BackoffStrategy,
    Phase,
    StepType,
    StoryPattern,
    WorkloadMode,
)
from bobrapet_tpu.api.policy import grant_allows, make_reference_grant, reference_granted
from bobrapet_tpu.api.runs import (
    StepRunSpec,
    StepState,
    StoryRunSpec,
    StoryTriggerSpec,
    get_step_states,
    make_storyrun,
    set_step_state,
)
from bobrapet_tpu.api.shared import RetryPolicy, StoragePolicy, TPUPolicy
from bobrapet_tpu.api.story import Step, StorySpec, make_story
from bobrapet_tpu.api.transport import (
    TransportBindingSpec,
    TransportSpec,
    TransportStreamingSettings,
)
from bobrapet_tpu.core import ResourceStore


class TestStory:
    def test_step_keyword_fields(self):
        d = {
            "name": "gen",
            "needs": ["embed"],
            "if": "{{ steps.embed.output.ok }}",
            "with": {"prompt": "hi"},
            "ref": {"name": "llama"},
        }
        step = Step.from_dict(d)
        assert step.if_ == "{{ steps.embed.output.ok }}"
        assert step.with_ == {"prompt": "hi"}
        assert step.ref.name == "llama"
        out = step.to_dict()
        assert out["if"] == d["if"] and out["with"] == d["with"]
        assert "if_" not in out and "with_" not in out

    def test_primitive_step(self):
        step = Step.from_dict({"name": "pause", "type": "sleep", "with": {"duration": "5s"}})
        assert step.type is StepType.SLEEP and step.is_primitive

    def test_story_spec_roundtrip(self):
        spec = StorySpec.from_dict(
            {
                "pattern": "batch",
                "steps": [{"name": "a"}, {"name": "b", "needs": ["a"]}],
                "finally": [{"name": "cleanup", "ref": {"name": "cleaner"}}],
                "policy": {
                    "concurrency": 3,
                    "queue": "tpu-v5e",
                    "priority": 10,
                    "timeouts": {"story": "1h", "step": "10m"},
                    "with": {"env": "prod"},
                },
                "output": {"result": "{{ steps.b.output }}"},
            }
        )
        assert spec.effective_pattern is StoryPattern.BATCH
        assert [s.name for s in spec.steps] == ["a", "b"]
        assert spec.finally_[0].name == "cleanup"
        assert spec.policy.queue == "tpu-v5e"
        assert spec.policy.with_defaults == {"env": "prod"}
        out = spec.to_dict()
        assert out["finally"][0]["name"] == "cleanup"
        assert out["policy"]["with"] == {"env": "prod"}
        # full roundtrip is stable
        assert StorySpec.from_dict(out).to_dict() == out

    def test_tpu_policy(self):
        step = Step.from_dict(
            {
                "name": "train",
                "ref": {"name": "trainer"},
                "tpu": {
                    "accelerator": "tpu-v5-lite-podslice",
                    "topology": "4x4",
                    "iciContiguous": True,
                    "meshAxes": {"data": 2, "tensor": 8},
                },
            }
        )
        assert step.tpu.accelerator is AcceleratorType.TPU_V5E
        assert step.tpu.chip_count() == 16
        assert step.tpu.mesh_axes == {"data": 2, "tensor": 8}

    def test_make_story(self):
        r = make_story("rag", steps=[{"name": "a", "ref": {"name": "x"}}])
        assert r.kind == "Story" and r.spec["steps"][0]["name"] == "a"


class TestSharedPolicies:
    def test_retry_policy_enum_coercion(self):
        rp = RetryPolicy.from_dict({"maxRetries": 3, "delay": "2s", "backoff": "exponential", "jitter": 20})
        assert rp.backoff is BackoffStrategy.EXPONENTIAL
        assert rp.to_dict() == {"maxRetries": 3, "delay": "2s", "backoff": "exponential", "jitter": 20}

    def test_storage_policy_providers(self):
        sp = StoragePolicy.from_dict(
            {
                "s3": {"bucket": "b", "endpoint": "http://minio", "usePathStyle": True},
                "sliceLocalSsd": {"path": "/mnt/ssd0", "maxBytes": 1 << 30},
                "maxInlineSize": 4096,
            }
        )
        assert sp.s3.bucket == "b" and sp.s3.use_path_style
        assert sp.slice_local_ssd.path == "/mnt/ssd0"
        assert sp.max_inline_size == 4096

    def test_unknown_keys_ignored(self):
        rp = RetryPolicy.from_dict({"maxRetries": 1, "futureKnob": "x"})
        assert rp.max_retries == 1


class TestRuns:
    def test_storyrun_spec(self):
        spec = StoryRunSpec.from_dict(
            {"storyRef": {"name": "rag", "version": "v2"}, "inputs": {"q": "hi"}}
        )
        assert spec.story_ref.name == "rag" and spec.story_ref.version == "v2"

    def test_steprun_spec_with_slice_grant(self):
        spec = StepRunSpec.from_dict(
            {
                "storyRunRef": {"name": "run1"},
                "stepId": "train",
                "engramRef": {"name": "trainer"},
                "input": {"x": 1},
                "retry": {"maxRetries": 2},
                "sliceGrant": {"topology": "2x4", "meshAxes": {"data": 8}},
            }
        )
        assert spec.retry.max_retries == 2
        assert spec.slice_grant["topology"] == "2x4"

    def test_empty_output_survives_roundtrip(self):
        from bobrapet_tpu.api.runs import StepState

        s = StepState(phase=Phase.SUCCEEDED, output={})
        assert StepState.from_dict(s.to_dict()).output == {}
        s2 = StepState(phase=Phase.SUCCEEDED, output=[])
        assert StepState.from_dict(s2.to_dict()).output == []

    def test_step_state_helpers(self):
        run = make_storyrun("r1", "rag")
        set_step_state(run, "embed", StepState(phase=Phase.RUNNING, started_at=1.0))
        states = get_step_states(run)
        assert states["embed"].effective_phase is Phase.RUNNING
        assert not states["embed"].is_terminal

    def test_trigger_identity(self):
        spec = StoryTriggerSpec.from_dict(
            {
                "storyRef": {"name": "rag"},
                "identity": {"mode": "keyAndInputHash", "key": "evt-1", "inputHash": "abc"},
            }
        )
        assert spec.identity.mode == "keyAndInputHash"


class TestCatalog:
    def test_template_mode_support(self):
        spec = EngramTemplateSpec.from_dict(
            {
                "image": "gcr.io/x/llama:1",
                "entrypoint": "my.pkg:run",
                "supportedModes": ["job", "deployment"],
                "declaredOutputKeys": ["text"],
            }
        )
        assert spec.supports_mode(WorkloadMode.JOB)
        assert not spec.supports_mode(WorkloadMode.STATEFULSET)
        assert spec.entrypoint == "my.pkg:run"

    def test_cluster_scoped(self):
        r = make_engram_template("llama", image="img")
        assert r.namespace == "_cluster"


class TestTransport:
    def test_streaming_settings_roundtrip(self):
        s = TransportStreamingSettings.from_dict(
            {
                "backpressure": {"buffer": {"maxMessages": 100, "dropPolicy": "dropOldest"}},
                "flowControl": {"mode": "credits", "initialCredits": {"messages": 32}},
                "delivery": {"semantics": "atLeastOnce", "ordering": "perKey"},
                "routing": {"mode": "auto", "maxDownstreams": 8},
                "lanes": [{"name": "ctl", "kind": "control", "direction": "both"}],
                "partitioning": {"mode": "keyHash", "partitions": 4},
                "lifecycle": {"strategy": "drain", "drainTimeoutSeconds": 30},
            }
        )
        assert s.flow_control.initial_credits.messages == 32
        assert s.lanes[0].kind == "control"
        out = s.to_dict()
        assert TransportStreamingSettings.from_dict(out).to_dict() == out

    def test_ici_transport(self):
        t = TransportSpec.from_dict(
            {"provider": "tpu", "driver": "ici", "meshTopology": "2x4"}
        )
        assert t.driver == "ici" and t.mesh_topology == "2x4"

    def test_binding(self):
        b = TransportBindingSpec.from_dict(
            {
                "transportRef": "bobravoz",
                "storyRunRef": {"name": "r1"},
                "stepName": "gen",
                "engramName": "llama",
                "driver": "grpc",
                "audio": {"direction": "both", "codecs": [{"name": "opus", "sampleRateHz": 48000}]},
            }
        )
        assert b.audio.codecs[0].name == "opus"


class TestReferenceGrant:
    def test_grant_evaluation(self):
        g = make_reference_grant(
            "allow-runs",
            "prod",
            from_=[{"kind": "StoryRun", "namespace": "dev"}],
            to=[{"kind": "Story"}],
        )
        assert grant_allows(g, "StoryRun", "dev", "Story", "rag")
        assert not grant_allows(g, "StoryRun", "other", "Story", "rag")
        assert not grant_allows(g, "StoryRun", "dev", "Engram", "x")

    def test_reference_granted_same_ns_always(self):
        store = ResourceStore()
        assert reference_granted(store, "StoryRun", "ns1", "Story", "ns1", "s")
        assert not reference_granted(store, "StoryRun", "ns1", "Story", "ns2", "s")
        store.create(
            make_reference_grant(
                "g", "ns2", from_=[{"kind": "StoryRun", "namespace": "ns1"}], to=[{"kind": "Story"}]
            )
        )
        assert reference_granted(store, "StoryRun", "ns1", "Story", "ns2", "s")


class TestEngramImpulse:
    def test_engram_with_alias(self):
        e = EngramSpec.from_dict(
            {"templateRef": {"name": "llama"}, "mode": "job", "with": {"model": "8b"}}
        )
        assert e.with_config == {"model": "8b"}
        assert e.to_dict()["with"] == {"model": "8b"}
        assert e.mode is WorkloadMode.JOB


class TestParseCacheDebug:
    """BOBRA_PARSE_CACHE_DEBUG: a consumer that mutates a shared
    cached_parse object in place is caught at the next cache hit."""

    def test_mutation_caught_on_hit(self, monkeypatch):
        from bobrapet_tpu.api import specbase
        from bobrapet_tpu.api.story import Step

        monkeypatch.setattr(specbase, "PARSE_CACHE_DEBUG", True)
        spec = {"name": "dbg-step", "type": "condition",
                "with": {"marker": "parse-cache-debug-test"}}
        parsed = specbase.cached_parse(Step, dict(spec))
        # clean hit passes
        assert specbase.cached_parse(Step, dict(spec)) is parsed
        parsed.with_["marker"] = "poisoned"  # the bug class under test
        import pytest as _pytest
        with _pytest.raises(specbase.SharedParseMutated):
            specbase.cached_parse(Step, dict(spec))
        parsed.with_["marker"] = "parse-cache-debug-test"  # restore

    def test_identity_hit_returns_same_object(self):
        from bobrapet_tpu.api import specbase
        from bobrapet_tpu.api.story import Step

        spec = {"name": "id-step", "type": "condition"}
        a = specbase.cached_parse(Step, spec)
        assert specbase.cached_parse(Step, spec) is a  # id fast path
        assert specbase.cached_parse(Step, dict(spec)) is a  # content path


class TestStepStateFastPathParity:
    """StepState.from_dict/to_dict are hand-rolled for the DAG hot
    path; they must stay field-for-field equivalent to the generic
    SpecBase walk, or a future StepState field silently vanishes."""

    SAMPLE = {
        "phase": "Running", "reason": "r", "message": "m",
        "startedAt": 1.5, "finishedAt": 2.5, "retries": 2,
        "output": {"a": [1, {"b": 2}]}, "outputRef": {"key": "k"},
        "signals": {"s": 1}, "exitCode": 3, "exitClass": "retry",
        "preemptions": 1,
    }

    def test_roundtrip_matches_generic_walk(self):
        import dataclasses

        from bobrapet_tpu.api.runs import StepState
        from bobrapet_tpu.api.specbase import SpecBase

        fast = StepState.from_dict(dict(self.SAMPLE))
        generic = SpecBase.from_dict.__func__(StepState, dict(self.SAMPLE))
        assert fast == generic
        assert fast.to_dict() == SpecBase.to_dict(fast)
        # every dataclass field is covered by the hand-rolled pair: a
        # new field must appear in the round-trip or this fails
        full = StepState(**{
            f.name: getattr(fast, f.name) for f in dataclasses.fields(StepState)
        })
        assert set(full.to_dict()) >= {
            "phase", "reason", "message", "startedAt", "finishedAt",
            "retries", "output", "outputRef", "signals", "exitCode",
            "exitClass",
        }

    def test_every_field_survives_roundtrip(self):
        import dataclasses

        from bobrapet_tpu.api.runs import StepState

        parsed = StepState.from_dict(dict(self.SAMPLE))
        back = StepState.from_dict(parsed.to_dict())
        assert parsed == back
        # the hand-rolled serializers must know every declared field
        untouched = [
            f.name for f in dataclasses.fields(StepState)
            if getattr(parsed, f.name) is None
        ]
        assert untouched == [], (
            f"fields not exercised by SAMPLE (add them): {untouched}"
        )
