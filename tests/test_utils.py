"""Naming / duration / hashing utility tests."""

import pytest

from bobrapet_tpu.utils import (
    DurationError,
    cache_key,
    canonical_json,
    compose,
    format_duration,
    hash_inputs,
    parse_duration,
    steprun_name,
    truncate_with_hash,
)


class TestNaming:
    def test_compose_deterministic(self):
        assert compose("Run-1", "step_a") == compose("Run-1", "step_a")
        assert compose("run-1", "a") == "run-1-a"

    def test_truncation_stable_and_distinct(self):
        long_a = "a" * 100
        long_b = "a" * 99 + "b"
        ta, tb = truncate_with_hash(long_a), truncate_with_hash(long_b)
        assert len(ta) <= 63 and len(tb) <= 63
        assert ta != tb
        assert ta == truncate_with_hash(long_a)

    def test_steprun_name_idempotent(self):
        assert steprun_name("run-x", "embed") == steprun_name("run-x", "embed")
        assert steprun_name("run-x", "embed").startswith("run-x-embed-")

    def test_steprun_name_no_boundary_collision(self):
        # 'run-a'+'b-c' vs 'run-a-b'+'c' join to the same readable base;
        # the structured-identity hash keeps them distinct
        assert steprun_name("run-a", "b-c") != steprun_name("run-a-b", "c")
        assert steprun_name("run-a", "step_a") != steprun_name("run-a", "step-a")


class TestDuration:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("300ms", 0.3),
            ("2s", 2.0),
            ("5m", 300.0),
            ("1h30m", 5400.0),
            ("1.5s", 1.5),
            ("30", 30.0),
            (45, 45.0),
            (None, None),
            ("", None),
        ],
    )
    def test_parse(self, s, expected):
        assert parse_duration(s) == expected

    def test_parse_default(self):
        assert parse_duration(None, default=7.0) == 7.0

    @pytest.mark.parametrize("bad", ["soon", "nan", "inf", "-5", "1_0", -3, float("nan")])
    def test_parse_garbage(self, bad):
        with pytest.raises(DurationError):
            parse_duration(bad)

    def test_format_roundtrip(self):
        assert parse_duration(format_duration(90)) == 90


class TestHashing:
    def test_canonical_json_key_order(self):
        assert canonical_json({"b": 1, "a": 2}) == canonical_json({"a": 2, "b": 1})

    def test_hash_inputs_stable(self):
        assert hash_inputs({"x": [1, 2]}) == hash_inputs({"x": [1, 2]})
        assert hash_inputs({"x": 1}) != hash_inputs({"x": 2})

    def test_cache_key_salt_and_mode(self):
        base = cache_key({"a": 1})
        assert cache_key({"a": 1}, salt="s") != base
        assert cache_key({"a": 1}, mode="template") != base

    def test_cache_key_no_delimiter_collision(self):
        assert cache_key({"a": 1}, salt="b:c", mode="a") != cache_key(
            {"a": 1}, salt="c", mode="a:b"
        )

    def test_sets_hash_deterministically(self):
        assert hash_inputs({"tags": {"b", "a", "c"}}) == hash_inputs(
            {"tags": {"c", "a", "b"}}
        )

    def test_unserializable_raises(self):
        with pytest.raises(TypeError):
            hash_inputs({"fn": object()})
