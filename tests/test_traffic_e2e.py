"""Traffic harness against real engines: the drain contract (both
roles), the closed-loop fairness pin (ISSUE 14 acceptance — both
directions), and the autoscaler e2e (scale up via placement, down via
drain, zero lost/mis-routed rids, decisions in flight records +
metrics)."""

import jax
import pytest

from bobrapet_tpu.api.shared import TPUPolicy
from bobrapet_tpu.models import llama
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.observability.timeline import (
    FLIGHT,
    SLO_THRESHOLDS,
    set_slo_thresholds,
)
from bobrapet_tpu.parallel.placement import SlicePlacer, SlicePool
from bobrapet_tpu.serving import (
    PagedConfig,
    ServingEngine,
    ServingRouter,
    SharedPrefixRegistry,
)
from bobrapet_tpu.traffic import (
    Autoscaler,
    AutoscalePolicy,
    ClosedLoopLoadGen,
    EngineReplicaSet,
    TenantProfile,
    traffic_debug_payload,
)


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _pcfg(**over):
    kw = dict(max_slots=4, block_size=16, num_blocks=128,
              max_blocks_per_seq=8)
    kw.update(over)
    return PagedConfig(**kw)


def _engine(model, role="unified", reg=None, **pc_over):
    cfg, params = model
    return ServingEngine(params, cfg, _pcfg(**pc_over),
                         prefix_shared=reg if reg is not None else False,
                         role=role)


def _prompt(seed, n=12, vocab=256):
    import random

    rng = random.Random(seed)
    return [rng.randrange(vocab) for _ in range(n)]


# ---------------------------------------------------------------------------
# satellite: the explicit drain contract
# ---------------------------------------------------------------------------


class TestEngineDrain:
    def test_decode_role(self, model):
        eng = _engine(model, role="unified")
        eng.submit(_prompt(1), max_new_tokens=4)
        eng.submit(_prompt(2), max_new_tokens=4)
        eng.drain()
        assert eng.in_flight == 2 and not eng.drained
        with pytest.raises(ValueError, match="draining"):
            eng.submit(_prompt(3), max_new_tokens=4)
        fin = eng.run()
        assert len(fin) == 2
        assert eng.in_flight == 0 and eng.drained
        eng.undrain()
        assert eng.submit(_prompt(3), max_new_tokens=4) >= 0

    def test_prefill_role(self, model):
        reg = SharedPrefixRegistry(max_entries=256)
        eng = _engine(model, role="prefill", reg=reg)
        eng.submit(_prompt(4, n=20), max_new_tokens=8)
        eng.drain()
        assert eng.in_flight == 1
        fin = eng.run()
        # prefill retires at first token — still counts as retired work
        assert len(fin) == 1 and fin[0].prefilled
        assert eng.drained

    def test_drain_is_idempotent(self, model):
        eng = _engine(model)
        eng.drain()
        eng.drain()
        assert eng.drained  # empty + draining


class TestRouterDrain:
    def test_drain_stops_routing_and_remove_after_empty(self, model):
        e0, e1 = _engine(model), _engine(model)
        router = ServingRouter({"d0": e0, "d1": e1})
        for i in range(4):
            # 64-token budgets: several horizons of work, so the drain
            # observably overlaps live decoding
            router.submit(_prompt(10 + i), max_new_tokens=64)
        router.step()  # admissions land on both (least-loaded)
        assert e1.in_flight > 0
        status = router.drain("d1")
        assert status.draining and status.in_flight >= 1 and not status.empty
        # remove while live work exists must refuse
        with pytest.raises(ValueError, match="in flight"):
            router.remove_engine("d1")
        for i in range(4):
            router.submit(_prompt(20 + i), max_new_tokens=8)
        router.run()
        assert len(router.finished) == 8
        # every new admission avoided the draining engine
        assert e1.in_flight == 0
        assert router.drain_status("d1").empty
        removed = router.remove_engine("d1")
        assert removed is e1
        assert "d1" not in router.engines
        # the survivor keeps serving
        router.submit(_prompt(30), max_new_tokens=4)
        router.run()
        assert router.drain_status("d1") is None

    def test_undrain_restores_routing(self, model):
        e0, e1 = _engine(model), _engine(model)
        router = ServingRouter({"d0": e0, "d1": e1})
        router.drain("d1")
        router.undrain("d1")
        for i in range(6):
            router.submit(_prompt(40 + i), max_new_tokens=64)
        router.step()
        assert e1.in_flight > 0  # least-loaded uses it again
        router.run()

    def test_all_draining_queues_hold(self, model):
        e0 = _engine(model)
        router = ServingRouter({"d0": e0})
        router.drain("d0")
        rid = router.submit(_prompt(50), max_new_tokens=4)
        for _ in range(5):
            router.step()
        # nothing admitted anywhere, nothing lost
        assert router.queue_depths()["decode"] == 1
        router.undrain("d0")
        router.run()
        assert any(r.rid == rid for r in router.finished)

    def test_add_engine_scales_service(self, model):
        e0 = _engine(model)
        router = ServingRouter({"d0": e0})
        e1 = _engine(model)
        router.add_engine("d1", e1)
        with pytest.raises(ValueError, match="already registered"):
            router.add_engine("d1", e1)
        for i in range(6):
            router.submit(_prompt(60 + i), max_new_tokens=64)
        router.step()
        assert e1.in_flight > 0
        router.run()
        assert len(router.finished) == 6

    def test_live_role_demotion_via_drain(self, model):
        """router.set_role: the flip waits for the engine to empty
        under its OLD role — in-flight work is never truncated."""
        reg = SharedPrefixRegistry(max_entries=256)
        # one slot: direct submissions below stay observably in flight
        pf = _engine(model, role="prefill", reg=reg, max_slots=1)
        dec = _engine(model, role="decode", reg=reg)
        router = ServingRouter({"pf": pf, "dec": dec},
                               registry=reg, prefill_threshold=16)
        for i in range(3):  # direct prefill-pool traffic keeps pf busy
            pf.submit(_prompt(70 + i, n=24), max_new_tokens=6)
        router.set_role("pf", "decode")
        assert pf.role == "prefill"  # still busy: flip deferred
        assert router.drain_status("pf").in_flight == 3
        # routed work during the demotion must avoid pf entirely
        routed = [router.submit(_prompt(75 + i, n=24), max_new_tokens=6)
                  for i in range(2)]
        router.run()
        assert pf.role == "decode"  # applied once empty
        assert router.drain_status("pf").draining is False
        # pf's direct work retired under the OLD role (prefilled flag),
        # the routed requests completed with full budgets elsewhere
        assert all(r.prefilled for r in pf.finished[:3])
        done = {r.rid: r for r in router.finished}
        assert sorted(done) == sorted(routed)
        assert all(len(done[r].output) == 6 for r in routed)


class TestEvictEngine:
    def test_mid_decode_eviction_is_byte_identical(self, model):
        """Preempting a replica mid-decode requeues its work; outputs
        (greedy AND sampled) match an undisturbed run exactly, and
        every rid retires exactly once."""
        def build():
            e0, e1 = _engine(model), _engine(model)
            return ServingRouter({"d0": e0, "d1": e1})

        def submit_all(router):
            rids = []
            for i in range(8):
                rids.append(router.submit(
                    _prompt(80 + i, n=10 + i % 3), max_new_tokens=40,
                    temperature=0.8 if i % 2 else 0.0))
            return rids

        ref = build()
        ref_rids = submit_all(ref)
        ref_out = {r.rid: list(r.output) for r in ref.run()}

        router = build()
        rids = submit_all(router)
        assert rids == ref_rids
        for _ in range(3):
            router.step()  # some requests mid-decode on both engines
        victim = router.engines["d1"]
        assert victim.in_flight > 0  # the eviction interrupts real work
        requeued = router.evict_engine("d1")
        assert requeued > 0
        assert "d1" not in router.engines
        fin = router.run()
        assert sorted(r.rid for r in fin) == sorted(ref_rids)  # exactly once
        assert {r.rid: list(r.output) for r in fin} == ref_out

    def test_evict_unknown_engine(self, model):
        router = ServingRouter({"d0": _engine(model)})
        with pytest.raises(ValueError, match="unknown engine"):
            router.evict_engine("ghost")


# ---------------------------------------------------------------------------
# fairness acceptance: 10x burst cannot starve the victim (both ways)
# ---------------------------------------------------------------------------


VICTIM = "victim"
AGGRESSOR = "agg"
#: the pinned bound: with fair admission ON the victim's p95 TTFT under
#: a 10x flood stays within this factor of its solo baseline; with
#: fairness OFF the same scenario must exceed it (measured ~2x fair vs
#: ~15-40x FIFO on this image — the bound sits between with margin)
BOUND_FACTOR = 6.0


def _fairness_run(model, weights, seed=11):
    eng = _engine(model, max_slots=2)
    # warm every compiled shape OUTSIDE the measured runs (first-touch
    # compile landing in one tenant's TTFT would swamp the queueing
    # signal this test measures)
    eng.submit(_prompt(998, n=14), max_new_tokens=8)
    eng.submit(_prompt(999, n=56), max_new_tokens=12)
    eng.run()
    if weights is not None:
        eng.set_tenant_weights(weights)
    profiles = [
        TenantProfile(VICTIM, users=1, prompt_len=(12, 16),
                      new_tokens=(6, 8), max_requests=16),
        TenantProfile(AGGRESSOR, users=16, prompt_len=(48, 64),
                      new_tokens=(10, 14), max_requests=96),
    ]
    rep = ClosedLoopLoadGen(eng, profiles, seed=seed).run(
        max_duration_s=60.0)
    assert rep.lost == 0
    assert rep.tenant(VICTIM)["completed"] == 16
    return rep.tenant(VICTIM)["ttft_p95_s"]


def _solo_baseline(model, seed=11):
    eng = _engine(model, max_slots=2)
    eng.submit(_prompt(997, n=14), max_new_tokens=8)
    eng.run()
    rep = ClosedLoopLoadGen(
        eng,
        [TenantProfile(VICTIM, users=1, prompt_len=(12, 16),
                       new_tokens=(6, 8), max_requests=16)],
        seed=seed,
    ).run(max_duration_s=30.0)
    assert rep.tenant(VICTIM)["completed"] == 16
    return rep.tenant(VICTIM)["ttft_p95_s"]


class TestFairnessAcceptance:
    def test_fair_admission_bounds_victim_ttft_and_fifo_violates(
        self, model
    ):
        solo = _solo_baseline(model)
        assert solo is not None and solo > 0
        fair = _fairness_run(model, {VICTIM: 1.0, AGGRESSOR: 1.0})
        fifo = _fairness_run(model, None)
        # direction 1: weighted-fair ON -> bounded by construction
        assert fair <= BOUND_FACTOR * solo, (
            f"fair p95 {fair * 1000:.1f}ms vs solo {solo * 1000:.1f}ms "
            f"exceeds {BOUND_FACTOR}x"
        )
        # direction 2: FIFO demonstrably violates the same bound (if it
        # did not, the fairness machinery would be unfalsifiable here)
        assert fifo > BOUND_FACTOR * solo, (
            f"fifo p95 {fifo * 1000:.1f}ms vs solo {solo * 1000:.1f}ms "
            f"unexpectedly within {BOUND_FACTOR}x — the aggressor load "
            f"no longer stresses the queue"
        )
        # and the ordering that makes the story coherent
        assert fair < fifo


# ---------------------------------------------------------------------------
# autoscaler e2e: up via placement, down via drain, exactly-once rids
# ---------------------------------------------------------------------------


class TestAutoscalerE2E:
    def test_burst_scales_up_idle_scales_down_zero_lost(self, model):
        set_slo_thresholds(2.0, 0.000001)  # every tpot breaches: the
        # burn signal saturates under load, proving the metric plumbing
        try:
            self._run(model)
        finally:
            set_slo_thresholds(2.0, 0.1)
            assert SLO_THRESHOLDS["tpot"] == 0.1

    def _run(self, model):
        placer = SlicePlacer([SlicePool("serve", "4x4", chips_per_host=4)])
        pool = placer.pool("serve")
        assert pool is not None
        e0 = _engine(model)
        router = ServingRouter({"d0": e0})

        def factory():
            return _engine(model)

        rs = EngineReplicaSet(
            "decode", router, factory, placer=placer, queue="serve",
            tpu=TPUPolicy(topology="2x2"),
        )
        scaler = Autoscaler(
            {"decode": rs},
            AutoscalePolicy(
                min_replicas=1, max_replicas=3,
                scale_up_burn=0.5, scale_down_burn=0.05,
                queue_depth_per_replica=2,
                scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.05,
            ),
            interval_s=0.0,
        )
        free0 = pool.free_chips()
        assert free0 == 16

        # burst phase: 14 queued requests >> 2/replica threshold
        submitted = [
            router.submit(_prompt(200 + i, n=10 + i % 4), max_new_tokens=6)
            for i in range(14)
        ]
        saw_replicas = 1
        for _ in range(600):
            router.step()
            scaler.tick()
            saw_replicas = max(saw_replicas, rs.actual())
            if len(router.finished) == len(submitted):
                break
        assert len(router.finished) == len(submitted)
        # exactly-once retirement, no mis-routing
        assert sorted(r.rid for r in router.finished) == sorted(submitted)
        assert saw_replicas >= 2, "burst never scaled up"
        # scale-up went through the placement fast path
        assert any(g is not None for g in rs.grants.values())
        assert pool.free_chips() < free0

        # idle phase: calm signals drain the added replicas back down.
        # The settle/cooldown windows are wall-clock; an idle tick is
        # microseconds, so pace the loop with a real sleep
        import time as _t

        for _ in range(400):
            router.step()
            scaler.tick()
            _t.sleep(0.001)
            if rs.actual() == 1 and rs.draining() == 0:
                break
        assert rs.actual() == 1 and rs.draining() == 0
        assert pool.free_chips() == free0, "scale-down leaked a grant"
        assert list(router.engines) == ["d0"], "seed replica was retired"

        # decisions visible: metrics...
        ups = sum(
            metrics.traffic_autoscale.value("decode", "up", reason)
            for reason in ("tpot-burn", "queue-depth")
        )
        downs = metrics.traffic_autoscale.value("decode", "down", "calm")
        assert ups >= 1 and downs >= 1
        assert metrics.traffic_replicas.value("decode", "actual") == 1.0
        # ...flight records...
        kinds = [
            r for r in FLIGHT.timeline("bobrapet-system",
                                       "traffic-autoscaler")
            if r.get("kind") == "autoscale"
        ]
        assert any(r.get("direction") == "up" for r in kinds)
        assert any(r.get("outcome") == "down" for r in kinds)
        # ...and the /debug/traffic payload
        payload = traffic_debug_payload()
        ours = [
            s for s in payload["autoscalers"]
            if "decode" in s["pools"] and s["pools"]["decode"]["actual"] == 1
        ]
        assert ours and any(d["direction"] == "up"
                            for s in ours for d in s["decisions"])

    def test_scale_up_respects_placement_no_capacity(self, model):
        """A pool too full to place simply holds — the autoscaler must
        not crash, leak, or count a phantom replica."""
        placer = SlicePlacer([SlicePool("tiny", "2x2", chips_per_host=4)])
        tiny = placer.pool("tiny")
        blocker = tiny.allocate(want_topology="2x2")  # pool now full
        router = ServingRouter({"d0": _engine(model)})
        rs = EngineReplicaSet(
            "decode", router, lambda: _engine(model), placer=placer,
            queue="tiny", tpu=TPUPolicy(topology="2x2"),
        )
        scaler = Autoscaler(
            {"decode": rs},
            AutoscalePolicy(max_replicas=3, queue_depth_per_replica=1,
                            scale_up_cooldown_s=0.0),
            interval_s=0.0,
        )
        for i in range(6):
            router.submit(_prompt(300 + i), max_new_tokens=4)
        scaler.tick()
        assert rs.actual() == 1 and rs.grants == {}
        assert len(router.engines) == 1
        tiny.release(blocker.slice_id)
        scaler.tick()
        assert rs.actual() == 2  # capacity freed -> next window scales
        router.run()


class TestTenantWeightsLiveReload:
    def test_serving_reload_swaps_queues_without_losing_work(self, model):
        """`serving.tenant-weights` live path: engram.apply_tuning
        reaches engines AND routers; queued work survives the queue
        swap in arrival order; clearing the key restores FIFO."""
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram as engram_mod
        from bobrapet_tpu.traffic.fairness import WeightedFairQueue

        eng = _engine(model, max_slots=1)
        router = ServingRouter({"d0": _engine(model, max_slots=1)})
        engram_mod._LIVE_ENGINES.add(eng)
        try:
            # queue work BEFORE the reload: the swap must not lose it
            blocker = eng.submit(_prompt(400, n=8), max_new_tokens=48)
            queued = [eng.submit(_prompt(401 + i), max_new_tokens=4)
                      for i in range(3)]
            routed = [router.submit(_prompt(410 + i), max_new_tokens=4,
                                    tenant="gold")
                      for i in range(2)]
            scfg = ServingConfig(tenant_weights="gold:4,free:1")
            engram_mod.apply_tuning(scfg)
            assert isinstance(eng.pending, WeightedFairQueue)
            assert [r.rid for r in eng.pending] == [blocker] + queued
            assert isinstance(router._queues["decode"], WeightedFairQueue)
            fin = eng.run()
            assert sorted(r.rid for r in fin) == sorted([blocker] + queued)
            router.run()
            assert sorted(r.rid for r in router.finished) == sorted(routed)
            # clearing the key restores plain FIFO deques
            engram_mod.apply_tuning(ServingConfig(tenant_weights=""))
            from collections import deque as _deque

            assert isinstance(eng.pending, _deque)
            assert isinstance(router._queues["decode"], _deque)
        finally:
            engram_mod._LIVE_ENGINES.discard(eng)

    def test_step_pinned_weights_survive_reload(self, model):
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram as engram_mod

        eng = _engine(model)
        eng.set_tenant_weights({"pinned": 2.0})
        eng._engram_pinned = frozenset(["tenant_weights"])
        engram_mod._LIVE_ENGINES.add(eng)
        try:
            engram_mod.apply_tuning(ServingConfig(tenant_weights="other:9"))
            assert eng._tenant_weights == {"pinned": 2.0}
        finally:
            engram_mod._LIVE_ENGINES.discard(eng)
