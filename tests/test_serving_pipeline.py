"""Pipelined serving dispatch (PR 16): double-buffered horizons.

`dispatch_depth > 1` keeps multiple decode horizons enqueued on the
device while the host commits the oldest and schedules the next —
jax's async dispatch is the buffer. The contract that makes the
pipeline deployable is the same one the horizon engine set: every
output stream is BYTE-IDENTICAL to the single-buffered
`dispatch_depth=1` reference for every scheduling shape — greedy,
sampled, mixed temperatures, speculation on/off, EOS inside a
horizon, preemption mid-flight, a live depth reload mid-stream, and
requests admitted while horizons are in flight.

Parity is not luck here either: sampled streams are a pure function
of (engine seed, rid, token index), and the commit path tolerates the
one-horizon lag by folding device-authoritative lane state back into
the host mirror under patch epochs.
"""

import jax
import numpy as np
import pytest

from bobrapet_tpu.models import llama, quant
from bobrapet_tpu.serving import PagedConfig, ServingEngine


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def draft(model):
    _cfg, params = model
    return quant.quantize_params(params)


def _pcfg(**over):
    kw = dict(max_slots=4, block_size=16, num_blocks=128,
              max_blocks_per_seq=8)
    kw.update(over)
    return PagedConfig(**kw)


def _prompts(cfg, n=8, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, 8 + (i % 5) * 7).tolist()
            for i in range(n)]


def _drain(engine, prompts, *, max_new=12, temps=None, eos=None):
    for i, p in enumerate(prompts):
        engine.submit(list(p), max_new_tokens=max_new,
                      temperature=(temps[i] if temps else 0.0),
                      eos_token=eos)
    done = engine.run()
    return {r.rid: r.output for r in done}


class TestPipelineParity:
    """Every case: pipelined engine vs the dispatch_depth=1 reference
    (both on the SAME decode horizon, so only the pipelining moves)."""

    def _pair(self, model, depth=2, pc=None, **kw):
        cfg, params = model
        ref = ServingEngine(params, cfg, pc or _pcfg(), decode_horizon=8,
                            dispatch_depth=1, **kw)
        pipe = ServingEngine(params, cfg, pc or _pcfg(), decode_horizon=8,
                             dispatch_depth=depth, **kw)
        return ref, pipe

    def test_greedy_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg)
        ref, pipe = self._pair(model)
        assert _drain(ref, prompts) == _drain(pipe, prompts)
        assert pipe.phase_counts["horizons"] > 0
        # the pipeline drained fully: nothing left enqueued
        assert not pipe._inflight
        # host work actually overlapped an in-flight horizon
        assert pipe.phase_seconds["host_overlap"] > 0

    def test_depth3_greedy_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, seed=2)
        ref, pipe = self._pair(model, depth=3)
        assert _drain(ref, prompts) == _drain(pipe, prompts)

    def test_sampled_fixed_seed_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, seed=3)
        temps = [0.7, 1.1, 0.9, 1.3, 0.8, 1.0, 0.6, 1.2]
        ref, pipe = self._pair(model)
        assert _drain(ref, prompts, temps=temps) == _drain(
            pipe, prompts, temps=temps)

    def test_mixed_temperature_batch_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, seed=4)
        temps = [0.0, 0.8, 0.0, 1.2, 0.0, 0.0, 0.9, 0.0]
        ref, pipe = self._pair(model)
        assert _drain(ref, prompts, temps=temps) == _drain(
            pipe, prompts, temps=temps)

    def test_eos_fires_inside_horizon(self, model):
        """EOS lands mid-horizon while a LATER horizon is already
        enqueued: retirement must tolerate the one-horizon commit lag
        and still cut the stream at the reference position."""
        cfg, _ = model
        prompts = _prompts(cfg, seed=5)
        ref, pipe = self._pair(model)
        base = _drain(ref, prompts, max_new=16)
        eos = next(t for out in base.values() for t in out[3:10])
        ref2, pipe2 = self._pair(model)
        a = _drain(ref2, prompts, max_new=16, eos=eos)
        b = _drain(pipe2, prompts, max_new=16, eos=eos)
        assert a == b
        assert any(len(v) < 16 for v in a.values())

    def test_spec_on_off_byte_identical(self, model, draft):
        cfg, _ = model
        prompts = _prompts(cfg, seed=6)
        ref, _unused = self._pair(model)
        base = _drain(ref, prompts, max_new=14)
        for depth in (1, 2):
            spec = ServingEngine(
                model[1], cfg, _pcfg(), decode_horizon=8,
                dispatch_depth=depth, draft_params=draft, draft_cfg=cfg,
                spec_k=4, spec_guard=False)
            assert _drain(spec, prompts, max_new=14) == base
            assert spec.spec_drafted > 0

    def test_spec_mixed_temps_byte_identical(self, model, draft):
        cfg, _ = model
        prompts = _prompts(cfg, seed=7)
        temps = [0.0, 0.9, 0.0, 1.1, 0.0, 0.7, 0.0, 0.0]
        ref, _unused = self._pair(model)
        base = _drain(ref, prompts, temps=temps)
        spec = ServingEngine(model[1], cfg, _pcfg(), decode_horizon=8,
                             dispatch_depth=2, draft_params=draft,
                             draft_cfg=cfg, spec_k=4, spec_guard=False)
        assert _drain(spec, prompts, temps=temps) == base

    def test_preemption_mid_flight_byte_identical(self, model, draft):
        """Tight block pool: growth becomes unfundable while horizons
        are in flight — the pipeline drains to the settled eviction
        tick and resumes, with recompute keeping streams identical."""
        cfg, params = model
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, cfg.vocab_size, 10 + (i % 3) * 9).tolist()
                   for i in range(6)]
        pc = dict(max_slots=4, block_size=8, num_blocks=18,
                  max_blocks_per_seq=8, prefix_caching=False)

        def run(depth, spec=False):
            kw = dict(draft_params=draft, draft_cfg=cfg, spec_k=4,
                      spec_guard=False) if spec else {}
            eng = ServingEngine(params, cfg, PagedConfig(**pc),
                                decode_horizon=8, dispatch_depth=depth,
                                **kw)
            for p in prompts:
                eng.submit(list(p), max_new_tokens=24)
            done = eng.run()
            return ({r.rid: r.output for r in done},
                    sum(r.preemptions for r in done))

        base, pre_ref = run(1)
        pipe, pre_pipe = run(2)
        spec_pipe, _ = run(2, spec=True)
        assert pre_ref > 0 and pre_pipe > 0
        assert base == pipe == spec_pipe

    def test_depth_live_reload_mid_stream(self, model):
        """set_dispatch_depth between ticks (the serving.dispatch-depth
        reload path) must not change a single output byte — including
        the drop to 1, which forces the pipeline to drain."""
        cfg, params = model
        prompts = _prompts(cfg, seed=9)
        ref, _unused = self._pair(model)
        base = _drain(ref, prompts, max_new=16)
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=2)
        for p in prompts:
            eng.submit(list(p), max_new_tokens=16)
        for depth in (2, 1, 3, 2):
            eng.set_dispatch_depth(depth)
            eng.step()
        done = eng.run()
        assert {r.rid: r.output for r in done} == base

    def test_mid_flight_admission_byte_identical(self, model):
        """Requests submitted while horizons are in flight fold into
        the next enqueued horizon without a drain, and the streams
        match a quiesced submit-everything-upfront drain."""
        cfg, params = model
        prompts = _prompts(cfg, n=8, seed=12)
        ref = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=1)
        base = _drain(ref, prompts, max_new=16)
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=2)
        for p in prompts[:4]:
            eng.submit(list(p), max_new_tokens=16)
        eng.step()
        assert eng._inflight  # horizons genuinely in flight
        for p in prompts[4:]:
            eng.submit(list(p), max_new_tokens=16)
        done = eng.run()
        assert {r.rid: r.output for r in done} == base

    def test_invalid_depth_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError):
            ServingEngine(params, cfg, _pcfg(), dispatch_depth=0)
        eng = ServingEngine(params, cfg, _pcfg())
        with pytest.raises(ValueError):
            eng.set_dispatch_depth(0)


class TestShardingCheck:
    """KV view-chain sharding audit: gather_views -> attention ->
    scatter_window must chain with zero hidden repartitions."""

    def test_plain_chain_stable(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8)
        assert eng.check_view_chain(include_spec=False) == []

    def test_spec_chain_stable(self, model, draft):
        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            draft_params=draft, draft_cfg=cfg, spec_k=4,
                            spec_guard=False)
        assert eng.check_view_chain(include_spec=True) == []

    def test_env_armed_startup_check(self, model, monkeypatch):
        """BOBRA_SERVING_SHARDING_CHECK=1 runs the audit once at the
        first horizon and passes on a sharding-stable chain."""
        cfg, params = model
        monkeypatch.setenv("BOBRA_SERVING_SHARDING_CHECK", "1")
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=2)
        for p in _prompts(cfg, n=4, seed=13):
            eng.submit(list(p), max_new_tokens=8)
        eng.run()  # would raise on a repartition
        assert eng._view_chain_checked

    def test_check_runs_once(self, model, monkeypatch):
        cfg, params = model
        monkeypatch.setenv("BOBRA_SERVING_SHARDING_CHECK", "1")
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8)
        calls = []
        orig = eng.check_view_chain

        def counting(**kw):
            calls.append(kw)
            return orig(**kw)

        monkeypatch.setattr(eng, "check_view_chain", counting)
        for p in _prompts(cfg, n=4, seed=14):
            eng.submit(list(p), max_new_tokens=8)
        eng.run()
        assert len(calls) == 1


class TestPipelineObservability:
    def test_phase_keys_and_reset(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=2)
        assert "host_gap" in eng.phase_seconds
        assert "host_overlap" in eng.phase_seconds
        for p in _prompts(cfg, n=4, seed=15):
            eng.submit(list(p), max_new_tokens=10)
        eng.run()
        eng.reset_phase_stats()
        assert eng.phase_seconds["host_gap"] == 0.0
        assert eng.phase_seconds["host_overlap"] == 0.0
        # a stale idle stamp must not leak the reset boundary into the
        # next measured window's first dispatch gap
        assert eng._dev_idle_at is None

    def test_pipeline_series_emitted(self, model):
        from bobrapet_tpu.observability.metrics import metrics

        cfg, params = model
        # depth 1: every horizon-to-horizon round-trip is a device-idle
        # gap, so the histogram must accumulate observations
        gaps_before = metrics.serving_host_gap.count()
        ref = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=1)
        for p in _prompts(cfg, n=8, seed=16):
            ref.submit(list(p), max_new_tokens=10)
        ref.run()
        assert metrics.serving_host_gap.count() > gaps_before
        assert metrics.serving_dispatch_depth.value() == 1.0
        # depth 2 on a single-wave drain: the pipeline never goes empty
        # mid-drain, so the ENGINE's own gap stays (near) zero while
        # the gauge reports the configured depth
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            dispatch_depth=2)
        for p in _prompts(cfg, n=4, seed=16):
            eng.submit(list(p), max_new_tokens=10)
        eng.run()
        assert metrics.serving_dispatch_depth.value() == 2.0
        # drained: nothing in flight is the resting state of the gauge
        assert metrics.serving_inflight.value() == 0.0


class TestDispatchDepthKnob:
    """`serving.dispatch-depth`: registration, validation, and the
    live-reload path through serving/engram.apply_tuning."""

    def test_key_parses_and_validates(self):
        from bobrapet_tpu.config.operator import parse_config

        cfg = parse_config({"serving.dispatch-depth": "3"})
        assert cfg.serving.dispatch_depth == 3
        assert cfg.validate() == []
        cfg.serving.dispatch_depth = 0
        assert any("serving.dispatch-depth" in e for e in cfg.validate())

    def test_apply_tuning_retunes_live_engine(self, model):
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram

        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), dispatch_depth=2)
        engram._LIVE_ENGINES.add(eng)
        try:
            engram.apply_tuning(ServingConfig(dispatch_depth=1))
            assert eng.dispatch_depth == 1
            engram.apply_tuning(ServingConfig(dispatch_depth=3))
            assert eng.dispatch_depth == 3
        finally:
            engram._LIVE_ENGINES.discard(eng)
            engram._TUNING = None

    def test_apply_tuning_respects_pinned_depth(self, model):
        """An EngramSpec that pins dispatchDepth keeps its value across
        operator reloads of unrelated serving keys."""
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram

        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), dispatch_depth=1)
        eng._engram_pinned = frozenset({"dispatch_depth"})
        engram._LIVE_ENGINES.add(eng)
        try:
            engram.apply_tuning(ServingConfig(dispatch_depth=4))
            assert eng.dispatch_depth == 1  # pinned single-buffered
        finally:
            engram._LIVE_ENGINES.discard(eng)
            engram._TUNING = None
