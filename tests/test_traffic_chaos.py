"""Chaos soak: the traffic autoscaler under PR-3 preemption injection.

The trap this pins (ISSUE 14): a replica's slice reclaimed WHILE the
autoscaler is mid-decision must neither double-count capacity (the
dead replica's chips released once, never twice; max-replicas honored
against the true footprint) nor strand a drain (a drain in progress on
the preempted replica is finished by force, not left dangling).

Deterministic: the PreemptionInjector's seeded plan() decides which
autoscaler-added replica dies and after how many loadgen ticks —
exactly the contract the gang executor consults — and every loadgen
arrival is seed-replayed. Condition-wait based: loops wait on state,
never on wall-clock guesses.
"""

import types

import jax
import pytest

from bobrapet_tpu.api.shared import TPUPolicy
from bobrapet_tpu.controllers.workload_sim import PreemptionInjector
from bobrapet_tpu.models import llama
from bobrapet_tpu.parallel.placement import SlicePlacer, SlicePool
from bobrapet_tpu.serving import PagedConfig, ServingEngine, ServingRouter
from bobrapet_tpu.traffic import (
    Autoscaler,
    AutoscalePolicy,
    ClosedLoopLoadGen,
    EngineReplicaSet,
    TenantProfile,
    TrafficPhase,
)

pytestmark = [pytest.mark.slow, pytest.mark.chaos]


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    """Lockdep for the traffic chaos soak (see test_concurrency.py)."""
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace over the traffic harness: loadgen user tables, fair
    queues, autoscaler pools and serving router queues are tracked
    (see test_concurrency.py for the contract)."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(model):
    cfg, params = model
    return ServingEngine(params, cfg, PagedConfig(
        max_slots=4, block_size=16, num_blocks=128, max_blocks_per_seq=8))


def _grant_job(grant: dict) -> types.SimpleNamespace:
    """Duck-typed gang Job over a replica's slice grant — the exact
    surface PreemptionInjector.plan consults."""
    return types.SimpleNamespace(
        spec={"hosts": grant.get("hosts", 1), "sliceGrant": grant}
    )


class _ChaosReplicaSet(EngineReplicaSet):
    """Replica set that rolls the injector's plan on every scale-up —
    a planned replica is preempted after the plan's poll count of
    loadgen ticks (the injector's cooperative-deadline fuse, with
    loadgen ticks standing in for deadline polls)."""

    def __init__(self, *a, injector: PreemptionInjector, **kw):
        super().__init__(*a, **kw)
        self.injector = injector
        #: name -> remaining ticks until the planned preemption fires
        self.fuses: dict[str, int] = {}
        self.preempted: list[str] = []

    def scale_up(self, now, reason):
        name = super().scale_up(now, reason)
        if name is not None:
            grant = self.grants.get(name)
            if grant is not None:
                plan = self.injector.plan(_grant_job(grant))
                if plan is not None:
                    # one loadgen tick stands in for a (much longer)
                    # cooperative deadline poll: a short fuse fires
                    # while the replica still holds live work
                    self.fuses[name] = plan["afterPolls"] * 5
        return name

    def chaos_tick(self) -> None:
        for name in list(self.fuses):
            if name not in self.grants:
                self.fuses.pop(name)  # already drained/removed
                continue
            self.fuses[name] -= 1
            if self.fuses[name] <= 0:
                self.fuses.pop(name)
                self.preempted.append(name)
                self.preempt(name)


class TestTrafficChaosSoak:
    def test_preemption_during_autoscale_exactly_once(self, model):
        placer = SlicePlacer([SlicePool("serve", "4x4", chips_per_host=4)])
        pool = placer.pool("serve")
        router = ServingRouter({"d0": _engine(model)})
        injector = PreemptionInjector(rate=1.0, seed=1234, min_hosts=1)
        rs = _ChaosReplicaSet(
            "decode", router, lambda: _engine(model),
            placer=placer, queue="serve", tpu=TPUPolicy(topology="2x2"),
            injector=injector,
        )
        scaler = Autoscaler(
            {"decode": rs},
            AutoscalePolicy(
                min_replicas=1, max_replicas=3,
                scale_up_burn=0.5, scale_down_burn=0.05,
                queue_depth_per_replica=2,
                scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.02,
            ),
            interval_s=0.0,
        )
        free0 = pool.free_chips()
        min_free_seen = [free0]

        def hook(_now):
            scaler.tick()
            rs.chaos_tick()
            min_free_seen[0] = min(min_free_seen[0], pool.free_chips())

        profiles = [
            TenantProfile("alpha", users=8, prompt_len=(10, 20),
                          new_tokens=(12, 24), max_requests=80),
            TenantProfile("beta", users=8, prompt_len=(10, 20),
                          new_tokens=(12, 24), max_requests=80),
        ]
        phases = [TrafficPhase("burst", 3.0, rate=20.0),
                  TrafficPhase("trough", 2.0, rate=0.2)]
        rep = ClosedLoopLoadGen(
            router, profiles, phases=phases, seed=42, tick_hooks=[hook],
        ).run(max_duration_s=90.0)

        # the soak actually exercised the chaos path
        assert injector.planned >= 1 and rs.preempted, (
            "seeded plan never fired — chaos leg inert"
        )
        # zero lost work: every submitted rid retired exactly ONCE even
        # through evictions (requeued continuations keep their rid)
        assert rep.lost == 0
        rids = [r.rid for r in router.finished]
        assert len(rids) == len(set(rids)) == rep.completed == rep.submitted
        # capacity never double-counted: the replica cap bounds grants
        # at every instant (3 x 2x2 = 12 chips over the 16-chip pool)
        assert min_free_seen[0] >= free0 - 12

        # condition-wait the system back to quiescence: drains finish,
        # grants release, nothing stranded
        import time as _t

        deadline = _t.monotonic() + 30.0
        while _t.monotonic() < deadline:
            router.step()
            scaler.tick()
            rs.chaos_tick()
            if (rs.draining() == 0 and rs.actual() == 1
                    and pool.free_chips() == free0):
                break
            _t.sleep(0.002)
        assert rs.draining() == 0, "stranded drain"
        assert rs.actual() == 1
        assert pool.free_chips() == free0, (
            "grant leaked or double-released"
        )
        assert rs.grants == {}

    def test_preempt_mid_drain_is_not_stranded(self, model):
        """The sharpest corner: the victim of a scale-down drain is
        preempted BEFORE its drain empties. The drain must resolve (by
        force), its grant release exactly once, and its in-flight work
        requeue and finish."""
        placer = SlicePlacer([SlicePool("serve", "4x4", chips_per_host=4)])
        pool = placer.pool("serve")
        router = ServingRouter({"d0": _engine(model)})
        rs = EngineReplicaSet(
            "decode", router, lambda: _engine(model),
            placer=placer, queue="serve", tpu=TPUPolicy(topology="2x2"),
        )
        free0 = pool.free_chips()
        name = rs.scale_up(now=0.0, reason="test")
        assert name is not None and pool.free_chips() == free0 - 4

        rids = [router.submit(list(range(5, 5 + 10)), max_new_tokens=48)
                for _ in range(8)]
        for _ in range(2):
            router.step()  # work lands on both replicas
        assert router.engines[name].in_flight > 0
        rs.begin_drain(now=1.0, reason="test")
        assert rs.draining() == 1
        # the draining replica's slice is reclaimed mid-retirement
        requeued = rs.preempt(name)
        assert requeued > 0
        assert rs.draining() == 0, "drain stranded by the preemption"
        assert pool.free_chips() == free0, "grant not released exactly once"
        # a concurrent scale decision sees truthful capacity: the dead
        # replica is gone from actual AND draining
        assert rs.actual() == 1
        fin = router.run()
        assert sorted(r.rid for r in fin) == sorted(rids)
        assert len({r.rid for r in fin}) == len(rids)
        # poll_drains on the evicted name is a no-op, not an error
        assert rs.poll_drains(now=2.0) == []
