"""S3 through the wire: SigV4-signed HTTP against a real protocol stub.

VERDICT r4 #2: "a Story with storage.s3 policy offloads and rehydrates
through the wire protocol in tests, no injected fake." The stub here is
an in-process HTTP server speaking the S3 REST dialect (PutObject /
GetObject / DeleteObject / HeadObject / ListObjectsV2 XML) that
VERIFIES each request's AWS SigV4 signature by recomputing it from the
shared secret — so the client's canonicalization, signing-key
derivation, and header set are all exercised for real, not assumed.
An env-gated mode (``BOBRA_S3_TEST_ENDPOINT``) points the same tests at
a real S3-compatible endpoint (e.g. MinIO), mirroring the reference's
gated integration test (pkg/storage/s3_integration_test.go).
"""

from __future__ import annotations

import http.server
import os
import threading
import urllib.parse
from xml.sax.saxutils import escape

import pytest

from bobrapet_tpu.storage import S3Store, build_store
from bobrapet_tpu.storage.s3http import (
    ENV_S3_ACCESS_KEY_ID,
    ENV_S3_ENDPOINT,
    ENV_S3_SECRET_ACCESS_KEY,
    ENV_S3_USE_PATH_STYLE,
    S3HttpClient,
    SigV4Signer,
    client_from_policy,
)
from bobrapet_tpu.storage.store import BlobNotFound, StorageError

ACCESS_KEY, SECRET_KEY = "bobra-test-key", "bobra-test-secret"  # noqa: S105


class S3Stub(http.server.ThreadingHTTPServer):
    """In-memory S3-compatible endpoint with SigV4 verification."""

    def __init__(self, require_auth: bool = True, page_size: int = 1000):
        self.blobs: dict[tuple[str, str], bytes] = {}
        self.require_auth = require_auth
        self.page_size = page_size
        self.requests_seen: list[str] = []
        super().__init__(("127.0.0.1", 0), _Handler)

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.server_address[1]}"


class _Handler(http.server.BaseHTTPRequestHandler):
    server: S3Stub

    def log_message(self, fmt, *args):  # noqa: D102 - quiet
        pass

    # -- SigV4 verification ------------------------------------------------

    def _verify_sig(self) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return False
        parts = dict(
            p.strip().split("=", 1)
            for p in auth[len("AWS4-HMAC-SHA256 "):].split(",")
        )
        credential = parts.get("Credential", "")
        access_key, _date, region, _svc, _term = (
            credential.split("/") + [""] * 5
        )[:5]
        if access_key != ACCESS_KEY:
            return False
        # recompute the signature over the request exactly as received
        signer = SigV4Signer(ACCESS_KEY, SECRET_KEY, region=region)
        signed_names = parts.get("SignedHeaders", "").split(";")
        headers = {
            name: self.headers.get(name, "") for name in signed_names
        }
        import datetime

        amz = self.headers.get("x-amz-date", "")
        now = datetime.datetime.strptime(
            amz, "%Y%m%dT%H%M%SZ"
        ).replace(tzinfo=datetime.timezone.utc)
        url = f"http://{self.headers.get('host')}{self.path}"
        recomputed = signer.sign(
            self.command, url, {
                k: v for k, v in headers.items()
                if k not in ("x-amz-date", "x-amz-content-sha256", "host")
            },
            self.headers.get("x-amz-content-sha256", ""), now=now,
        )["Authorization"]
        return recomputed.rsplit("Signature=", 1)[-1] == parts.get(
            "Signature"
        )

    # -- request routing ---------------------------------------------------

    def _route(self):
        self.server.requests_seen.append(f"{self.command} {self.path}")
        if self.server.require_auth and not self._verify_sig():
            self.send_response(403)
            self.end_headers()
            self.wfile.write(b"<Error><Code>SignatureDoesNotMatch</Code></Error>")
            return None
        parsed = urllib.parse.urlsplit(self.path)
        segs = parsed.path.lstrip("/").split("/", 1)
        bucket = segs[0]
        key = urllib.parse.unquote(segs[1]) if len(segs) > 1 else ""
        query = dict(urllib.parse.parse_qsl(parsed.query))
        return bucket, key, query

    def do_PUT(self):  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        bucket, key, _ = routed
        length = int(self.headers.get("Content-Length", "0"))
        self.server.blobs[(bucket, key)] = self.rfile.read(length)
        self.send_response(200)
        self.end_headers()

    def do_GET(self):  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        bucket, key, query = routed
        if not key and query.get("list-type") == "2":
            return self._list(bucket, query)
        data = self.server.blobs.get((bucket, key))
        if data is None:
            self.send_response(404)
            self.end_headers()
            self.wfile.write(b"<Error><Code>NoSuchKey</Code></Error>")
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Last-Modified", self.date_time_string())
        self.end_headers()
        self.wfile.write(data)

    def do_HEAD(self):  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        bucket, key, _ = routed
        data = self.server.blobs.get((bucket, key))
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Last-Modified", self.date_time_string())
        self.end_headers()

    def do_DELETE(self):  # noqa: N802
        routed = self._route()
        if routed is None:
            return
        bucket, key, _ = routed
        self.server.blobs.pop((bucket, key), None)
        self.send_response(204)
        self.end_headers()

    def _list(self, bucket: str, query: dict):
        prefix = query.get("prefix", "")
        after = query.get("start-after", "")
        keys = sorted(
            k for (b, k) in self.server.blobs
            if b == bucket and k.startswith(prefix) and k > after
        )
        page, truncated = (
            keys[: self.server.page_size],
            len(keys) > self.server.page_size,
        )
        body = (
            '<?xml version="1.0"?>'
            '<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            + f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            + "".join(
                f"<Contents><Key>{escape(k)}</Key>"
                "<LastModified>2026-01-01T00:00:00.000Z</LastModified>"
                "</Contents>"
                for k in page
            )
            + "</ListBucketResult>"
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/xml")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def stub():
    srv = S3Stub()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def make_client(stub, **kw) -> S3HttpClient:
    kw.setdefault("access_key", ACCESS_KEY)
    kw.setdefault("secret_key", SECRET_KEY)
    return S3HttpClient(endpoint=stub.endpoint, use_path_style=True, **kw)


class TestWireRoundTrip:
    def test_put_get_delete_exists(self, stub):
        store = S3Store(bucket="runs", client=make_client(stub))
        store.put("ns/run/step.json", b'{"x": 1}')
        assert stub.blobs[("runs", "ns/run/step.json")] == b'{"x": 1}'
        assert store.get("ns/run/step.json") == b'{"x": 1}'
        assert store.exists("ns/run/step.json") is True
        assert store.stat_mtime("ns/run/step.json") > 0
        store.delete("ns/run/step.json")
        assert store.exists("ns/run/step.json") is False
        with pytest.raises(BlobNotFound):
            store.get("ns/run/step.json")

    def test_list_with_prefix_and_pagination(self, stub):
        stub.page_size = 2
        store = S3Store(bucket="runs", client=make_client(stub))
        for i in range(5):
            store.put(f"recordings/s/{i:03d}.jsonl", b"x")
        store.put("other/blob", b"y")
        keys = store.list("recordings/s/")
        assert keys == [f"recordings/s/{i:03d}.jsonl" for i in range(5)]

    def test_prefix_scoping(self, stub):
        store = S3Store(bucket="runs", client=make_client(stub),
                        prefix="tenant-a")
        store.put("k", b"v")
        assert ("runs", "tenant-a/k") in stub.blobs
        assert store.list("") == ["k"]

    def test_special_characters_in_keys(self, stub):
        store = S3Store(bucket="runs", client=make_client(stub))
        key = "ns/run a+b/step=1/out put.json"
        store.put(key, b"data")
        assert store.get(key) == b"data"
        assert key in store.list("ns/")


class TestSigV4:
    def test_bad_secret_rejected_by_wire(self, stub):
        store = S3Store(
            bucket="runs",
            client=make_client(stub, secret_key="wrong-secret"),
            retries=0,
        )
        with pytest.raises(StorageError, match="403|Signature"):
            store.put("k", b"v")

    def test_anonymous_rejected_when_auth_required(self, stub):
        client = S3HttpClient(endpoint=stub.endpoint, use_path_style=True)
        store = S3Store(bucket="runs", client=client, retries=0)
        with pytest.raises(StorageError):
            store.put("k", b"v")

    def test_anonymous_allowed_without_auth(self):
        srv = S3Stub(require_auth=False)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            client = S3HttpClient(endpoint=srv.endpoint, use_path_style=True)
            store = S3Store(bucket="pub", client=client)
            store.put("k", b"v")
            assert store.get("k") == b"v"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_session_token_is_signed(self, stub):
        store = S3Store(
            bucket="runs",
            client=make_client(stub, session_token="tok-123"),  # noqa: S106
        )
        store.put("k", b"v")  # stub recomputes WITH the token header
        assert store.get("k") == b"v"


class TestBuildStore:
    def test_policy_to_wire(self, stub, monkeypatch):
        from bobrapet_tpu.api.shared import S3StorageProvider, StoragePolicy

        monkeypatch.setenv(ENV_S3_ACCESS_KEY_ID, ACCESS_KEY)
        monkeypatch.setenv(ENV_S3_SECRET_ACCESS_KEY, SECRET_KEY)
        policy = StoragePolicy(s3=S3StorageProvider(
            bucket="runs", endpoint=stub.endpoint, use_path_style=True,
        ))
        store = build_store(policy)
        store.put("from-policy", b"bytes")
        assert stub.blobs[("runs", "from-policy")] == b"bytes"
        assert store.get("from-policy") == b"bytes"

    def test_env_overrides_policy(self, stub, monkeypatch):
        from bobrapet_tpu.api.shared import S3StorageProvider

        monkeypatch.setenv(ENV_S3_ENDPOINT, stub.endpoint)
        monkeypatch.setenv(ENV_S3_USE_PATH_STYLE, "true")
        client = client_from_policy(S3StorageProvider(
            bucket="b", endpoint="https://unreachable.invalid",
        ))
        assert client.endpoint == stub.endpoint
        assert client.use_path_style is True

    def test_default_region_and_endpoint_shape(self):
        from bobrapet_tpu.api.shared import S3StorageProvider

        client = client_from_policy(S3StorageProvider(bucket="b"),
                                    environ={})
        assert client.region == "us-east-1"
        assert client.endpoint == "https://s3.us-east-1.amazonaws.com"
        assert client._url("b", "k") == (
            "https://b.s3.us-east-1.amazonaws.com/k"
        )


class TestStoryOffloadThroughWire:
    def test_story_offloads_and_rehydrates_via_s3(self, stub, monkeypatch):
        """The full path: engram output > inline cap -> dehydrated into
        the S3 stub over signed HTTP -> next step and story output
        hydrate it back. No injected fakes anywhere."""
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.shared import S3StorageProvider, StoragePolicy
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.runtime import Runtime
        from bobrapet_tpu.sdk import register_engram

        monkeypatch.setenv(ENV_S3_ACCESS_KEY_ID, ACCESS_KEY)
        monkeypatch.setenv(ENV_S3_SECRET_ACCESS_KEY, SECRET_KEY)
        policy = StoragePolicy(s3=S3StorageProvider(
            bucket="offload", endpoint=stub.endpoint, use_path_style=True,
        ))
        rt = Runtime(blob_store=build_store(policy))

        big = "x" * (64 * 1024)

        @register_engram("s3-producer")
        def producer(ctx):
            return {"blob": big}

        @register_engram("s3-consumer")
        def consumer(ctx):
            return {"length": len(ctx.inputs["data"])}

        rt.apply(make_engram_template("s3-producer-tpl",
                                      entrypoint="s3-producer"))
        rt.apply(make_engram("producer", "s3-producer-tpl"))
        rt.apply(make_engram_template("s3-consumer-tpl",
                                      entrypoint="s3-consumer"))
        rt.apply(make_engram("consumer", "s3-consumer-tpl"))
        rt.apply(make_story("s3-story", steps=[
            {"name": "make", "ref": {"name": "producer"}},
            {"name": "use", "ref": {"name": "consumer"}, "needs": ["make"],
             "with": {"data": "{{ steps.make.output.blob }}"}},
        ], output={"length": "{{ steps.use.output.length }}"}))

        run = rt.run_story("s3-story")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded", (
            rt.store.get("StoryRun", "default", run).status
        )
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["output"]["length"] == 64 * 1024
        # the big payload really crossed the wire into the stub
        offloaded = [k for (b, k) in stub.blobs if b == "offload"]
        assert offloaded, stub.requests_seen[-10:]
        signed_puts = [r for r in stub.requests_seen if r.startswith("PUT ")]
        assert signed_puts


@pytest.mark.skipif(
    not os.environ.get("BOBRA_S3_TEST_ENDPOINT"),
    reason="set BOBRA_S3_TEST_ENDPOINT (+ credentials env) for the "
           "real-endpoint S3 integration mode",
)
class TestRealEndpoint:
    """Env-gated real-endpoint mode (reference:
    pkg/storage/s3_integration_test.go gates on env the same way)."""

    def test_round_trip_against_real_endpoint(self):
        client = S3HttpClient(
            endpoint=os.environ["BOBRA_S3_TEST_ENDPOINT"],
            region=os.environ.get("BOBRA_STORAGE_S3_REGION", "us-east-1"),
            access_key=os.environ.get(ENV_S3_ACCESS_KEY_ID),
            secret_key=os.environ.get(ENV_S3_SECRET_ACCESS_KEY),
            use_path_style=True,
        )
        bucket = os.environ.get("BOBRA_S3_TEST_BUCKET", "bobra-test")
        store = S3Store(bucket=bucket, client=client)
        store.put("integration/probe", b"hello")
        assert store.get("integration/probe") == b"hello"
        store.delete("integration/probe")
