"""bobralint analyzer tests (ISSUE 4).

Three layers:

1. **fixture corpus** — ``tests/analysis_corpus/`` holds a good/bad
   pair per checker. Every line tagged ``# BAD`` in a bad fixture must
   be flagged by its checker; the good twin must produce zero findings.
   Corpus files are fed to the checkers under a ``bobrapet_tpu/``
   pseudo-path (so path-scoped checkers engage) against the REAL repo
   context, so the drift checkers validate against the live registries.
2. **framework** — fingerprint stability under line shifts, baseline
   loader rejections (placeholder justifications, duplicates), stale
   detection.
3. **self-run** — the repo itself is clean modulo the checked-in
   baseline, and the baseline carries no stale entries; this is the
   same gate ``make analyze`` / CI runs.
"""

from __future__ import annotations

import ast
import json
import os

import pytest

from bobrapet_tpu.analysis import Baseline, BaselineError, load_project, run_checkers
from bobrapet_tpu.analysis.checkers import ALL_CHECKERS
from bobrapet_tpu.analysis.context import DYNAMIC_CONFIG_FAMILIES
from bobrapet_tpu.analysis.core import ProjectFile

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
CORPUS = os.path.join(os.path.dirname(__file__), "analysis_corpus")
CHECKERS = {c.name: c for c in ALL_CHECKERS}


@pytest.fixture(scope="module")
def repo_ctx():
    ctx, errors = load_project(REPO_ROOT)
    assert not errors, errors
    return ctx


def corpus_findings(ctx, checker_name: str, fname: str):
    path = os.path.join(CORPUS, fname)
    with open(path, encoding="utf-8") as f:
        source = f.read()
    rel = f"bobrapet_tpu/_corpus/{fname}"
    pf = ProjectFile(path=path, rel=rel, source=source, tree=ast.parse(source))
    found = CHECKERS[checker_name].run([pf], ctx)
    return [f for f in found if f.path == rel], source


def bad_lines(source: str) -> set[int]:
    return {
        i for i, line in enumerate(source.splitlines(), 1) if "# BAD" in line
    }


class TestCheckerCorpus:
    @pytest.mark.parametrize("name", sorted(CHECKERS))
    def test_bad_fixture_fully_flagged(self, repo_ctx, name):
        fname = name.replace("-", "_") + "_bad.py"
        findings, source = corpus_findings(repo_ctx, name, fname)
        assert findings, f"{name} found nothing in its bad fixture"
        assert {f.checker for f in findings} == {name}
        flagged = {f.line for f in findings}
        missed = bad_lines(source) - flagged
        assert not missed, (
            f"{name} missed tagged lines {sorted(missed)} in {fname} "
            f"(flagged: {sorted(flagged)})"
        )

    @pytest.mark.parametrize("name", sorted(CHECKERS))
    def test_good_fixture_clean(self, repo_ctx, name):
        fname = name.replace("-", "_") + "_good.py"
        findings, _ = corpus_findings(repo_ctx, name, fname)
        assert not findings, (
            f"{name} false positives in its good fixture:\n"
            + "\n".join(f.render() for f in findings)
        )


class TestFramework:
    def test_fingerprint_survives_line_shift(self, repo_ctx):
        fname = "cow_discipline_bad.py"
        a, src = corpus_findings(repo_ctx, "cow-discipline", fname)
        # same code, pushed 3 lines down: fingerprints must not move
        shifted_src = "\n\n\n" + src
        rel = f"bobrapet_tpu/_corpus/{fname}"
        pf = ProjectFile(
            path="x", rel=rel, source=shifted_src, tree=ast.parse(shifted_src)
        )
        b = [
            f
            for f in CHECKERS["cow-discipline"].run([pf], repo_ctx)
            if f.path == rel
        ]
        assert {f.fingerprint for f in a} == {f.fingerprint for f in b}
        assert {f.line for f in a} != {f.line for f in b}

    def test_baseline_rejects_placeholder_justification(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({
            "version": 1,
            "suppressions": [{
                "fingerprint": "abc123def456", "checker": "x", "path": "y",
                "scope": "", "message": "m", "justification": "TODO",
            }],
        }))
        with pytest.raises(BaselineError, match="real justification"):
            Baseline.load(str(p))

    def test_baseline_rejects_duplicates(self, tmp_path):
        entry = {
            "fingerprint": "abc123def456", "checker": "x", "path": "y",
            "scope": "", "message": "m",
            "justification": "a perfectly valid reason for keeping this",
        }
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"version": 1, "suppressions": [entry, entry]}))
        with pytest.raises(BaselineError, match="duplicate"):
            Baseline.load(str(p))

    def test_partition_new_suppressed_stale(self, repo_ctx, tmp_path):
        findings, _ = corpus_findings(
            repo_ctx, "cow-discipline", "cow_discipline_bad.py"
        )
        keep = findings[0]
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({
            "version": 1,
            "suppressions": [
                {"fingerprint": keep.fingerprint, "checker": keep.checker,
                 "path": keep.path, "scope": keep.scope, "message": keep.message,
                 "justification": "corpus fixture entry used by the test"},
                {"fingerprint": "dead00000000", "checker": "x", "path": "y",
                 "scope": "", "message": "m",
                 "justification": "entry for code that no longer exists"},
            ],
        }))
        new, suppressed, stale = Baseline.load(str(p)).partition(findings)
        assert keep.fingerprint not in {f.fingerprint for f in new}
        assert keep.fingerprint in {f.fingerprint for f in suppressed}
        assert [s.fingerprint for s in stale] == ["dead00000000"]

    def test_dynamic_config_families_still_parsed(self):
        """The checker's hardcoded dynamic-family regexes must keep
        matching keys _apply_dotted actually parses structurally."""
        from bobrapet_tpu.config.operator import parse_config

        keys = {
            "controllers.steprun.max-concurrent-reconciles": "8",
            "scheduling.queue.gpu.max-concurrent": "2",
            "scheduling.queue.gpu.priority-aging": "60s",
            "scheduling.queue.gpu.accelerator": "tpu-v5p-slice",
            "scheduling.queue.gpu.chip-budget": "16",
        }
        cfg = parse_config(keys)
        assert cfg.controllers.per_controller["steprun"] == 8
        q = cfg.scheduling.queues["gpu"]
        assert (q.max_concurrent, q.chip_budget) == (2, 16)
        for key in keys:
            assert any(f.match(key) for f in DYNAMIC_CONFIG_FAMILIES), key


class TestSelfRun:
    """The merged tree must be clean modulo the checked-in baseline —
    the exact gate `make analyze` enforces in CI."""

    def test_repo_clean_modulo_baseline(self, repo_ctx):
        findings = run_checkers(repo_ctx, ALL_CHECKERS)
        baseline = Baseline.load(os.path.join(REPO_ROOT, "bobralint-baseline.json"))
        new, _suppressed, stale = baseline.partition(findings)
        assert not new, "NEW findings:\n" + "\n".join(f.render() for f in new)
        assert not stale, (
            "stale baseline entries (prune them): "
            + ", ".join(s.fingerprint for s in stale)
        )

    def test_every_suppression_is_justified_and_reachable(self):
        baseline = Baseline.load(os.path.join(REPO_ROOT, "bobralint-baseline.json"))
        assert baseline.suppressions, "baseline unexpectedly empty"
        for s in baseline.suppressions:
            # loader already enforces this; pin it against loader edits
            assert len(s.justification) >= 10
            assert s.checker in CHECKERS
