"""kubectl is the front door: CR sync between the cluster API and the
bus (cluster/crsync.py).

Reference behaviors under test: CRD kinds served by the API server are
the user interface (cmd/main.go:81-90, :613-790); gate approval is a
``kubectl patch storyrun ... --subresource status`` (README.md
§Workflow Primitives); admission rejection is visible to kubectl.

Every resource in these tests is created ONLY through the cluster API
(the FakeCluster envtest analog) — nothing touches rt.apply().
"""

import pytest

from bobrapet_tpu.api.catalog import CLUSTER_NAMESPACE, make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.api.runs import make_storyrun
from bobrapet_tpu.cluster import FakeCluster
from bobrapet_tpu.cluster.crsync import (
    CR_KINDS,
    manifest_to_resource,
    resource_to_manifest,
)
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram

RUNS_API = "runs.bobrapet.io/v1alpha1"
CORE_API = "bobrapet.io/v1alpha1"
CATALOG_API = "catalog.bobrapet.io/v1alpha1"


def kubectl_apply(cluster, resource):
    """Create a bus-typed resource through the cluster API only."""
    return cluster.create(resource_to_manifest(resource))


@pytest.fixture
def rt():
    return Runtime(executor_backend="cluster")


def admitted_condition(obj):
    for c in (obj.get("status") or {}).get("conditions") or []:
        if c.get("type") == "Admitted":
            return c
    return None


class TestManifestRoundTrip:
    def test_all_12_kinds_have_api_versions(self):
        assert len(CR_KINDS) == 12
        assert CR_KINDS["Story"] == (CORE_API, False)
        assert CR_KINDS["EngramTemplate"] == (CATALOG_API, True)
        assert CR_KINDS["Transport"][1] is True  # cluster-scoped

    def test_round_trip_preserves_spec_and_meta(self):
        story = make_story("s", steps=[{"name": "a", "type": "sleep",
                                        "with": {"duration": "1s"}}])
        story.meta.labels["team"] = "ml"
        m = resource_to_manifest(story)
        assert m["apiVersion"] == CORE_API
        back = manifest_to_resource(m)
        assert back.spec == story.spec
        assert back.meta.labels == {"team": "ml"}
        assert back.meta.namespace == "default"

    def test_cluster_scoped_maps_to_bus_pseudo_namespace(self):
        tpl = make_engram_template("t", entrypoint="x")
        m = resource_to_manifest(tpl)
        assert m["metadata"]["namespace"] == ""
        back = manifest_to_resource(m)
        assert back.meta.namespace == CLUSTER_NAMESPACE


class TestKubectlFrontDoor:
    def test_story_applied_via_cluster_runs_to_completion(self, rt):
        @register_engram("front-impl")
        def impl(ctx):
            return {"ok": True}

        kubectl_apply(rt.cluster, make_engram_template("front-tpl",
                                                       entrypoint="front-impl"))
        kubectl_apply(rt.cluster, make_engram("front", "front-tpl"))
        kubectl_apply(rt.cluster, make_story("front-story", steps=[
            {"name": "a", "ref": {"name": "front"}},
        ]))
        kubectl_apply(rt.cluster, make_storyrun("front-run", "front-story"))
        rt.pump()

        # bus saw it and ran it through the cluster backend
        assert rt.run_phase("front-run") == "Succeeded"
        # ...and kubectl sees the result: status flowed back out
        live = rt.cluster.get(RUNS_API, "StoryRun", "default", "front-run")
        assert live["status"]["phase"] == "Succeeded"
        # bus-originated StepRuns are mirrored for kubectl get stepruns
        steprun_objs = rt.cluster.list(RUNS_API, "StepRun", "default")
        assert len(steprun_objs) == 1
        assert steprun_objs[0]["status"]["phase"] == "Succeeded"

    def test_gate_approved_by_cluster_side_status_patch(self, rt):
        kubectl_apply(rt.cluster, make_story("gated", steps=[
            {"name": "approval", "type": "gate", "with": {"timeout": "1h"}},
        ]))
        kubectl_apply(rt.cluster, make_storyrun("gated-run", "gated"))
        rt.pump()
        assert rt.run_phase("gated-run") == "Running"

        # kubectl patch storyrun gated-run --subresource status ...
        rt.cluster.patch_status(
            RUNS_API, "StoryRun", "default", "gated-run",
            {"status": {"gates": {"approval": {"approved": True,
                                               "approver": "alice"}}}},
        )
        rt.pump()
        assert rt.run_phase("gated-run") == "Succeeded"
        live = rt.cluster.get(RUNS_API, "StoryRun", "default", "gated-run")
        assert live["status"]["phase"] == "Succeeded"
        assert live["status"]["gates"]["approval"]["approver"] == "alice"

    def test_cancel_requested_via_cluster_spec_patch(self, rt):
        kubectl_apply(rt.cluster, make_story("slow", steps=[
            {"name": "z", "type": "gate", "with": {"timeout": "10h"}},
        ]))
        kubectl_apply(rt.cluster, make_storyrun("slow-run", "slow"))
        rt.pump()
        assert rt.run_phase("slow-run") == "Running"
        rt.cluster.patch(RUNS_API, "StoryRun", "default", "slow-run",
                         {"spec": {"cancelRequested": True}})
        rt.pump()
        # graceful cancel drains to Finished (e2e suite parity)
        assert rt.run_phase("slow-run") == "Finished"
        live = rt.cluster.get(RUNS_API, "StoryRun", "default", "slow-run")
        assert live["status"]["phase"] == "Finished"

    def test_spec_edit_flows_in(self, rt):
        kubectl_apply(rt.cluster, make_story("editable", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt.pump()
        story = rt.store.get("Story", "default", "editable")
        gen0 = story.meta.generation
        rt.cluster.patch(CORE_API, "Story", "default", "editable", {
            "spec": {"steps": [{"name": "a", "type": "sleep",
                                "with": {"duration": "2s"}}]},
        })
        story = rt.store.get("Story", "default", "editable")
        assert story.spec["steps"][0]["with"]["duration"] == "2s"
        assert story.meta.generation > gen0

    def test_cluster_delete_removes_bus_object(self, rt):
        kubectl_apply(rt.cluster, make_story("doomed", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        assert rt.store.try_get("Story", "default", "doomed") is not None
        rt.cluster.delete(CORE_API, "Story", "default", "doomed")
        assert rt.store.try_get("Story", "default", "doomed") is None

    def test_bus_delete_mirrors_out(self, rt):
        kubectl_apply(rt.cluster, make_story("mirrored", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        assert rt.cluster.get(CORE_API, "Story", "default", "mirrored")
        rt.store.delete("Story", "default", "mirrored")
        assert rt.cluster.get(CORE_API, "Story", "default", "mirrored") is None


class TestClusterAdmission:
    def test_invalid_story_rejected_with_field_errors(self, rt):
        bad = make_story("bad", steps=[
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
        ])
        kubectl_apply(rt.cluster, bad)
        # never reached the bus
        assert rt.store.try_get("Story", "default", "bad") is None
        # kubectl-visible denial with the field path
        live = rt.cluster.get(CORE_API, "Story", "default", "bad")
        cond = admitted_condition(live)
        assert cond is not None and cond["status"] == "False"
        assert cond["reason"] == "AdmissionDenied"
        assert "duplicate step name" in cond["message"]
        assert "spec.steps[1].name" in cond["message"]

    def test_fixing_the_spec_admits_and_clears_condition(self, rt):
        bad = make_story("fixable", steps=[
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
        ])
        kubectl_apply(rt.cluster, bad)
        assert rt.store.try_get("Story", "default", "fixable") is None
        rt.cluster.patch(CORE_API, "Story", "default", "fixable", {
            "spec": {"steps": [
                {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
                {"name": "dup2", "type": "sleep", "with": {"duration": "1s"}},
            ]},
        })
        assert rt.store.try_get("Story", "default", "fixable") is not None
        live = rt.cluster.get(CORE_API, "Story", "default", "fixable")
        cond = admitted_condition(live)
        assert cond is not None and cond["status"] == "True"

    def test_unchanged_invalid_spec_is_not_rehammered(self, rt):
        """Identical denied spec re-delivered by the watch must not
        re-run admission forever (the rejected-hash guard)."""
        bad = make_story("parked", steps=[
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
        ])
        kubectl_apply(rt.cluster, bad)
        live = rt.cluster.get(CORE_API, "Story", "default", "parked")
        t0 = admitted_condition(live)["lastTransitionTime"]
        # a no-spec-change touch (labels on status patch path) re-fires
        # the watch; condition must not churn
        rt.cluster.patch_status(CORE_API, "Story", "default", "parked",
                                {"status": {"noise": 1}})
        live = rt.cluster.get(CORE_API, "Story", "default", "parked")
        assert admitted_condition(live)["lastTransitionTime"] == t0

    def test_invalid_spec_update_leaves_bus_at_last_good(self, rt):
        kubectl_apply(rt.cluster, make_story("held", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        assert rt.store.try_get("Story", "default", "held") is not None
        rt.cluster.patch(CORE_API, "Story", "default", "held", {
            "spec": {"steps": [
                {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
                {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
            ]},
        })
        # bus keeps the last admitted spec
        story = rt.store.get("Story", "default", "held")
        assert len(story.spec["steps"]) == 1
        live = rt.cluster.get(CORE_API, "Story", "default", "held")
        cond = admitted_condition(live)
        assert cond is not None and cond["status"] == "False"


class TestResyncAndOrdering:
    def test_objects_created_before_manager_sync_on_start(self):
        """Cluster state that predates the manager (apply while the
        operator was down) is picked up by the list-based resync, in
        dependency order, and runs normally."""
        cluster = FakeCluster()
        kubectl_apply(cluster, make_story("early", steps=[
            {"name": "a", "ref": {"name": "w-early"}},
        ]))  # story BEFORE its engram: resync order must still admit
        kubectl_apply(cluster, make_engram_template("tpl-early",
                                                    entrypoint="early-impl"))
        kubectl_apply(cluster, make_engram("w-early", "tpl-early"))
        kubectl_apply(cluster, make_storyrun("early-run", "early"))

        @register_engram("early-impl")
        def impl(ctx):
            return {"ok": 1}

        rt = Runtime(executor_backend="cluster", cluster_client=cluster)
        from bobrapet_tpu.cluster import FakeKubelet
        FakeKubelet(cluster, store=rt.store, storage=rt.storage,
                    clock=rt.clock, mode="sync")
        rt.pump()
        assert rt.run_phase("early-run") == "Succeeded"

    def test_cluster_scoped_template_lands_in_pseudo_namespace(self, rt):
        kubectl_apply(rt.cluster, make_engram_template("scoped-tpl",
                                                       entrypoint="x"))
        tpl = rt.store.try_get("EngramTemplate", CLUSTER_NAMESPACE, "scoped-tpl")
        assert tpl is not None
        # and mirrors back out under the empty cluster namespace
        live = rt.cluster.get(CATALOG_API, "EngramTemplate", "", "scoped-tpl")
        assert live is not None

    def test_local_apply_still_mirrors_out(self, rt):
        """The bus-side API keeps working under the cluster backend; a
        locally applied Story is visible to kubectl."""
        rt.apply(make_story("local", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        live = rt.cluster.get(CORE_API, "Story", "default", "local")
        assert live is not None
        assert live["spec"]["steps"][0]["name"] == "a"


class TestOwnershipAndHealing:
    def test_status_push_does_not_revert_parked_cluster_edit(self, rt):
        """A controller status write must not push the bus spec back
        over a newer (parked-invalid) cluster-side edit."""
        kubectl_apply(rt.cluster, make_story("ownr", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        bad_steps = [
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
        ]
        rt.cluster.patch(CORE_API, "Story", "default", "ownr",
                         {"spec": {"steps": bad_steps}})
        live = rt.cluster.get(CORE_API, "Story", "default", "ownr")
        assert admitted_condition(live)["status"] == "False"
        # a bus status write (controller activity) fires a push
        rt.store.patch_status("Story", "default", "ownr",
                              lambda s: s.update(observed=1))
        live = rt.cluster.get(CORE_API, "Story", "default", "ownr")
        # the parked edit survived — no silent revert to the bus spec
        assert [s["name"] for s in live["spec"]["steps"]] == ["dup", "dup"]
        # and the kubectl-visible denial survived the status push too
        assert admitted_condition(live)["status"] == "False"

    def test_denial_condition_survives_status_push_without_conditions(self, rt):
        kubectl_apply(rt.cluster, make_story("denied", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt.cluster.patch(CORE_API, "Story", "default", "denied", {
            "spec": {"steps": [
                {"name": "d", "type": "sleep", "with": {"duration": "1s"}},
                {"name": "d", "type": "sleep", "with": {"duration": "1s"}},
            ]},
        })
        assert admitted_condition(
            rt.cluster.get(CORE_API, "Story", "default", "denied"))["status"] == "False"
        # bus status has no 'conditions' key at all
        rt.store.patch_status("Story", "default", "denied",
                              lambda s: s.update(phase="Ready"))
        live = rt.cluster.get(CORE_API, "Story", "default", "denied")
        cond = admitted_condition(live)
        assert cond is not None and cond["status"] == "False"

    def test_parked_rejection_heals_via_dependency_update(self, rt):
        """A cycle rejection heals when the OTHER story is edited to
        break the cycle (retry fires on the update-admit path)."""
        kubectl_apply(rt.cluster, make_story("y-story", steps=[
            {"name": "call", "type": "executeStory",
             "with": {"storyRef": {"name": "x-story"}}},
        ]))
        # x -> y while y -> x: rejected as a cycle
        kubectl_apply(rt.cluster, make_story("x-story", steps=[
            {"name": "call", "type": "executeStory",
             "with": {"storyRef": {"name": "y-story"}}},
        ]))
        assert rt.store.try_get("Story", "default", "x-story") is None
        # break the cycle by editing Y cluster-side
        rt.cluster.patch(CORE_API, "Story", "default", "y-story", {
            "spec": {"steps": [
                {"name": "call", "type": "sleep", "with": {"duration": "1s"}},
            ]},
        })
        assert rt.store.try_get("Story", "default", "x-story") is not None
        cond = admitted_condition(
            rt.cluster.get(CORE_API, "Story", "default", "x-story"))
        assert cond is not None and cond["status"] == "True"


class TestManagerDowntime:
    def test_kubectl_delete_while_down_is_honored_not_resurrected(self, tmp_path):
        """A mirrored object deleted cluster-side while the manager is
        down is pruned from the persisted bus on restart, not pushed
        back to the cluster."""
        persist = str(tmp_path / "bus")
        cluster = FakeCluster()
        rt1 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        kubectl_apply(cluster, make_story("ephemeral", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        assert rt1.store.try_get("Story", "default", "ephemeral") is not None
        rt1.stop()
        # manager down; user deletes via kubectl
        cluster.delete(CORE_API, "Story", "default", "ephemeral")

        rt2 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        assert rt2.store.try_get("Story", "default", "ephemeral") is None
        assert cluster.get(CORE_API, "Story", "default", "ephemeral") is None
        rt2.stop()

    def test_bus_object_never_mirrored_is_pushed_not_pruned(self, tmp_path):
        persist = str(tmp_path / "bus")
        rt1 = Runtime(persist_dir=persist)  # LOCAL backend: no mirroring
        rt1.apply(make_story("fresh", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt1.stop()
        cluster = FakeCluster()
        rt2 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        # first cluster-backed start: the un-mirrored object bootstraps out
        assert cluster.get(CORE_API, "Story", "default", "fresh") is not None
        rt2.stop()

    def test_gate_approved_while_down_is_merged_on_first_sync(self):
        """kubectl gate approval landed while the manager was down; the
        restart's resync must deliver it (create path merges user
        status)."""
        cluster = FakeCluster()
        kubectl_apply(cluster, make_story("gated-dt", steps=[
            {"name": "approval", "type": "gate", "with": {"timeout": "1h"}},
        ]))
        run_manifest = resource_to_manifest(make_storyrun("dt-run", "gated-dt"))
        run_manifest["status"] = {
            "gates": {"approval": {"approved": True, "approver": "bob"}}
        }
        cluster.create(run_manifest)

        rt = Runtime(executor_backend="cluster", cluster_client=cluster)
        rt.pump()
        assert rt.run_phase("dt-run") == "Succeeded"
        rt.stop()

    def test_stop_detaches_the_mirror(self, rt):
        rt.stop()
        rt.apply(make_story("post-stop", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        assert rt.cluster.get(CORE_API, "Story", "default", "post-stop") is None


class TestDowntimeEdits:
    def test_parked_edit_survives_manager_restart(self, tmp_path):
        """An invalid cluster-side edit made while the manager is down
        must stay parked (Admitted=False) after restart — not be
        silently reverted by the resync push-out."""
        persist = str(tmp_path / "bus")
        cluster = FakeCluster()
        rt1 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        kubectl_apply(cluster, make_story("edit-dt", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt1.stop()
        cluster.patch(CORE_API, "Story", "default", "edit-dt", {
            "spec": {"steps": [
                {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
                {"name": "dup", "type": "sleep", "with": {"duration": "1s"}},
            ]},
        })
        rt2 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        live = cluster.get(CORE_API, "Story", "default", "edit-dt")
        # the user's pending edit is intact, visibly denied
        assert [s["name"] for s in live["spec"]["steps"]] == ["dup", "dup"]
        assert admitted_condition(live)["status"] == "False"
        # bus keeps last-good
        assert len(rt2.store.get("Story", "default", "edit-dt").spec["steps"]) == 1
        rt2.stop()

    def test_failed_list_parks_pushout_for_that_kind(self, tmp_path):
        """When a kind's resync list fails, push-out must not run for
        it — blind pushes would resurrect kubectl-deleted objects."""
        persist = str(tmp_path / "bus")
        cluster = FakeCluster()
        rt1 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        kubectl_apply(cluster, make_story("blip", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt1.stop()
        cluster.delete(CORE_API, "Story", "default", "blip")

        orig_list = cluster.list

        def flaky_list(api_version, kind, namespace=None, labels=None):
            if kind == "Story":
                raise RuntimeError("transient apiserver blip")
            return orig_list(api_version, kind, namespace, labels)

        cluster.list = flaky_list
        rt2 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        cluster.list = orig_list
        # not resurrected cluster-side despite the failed list
        assert cluster.get(CORE_API, "Story", "default", "blip") is None
        rt2.stop()

    def test_second_gate_patch_merges_nested_fields(self, rt):
        kubectl_apply(rt.cluster, make_story("g2", steps=[
            {"name": "approval", "type": "gate", "with": {"timeout": "1h"}},
        ]))
        kubectl_apply(rt.cluster, make_storyrun("g2-run", "g2"))
        rt.pump()
        rt.cluster.patch_status(
            RUNS_API, "StoryRun", "default", "g2-run",
            {"status": {"gates": {"approval": {"approved": True}}}},
        )
        # a second kubectl patch ADDING a sub-field to the existing gate
        rt.cluster.patch_status(
            RUNS_API, "StoryRun", "default", "g2-run",
            {"status": {"gates": {"approval": {"comment": "lgtm"}}}},
        )
        rt.pump()
        run = rt.store.get("StoryRun", "default", "g2-run")
        assert run.status["gates"]["approval"]["comment"] == "lgtm"
        live = rt.cluster.get(RUNS_API, "StoryRun", "default", "g2-run")
        assert live["status"]["gates"]["approval"]["comment"] == "lgtm"
        assert live["status"]["phase"] == "Succeeded"


class TestFreshBusRestart:
    def test_completed_run_is_adopted_not_reexecuted(self):
        """Restarting with a fresh in-memory bus adopts the cluster's
        persisted run state; it must not wipe status and re-fire side
        effects."""
        calls = []

        @register_engram("fresh.impl")
        def impl(ctx):
            calls.append(1)
            return {"ok": True}

        cluster = FakeCluster()
        rt1 = Runtime(executor_backend="cluster", cluster_client=cluster)
        kubectl_apply(cluster, make_engram_template("fr-tpl",
                                                    entrypoint="fresh.impl"))
        kubectl_apply(cluster, make_engram("fr", "fr-tpl"))
        kubectl_apply(cluster, make_story("fr-story", steps=[
            {"name": "a", "ref": {"name": "fr"}},
        ]))
        kubectl_apply(cluster, make_storyrun("fr-run", "fr-story"))
        rt1.pump()
        assert rt1.run_phase("fr-run") == "Succeeded"
        assert calls == [1]
        rt1.stop()

        # fresh bus, same cluster
        from bobrapet_tpu.cluster import FakeKubelet
        rt2 = Runtime(executor_backend="cluster", cluster_client=cluster)
        FakeKubelet(cluster, store=rt2.store, storage=rt2.storage,
                    clock=rt2.clock, mode="sync")
        rt2.pump()
        # adopted, still Succeeded, NOT re-executed
        assert rt2.run_phase("fr-run") == "Succeeded"
        live = cluster.get(RUNS_API, "StoryRun", "default", "fr-run")
        assert live["status"]["phase"] == "Succeeded"
        assert calls == [1]
        rt2.stop()

    def test_gate_approval_flows_while_spec_is_parked(self, rt):
        """A parked-invalid spec edit must not block gate decisions."""
        kubectl_apply(rt.cluster, make_story("pk", steps=[
            {"name": "approval", "type": "gate", "with": {"timeout": "1h"}},
        ]))
        kubectl_apply(rt.cluster, make_storyrun("pk-run", "pk"))
        rt.pump()
        assert rt.run_phase("pk-run") == "Running"
        # park an invalid spec edit on the RUN object
        rt.cluster.patch(RUNS_API, "StoryRun", "default", "pk-run",
                         {"spec": {"storyRef": {}}})
        # approval patched while parked still reaches the controller
        rt.cluster.patch_status(
            RUNS_API, "StoryRun", "default", "pk-run",
            {"status": {"gates": {"approval": {"approved": True}}}},
        )
        rt.pump()
        assert rt.run_phase("pk-run") == "Succeeded"

    def test_transient_get_error_does_not_crash_startup(self, tmp_path):
        persist = str(tmp_path / "bus")
        cluster = FakeCluster()
        rt1 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        kubectl_apply(cluster, make_story("geterr", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt1.stop()
        orig_get = cluster.get

        def flaky_get(api_version, kind, namespace, name):
            if kind == "Story" and name == "geterr":
                raise RuntimeError("connection reset")
            return orig_get(api_version, kind, namespace, name)

        cluster.get = flaky_get
        # startup survives the blip (the object is skipped this cycle)
        rt2 = Runtime(persist_dir=persist, executor_backend="cluster",
                      cluster_client=cluster)
        cluster.get = orig_get
        assert rt2.store.try_get("Story", "default", "geterr") is not None
        rt2.stop()


class TestMergePatchDiff:
    def test_no_change_sentinel_vs_literal_empty_dict(self):
        from bobrapet_tpu.cluster.crsync import NO_CHANGE, merge_patch_diff

        assert merge_patch_diff({"a": 1}, {"a": 1}) is NO_CHANGE
        # scalar -> literal {} must produce a replacement, not no-op
        assert merge_patch_diff({"a": {}}, {"a": "x"}) == {"a": {}}
        assert merge_patch_diff({}, {}) is NO_CHANGE

    def test_deletions_become_explicit_nulls(self):
        from bobrapet_tpu.cluster.crsync import merge_patch_diff

        assert merge_patch_diff({"keep": 1}, {"keep": 1, "gone": 2}) == {
            "gone": None
        }
        assert merge_patch_diff(
            {"nested": {"a": 1}}, {"nested": {"a": 1, "b": 2}}
        ) == {"nested": {"b": None}}

    def test_status_key_removal_propagates_out(self, rt):
        """A controller-removed bus status key must vanish cluster-side
        (the push is a real diff with null deletions, not accumulate)."""
        kubectl_apply(rt.cluster, make_story("skey", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        rt.store.patch_status("Story", "default", "skey",
                              lambda s: s.update(transient="x"))
        live = rt.cluster.get(CORE_API, "Story", "default", "skey")
        assert live["status"]["transient"] == "x"
        rt.store.patch_status("Story", "default", "skey",
                              lambda s: s.pop("transient"))
        live = rt.cluster.get(CORE_API, "Story", "default", "skey")
        assert "transient" not in live["status"]


class TestServerSideSchema:
    """CRD schemas enforce webhook-parity bounds at the API server
    (FakeCluster.install_crds = envtest with schemas applied)."""

    @pytest.fixture
    def vc(self):
        c = FakeCluster()
        c.install_crds()
        return c

    def test_duplicate_step_names_rejected_by_list_map(self, vc):
        from bobrapet_tpu.cluster import ClusterInvalid

        bad = make_story("dup", steps=[
            {"name": "x", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "x", "type": "sleep", "with": {"duration": "1s"}},
        ])
        with pytest.raises(ClusterInvalid, match="duplicate list-map key"):
            kubectl_apply(vc, bad)

    def test_port_and_enum_bounds(self, vc):
        from bobrapet_tpu.cluster import ClusterInvalid

        manifest = {
            "apiVersion": "transport.bobrapet.io/v1alpha1",
            "kind": "Transport",
            "metadata": {"name": "t1", "namespace": ""},
            "spec": {"settings": {}},
        }
        vc.create(manifest)  # valid baseline
        bad = {
            "apiVersion": "bobrapet.io/v1alpha1",
            "kind": "Engram",
            "metadata": {"name": "e1", "namespace": "default"},
            "spec": {"transport": {"grpcPort": 99999}},
        }
        with pytest.raises(ClusterInvalid, match="above maximum 65535"):
            vc.create(bad)

    def test_missing_story_ref_rejected(self, vc):
        from bobrapet_tpu.cluster import ClusterInvalid

        with pytest.raises(ClusterInvalid, match="storyRef.*required"):
            vc.create({
                "apiVersion": "runs.bobrapet.io/v1alpha1",
                "kind": "StoryRun",
                "metadata": {"name": "r1", "namespace": "default"},
                "spec": {},
            })

    def test_invalid_patch_leaves_live_object_untouched(self, vc):
        from bobrapet_tpu.cluster import ClusterInvalid

        kubectl_apply(vc, make_story("pat", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
        ]))
        with pytest.raises(ClusterInvalid):
            vc.patch(CORE_API, "Story", "default", "pat", {
                "spec": {"steps": [
                    {"name": "z", "type": "sleep", "with": {"duration": "1s"}},
                    {"name": "z", "type": "sleep", "with": {"duration": "1s"}},
                ]},
            })
        live = vc.get(CORE_API, "Story", "default", "pat")
        assert [s["name"] for s in live["spec"]["steps"]] == ["a"]

    def test_full_run_passes_schema_validation(self, vc):
        """The mirror's own pushes (defaulted specs, status subtrees)
        must satisfy the exported schemas end to end."""
        @register_engram("schema.impl")
        def impl(ctx):
            return {"ok": True}

        rt = Runtime(executor_backend="cluster", cluster_client=vc)
        from bobrapet_tpu.cluster import FakeKubelet
        FakeKubelet(vc, store=rt.store, storage=rt.storage,
                    clock=rt.clock, mode="sync")
        kubectl_apply(vc, make_engram_template("sc-tpl",
                                               entrypoint="schema.impl"))
        kubectl_apply(vc, make_engram("sc", "sc-tpl"))
        kubectl_apply(vc, make_story("sc-story", steps=[
            {"name": "a", "ref": {"name": "sc"}},
        ]))
        kubectl_apply(vc, make_storyrun("sc-run", "sc-story"))
        rt.pump()
        assert rt.run_phase("sc-run") == "Succeeded"
        live = vc.get(RUNS_API, "StoryRun", "default", "sc-run")
        assert live["status"]["phase"] == "Succeeded"
        rt.stop()

    def test_exported_schemas_carry_cel_and_patterns(self):
        from bobrapet_tpu.api.schemas import DURATION_PATTERN, all_crd_manifests

        by_kind = {
            m["spec"]["names"]["kind"]: m for m in all_crd_manifests()
        }
        story_schema = (by_kind["Story"]["spec"]["versions"][0]["schema"]
                        ["openAPIV3Schema"]["properties"]["spec"])
        steps = story_schema["properties"]["steps"]
        assert steps["x-kubernetes-list-type"] == "map"
        assert steps["x-kubernetes-list-map-keys"] == ["name"]
        item = steps["items"]
        assert item["required"] == ["name"]
        rules = {r["rule"] for r in item["x-kubernetes-validations"]}
        assert "has(self.ref) != has(self.type)" in rules
        # duration pattern accepts the grammar, rejects garbage
        import re
        for ok in ("30s", "1.5h", "2m30s", "100ms", "42"):
            assert re.search(DURATION_PATTERN, ok), ok
        for bad in ("fast", "1 hour", "-3s", "3ss"):
            assert not re.search(DURATION_PATTERN, bad), bad


class TestManagerFlag:
    def test_cluster_backend_without_api_server_exits_2(self, monkeypatch):
        from bobrapet_tpu.__main__ import main

        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        rc = main(["manager", "--executor-backend", "cluster",
                   "--metrics-bind-address", "127.0.0.1:0"])
        assert rc == 2

    def test_env_backend_typo_is_rejected(self, monkeypatch):
        """argparse skips choices-validation for env-derived defaults;
        the manager must still refuse a typo'd backend instead of
        silently running local."""
        from bobrapet_tpu.__main__ import main

        monkeypatch.setenv("BOBRA_EXECUTOR_BACKEND", "Cluster")
        rc = main(["manager", "--metrics-bind-address", "127.0.0.1:0"])
        assert rc == 2

    def test_kube_lease_mode_outside_cluster_exits_2(self, monkeypatch):
        from bobrapet_tpu.__main__ import main

        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        rc = main(["manager", "--leader-elect", "--leader-elect-mode", "kube",
                   "--metrics-bind-address", "127.0.0.1:0"])
        assert rc == 2


class TestCRSyncSoak:
    """Threaded mirror under concurrency: many kubectl-applied stories
    running while the bus churns status — the level-based sync must
    converge with no lost runs, no spec reverts, and no livelock."""

    def test_sixteen_kubectl_runs_on_threaded_cluster(self):
        import threading

        from conftest import wait_for

        from bobrapet_tpu.controllers.manager import Clock

        rt = Runtime(clock=Clock(), executor_mode="threaded",
                     executor_backend="cluster")
        rt.start()
        try:
            results = {}
            lock = threading.Lock()

            @register_engram("crsoak.echo")
            def echo(ctx):
                with lock:
                    results[ctx.story_run] = ctx.inputs.get("i")
                return {"i": ctx.inputs.get("i")}

            kubectl_apply(rt.cluster, make_engram_template(
                "crsoak-tpl", entrypoint="crsoak.echo"))
            kubectl_apply(rt.cluster, make_engram("crsoak", "crsoak-tpl"))
            kubectl_apply(rt.cluster, make_story("crsoak-story", steps=[
                {"name": "one", "ref": {"name": "crsoak"},
                 "with": {"i": "{{ inputs.i }}"}},
            ], output={"i": "{{ steps.one.output.i }}"}))

            # 16 runs created ONLY via the cluster API, from 4 threads
            def submit(base):
                for i in range(base, base + 4):
                    kubectl_apply(rt.cluster, make_storyrun(
                        f"cr-run-{i}", "crsoak-story", inputs={"i": i}))

            threads = [threading.Thread(target=submit, args=(b,))
                       for b in (0, 4, 8, 12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

            runs = [f"cr-run-{i}" for i in range(16)]
            assert wait_for(
                lambda: all(rt.run_phase(r) == "Succeeded" for r in runs),
                timeout=60.0,
            ), [rt.run_phase(r) for r in runs]
            # the engram-side record agrees: each run saw only its input
            assert sorted(results.values()) == list(range(16))

            # every completion becomes visible to kubectl (the mirror
            # drains asynchronously after the bus-side phase flips)
            def mirrored(r):
                live = rt.cluster.get(RUNS_API, "StoryRun", "default", r)
                return live and live["status"].get("phase") == "Succeeded"

            assert wait_for(lambda: all(mirrored(r) for r in runs))
            for i, r in enumerate(runs):
                live = rt.cluster.get(RUNS_API, "StoryRun", "default", r)
                assert live["status"]["output"] == {"i": i}
            # mirrored StepRuns all arrive and none leaks mid-state
            assert wait_for(lambda: (
                len(rt.cluster.list(RUNS_API, "StepRun", "default")) == 16
                and all(o["status"].get("phase") == "Succeeded"
                        for o in rt.cluster.list(RUNS_API, "StepRun",
                                                 "default"))
            ))
        finally:
            rt.stop()


class TestConfigMapBridge:
    """VERDICT r4 #6: `kubectl edit configmap` live-reloads the manager
    — crsync mirrors the operator ConfigMap cluster -> bus (read-only,
    one object) and the bus-side OperatorConfigManager reload fires
    (reference: internal/config/operator.go:356-383, the config manager
    is a reconciler on the real ConfigMap)."""

    @staticmethod
    def _wait(cond):
        from conftest import wait_for

        return wait_for(cond)

    CM = {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "operator-config",
                     "namespace": "bobrapet-system"},
        "data": {"templating.offloaded-data-policy": "inject"},
    }

    def test_cluster_side_edit_reloads_config(self):
        import json as _json

        cluster = FakeCluster()
        rt = Runtime(executor_backend="cluster", cluster_client=cluster)
        rt.start()
        try:
            assert (rt.config_manager.config.templating
                    .offloaded_data_policy.value) == "fail"
            cluster.create(_json.loads(_json.dumps(self.CM)))
            assert self._wait(lambda: (
                rt.config_manager.config.templating
                .offloaded_data_policy.value) == "inject")
            # an EDIT (kubectl edit configmap) flips it again, live
            cluster.patch("v1", "ConfigMap", "bobrapet-system",
                          "operator-config",
                          {"data": {"templating.offloaded-data-policy":
                                    "controller"}})
            assert self._wait(lambda: (
                rt.config_manager.config.templating
                .offloaded_data_policy.value) == "controller")
        finally:
            rt.stop()

    def test_configmap_predating_manager_loads_at_resync(self):
        import json as _json

        cluster = FakeCluster()
        cluster.create(_json.loads(_json.dumps(self.CM)))
        rt = Runtime(executor_backend="cluster", cluster_client=cluster)
        rt.start()
        try:
            assert self._wait(lambda: (
                rt.config_manager.config.templating
                .offloaded_data_policy.value) == "inject")
        finally:
            rt.stop()

    def test_delete_keeps_last_good_config(self):
        import json as _json

        cluster = FakeCluster()
        rt = Runtime(executor_backend="cluster", cluster_client=cluster)
        rt.start()
        try:
            cluster.create(_json.loads(_json.dumps(self.CM)))
            assert self._wait(lambda: (
                rt.config_manager.config.templating
                .offloaded_data_policy.value) == "inject")
            cluster.delete("v1", "ConfigMap", "bobrapet-system",
                           "operator-config")
            assert self._wait(lambda: rt.store.try_get(
                "ConfigMap", "bobrapet-system", "operator-config") is None)
            # reference behavior: the last good config stays active
            assert (rt.config_manager.config.templating
                    .offloaded_data_policy.value) == "inject"
        finally:
            rt.stop()

    def test_other_configmaps_ignored(self):
        cluster = FakeCluster()
        rt = Runtime(executor_backend="cluster", cluster_client=cluster)
        rt.start()
        try:
            cluster.create({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "unrelated",
                             "namespace": "bobrapet-system"},
                "data": {"templating.offloaded-data-policy": "inject"},
            })
            rt.pump()
            assert rt.store.try_get(
                "ConfigMap", "bobrapet-system", "unrelated") is None
            assert (rt.config_manager.config.templating
                    .offloaded_data_policy.value) == "fail"
        finally:
            rt.stop()
