"""Fleet analytics suite (ISSUE 13): chip-time ledger balance, the
critical-path analyzer's phase attribution, the utilization tracker,
the backend-fallback surface, and the continuous control-plane
profiler — plus the acceptance e2e: a story through one preemption AND
one user-budget retry whose phase attributions cover >= 95% of the
terminal wall-clock while every grant's ledger balances exactly.
"""

from __future__ import annotations

import threading
import time

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.observability.analytics import (
    LEDGER,
    UTILIZATION,
    ChipLedger,
    UtilizationTracker,
    analyze_run,
    compact_analysis,
    record_backend_fallback,
    reset_backend_fallback_log,
)
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.observability.profiler import PROFILER, SamplingProfiler
from bobrapet_tpu.parallel.placement import SlicePlacer, SlicePool
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram


def _grant(sid="p-s1", pool="p", topology="2x2", span=None):
    g = {"sliceId": sid, "pool": pool, "topology": topology}
    if span:
        g["span"] = span
    return g


class TestChipLedger:
    def test_granted_equals_sum_of_buckets_exactly(self):
        led = ChipLedger()
        led.open_grant(_grant(), 10.0)
        led.account("p-s1", "park", 10.3)
        led.account("p-s1", "productive", 17.9)
        led.close_grant("p-s1", "drain", 18.0001)
        (entry,) = led.entries()
        assert entry["closed"]
        assert led.unbalanced() == []
        # 4 chips x 8.0001s granted; the bucket split is exact
        assert entry["grantedSeconds"] == pytest.approx(8.0001)
        assert set(entry["buckets"]) == {"park", "productive", "drain"}

    def test_chip_seconds_metrics_scale_by_chips(self):
        led = ChipLedger()
        led.open_grant(_grant(topology="2x2"), 0.0)  # 4 chips
        led.account("p-s1", "productive", 10.0)
        led.close_grant("p-s1", "drain", 10.0)
        assert metrics.fleet_chip_seconds.value("p", "productive") == (
            pytest.approx(40.0)
        )

    def test_goodput_counts_per_tenant(self):
        led = ChipLedger()
        led.open_grant(_grant(), 0.0, tenant="acme")
        led.account("p-s1", "productive", 2.0)
        led.close_grant("p-s1", "drain", 2.0)
        assert led.summary()["goodputChipSeconds"]["acme"] == (
            pytest.approx(8.0)
        )
        assert metrics.fleet_goodput_chip_seconds.value("acme") == (
            pytest.approx(8.0)
        )

    def test_waste_fraction(self):
        led = ChipLedger()
        led.open_grant(_grant(topology="1"), 0.0)
        led.account("p-s1", "productive", 6.0)
        led.account("p-s1", "retry", 8.0)
        led.close_grant("p-s1", "drain", 10.0)
        pool = led.summary()["pools"]["p"]
        assert pool["wasteFraction"] == pytest.approx(0.4)

    def test_backwards_clock_never_goes_negative(self):
        led = ChipLedger()
        led.open_grant(_grant(), 100.0)
        led.account("p-s1", "park", 99.0)  # clock stepped back
        led.close_grant("p-s1", "drain", 101.0)
        assert led.unbalanced() == []
        (entry,) = led.entries()
        assert all(v >= 0 for v in entry["buckets"].values())

    def test_unknown_and_double_close_are_noops(self):
        led = ChipLedger()
        led.account("ghost", "productive", 1.0)
        led.close_grant("ghost", "drain", 1.0)
        led.open_grant(_grant(), 0.0)
        led.close_grant("p-s1", "drain", 1.0)
        led.close_grant("p-s1", "drain", 2.0)
        assert len(led.entries()) == 1

    def test_reopen_of_live_grant_keeps_original_entry(self):
        # the adopt path re-announces a surviving grant: the ORIGINAL
        # open time and tenant must win, or the live grant's park time
        # would misattribute to drain on every adopt
        led = ChipLedger()
        led.open_grant(_grant(sid="local-s1"), 0.0, tenant="acme")
        led.open_grant(_grant(sid="local-s1"), 5.0, tenant="other")
        led.account("local-s1", "productive", 10.0)
        led.close_grant("local-s1", "drain", 10.0)
        (entry,) = led.entries()
        assert entry["grantedSeconds"] == pytest.approx(10.0)
        assert entry["tenant"] == "acme"
        assert led.unbalanced() == []

    def test_failed_validation_attempt_counts_as_failed_waste(self):
        # steprun._fail (schema/postExecution failures) accounts the
        # attempt under "failed" before release closes the grant
        rt = Runtime()
        rt.placer.add_pool(SlicePool("v5e", "4x4", chips_per_host=2))

        @register_engram("an-badout")
        def badout(ctx):
            ctx._clock.sleep(1.0)
            return {"wrong": "shape"}

        rt.apply(make_engram_template(
            "an-bad-tpl", entrypoint="an-badout",
            outputSchema={"type": "object", "required": ["ok"],
                          "properties": {"ok": {"type": "boolean"}}},
        ))
        rt.apply(make_engram("an-bad-worker", "an-bad-tpl"))
        rt.apply(make_story("an-bad-story", steps=[
            {"name": "fit", "ref": {"name": "an-bad-worker"},
             "tpu": {"topology": "2x2"},
             "execution": {"retry": {"maxRetries": 0}}},
        ], policy={"queue": "v5e"}))
        LEDGER.reset()
        run = rt.run_story("an-bad-story")
        while rt.pump(max_virtual_seconds=43_200.0) > 0:
            pass
        assert rt.run_phase(run) == "Failed"
        (entry,) = LEDGER.entries()
        assert entry["closed"]
        assert entry["buckets"].get("failed", 0) > 0
        assert LEDGER.unbalanced() == []

    def test_span_level_utilization_aggregates_pools(self):
        led = ChipLedger()
        span = {"id": "span-1", "pools": ["a", "b"]}
        led.open_grant(_grant(sid="a-s1", pool="a", span=span), 0.0)
        led.open_grant(_grant(sid="b-s1", pool="b", span=span), 0.0)
        led.account("a-s1", "productive", 10.0)
        led.account("b-s1", "productive", 10.0)
        led.close_grant("a-s1", "drain", 10.0)
        led.close_grant("b-s1", "drain", 10.0)
        spans = led.summary()["spans"]
        assert spans["span-1"]["grants"] == 2
        assert spans["span-1"]["pools"] == ["a", "b"]
        assert spans["span-1"]["utilization"] == pytest.approx(1.0)


class TestUtilizationTracker:
    def test_snapshots_and_percentiles(self):
        placer = SlicePlacer([SlicePool("v5e", "4x4")])
        tracker = UtilizationTracker()
        tracker.sample(placer, 1.0, force=True)
        g = placer.pool("v5e").allocate(want_topology="4x4")
        tracker.sample(placer, 2.0, force=True)
        placer.pool("v5e").release(g.slice_id)
        tracker.sample(placer, 3.0, force=True)
        snaps = tracker.snapshots("v5e")
        assert [s["occupancy"] for s in snaps] == [0.0, 1.0, 0.0]
        pct = tracker.occupancy_percentiles("v5e")
        assert pct["samples"] == 3
        assert pct["p50"] == 0.0 and pct["p95"] == 1.0

    def test_rate_limit_skips_unforced_samples(self):
        placer = SlicePlacer()
        tracker = UtilizationTracker(min_interval=60.0)
        assert tracker.sample(placer, 1.0)
        assert not tracker.sample(placer, 2.0)
        assert tracker.sample(placer, 3.0, force=True)


class TestAnalyzer:
    def _status(self, steps=None):
        return {
            "startedAt": 0.0,
            "finishedAt": 100.0,
            "stepStates": steps or {},
        }

    def test_phase_attribution_on_known_durations(self):
        timeline = [
            {"at": 10.0, "kind": "launch"},       # 0-10 scheduling
            {"at": 20.0, "kind": "dispatch"},     # 10-20 dispatch-wait
            {"at": 50.0, "kind": "preemption"},   # 20-50 execution
            {"at": 60.0, "kind": "dispatch"},     # 50-60 preempted-retry
        ]                                          # 60-100 execution
        a = analyze_run(self._status(), timeline)
        assert a["wallClockSeconds"] == pytest.approx(100.0)
        assert a["phases"]["scheduling"] == pytest.approx(10.0)
        assert a["phases"]["dispatch-wait"] == pytest.approx(10.0)
        assert a["phases"]["preempted-retry"] == pytest.approx(10.0)
        assert a["phases"]["execution"] == pytest.approx(70.0)
        # the state machine is total: attribution covers the wall-clock
        assert sum(a["phases"].values()) == pytest.approx(100.0)
        assert a["coverage"] == pytest.approx(1.0)

    def test_queue_and_park_phases(self):
        timeline = [
            {"at": 5.0, "kind": "queued"},
            {"at": 30.0, "kind": "no-capacity"},
            {"at": 70.0, "kind": "launch"},
            {"at": 75.0, "kind": "dispatch"},
        ]
        a = analyze_run(self._status(), timeline)
        assert a["phases"]["queue-wait"] == pytest.approx(25.0)
        assert a["phases"]["placement-park"] == pytest.approx(40.0)
        assert a["phases"]["execution"] == pytest.approx(25.0)

    def test_records_from_another_time_base_are_ignored(self):
        # span-sink records carry wall-clock stamps in virtual-clock
        # runs; they must not fold the state machine
        timeline = [
            {"at": 10.0, "kind": "dispatch"},
            {"at": 1.7e9, "kind": "dispatch"},
        ]
        a = analyze_run(self._status(), timeline)
        assert sum(a["phases"].values()) == pytest.approx(100.0)

    def test_critical_path_walks_predecessors(self):
        steps = {
            "a": {"startedAt": 0.0, "finishedAt": 40.0, "phase": "Succeeded"},
            "side": {"startedAt": 0.0, "finishedAt": 10.0,
                     "phase": "Succeeded"},
            "b": {"startedAt": 40.0, "finishedAt": 100.0,
                  "phase": "Succeeded"},
        }
        a = analyze_run(self._status(steps), [])
        assert [c["step"] for c in a["criticalPath"]] == ["a", "b"]
        assert a["criticalPath"][-1]["seconds"] == pytest.approx(60.0)

    def test_span_breakdown_sums_durations(self):
        timeline = [
            {"at": 1.0, "kind": "span", "message": "sdk.step",
             "durationMs": 1500.0},
            {"at": 2.0, "kind": "span", "message": "sdk.step",
             "durationMs": 500.0},
            {"at": 3.0, "kind": "span", "message": "steprun.dispatch",
             "durationMs": 10.0},
        ]
        a = analyze_run(self._status(), timeline)
        assert a["spanBreakdown"]["sdk-execution"] == pytest.approx(2.0)
        assert a["spanBreakdown"]["dispatch"] == pytest.approx(0.01)

    def test_no_clock_bounds_returns_none(self):
        assert analyze_run({"startedAt": 5.0}, []) is None
        assert analyze_run({}, []) is None

    def test_compact_form_is_small(self):
        a = analyze_run(self._status(), [{"at": 10.0, "kind": "dispatch"}])
        c = compact_analysis(a)
        assert set(c) == {"wallClockSeconds", "phases", "coverage",
                          "criticalPath"}


class TestBackendFallback:
    def test_counts_and_logs_once_per_reason(self, caplog):
        reset_backend_fallback_log()
        with caplog.at_level("WARNING"):
            record_backend_fallback("probe-timeout", "tunnel cold")
            record_backend_fallback("probe-timeout", "still cold")
        assert metrics.backend_fallback.value("probe-timeout") == 2
        assert sum(
            "backend fallback" in r.message for r in caplog.records
        ) == 1


class TestProfiler:
    def test_samples_busy_and_idle_threads(self):
        prof = SamplingProfiler(interval=0.005, depth=8)
        stop = threading.Event()

        def busy():
            x = 0
            while not stop.is_set():
                x += 1  # pure CPU

        def idle():
            stop.wait(5.0)

        threads = [threading.Thread(target=busy, daemon=True),
                   threading.Thread(target=idle, daemon=True)]
        for t in threads:
            t.start()
        prof.start()
        try:
            time.sleep(0.4)
        finally:
            prof.stop()
            stop.set()
            for t in threads:
                t.join(timeout=2.0)
        snap = prof.snapshot()
        assert snap["samples"] > 10
        kinds = {s["kind"] for s in snap["topStacks"]}
        assert "busy" in kinds and "idle" in kinds
        # the self-overhead is measured and plausibly nonzero
        assert 0.0 < snap["overheadRatio"] < 0.5
        assert metrics.profiler_overhead.value() > 0.0

    def test_lock_wait_attribution_via_sanitizer_classes(self):
        from bobrapet_tpu.analysis.lockorder import sanitize_locks

        with sanitize_locks():
            lock = threading.Lock()  # repo-tracked allocation site

            def holder():
                # deliberately HOLDS the lock across a sleep — the
                # condition under test, so no with-block sugar here
                lock.acquire()
                try:
                    time.sleep(0.5)
                finally:
                    lock.release()

            def blocker():
                with lock:
                    pass

            prof = SamplingProfiler(interval=0.005, depth=8)
            h = threading.Thread(target=holder, daemon=True)
            h.start()
            time.sleep(0.05)  # holder owns the lock
            b = threading.Thread(target=blocker, daemon=True)
            b.start()
            prof.start()
            try:
                time.sleep(0.3)
            finally:
                prof.stop()
                h.join(timeout=2.0)
                b.join(timeout=2.0)
        snap = prof.snapshot()
        # the blocked thread attributes to the lock's ALLOCATION-SITE
        # class (module:lineno), the lockdep naming
        assert snap["lockWaits"], snap["topStacks"]
        assert any("test_analytics" in k for k in snap["lockWaits"])

    def test_configure_is_live(self):
        prof = SamplingProfiler(interval=0.5)
        prof.configure(True, interval=0.005, depth=4)
        try:
            assert prof.running
            assert prof.interval == 0.005 and prof.depth == 4
            time.sleep(0.05)
        finally:
            prof.configure(False)
        assert not prof.running

    def test_runtime_toggles_profiler_from_config(self):
        from bobrapet_tpu.core.object import new_resource

        rt = Runtime()
        assert not PROFILER.running
        rt.store.create(new_resource(
            "ConfigMap", "operator-config", "bobrapet-system",
            spec={"data": {"telemetry.profiler-enabled": "true",
                           "telemetry.profiler-interval": "5ms"}},
        ))
        try:
            assert PROFILER.running
            assert PROFILER.interval == pytest.approx(0.005)
        finally:
            PROFILER.configure(False)


class TestConfigKeys:
    def test_profiler_keys_parse_and_validate(self):
        from bobrapet_tpu.config.operator import OperatorConfig, parse_config

        cfg = parse_config({
            "telemetry.profiler-enabled": "true",
            "telemetry.profiler-interval": "50ms",
            "telemetry.profiler-depth": "6",
        })
        assert cfg.telemetry.profiler_enabled
        assert cfg.telemetry.profiler_interval_seconds == pytest.approx(0.05)
        assert cfg.telemetry.profiler_depth == 6
        bad = OperatorConfig()
        bad.telemetry.profiler_interval_seconds = 0.0
        assert any("profiler-interval" in e for e in bad.validate())
        bad = OperatorConfig()
        bad.telemetry.profiler_depth = 0
        assert any("profiler-depth" in e for e in bad.validate())


# ---------------------------------------------------------------------------
# acceptance e2e: preemption + retry, >=95% attribution, exact balance
# ---------------------------------------------------------------------------


class _OnePreemption:
    """Minimal injector: preempt host 0 of the first eligible gang Job
    once (duck-types PreemptionInjector's plan())."""

    min_hosts = 2

    def __init__(self):
        self.fired = False
        self.planned = 0

    def plan(self, job):
        if self.fired:
            return None
        if int(job.spec.get("hosts") or 1) < self.min_hosts:
            return None
        if not job.spec.get("sliceGrant"):
            return None
        self.fired = True
        self.planned += 1
        return {"host": 0, "afterPolls": 2}


class TestE2ECriticalPathAndLedger:
    def test_preemption_plus_retry_run_attributes_and_balances(self):
        LEDGER.reset()
        UTILIZATION.reset()
        rt = Runtime(preemption_injector=_OnePreemption())
        rt.config_manager.config.retention.children_ttl_seconds = 7 * 86400.0
        rt.config_manager.config.retention.storyrun_retention_seconds = (
            14 * 86400.0
        )
        rt.placer.add_pool(SlicePool("v5e", "4x4", chips_per_host=2))
        calls = {"n": 0}

        @register_engram("an-train")
        def train(ctx):
            # each training step burns VIRTUAL time (the sync executor
            # is otherwise instantaneous under ManualClock), so the
            # attempt segments — preempted, retry, productive — have
            # nonzero chip-seconds to account
            if ctx.host_id != 0:
                for _ in range(4):
                    ctx.check_deadline()
                return None
            calls["n"] += 1
            for _ in range(4):
                ctx._clock.sleep(0.5)
                ctx.check_deadline()
            if calls["n"] == 2:
                # the attempt after the preemption redrive dies once of
                # a retryable signal (SIGTERM-class, USER budget) — the
                # run sees both waste shapes
                from bobrapet_tpu.sdk.context import EngramExit

                raise EngramExit(143, "transient wobble")
            return {"ok": calls["n"]}

        rt.apply(make_engram_template("an-tpl", entrypoint="an-train"))
        rt.apply(make_engram("an-worker", "an-tpl"))
        rt.apply(make_story("an-story", steps=[
            {"name": "fit", "ref": {"name": "an-worker"},
             "tpu": {"topology": "2x2"},
             "execution": {"retry": {"maxRetries": 2}}},
        ], policy={"queue": "v5e"}))
        run = rt.run_story("an-story")
        while rt.pump(max_virtual_seconds=43_200.0) > 0:
            pass

        srun = rt.store.get("StoryRun", "default", run)
        assert srun.status["phase"] == "Succeeded", srun.status
        (sr,) = [
            s for s in rt.store.list("StepRun")
            if (s.spec.get("storyRunRef") or {}).get("name") == run
        ]
        assert sr.status.get("preemptions") == 1
        assert int(sr.status.get("retries") or 0) >= 1

        # --- acceptance: phase attributions cover >= 95% wall-clock ---
        analysis = srun.status.get("analysis")
        assert analysis is not None
        wall = analysis["wallClockSeconds"]
        assert wall > 0.0  # redrive + retry delays advanced the clock
        assert sum(analysis["phases"].values()) >= 0.95 * wall
        assert analysis["coverage"] >= 0.95
        assert analysis["criticalPath"] == ["fit"]
        # both waste shapes are visible in the attribution
        assert "preempted-retry" in analysis["phases"]

        # --- acceptance: ledger balances exactly for every grant ---
        assert LEDGER.unbalanced() == []
        entries = LEDGER.entries()
        assert len(entries) == 2  # the preempted grant + its replacement
        assert all(e["closed"] for e in entries)
        buckets = set()
        for e in entries:
            buckets |= set(e["buckets"])
        assert "preempted" in buckets
        assert "productive" in buckets
        assert "retry" in buckets
        summary = LEDGER.summary()
        pool = summary["pools"]["v5e"]
        assert pool["grantedChipSeconds"] > 0
        assert 0.0 < pool["wasteFraction"] < 1.0
        # goodput landed on the run's namespace tenant
        assert summary["goodputChipSeconds"]["default"] > 0
