"""Runtime lock-order sanitizer tests (ISSUE 4).

The sanitizer itself must be trustworthy before the threaded suites
lean on it: wrappers must be transparent (Condition protocol included),
ordering edges must be recorded per allocation-site lock class,
lockdep-style cycles must be detected WITHOUT needing an actual
deadlock to strike, and Condition waits must not count as hold time.
"""

from __future__ import annotations

import threading
import time

import pytest

from bobrapet_tpu.analysis.lockorder import (
    LockOrderViolation,
    sanitize_locks,
)


class TestTransparency:
    def test_lock_and_rlock_still_work(self):
        with sanitize_locks():
            lock = threading.Lock()
            rlock = threading.RLock()
            with lock:
                assert lock.locked()
            with rlock:
                with rlock:  # re-entrant
                    pass
            assert lock.acquire(blocking=False)
            lock.release()

    def test_condition_wait_notify_roundtrip(self):
        with sanitize_locks():
            lock = threading.Lock()
            cond = threading.Condition(lock)
            hits = []

            def waiter():
                with cond:
                    hits.append("waiting")
                    cond.wait(timeout=5.0)
                    hits.append("woke")

            t = threading.Thread(target=waiter)
            t.start()
            for _ in range(500):
                if hits:
                    break
                time.sleep(0.005)
            with cond:
                cond.notify_all()
            t.join(timeout=5.0)
            assert hits == ["waiting", "woke"]

    def test_locks_keep_working_after_session(self):
        with sanitize_locks():
            lock = threading.Lock()
        with lock:  # session over: recording off, lock still functional
            pass
        assert not lock.locked()


class TestOrdering:
    def test_consistent_order_is_clean(self):
        with sanitize_locks() as mon:
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        assert len(mon.edges) == 1
        assert mon.cycles() == []
        mon.assert_clean()

    def test_inverted_order_is_a_cycle_without_deadlocking(self):
        with sanitize_locks() as mon:
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:  # inversion: never deadlocks single-threaded,
                    pass  # but two threads interleaving it would
        cycles = mon.cycles()
        assert len(cycles) == 1 and len(cycles[0]) == 2
        with pytest.raises(LockOrderViolation, match="CYCLE"):
            mon.assert_clean()

    def test_distinct_instances_of_one_class_self_edge(self):
        def make():
            return threading.Lock()  # one allocation site = one class

        with sanitize_locks() as mon:
            a, b = make(), make()
            with a:
                with b:
                    pass
        assert [c for c in mon.cycles()], "self-edge over distinct instances"
        with pytest.raises(LockOrderViolation):
            mon.assert_clean()

    def test_reentrant_rlock_is_not_a_self_edge(self):
        with sanitize_locks() as mon:
            r = threading.RLock()
            with r:
                with r:
                    pass
        assert mon.edges == {}
        mon.assert_clean()

    def test_out_of_order_release_is_legal(self):
        with sanitize_locks() as mon:
            a = threading.Lock()
            b = threading.Lock()
            a.acquire()
            b.acquire()
            a.release()  # hand-over-hand: release a before b
            b.release()
        assert mon.cycles() == []
        mon.assert_clean()


class TestHoldBudget:
    def test_overlong_hold_is_a_warning_not_a_failure(self, capsys):
        with sanitize_locks(hold_budget=0.01) as mon:
            lock = threading.Lock()
            with lock:
                time.sleep(0.05)
        assert mon.hold_violations
        mon.assert_clean(strict_hold=False)  # warns, does not raise
        assert "HOLD" in capsys.readouterr().err

    def test_strict_mode_fails_on_hold_violation(self):
        with sanitize_locks(hold_budget=0.01) as mon:
            lock = threading.Lock()
            with lock:
                time.sleep(0.05)
        with pytest.raises(LockOrderViolation, match="HOLD"):
            mon.assert_clean(strict_hold=True)

    def test_recursive_hold_survives_condition_wait(self):
        """A doubly-acquired RLock that waits on its Condition must come
        back with recursion depth 2 in the monitor: after wake, the
        FIRST release still leaves the lock held, so ordering edges to
        later acquisitions must still be recorded."""
        with sanitize_locks() as mon:
            r = threading.RLock()
            cond = threading.Condition(r)
            b = threading.Lock()
            with r:  # depth 1
                with cond:  # depth 2 (same lock)
                    cond.wait(timeout=0.01)
                # back to depth 1 — the lock is STILL held here
                with b:
                    pass
        r_label = next(lbl for lbl in mon.max_hold if "test_lockorder" in lbl)
        assert any(
            a == r_label for (a, bl) in mon.edges if bl != r_label
        ), f"missing edge from still-held RLock: {mon.edges}"

    def test_condition_wait_does_not_count_as_hold(self):
        with sanitize_locks(hold_budget=0.02) as mon:
            lock = threading.RLock()
            cond = threading.Condition(lock)
            with cond:
                cond.wait(timeout=0.1)  # releases the lock while waiting
        assert mon.hold_violations == []
        mon.assert_clean(strict_hold=True)


class TestCrossThread:
    def test_edges_merge_across_threads(self):
        """Each thread contributes its own acquisition order; the graph
        (and the cycle) only exists in the union — exactly the deadlock
        that never fires in either thread alone."""
        with sanitize_locks() as mon:
            a = threading.Lock()
            b = threading.Lock()

            def ab():
                with a:
                    with b:
                        pass

            def ba():
                with b:
                    with a:
                        pass

            # STRICTLY sequential on purpose: overlapping them could
            # strike the very deadlock under discussion. The sanitizer
            # must see the hazard from the per-thread orders alone.
            t1 = threading.Thread(target=ab)
            t1.start()
            t1.join(timeout=10.0)
            t2 = threading.Thread(target=ba)
            t2.start()
            t2.join(timeout=10.0)
        assert mon.cycles(), "cross-thread inversion must form a cycle"
