"""Template engine tests: scopes, conditions, offloaded-data channel,
static validation, dependency mining."""

import pytest

from bobrapet_tpu.templating import (
    EvaluationBlocked,
    EvaluationError,
    Evaluator,
    OffloadedDataUsage,
    TemplateConfig,
    TemplateValidationError,
    find_storage_refs,
    is_storage_ref,
)


@pytest.fixture
def ev():
    return Evaluator()


@pytest.fixture
def scope():
    return {
        "inputs": {"q": "what is a tpu", "k": 5, "flags": {"fast": True}},
        "steps": {
            "embed": {"output": {"vec": [1, 2, 3], "ok": True}},
            "retrieve": {"output": {"hits": [{"id": "a"}, {"id": "b"}], "count": 2}},
            "offloaded": {"output": {"storageRef": {"key": "runs/r1/offloaded", "size": 10_000_000}}},
        },
        "run": {"name": "r1", "namespace": "default", "storyName": "rag"},
    }


class TestEvaluation:
    def test_native_value_passthrough(self, ev, scope):
        assert ev.evaluate_string("{{ steps.embed.output.vec }}", scope) == [1, 2, 3]
        assert ev.evaluate_string("{{ inputs.k }}", scope) == 5

    def test_interpolation(self, ev, scope):
        s = ev.evaluate_string("query={{ inputs.q }} k={{ inputs.k }}", scope)
        assert s == "query=what is a tpu k=5"

    def test_recursive_with_block(self, ev, scope):
        result = ev.evaluate_value(
            {"prompt": "{{ inputs.q }}", "docs": "{{ steps.retrieve.output.hits }}", "n": 3},
            scope,
        )
        assert result == {
            "prompt": "what is a tpu",
            "docs": [{"id": "a"}, {"id": "b"}],
            "n": 3,
        }

    def test_subscript_and_arithmetic(self, ev, scope):
        assert ev.evaluate_string("{{ steps.retrieve.output.hits[0].id }}", scope) == "a"
        assert ev.evaluate_string("{{ inputs.k * 2 + 1 }}", scope) == 11
        assert ev.evaluate_string("{{ steps.retrieve.output.count % 2 }}", scope) == 0

    def test_functions(self, ev, scope):
        assert ev.evaluate_string("{{ size(steps.retrieve.output.hits) }}", scope) == 2
        assert ev.evaluate_string("{{ default(inputs.missing, 'x') }}", scope) == "x"
        assert ev.evaluate_string("{{ has(inputs.q) }}", scope) is True
        assert ev.evaluate_string("{{ has(inputs.nope) }}", scope) is False
        assert ev.evaluate_string("{{ upper(inputs.q) }}", scope) == "WHAT IS A TPU"
        assert ev.evaluate_string("{{ join(',', ['a','b']) }}", scope) == "a,b"

    def test_missing_key_raises_outside_guards(self, ev, scope):
        with pytest.raises(EvaluationError):
            ev.evaluate_string("{{ inputs.nope + 1 }}", scope)

    def test_bool_rendering(self, ev, scope):
        assert ev.evaluate_string("ok={{ steps.embed.output.ok }}", scope) == "ok=true"

    def test_dict_rendering_is_json(self, ev, scope):
        assert ev.evaluate_string("h={{ steps.retrieve.output.hits[0] }}", scope) == 'h={"id":"a"}'


class TestConditions:
    def test_truthy(self, ev, scope):
        assert ev.evaluate_condition("{{ steps.embed.output.ok }}", scope)
        assert ev.evaluate_condition("steps.retrieve.output.count > 1", scope)
        assert not ev.evaluate_condition("inputs.k > 100", scope)
        assert ev.evaluate_condition("", scope)  # empty = always

    def test_missing_is_falsy_in_conditions(self, ev, scope):
        assert not ev.evaluate_condition("{{ steps.nope.output.ok }}", scope)
        assert ev.evaluate_condition("{{ not has(steps.nope) }}", scope)

    def test_comparison_with_missing_is_null(self, ev, scope):
        assert ev.evaluate_condition("{{ inputs.missing == null }}", scope)

    def test_and_or(self, ev, scope):
        assert ev.evaluate_condition(
            "{{ steps.embed.output.ok and inputs.k >= 5 }}", scope
        )
        assert ev.evaluate_condition("{{ inputs.nope or inputs.k }}", scope)


class TestOffloadedData:
    def test_traversal_raises(self, ev, scope):
        with pytest.raises(OffloadedDataUsage) as ei:
            ev.evaluate_string("{{ steps.offloaded.output.field }}", scope)
        assert ei.value.refs[0]["key"] == "runs/r1/offloaded"

    def test_condition_on_offloaded_raises(self, ev, scope):
        with pytest.raises(OffloadedDataUsage):
            ev.evaluate_condition("{{ steps.offloaded.output }}", scope)

    def test_interpolating_offloaded_raises(self, ev, scope):
        with pytest.raises(OffloadedDataUsage):
            ev.evaluate_string("data={{ steps.offloaded.output }}", scope)

    def test_passthrough_reference_is_allowed(self, ev, scope):
        # passing the placeholder through untouched is fine (it rehydrates
        # at the consumer); only *using* it is blocked
        v = ev.evaluate_string("{{ steps.offloaded.output }}", scope)
        assert is_storage_ref(v)

    def test_find_storage_refs(self, scope):
        refs = find_storage_refs(scope["steps"])
        assert len(refs) == 1 and refs[0]["size"] == 10_000_000


class TestSafety:
    @pytest.mark.parametrize(
        "expr",
        [
            "{{ __import__('os') }}",
            "{{ ().__class__ }}",
            "{{ [x for x in steps] }}",
            "{{ lambda: 1 }}",
            "{{ open('/etc/passwd') }}",
            "{{ inputs.q.__class__ }}",
        ],
    )
    def test_dangerous_constructs_rejected(self, ev, scope, expr):
        with pytest.raises((TemplateValidationError, EvaluationError, OffloadedDataUsage)):
            v = ev.evaluate_string(expr, scope)
            # attribute access on str returns Missing -> unwrap check
            if hasattr(v, "path"):
                raise EvaluationError(v.path)

    def test_expression_node_budget(self, scope):
        ev = Evaluator(TemplateConfig(max_expression_nodes=10))
        with pytest.raises(EvaluationBlocked):
            ev.evaluate_string("{{ 1+1+1+1+1+1+1+1+1+1+1+1 }}", scope)

    def test_output_size_cap(self, scope):
        ev = Evaluator(TemplateConfig(max_output_bytes=64))
        with pytest.raises(EvaluationBlocked):
            ev.evaluate_value({"big": "{{ inputs.q }}" * 20}, scope)

    def test_deterministic_mode_blocks_now(self, ev, scope):
        with pytest.raises(TemplateValidationError):
            ev.evaluate_string("{{ now() }}", scope)

    def test_nondeterministic_allowed_when_configured(self, scope):
        ev = Evaluator(TemplateConfig(deterministic=False))
        assert ev.evaluate_string("{{ now() }}", scope) > 0

    def test_division_by_zero(self, ev, scope):
        with pytest.raises(EvaluationError):
            ev.evaluate_string("{{ 1 / 0 }}", scope)


class TestStaticValidation:
    def test_valid_scopes(self, ev):
        ev.validate("{{ inputs.a }} and {{ steps.b.output }}")

    def test_scope_restriction(self, ev):
        with pytest.raises(TemplateValidationError):
            ev.validate("{{ steps.b.output }}", allowed_roots={"inputs"})

    def test_unknown_root(self, ev):
        with pytest.raises(TemplateValidationError):
            ev.validate("{{ secrets.password }}")

    def test_builtin_names_ok(self, ev):
        ev.validate("{{ default(inputs.a, null) or true }}", allowed_roots={"inputs"})


class TestDependencyMining:
    def test_attribute_and_subscript_refs(self):
        deps = Evaluator.find_step_references(
            {
                "a": "{{ steps.embed.output }}",
                "b": ["{{ steps['retrieve'].output.count }}"],
                "c": "no template",
                "d": "{{ inputs.x }}",
            }
        )
        assert deps == {"embed", "retrieve"}

    def test_bad_syntax_ignored(self):
        assert Evaluator.find_step_references("{{ steps. }}") == set()
