"""Schema <-> webhook equivalence (VERDICT r4 #8).

The webhook chain (webhooks/*.py) and the exported CRD schemas
(api/schemas.py, enforced server-side through
cluster/schema_validate.py) state many rules twice — the reference
generates its 18.5k schema lines from the same Go types its webhooks
validate, so it cannot drift; here the mirror is hand-maintained, so
THIS suite is the drift alarm. Every rule family gets one invalid
object pushed through BOTH layers:

- families mirrored in both layers must be rejected by both;
- intended asymmetries are pinned explicitly: cross-field/cross-
  resource semantics are webhook-only (no schema can see another
  object), and CEL rules are schema-documented but evaluated only by a
  real API server (schema_validate skips them; the webhook enforces
  the same semantics in-process).

If someone tightens a webhook without mirroring the schema (or vice
versa), the corresponding case here flips and the suite fails.
"""

from __future__ import annotations

import pytest

from bobrapet_tpu.api.schemas import all_crd_manifests
from bobrapet_tpu.cluster.admission import _admission_resource
from bobrapet_tpu.cluster.schema_validate import CRDRegistry
from bobrapet_tpu.core.store import AdmissionDenied
from bobrapet_tpu.runtime import Runtime

CORE = "bobrapet.io/v1alpha1"
RUNS = "runs.bobrapet.io/v1alpha1"
CATALOG = "catalog.bobrapet.io/v1alpha1"
TRANSPORT = "transport.bobrapet.io/v1alpha1"


@pytest.fixture(scope="module")
def registry():
    reg = CRDRegistry()
    for m in all_crd_manifests():
        reg.install(m)
    return reg


@pytest.fixture(scope="module")
def rt():
    return Runtime()


def schema_rejects(registry, manifest) -> list[str]:
    return registry.validate(manifest)


def webhook_rejects(rt, manifest) -> str | None:
    kind = manifest["kind"]
    resource = _admission_resource(manifest)
    _defaulters, validators, _status = rt.store.admission_chain(kind)
    try:
        for fn in validators:
            fn(resource, None)
    except AdmissionDenied as e:
        return str(e)
    return None


def manifest(kind, api, name="x", spec=None):
    return {
        "apiVersion": api, "kind": kind,
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec or {},
    }


#: one case per rule family: (id, manifest, schema_rejects?,
#: webhook_rejects?, why-asymmetric-or-None)
CASES = [
    (
        "enum: unknown step type",
        manifest("Story", CORE, spec={"steps": [
            {"name": "a", "type": "bogus-primitive"},
        ]}),
        True, True, None,
    ),
    (
        "required: StoryRun without storyRef",
        manifest("StoryRun", RUNS, spec={}),
        True, True, None,
    ),
    (
        "bounds: story concurrency below minimum",
        manifest("Story", CORE, spec={
            "steps": [{"name": "a", "type": "condition"}],
            "policy": {"concurrency": 0},
        }),
        True, True, None,
    ),
    (
        "bounds: retry jitter above maximum",
        manifest("Story", CORE, spec={
            "steps": [{"name": "a", "type": "condition",
                       "execution": {"retry": {"jitter": 150}}}],
        }),
        True, True, None,
    ),
    (
        "pattern: ref name not DNS-1123",
        manifest("StepRun", RUNS, spec={
            "storyRunRef": {"name": "Bad_Name!"},
            "stepId": "a",
            "engramRef": {"name": "w"},
        }),
        True, True, None,
    ),
    (
        "pattern: unparseable duration",
        manifest("Story", CORE, spec={
            "steps": [{"name": "a", "type": "sleep",
                       "with": {"duration": "soon"}}],
        }),
        # `with` is a preserve-unknown block schema-side (primitive
        # configs are polymorphic); only the webhook parses durations
        False, True,
        "primitive `with` blocks are opaque to the schema "
        "(x-kubernetes-preserve-unknown-fields); the webhook owns "
        "their shapes",
    ),
    (
        "list-map: duplicate step names",
        manifest("Story", CORE, spec={"steps": [
            {"name": "a", "type": "condition"},
            {"name": "a", "type": "condition"},
        ]}),
        True, True, None,
    ),
    (
        "cross-field: unknown needs target",
        manifest("Story", CORE, spec={"steps": [
            {"name": "a", "type": "condition", "needs": ["ghost"]},
        ]}),
        False, True,
        "needs-existence relates two list entries; OpenAPI cannot "
        "express it (a real apiserver would need CEL over the whole "
        "list; the reference also rejects it in the webhook, "
        "story_webhook.go needs validation)",
    ),
    (
        "cross-resource: executeStory self-reference",
        manifest("Story", CORE, name="loop", spec={"steps": [
            {"name": "again", "type": "executeStory",
             "with": {"storyRef": {"name": "loop"}}},
        ]}),
        False, True,
        "cycle detection needs the object graph; schemas see one "
        "object (reference: story_webhook.go executeStory cycles)",
    ),
    (
        "cross-resource: Engram templateRef must exist",
        manifest("Engram", CORE, spec={"templateRef": {"name": "nope"}}),
        False, True,
        "referential integrity is webhook-only in the reference too "
        "(engram_webhook.go templateRef resolution)",
    ),
    (
        "cel: step with both ref and type",
        manifest("Story", CORE, spec={"steps": [
            {"name": "a", "type": "condition", "ref": {"name": "w"}},
        ]}),
        False, True,
        "exactly-one-of is an x-kubernetes-validations CEL rule in the "
        "exported schema; schema_validate.py documents-but-skips CEL "
        "(a REAL apiserver enforces it server-side — the gated "
        "envtest e2e covers that), while the webhook enforces the "
        "same semantics in-process",
    ),
    (
        "cel: step self-dependency",
        manifest("Story", CORE, spec={"steps": [
            {"name": "a", "type": "condition", "needs": ["a"]},
        ]}),
        False, True,
        "same CEL-vs-in-process split as exactly-one-of",
    ),
]


class TestAdmissionParity:
    @pytest.mark.parametrize(
        "case_id,obj,schema_expected,webhook_expected,why",
        CASES, ids=[c[0] for c in CASES],
    )
    def test_rule_family(self, registry, rt, case_id, obj,
                         schema_expected, webhook_expected, why):
        schema_errs = schema_rejects(registry, obj)
        webhook_err = webhook_rejects(rt, obj)
        assert bool(schema_errs) == schema_expected, (
            f"{case_id}: schema layer drifted "
            f"(errors={schema_errs!r}, expected reject={schema_expected})"
        )
        assert bool(webhook_err) == webhook_expected, (
            f"{case_id}: webhook layer drifted "
            f"(error={webhook_err!r}, expected reject={webhook_expected})"
        )
        if schema_expected != webhook_expected:
            assert why, f"{case_id}: undocumented asymmetry"

    def test_every_cel_rule_has_a_case_or_is_known(self, registry):
        """Each CEL rule family in the exported schemas must appear in
        the case table (the webhook enforces its semantics; the schema
        documents it): a NEW CEL rule without a parity case fails
        here."""
        import json

        known_markers = {
            "has(self.ref) != has(self.type)",
            "!has(self.needs) || !(self.name in self.needs)",
        }
        found = set()
        for m in all_crd_manifests():
            text = json.dumps(m)
            for marker in list(known_markers):
                if marker.replace('"', '\\"') in text or marker in text:
                    found.add(marker)
            # count every x-kubernetes-validations rule
        all_rules = []

        def walk(node):
            if isinstance(node, dict):
                for r in node.get("x-kubernetes-validations") or []:
                    all_rules.append(r.get("rule"))
                for v in node.values():
                    walk(v)
            elif isinstance(node, list):
                for v in node:
                    walk(v)

        for m in all_crd_manifests():
            walk(m)
        unknown = set(all_rules) - known_markers
        assert not unknown, (
            f"new CEL rules without a parity case: {unknown} — add a "
            "case to CASES and a webhook enforcement test"
        )

    def test_both_layers_accept_the_valid_shape(self, registry, rt):
        ok = manifest("Story", CORE, spec={"steps": [
            {"name": "a", "type": "condition"},
            {"name": "b", "type": "sleep", "needs": ["a"],
             "with": {"duration": "5s"}},
        ]})
        assert schema_rejects(registry, ok) == []
        assert webhook_rejects(rt, ok) is None
