"""Process-mode sharded control plane: real OS processes, real kills.

Every test here spawns the store service plus N shard manager
*processes* (``ShardedControlPlane(..., processes=True)``), so the
PR-6 rebalance/lease contract and the PR-16 serving-era invariants are
exercised where the in-process harness cannot honestly reach: across a
real ``kill -9`` (no interpreter survives to run courtesy cleanup) and
across a crash of the bus itself (SIGKILL the store service; clients
reconnect, the journal replays).

Parent-side shims (StoreClient, the harness) run with bobrarace armed;
child processes arm nothing — their verdicts travel back as
ShardReport resources (per-process double-reconcile violations,
ChipLedger imbalance, reconcile counts) and the cross-process
exactly-once-retirement assert is computed from the parent's own watch
stream. The slow leg drives load through the PR-14 closed-loop
generator via a StoryRun-submitting target adapter.
"""

from __future__ import annotations

import time

import pytest

from bobrapet_tpu.api.enums import Phase

from tests.proc_workload import apply_resources


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace over the parent-side process shims: StoreClient's
    pending-call/event/watcher tables and the harness's child/report
    registries are @guarded_state — every cross-process test in this
    module runs them tracked."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


@pytest.fixture()
def plane_factory(request):
    """Build process planes with an ALWAYS-run reaper: a failing assert
    must not strand shard processes (or the store service) on the box —
    the finalizer SIGKILLs whatever graceful teardown missed."""
    from bobrapet_tpu.shard import ShardedControlPlane

    planes = []

    def build(**kwargs):
        cp = ShardedControlPlane(processes=True, **kwargs)
        planes.append(cp)
        request.addfinalizer(cp.reap)
        return cp

    return build


def _assert_reports_clean(cp, sids) -> None:
    for sid in sids:
        rep = cp.reports.get(sid)
        assert rep is not None, f"shard {sid} never published its report"
        assert rep["violations"] == [], f"shard {sid}: {rep['violations']}"
        assert rep["ledgerUnbalanced"] == [], (
            f"shard {sid} ledger: {rep['ledgerUnbalanced']}")
        assert rep["processed"] > 0, f"shard {sid} processed no reconciles"


def _assert_byte_identical_recovery(cp) -> None:
    """Quiesce writers (children already stopped), dump through the
    live service, SIGKILL it, and replay journal+snapshot offline: the
    recovered bytes must equal the dump exactly."""
    from bobrapet_tpu.store_service.journal import dump_recovered

    d0 = cp.dump_store()
    cp.kill_store_service()
    d1 = dump_recovered(cp.data_dir)
    assert d0 == d1, (
        f"journal replay diverged: {len(d0)} vs {len(d1)} bytes")


class TestProcessSmoke:
    def test_two_processes_survive_kill_nine(self, plane_factory):
        """Tier-1 leg: 2 shard processes + the service; runs complete
        across a real SIGKILL of one shard, nothing lost, every run
        retired exactly once, recovery is byte-identical."""
        cp = plane_factory(shards=2)
        with cp:
            cp.wait_members({"0", "1"}, timeout=90.0)
            story = apply_resources(cp, "proc-fast")
            runs = [cp.run_story(story, inputs={"i": i}) for i in range(6)]
            cp.wait_runs(runs, timeout=90.0)
            # kill -9 mid-flight: submit first, then kill, then wait
            runs2 = [cp.run_story(story, inputs={"i": 10 + i})
                     for i in range(6)]
            cp.kill_shard("1")
            cp.wait_members({"0"}, timeout=90.0)
            cp.wait_runs(runs2, timeout=120.0)
            for r in runs + runs2:
                assert cp.run_phase(r) == Phase.SUCCEEDED, (
                    r, cp.run_phase(r), cp.logs("shard-0")[-2000:])
            cp.assert_exactly_once(runs + runs2)
            # graceful stop of the survivor so its report publishes,
            # then the byte-identity check (service still up)
            cp.stop_shard("0", timeout=90.0)
            _assert_reports_clean(cp, ["0"])
            _assert_byte_identical_recovery(cp)


class _StoryRunTarget:
    """PR-14 loadgen target adapter: ``submit`` creates a StoryRun,
    ``step`` polls outstanding phases, ``finished`` grows as runs turn
    terminal. Token/latency fields exist so TrafficReport stats
    compute; the soak gates on ``lost == 0``, not on them."""

    class _Req:
        __slots__ = ("rid", "run", "t0", "ttft_seconds", "tpot_seconds",
                     "output", "preemptions")

        def __init__(self, rid, run, t0):
            self.rid = rid
            self.run = run
            self.t0 = t0
            self.ttft_seconds = None
            self.tpot_seconds = None
            self.output = []
            self.preemptions = 0

    def __init__(self, cp, story: str):
        self.cp = cp
        self.story = story
        self.finished: list = []
        self._outstanding: dict[int, _StoryRunTarget._Req] = {}
        self._next = 0
        self.runs: list[str] = []

    def submit(self, prompt, max_new_tokens=0, temperature=0.0,
               tenant=None) -> int:
        rid = self._next
        self._next += 1
        run = self.cp.run_story(self.story, inputs={"i": rid})
        self.runs.append(run)
        self._outstanding[rid] = self._Req(rid, run, time.perf_counter())
        return rid

    def step(self) -> None:
        now = time.perf_counter()
        for rid, req in list(self._outstanding.items()):
            phase = self.cp.run_phase(req.run)
            if phase in (Phase.SUCCEEDED, Phase.FAILED):
                req.ttft_seconds = now - req.t0
                self.finished.append(req)
                del self._outstanding[rid]
        time.sleep(0.02)  # closed loop over RPCs: don't spin the socket


@pytest.mark.slow
class TestProcessSoak:
    def test_four_processes_churn_and_store_crash(self, plane_factory):
        """The acceptance soak: 4 shard processes under closed-loop
        load, one shard SIGKILLed and one joined mid-soak, THEN the
        store service itself SIGKILLed and restarted mid-soak. Gates:
        zero lost runs, every run retired exactly once, per-process
        detectors and ChipLedgers clean, byte-identical replay."""
        from bobrapet_tpu.traffic.loadgen import ClosedLoopLoadGen, TenantProfile

        cp = plane_factory(
            shards=4,
            config_data={"scheduling.global-max-concurrent-steps": "4"},
            fsync_batch=8,
        )
        with cp:
            cp.wait_members({"0", "1", "2", "3"}, timeout=120.0)
            story = apply_resources(cp, "proc-soak")
            target = _StoryRunTarget(cp, story)

            chaos_state = {"at": None}

            def chaos(now: float) -> None:
                """Mid-soak fault schedule, driven off loadgen ticks:
                ~3s in, SIGKILL shard 3 and join a replacement; ~8s in,
                SIGKILL the store service and restart it."""
                if chaos_state["at"] is None:
                    chaos_state["at"] = now
                    return
                elapsed = now - chaos_state["at"]
                if elapsed > 3.0 and "killed" not in chaos_state:
                    chaos_state["killed"] = True
                    cp.kill_shard("3")
                    chaos_state["joined"] = cp.add_shard()
                if elapsed > 8.0 and "crashed" not in chaos_state:
                    chaos_state["crashed"] = True
                    cp.kill_store_service()
                    cp.restart_store_service()

            gen = ClosedLoopLoadGen(
                target,
                profiles=[
                    TenantProfile(tenant="batch", users=6,
                                  think_time_s=0.05, max_requests=60),
                    TenantProfile(tenant="interactive", users=2,
                                  think_time_s=0.2, max_requests=20),
                ],
                seed=20260807,
                tick_hooks=[chaos],
            )
            report = gen.run(max_duration_s=240.0)
            assert "crashed" in chaos_state, (
                "soak finished before the store-service crash fired — "
                f"wall {report.wall_s:.1f}s; raise the load budget")
            # the loadgen's own ledger: everything submitted retired
            assert report.lost == 0, (
                f"{report.lost} runs lost; phases: "
                f"{[(r, cp.run_phase(r)) for r in target.runs[-8:]]}")
            assert report.completed == report.submitted >= 70
            cp.wait_runs(target.runs, timeout=120.0)
            for r in target.runs:
                assert cp.run_phase(r) == Phase.SUCCEEDED, (r, cp.run_phase(r))
            cp.assert_exactly_once(target.runs)

            joined = chaos_state["joined"]
            survivors = ["0", "1", "2", joined]
            cp.wait_members(set(survivors), timeout=120.0)
            for sid in survivors:
                cp.stop_shard(sid, timeout=120.0)
            _assert_reports_clean(cp, survivors)
            # work actually spread across processes, including the joiner
            assert sum(cp.reports[s]["processed"] for s in survivors) > 0
            _assert_byte_identical_recovery(cp)
