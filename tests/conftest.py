"""Test harness configuration.

Compute-plane tests run on a virtual 8-device CPU mesh so multi-chip
sharding (dp/fsdp/tp/sp, ring attention collectives) is exercised without
TPU hardware — the moral equivalent of the reference's envtest strategy
(real control plane, simulated kubelet; reference:
internal/controller/runs/suite_test.go:32-54).
"""

import os

# Must be set before jax is imported anywhere in the test process.
# Force-override: the driver environment pins JAX_PLATFORMS to the real
# TPU platform, but the suite runs on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A pytest plugin may import jax before this conftest; the config update
# still wins as long as no computation has initialized the backends.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite compiles the SAME tiny-model
# graphs over and over (every ServingEngine/train-step instance builds
# fresh partials, so the in-process jit cache never dedupes them); the
# disk cache dedupes by computation hash both within one run and
# across runs, cutting JAX-heavy wall-clock ~4x (VERDICT r3 #10).
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import pytest  # noqa: E402


def wait_for(cond, timeout=30.0, interval=0.02):
    """Poll ``cond`` until truthy or timeout; shared by threaded
    tests (one definition — per-file copies drift)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def tmp_store_dir(tmp_path):
    return str(tmp_path / "store")


@pytest.fixture
def rt():
    from bobrapet_tpu.runtime import Runtime

    return Runtime()


@pytest.fixture(autouse=True)
def _shared_clean_registry():
    yield
    from bobrapet_tpu.sdk.registry import clear_registry
    from bobrapet_tpu.observability.analytics import LEDGER, UTILIZATION
    from bobrapet_tpu.observability.metrics import REGISTRY
    from bobrapet_tpu.observability.profiler import PROFILER

    clear_registry()
    REGISTRY.reset()
    # fleet analytics are process-global like the metrics registry:
    # reset between tests so balance asserts see only their own grants
    # and a profiler a test enabled never samples into the next one
    PROFILER.configure(False)
    LEDGER.reset()
    UTILIZATION.reset()
