"""Native slice-local SSD blob cache: roundtrip, eviction, integrity.

Exercises the C++ store (native/blobcache.cc) through its ctypes
bindings and through the full StorageManager dehydrate/hydrate path —
same Store contract as the S3/file/memory providers
(reference: pkg/storage test model: store_mock.go, manager_fuzz_test.go).
"""

import os
import struct
import time

import pytest

from bobrapet_tpu.storage.manager import StorageManager
from bobrapet_tpu.storage.ssd import SSDStore, make_ssd_store
from bobrapet_tpu.storage.store import (
    BlobNotFound,
    SliceLocalSSDStore,
    StorageError,
)


@pytest.fixture
def ssd(tmp_path):
    store = SSDStore(str(tmp_path / "cache"))
    yield store
    store.close()


def _close(store):
    if hasattr(store, "close"):
        store.close()


@pytest.fixture(params=["native", "python"])
def bounded_factory(request, tmp_path):
    """Both slice-SSD implementations under one eviction contract:
    the native C++ blob cache and the capacity-bounded Python layout
    must agree on LRU order, pinning, capacity accounting, and
    oversized-put rejection."""

    def make(capacity_bytes, subdir="cache"):
        path = str(tmp_path / subdir)
        if request.param == "native":
            return SSDStore(path, capacity_bytes=capacity_bytes)
        return SliceLocalSSDStore(path, capacity_bytes=capacity_bytes)

    return make


class TestRoundtrip:
    def test_put_get(self, ssd):
        ssd.put("runs/default/r1/steps/a/output", b"payload-bytes")
        assert ssd.get("runs/default/r1/steps/a/output") == b"payload-bytes"

    def test_missing_raises(self, ssd):
        with pytest.raises(BlobNotFound):
            ssd.get("nope")

    def test_overwrite(self, ssd):
        ssd.put("k", b"v1")
        ssd.put("k", b"v2-longer")
        assert ssd.get("k") == b"v2-longer"

    def test_delete(self, ssd):
        ssd.put("k", b"v")
        ssd.delete("k")
        assert not ssd.exists("k")
        ssd.delete("k")  # idempotent

    def test_empty_blob(self, ssd):
        ssd.put("empty", b"")
        assert ssd.get("empty") == b""

    def test_large_blob(self, ssd):
        big = os.urandom(4 << 20)
        ssd.put("big", big)
        assert ssd.get("big") == big

    def test_list_prefix(self, ssd):
        ssd.put("runs/ns/r1/a", b"1")
        ssd.put("runs/ns/r1/b", b"2")
        ssd.put("runs/ns/r2/a", b"3")
        assert sorted(ssd.list("runs/ns/r1/")) == ["runs/ns/r1/a", "runs/ns/r1/b"]
        assert len(ssd.list("")) == 3

    def test_stat_mtime(self, ssd):
        ssd.put("k", b"v")
        assert ssd.stat_mtime("k") > 0


class TestDurability:
    def test_index_rebuilt_after_reopen(self, tmp_path):
        d = str(tmp_path / "cache")
        s1 = SSDStore(d)
        s1.put("persist/me", b"still-here")
        s1.close()
        s2 = SSDStore(d)
        try:
            assert s2.get("persist/me") == b"still-here"
            assert s2.list("persist/") == ["persist/me"]
        finally:
            s2.close()

    def test_corruption_detected(self, tmp_path):
        d = str(tmp_path / "cache")
        s = SSDStore(d)
        s.put("victim", b"A" * 1024)
        # flip payload bytes on disk behind the cache's back
        blob_files = []
        for root, _, files in os.walk(d):
            blob_files += [os.path.join(root, f) for f in files if f.endswith(".blob")]
        assert len(blob_files) == 1
        with open(blob_files[0], "r+b") as f:
            f.seek(-8, os.SEEK_END)
            f.write(b"XXXXXXXX")
        with pytest.raises(StorageError, match="corrupt"):
            s.get("victim")
        s.close()


class TestEviction:
    def test_lru_eviction_under_budget(self, tmp_path):
        # capacity fits ~3 of the 1KiB blobs (plus headers)
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=3 * 1100)
        for i in range(5):
            s.put(f"blob/{i}", bytes([i]) * 1024)
        kept = [k for k in (f"blob/{i}" for i in range(5)) if s.exists(k)]
        assert len(kept) < 5  # older blobs evicted
        assert "blob/4" in kept  # newest survives
        assert s.used_bytes() <= 3 * 1100
        s.close()

    def test_oversized_put_rejected(self, tmp_path):
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=512)
        with pytest.raises(StorageError):
            s.put("huge", b"x" * 4096)
        s.close()


class TestManagerIntegration:
    def test_dehydrate_hydrate_through_ssd(self, tmp_path):
        mgr = StorageManager(
            make_ssd_store(str(tmp_path / "cache")), max_inline_size=64
        )
        value = {"small": 1, "big": "z" * 10_000}
        out = mgr.dehydrate_inputs(value, "runs/default/r/steps/s/output")
        assert out["small"] == 1
        assert "storageRef" in str(out["big"])
        back = mgr.hydrate(out, allowed_prefixes=["runs/default/r"])
        assert back == value


class TestProviderWiring:
    def test_build_store_prefers_native(self, tmp_path):
        from bobrapet_tpu.api.shared import SliceLocalSSDProvider, StoragePolicy
        from bobrapet_tpu.storage import build_store
        from bobrapet_tpu.storage.ssd import SSDStore

        policy = StoragePolicy(
            slice_local_ssd=SliceLocalSSDProvider(
                path=str(tmp_path / "ssd"), max_bytes=1 << 20
            )
        )
        store = build_store(policy)
        assert isinstance(store, SSDStore)
        assert store.provider == "slice-ssd-native"
        store.put("k", b"v")
        assert store.get("k") == b"v"
        store.close()


class TestReviewRegressions:
    def test_overwrite_as_eviction_victim_keeps_accounting(self, tmp_path):
        """Overwriting a key that eviction would also pick must not
        double-subtract its size (regression: uint64 wraparound left the
        budget permanently undercounted)."""
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=2000)
        s.put("a", b"A" * 900)
        s.put("b", b"B" * 50)
        s.put("a", b"A" * 1900)  # forces eviction; old 'a' is the LRU
        on_disk = 0
        for root, _, files in os.walk(str(tmp_path / "cache")):
            on_disk += sum(
                os.path.getsize(os.path.join(root, f))
                for f in files if f.endswith(".blob")
            )
        assert s.used_bytes() == on_disk
        assert s.used_bytes() <= 2000
        assert s.get("a") == b"A" * 1900
        s.close()

    def test_lru_uses_access_order_not_mtime_seconds(self, tmp_path):
        """Burst writes within one second must still evict in true access
        order (regression: second-granularity mtime ties evicted
        alphabetically)."""
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=2000)
        s.put("a", b"A" * 900)
        s.put("b", b"B" * 900)
        s.get("a")  # 'a' is now hotter than 'b'
        s.put("c", b"C" * 900)  # evicts exactly one -> must be 'b'
        assert s.exists("a")
        assert not s.exists("b")
        assert s.exists("c")
        s.close()

    def test_corrupt_header_length_returns_error_not_crash(self, tmp_path):
        """A garbage data_len with intact magic must surface as a corrupt
        blob error, not an allocation crash across the C boundary."""
        d = str(tmp_path / "cache")
        s = SSDStore(d)
        s.put("victim", b"V" * 64)
        blob = None
        for root, _, files in os.walk(d):
            for f in files:
                if f.endswith(".blob"):
                    blob = os.path.join(root, f)
        # header layout: magic(4) key_len(4) data_len(8) checksum(8)
        with open(blob, "r+b") as f:
            f.seek(8)
            f.write(struct.pack("<Q", 0xFFFFFFFFFFFF))
        with pytest.raises(StorageError):
            s.get("victim")
        # reopen rescans the tree: the corrupt file is skipped, not fatal
        s.close()
        s2 = SSDStore(d)
        assert not s2.exists("victim")
        s2.close()

    def test_provider_mismatch_fails_loudly(self, tmp_path):
        """Refs written by the native store must not silently resolve
        through the plain-file fallback (different on-disk layouts)."""
        from bobrapet_tpu.storage.store import SliceLocalSSDStore

        native_mgr = StorageManager(
            SSDStore(str(tmp_path / "cache")), max_inline_size=16
        )
        out = native_mgr.dehydrate_inputs(
            {"big": "y" * 4096}, "runs/default/r/in"
        )
        file_mgr = StorageManager(
            SliceLocalSSDStore(str(tmp_path / "cache")), max_inline_size=16
        )
        with pytest.raises(StorageError, match="provider"):
            file_mgr.hydrate(out, allowed_prefixes=["runs/default/r"])


class TestPinning:
    """Live-run blobs must survive LRU pressure (ADVICE: blobcache LRU
    could evict blobs that non-terminal runs still reference)."""

    def test_pinned_prefix_survives_eviction(self, tmp_path):
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=3 * 1100)
        s.pin_prefix("runs/default/live/")
        s.put("runs/default/live/a", b"p" * 1024)
        for i in range(5):
            s.put(f"cold/{i}", bytes([i]) * 1024)
        # the pinned blob is the LRU-oldest yet must not be a victim
        assert s.get("runs/default/live/a") == b"p" * 1024
        s.close()

    def test_unpin_restores_evictability(self, tmp_path):
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=2 * 1100)
        s.pin_prefix("runs/default/done/")
        s.put("runs/default/done/a", b"q" * 1024)
        s.unpin_prefix("runs/default/done/")
        for i in range(4):
            s.put(f"cold/{i}", bytes([i]) * 1024)
        assert not s.exists("runs/default/done/a")
        s.close()

    def test_pin_refcounted(self, tmp_path):
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=2 * 1100)
        s.pin_prefix("runs/r/")
        s.pin_prefix("runs/r/")
        s.unpin_prefix("runs/r/")  # one pin still held
        s.put("runs/r/a", b"z" * 1024)
        for i in range(4):
            s.put(f"cold/{i}", bytes([i]) * 1024)
        assert s.exists("runs/r/a")
        s.close()

    def test_budget_exceeded_rather_than_evict_pinned(self, tmp_path):
        s = SSDStore(str(tmp_path / "cache"), capacity_bytes=3 * 1100)
        s.pin_prefix("runs/r/")
        for i in range(3):
            s.put(f"runs/r/{i}", bytes([i]) * 1024)
        s.put("runs/r/extra", b"e" * 1024)  # over budget, all pinned
        for i in range(3):
            assert s.exists(f"runs/r/{i}")
        assert s.exists("runs/r/extra")
        assert s.used_bytes() > 3 * 1100  # budget yielded to live data
        s.close()

    def test_manager_pin_run_roundtrip(self, tmp_path):
        mgr = StorageManager(
            SSDStore(str(tmp_path / "cache"), capacity_bytes=3 * 1100),
            max_inline_size=64,
        )
        mgr.pin_run("default", "r1")
        mgr.store.put("runs/default/r1/steps/s/output", b"live" * 256)
        for i in range(5):
            mgr.store.put(f"cache/{i}", bytes([i]) * 1024)
        assert mgr.store.exists("runs/default/r1/steps/s/output")
        mgr.unpin_run("default", "r1")
        mgr.unpin_run("default", "r1")  # double-unpin tolerated
        mgr.store.close()


def _blob_paths(base_dir):
    out = set()
    for root, _, files in os.walk(base_dir):
        out |= {os.path.join(root, f) for f in files}
    return out


class TestEvictionContract:
    """One eviction contract, two implementations: the native blob
    cache and the capacity-bounded Python layout must agree on
    pin-exemption, capacity accounting across delete/re-put, and
    ``stat_mtime``-ordered eviction after a reopen."""

    def test_eviction_under_pin_prefix(self, bounded_factory):
        s = bounded_factory(3 * 1100)
        s.pin_prefix("runs/default/live/")
        s.put("runs/default/live/a", b"p" * 1024)
        for i in range(5):
            s.put(f"cold/{i}", bytes([i]) * 1024)
        # the pinned blob is the LRU-oldest yet must not be a victim;
        # the pressure lands on the unpinned cold blobs instead
        assert s.get("runs/default/live/a") == b"p" * 1024
        assert sum(s.exists(f"cold/{i}") for i in range(5)) < 5
        s.unpin_prefix("runs/default/live/")
        for i in range(5, 9):
            s.put(f"cold/{i}", bytes([i]) * 1024)
        assert not s.exists("runs/default/live/a")
        _close(s)

    def test_budget_yields_to_pinned_data(self, bounded_factory):
        s = bounded_factory(2 * 1100)
        s.pin_prefix("runs/r/")
        for i in range(3):
            s.put(f"runs/r/{i}", bytes([i]) * 1024)
        for i in range(3):
            assert s.exists(f"runs/r/{i}")
        assert s.used_bytes() > 2 * 1100  # budget yielded, data kept
        _close(s)

    def test_capacity_accounting_across_delete_and_reput(
        self, bounded_factory
    ):
        s = bounded_factory(64 * 1024)
        s.put("a", b"A" * 1000)
        ua = s.used_bytes()
        s.put("b", b"B" * 1000)
        uab = s.used_bytes()
        # same payload size + same key length = same on-disk cost
        assert uab == 2 * ua
        s.delete("a")
        assert s.used_bytes() == uab - ua
        s.delete("a")  # idempotent: no double subtraction
        assert s.used_bytes() == uab - ua
        s.put("a", b"A" * 1000)
        assert s.used_bytes() == uab
        s.put("a", b"A" * 2000)  # overwrite grows by exactly the delta
        assert s.used_bytes() == uab + 1000
        s.put("a", b"A" * 500)  # overwrite shrinks likewise
        assert s.used_bytes() == uab - 500
        _close(s)

    def test_stat_mtime_ordered_eviction_after_reopen(self, bounded_factory):
        s = bounded_factory(3 * 1100)
        paths, before = {}, set()
        for k in ("k0", "k1", "k2"):
            s.put(k, b"z" * 1024)
            now = _blob_paths(s.base_dir)
            paths[k] = (now - before).pop()
            before = now
        _close(s)
        # rewrite history on disk: k1 is oldest, k0 middle, k2 newest
        # (deliberately NOT the insertion order — a rebuilt index must
        # trust stat_mtime, the only recency fact that survives)
        t = time.time()
        for key, age in (("k1", 300), ("k0", 200), ("k2", 100)):
            os.utime(paths[key], (t - age, t - age))
        s2 = bounded_factory(3 * 1100)
        s2.put("k3", b"z" * 1024)  # over budget: evicts exactly one
        assert not s2.exists("k1")
        for k in ("k0", "k2", "k3"):
            assert s2.exists(k)
        _close(s2)

    def test_oversized_put_rejected_without_side_effects(
        self, bounded_factory
    ):
        s = bounded_factory(512)
        with pytest.raises(StorageError):
            s.put("huge", b"x" * 4096)
        assert not s.exists("huge")
        assert s.used_bytes() == 0
        _close(s)


class TestPythonFallbackBudget:
    """make_ssd_store / build_store now hand the byte budget to the
    Python fallback too (it used to be silently unenforced)."""

    def test_make_ssd_store_fallback_keeps_budget(self, tmp_path, monkeypatch):
        import bobrapet_tpu.storage.ssd as ssd_mod

        def boom(*a, **k):
            raise ssd_mod.NativeUnavailable("no toolchain")

        monkeypatch.setattr(ssd_mod, "load_native", boom)
        s = make_ssd_store(str(tmp_path / "c"), capacity_bytes=2 * 1100)
        assert isinstance(s, SliceLocalSSDStore)
        assert s.capacity_bytes == 2 * 1100
        for i in range(4):
            s.put(f"b/{i}", bytes([i]) * 1024)
        assert s.used_bytes() <= 2 * 1100

    def test_build_store_native_false_enforces_budget(self, tmp_path):
        from bobrapet_tpu.api.shared import SliceLocalSSDProvider, StoragePolicy
        from bobrapet_tpu.storage import build_store

        policy = StoragePolicy(slice_local_ssd=SliceLocalSSDProvider(
            path=str(tmp_path / "ssd"), max_bytes=2 * 1100, native=False))
        s = build_store(policy)
        for i in range(4):
            s.put(f"b/{i}", bytes([i]) * 1024)
        assert s.used_bytes() <= 2 * 1100
        assert not s.exists("b/0")
        assert s.exists("b/3")


class TestProviderPinning:
    """slice_local_ssd.native pins one implementation (ADVICE medium:
    autodetect could silently diverge between writer and reader)."""

    def test_native_false_forces_python_layout(self, tmp_path):
        from bobrapet_tpu.api.shared import SliceLocalSSDProvider, StoragePolicy
        from bobrapet_tpu.storage import SliceLocalSSDStore, build_store

        policy = StoragePolicy(slice_local_ssd=SliceLocalSSDProvider(
            path=str(tmp_path / "ssd"), native=False))
        store = build_store(policy)
        assert isinstance(store, SliceLocalSSDStore)
        assert store.provider == "slice-ssd"

    def test_native_true_requires_toolchain(self, tmp_path, monkeypatch):
        from bobrapet_tpu.api.shared import SliceLocalSSDProvider, StoragePolicy
        from bobrapet_tpu.storage import build_store
        import bobrapet_tpu.storage.ssd as ssd_mod

        def boom(*a, **k):
            raise ssd_mod.NativeUnavailable("no g++ in this image")

        monkeypatch.setattr(ssd_mod.SSDStore, "__init__", boom)
        policy = StoragePolicy(slice_local_ssd=SliceLocalSSDProvider(
            path=str(tmp_path / "ssd"), native=True))
        with pytest.raises(StorageError, match="native=true"):
            build_store(policy)

    def test_native_true_builds_native(self, tmp_path):
        from bobrapet_tpu.api.shared import SliceLocalSSDProvider, StoragePolicy
        from bobrapet_tpu.storage import build_store

        policy = StoragePolicy(slice_local_ssd=SliceLocalSSDProvider(
            path=str(tmp_path / "ssd"), native=True))
        store = build_store(policy)
        assert store.provider == "slice-ssd-native"
        store.close()
