"""SDK model checkpointing: sharded save/restore into the blob Store.

VERDICT r1 missing #4 / SURVEY §5.4: a redriven training step must
resume from checkpointed state instead of re-initializing. Covers
shard-dedup'd save, resharding restore across different meshes, pruning,
and the e2e kill→redrive→resume story.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bobrapet_tpu.sdk.checkpoint import (
    checkpoint_steps,
    latest_checkpoint_step,
    restore_checkpoint,
    save_checkpoint,
)
from bobrapet_tpu.storage.store import BlobNotFound, MemoryStore


def _mesh(axes):
    devs = jax.devices("cpu")
    n = 1
    for v in axes.values():
        n *= v
    return Mesh(
        np.array(devs[:n]).reshape(tuple(axes.values())), tuple(axes.keys())
    )


def _sharded(mesh, spec, shape, seed=0):
    arr = jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)
    return jax.device_put(arr, NamedSharding(mesh, spec))


class TestRoundTrip:
    def test_replicated_and_sharded_leaves(self):
        store = MemoryStore()
        mesh = _mesh({"data": 2, "model": 4})
        state = {
            "w": _sharded(mesh, P("data", "model"), (8, 16), seed=1),
            "b": _sharded(mesh, P(), (16,), seed=2),
            "step_count": jnp.array(7, jnp.int32),
        }
        save_checkpoint(store, "ck", state, step=7)

        like = jax.tree_util.tree_map(jnp.zeros_like, state)
        like = {
            "w": jax.device_put(like["w"], NamedSharding(mesh, P("data", "model"))),
            "b": jax.device_put(like["b"], NamedSharding(mesh, P())),
            "step_count": like["step_count"],
        }
        restored, step = restore_checkpoint(store, "ck", like)
        assert step == 7
        for k in state:
            np.testing.assert_array_equal(
                np.asarray(state[k]), np.asarray(restored[k]), err_msg=k
            )
        # sharding preserved on the restored arrays
        assert restored["w"].sharding.spec == P("data", "model")

    def test_shard_dedup_one_blob_per_unique_index(self):
        store = MemoryStore()
        mesh = _mesh({"data": 2, "model": 4})
        # sharded only over model: each column block replicated over data
        state = {"w": _sharded(mesh, P(None, "model"), (8, 16))}
        save_checkpoint(store, "ck", state, step=0)
        blobs = [k for k in store.list("ck/") if "leaf-0/" in k]
        assert len(blobs) == 4  # 4 unique column blocks, not 8 device shards

    def test_restore_onto_different_mesh(self):
        """Save on a 2x4 mesh, restore onto 4x2 and single-device —
        the stitching path."""
        store = MemoryStore()
        mesh_a = _mesh({"data": 2, "model": 4})
        state = {"w": _sharded(mesh_a, P("data", "model"), (8, 16), seed=3)}
        save_checkpoint(store, "ck", state, step=1)

        mesh_b = _mesh({"data": 4, "model": 2})
        like_b = {
            "w": jax.device_put(
                jnp.zeros((8, 16)), NamedSharding(mesh_b, P("data", "model"))
            )
        }
        restored_b, _ = restore_checkpoint(store, "ck", like_b)
        np.testing.assert_array_equal(
            np.asarray(state["w"]), np.asarray(restored_b["w"])
        )

        like_c = {"w": jnp.zeros((8, 16))}
        restored_c, _ = restore_checkpoint(store, "ck", like_c)
        np.testing.assert_array_equal(
            np.asarray(state["w"]), np.asarray(restored_c["w"])
        )

    def test_bfloat16_leaves(self):
        store = MemoryStore()
        state = {"w": jnp.arange(32, dtype=jnp.bfloat16).reshape(4, 8)}
        save_checkpoint(store, "ck", state, step=0)
        restored, _ = restore_checkpoint(
            store, "ck", {"w": jnp.zeros((4, 8), jnp.bfloat16)}
        )
        assert restored["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(state["w"], np.float32), np.asarray(restored["w"], np.float32)
        )

    def test_optax_state_round_trips(self):
        import optax

        store = MemoryStore()
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        save_checkpoint(store, "ck", {"p": params, "o": opt_state}, step=3)
        like = {"p": jax.tree_util.tree_map(jnp.zeros_like, params),
                "o": opt.init(params)}
        restored, step = restore_checkpoint(store, "ck", like)
        assert step == 3
        flat_a = jax.tree_util.tree_leaves(opt_state)
        flat_b = jax.tree_util.tree_leaves(restored["o"])
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestLifecycle:
    def test_prune_keeps_latest_k(self):
        store = MemoryStore()
        state = {"w": jnp.ones((2, 2))}
        for s in (1, 2, 3, 4):
            save_checkpoint(store, "ck", state, step=s, keep=2)
        assert checkpoint_steps(store, "ck") == [3, 4]
        assert latest_checkpoint_step(store, "ck") == 4

    def test_restore_missing_raises(self):
        with pytest.raises(BlobNotFound):
            restore_checkpoint(MemoryStore(), "nope", {"w": jnp.zeros(2)})


class TestRedriveResume:
    def test_training_story_resumes_from_checkpoint(self, rt):
        """Kill a training story mid-run, redrive-from-step, assert the
        second attempt resumes from the checkpointed step (VERDICT #6)."""
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.sdk import EngramExit, register_engram

        attempts = []

        @register_engram("train-impl")
        def train(ctx):
            params = {"w": jnp.zeros((2, 2))}
            start = 0
            restored = ctx.restore_model_checkpoint(params)
            if restored is not None:
                params, start = restored
                start += 1
            attempts.append(start)
            for step in range(start, 5):
                params = {"w": params["w"] + 1.0}
                ctx.save_model_checkpoint(params, step)
                if step == 2 and len(attempts) == 1:
                    raise EngramExit(9, "simulated crash mid-training")
            return {"final": float(params["w"][0, 0]), "resumed_at": start}

        rt.apply(make_engram_template("t-tpl", entrypoint="train-impl"))
        rt.apply(make_engram("trainer", "t-tpl"))
        rt.apply(make_story("training", steps=[
            {"name": "train", "ref": {"name": "trainer"},
             "execution": {"retry": {"maxRetries": 0}}},
        ], output={"final": "{{ steps.train.output.final }}",
                   "resumedAt": "{{ steps.train.output.resumed_at }}"}))

        run = rt.run_story("training")
        rt.pump()
        assert rt.run_phase(run) == "Failed"

        rt.store.mutate(
            "StoryRun", "default", run,
            lambda r: r.meta.annotations.update(
                {"runs.bobrapet.io/redrive": "from:train"}
            ),
        )
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        out = rt.run_output(run)
        # crashed after saving step 2 (w=3.0); resume at step 3, finish 5
        assert out["resumedAt"] == 3
        assert out["final"] == 5.0
        assert attempts == [0, 3]


class TestMultiHost:
    def test_cooperative_save_from_two_processes(self):
        """Two gang hosts write disjoint globally-indexed shards + their
        own manifests (never clobbering each other's); restore unions
        them into one complete checkpoint.

        Simulates what each host's save_checkpoint emits for a global
        array sharded over the data axis across hosts: blobs keyed by
        GLOBAL index ranges + a per-process manifest listing only the
        locally-addressable shards."""
        import json as _json

        store = MemoryStore()
        full = np.arange(32, dtype=np.float32).reshape(8, 4)
        ckpt = "ck/ckpt-000000000000"

        def host_write(process, shard_key, data):
            store.put(f"{ckpt}/leaf-0/{shard_key}", data.tobytes())
            manifest = {
                "step": 0,
                "treedef": "PyTreeDef({'w': *})",
                "leaves": [{
                    "path": "['w']", "index": 0, "shape": [8, 4],
                    "dtype": "float32", "shards": [shard_key],
                }],
            }
            store.put(f"{ckpt}/manifest-{process:05d}.json",
                      _json.dumps(manifest).encode())

        host_write(0, "0-4_0-4", full[:4])
        host_write(1, "4-8_0-4", full[4:])

        like = {"w": jnp.zeros((8, 4))}
        restored, step = restore_checkpoint(store, "ck", like)
        assert step == 0
        np.testing.assert_array_equal(full, np.asarray(restored["w"]))

    def test_partial_newest_checkpoint_falls_back_to_complete_one(self):
        """A preemption can land MID-SAVE: the newest step has host 0's
        manifest but not host 1's shards. Step-unset restore must fall
        back to the previous complete checkpoint instead of raising (a
        raise turns into a from-scratch restart upstream)."""
        import json as _json

        store = MemoryStore()
        full = np.arange(32, dtype=np.float32).reshape(8, 4)

        def host_write(ckpt, process, shard_key, data, step):
            store.put(f"{ckpt}/leaf-0/{shard_key}", data.tobytes())
            manifest = {
                "step": step,
                "treedef": "PyTreeDef({'w': *})",
                "leaves": [{
                    "path": "['w']", "index": 0, "shape": [8, 4],
                    "dtype": "float32", "shards": [shard_key],
                }],
            }
            store.put(f"{ckpt}/manifest-{process:05d}.json",
                      _json.dumps(manifest).encode())

        # complete checkpoint at step 3
        host_write("ck/ckpt-000000000003", 0, "0-4_0-4", full[:4], 3)
        host_write("ck/ckpt-000000000003", 1, "4-8_0-4", full[4:], 3)
        # partial checkpoint at step 4: host 1 never wrote
        host_write("ck/ckpt-000000000004", 0, "0-4_0-4", full[:4] + 1, 4)

        like = {"w": jnp.zeros((8, 4))}
        restored, step = restore_checkpoint(store, "ck", like)
        assert step == 3
        np.testing.assert_array_equal(full, np.asarray(restored["w"]))
        # explicit step still surfaces the partial failure
        with pytest.raises(Exception):
            restore_checkpoint(store, "ck", like, step=4)
        # the controller's resume probe skips the partial step too, so
        # BOBRA_RESUME_STEP never advertises unrestorable state
        from bobrapet_tpu.sdk.checkpoint import (
            latest_checkpoint_step,
            latest_restorable_checkpoint_step,
        )

        assert latest_checkpoint_step(store, "ck") == 4
        assert latest_restorable_checkpoint_step(store, "ck") == 3

    def test_restored_plain_numpy_leaf_is_writable(self):
        store = MemoryStore()
        state = {"ema": np.ones((4, 4), np.float32)}
        save_checkpoint(store, "ck", state, step=0)
        restored, _ = restore_checkpoint(store, "ck", {"ema": np.zeros((4, 4), np.float32)})
        restored["ema"] += 1.0  # must not raise read-only
        assert restored["ema"][0, 0] == 2.0
