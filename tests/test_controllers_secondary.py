"""Definition controllers, durable trigger admission, effect leases,
impulse workloads.

Coverage model: the reference's envtest suites for the Story/Engram/
catalog/StoryTrigger/EffectClaim/Impulse reconcilers (SURVEY §2.2) —
real store, real controllers, token-counting verified idempotent.
"""

import pytest

from bobrapet_tpu.api.catalog import make_engram_template, make_impulse_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.impulse import make_impulse
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.core.object import new_resource
from bobrapet_tpu.sdk import register_engram


def setup_engram(rt, name="worker", **template_fields):
    ep = f"{name}-impl"
    rt.apply(make_engram_template(f"{name}-tpl", entrypoint=ep,
                                  image=f"{name}:1", **template_fields))
    rt.apply(make_engram(name, f"{name}-tpl"))
    return ep


def make_trigger(name, story, key=None, inputs=None, mode=None, **extra):
    identity = {}
    if key is not None:
        identity = {"mode": mode or "key", "key": key}
    else:
        identity = {"mode": "none", "submissionId": name}
    spec = {"storyRef": {"name": story}, "identity": identity,
            "inputs": inputs or {}, **extra}
    return new_resource("StoryTrigger", name, "default", spec=spec)


class TestStoryController:
    def test_valid_story_status(self, rt):
        setup_engram(rt)
        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        rt.pump()
        st = rt.store.get("Story", "default", "s").status
        assert st["validationStatus"] == "valid"
        assert st["stepsTotal"] == 1
        assert st["validationErrors"] == []

    def test_missing_engram_invalid(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "ghost"}}]))
        rt.pump()
        st = rt.store.get("Story", "default", "s").status
        assert st["validationStatus"] == "invalid"
        assert any("ghost" in e for e in st["validationErrors"])

    def test_missing_execute_story_target(self, rt):
        rt.apply(make_story("s", steps=[
            {"name": "sub", "type": "executeStory",
             "with": {"storyRef": {"name": "nonexistent"}}},
        ]))
        rt.pump()
        st = rt.store.get("Story", "default", "s").status
        assert st["validationStatus"] == "invalid"

    def test_run_counting_is_idempotent(self, rt):
        ep = setup_engram(rt)

        @register_engram(ep)
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        r1 = rt.run_story("s")
        rt.pump()
        r2 = rt.run_story("s")
        rt.pump()
        rt.pump()  # extra pumps must not double-count
        st = rt.store.get("Story", "default", "s").status
        assert st["runsTriggered"] == 2

    def test_revalidates_when_engram_appears(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "late"}}]))
        rt.pump()
        assert rt.store.get("Story", "default", "s").status["validationStatus"] == "invalid"
        setup_engram(rt, name="late")
        rt.pump()
        assert rt.store.get("Story", "default", "s").status["validationStatus"] == "valid"


class TestEngramAndTemplates:
    def test_engram_usage_counters(self, rt):
        setup_engram(rt)
        rt.apply(make_story("s1", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        rt.apply(make_story("s2", steps=[{"name": "b", "ref": {"name": "worker"}}]))
        rt.pump()
        st = rt.store.get("Engram", "default", "worker").status
        assert st["usageCount"] == 2
        assert st["usedByStories"] == ["s1", "s2"]

    def test_engram_degraded_when_template_deleted(self, rt):
        setup_engram(rt, name="orphan")
        rt.pump()
        assert rt.store.get("Engram", "default", "orphan").status["phase"] == "Running"
        rt.store.delete("EngramTemplate", "_cluster", "orphan-tpl")
        rt.pump()
        st = rt.store.get("Engram", "default", "orphan").status
        assert st["phase"] == "Failed"

    def test_template_usage_and_validation(self, rt):
        setup_engram(rt)
        rt.pump()
        tpl = rt.store.get("EngramTemplate", "_cluster", "worker-tpl")
        assert tpl.status["usageCount"] == 1
        assert tpl.status["validationStatus"] == "valid"

    def test_entrypoint_only_template_valid(self, rt):
        """TPU-native templates may ship an entrypoint without an image
        (in-process engrams); the controller must accept what admission
        accepts."""
        rt.apply(make_engram_template("bare-tpl", entrypoint="x"))
        rt.pump()
        tpl = rt.store.get("EngramTemplate", "_cluster", "bare-tpl")
        assert tpl.status["validationStatus"] == "valid"


class TestStoryTriggerAdmission:
    def _story(self, rt):
        ep = setup_engram(rt)

        @register_engram(ep)
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))

    def test_created(self, rt):
        self._story(rt)
        rt.store.create(make_trigger("t1", "s", key="k1", inputs={"x": 1}))
        rt.pump()
        t = rt.store.get("StoryTrigger", "default", "t1")
        assert t.status["decision"] == "Created"
        run = rt.store.get("StoryRun", "default", t.status["storyRunName"])
        assert run.status["phase"] == "Succeeded"

    def test_duplicate_delivery_reused(self, rt):
        self._story(rt)
        rt.store.create(make_trigger("t1", "s", key="k1", inputs={"x": 1}))
        rt.pump()
        rt.store.create(make_trigger("t2", "s", key="k1", inputs={"x": 1}))
        rt.pump()
        t2 = rt.store.get("StoryTrigger", "default", "t2")
        assert t2.status["decision"] == "Reused"
        assert t2.status["storyRunName"] == (
            rt.store.get("StoryTrigger", "default", "t1").status["storyRunName"]
        )
        assert len(rt.store.list("StoryRun")) == 1

    def test_same_key_different_inputs_rejected(self, rt):
        self._story(rt)
        rt.store.create(make_trigger("t1", "s", key="k1", inputs={"x": 1}))
        rt.pump()
        rt.store.create(make_trigger("t2", "s", key="k1", inputs={"x": 2}))
        rt.pump()
        assert rt.store.get("StoryTrigger", "default", "t2").status["decision"] == "Rejected"

    def test_story_not_found_rejected(self, rt):
        rt.store.create(make_trigger("t1", "ghost", key="k1"))
        rt.pump()
        t = rt.store.get("StoryTrigger", "default", "t1")
        assert t.status["decision"] == "Rejected"
        assert "not found" in t.status["message"]

    def test_version_pinning_mismatch_rejected(self, rt):
        self._story(rt)
        rt.store.mutate("Story", "default", "s",
                        lambda r: r.spec.__setitem__("version", "v2"))
        trig = make_trigger("t1", "s", key="k1")
        trig.spec["storyRef"]["version"] = "v1"
        rt.store.create(trig)
        rt.pump()
        t = rt.store.get("StoryTrigger", "default", "t1")
        assert t.status["decision"] == "Rejected"
        assert "version" in t.status["message"]

    def test_distinct_keys_distinct_runs(self, rt):
        self._story(rt)
        rt.store.create(make_trigger("t1", "s", key="k1"))
        rt.store.create(make_trigger("t2", "s", key="k2"))
        rt.pump()
        assert len(rt.store.list("StoryRun")) == 2

    def test_oversized_inputs_offloaded_and_admitted(self, rt):
        """Dehydrated trigger inputs must land in the canonical
        runs/<ns>/<run>/ storage scope the StoryRun webhook accepts."""
        self._story(rt)
        big = "x" * (rt.storage.max_inline_size + 1)
        rt.store.create(make_trigger("t1", "s", key="k1", inputs={"blob": big}))
        rt.pump()
        t = rt.store.get("StoryTrigger", "default", "t1")
        assert t.status["decision"] == "Created", t.status
        run = rt.store.get("StoryRun", "default", t.status["storyRunName"])
        ref = run.spec["inputs"]["blob"]
        assert isinstance(ref, dict) and "storageRef" in ref

    def test_inadmissible_run_resolves_rejected(self, rt):
        """An admission-rejected StoryRun resolves the trigger as
        Rejected instead of crash-looping the reconciler."""
        self._story(rt)
        rt.store.mutate(
            "Story", "default", "s",
            lambda r: r.spec.__setitem__(
                "inputsSchema",
                {"type": "object", "required": ["must"],
                 "properties": {"must": {"type": "string"}}},
            ),
        )
        rt.store.create(make_trigger("t1", "s", key="k1", inputs={"wrong": 1}))
        rt.pump()
        t = rt.store.get("StoryTrigger", "default", "t1")
        assert t.status["decision"] == "Rejected"
        assert t.status["reason"] == "StoryRunInadmissible"


class TestEffectClaims:
    def _claim(self, rt, name="c", lease=30):
        ec = new_resource("EffectClaim", name, "default", spec={
            "stepRunRef": {"name": "sr-x"}, "effectId": "charge-card",
            "holderIdentity": "sdk-1", "leaseDurationSeconds": lease,
        })
        rt.store.create(ec)
        return ec

    def test_reserved_then_completed(self, rt):
        self._claim(rt)
        rt.pump(max_virtual_seconds=5)
        assert rt.store.get("EffectClaim", "default", "c").status["phase"] == "Reserved"
        rt.store.patch_status("EffectClaim", "default", "c",
                              lambda s: s.__setitem__("completed", True))
        rt.pump(max_virtual_seconds=5)
        assert rt.store.get("EffectClaim", "default", "c").status["phase"] == "Completed"

    def test_released(self, rt):
        self._claim(rt)
        rt.pump(max_virtual_seconds=5)
        rt.store.patch_status("EffectClaim", "default", "c",
                              lambda s: s.__setitem__("released", True))
        rt.pump(max_virtual_seconds=5)
        assert rt.store.get("EffectClaim", "default", "c").status["phase"] == "Released"

    def test_lease_expiry_abandons(self, rt):
        self._claim(rt, lease=30)
        rt.pump(max_virtual_seconds=5)
        assert rt.store.get("EffectClaim", "default", "c").status["phase"] == "Reserved"
        rt.pump(max_virtual_seconds=120)
        assert rt.store.get("EffectClaim", "default", "c").status["phase"] == "Abandoned"

    def test_renewal_extends_lease(self, rt):
        self._claim(rt, lease=30)
        rt.pump(max_virtual_seconds=5)
        # holder renews: spec.renewedAt moves the anchor forward
        far = rt.clock.now() + 100
        rt.store.mutate("EffectClaim", "default", "c",
                        lambda r: r.spec.__setitem__("renewedAt", far))
        rt.pump(max_virtual_seconds=60)
        assert rt.store.get("EffectClaim", "default", "c").status["phase"] == "Reserved"

    def test_owner_ref_set_when_steprun_exists(self, rt):
        ep = setup_engram(rt)

        @register_engram(ep)
        def impl(ctx):
            return {}

        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        rt.run_story("s")
        rt.pump()
        sr_name = rt.store.list("StepRun")[0].meta.name
        ec = new_resource("EffectClaim", "c", "default", spec={
            "stepRunRef": {"name": sr_name}, "effectId": "e",
            "holderIdentity": "h",
        })
        rt.store.create(ec)
        rt.pump(max_virtual_seconds=5)
        claim = rt.store.get("EffectClaim", "default", "c")
        assert claim.meta.owner_references
        assert claim.meta.owner_references[0].name == sr_name


class TestImpulse:
    def _setup(self, rt):
        ep = setup_engram(rt)

        @register_engram(ep)
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        rt.apply(make_impulse_template("hook-tpl", image="hook:1",
                                       supportedModes=["deployment"]))
        rt.apply(make_impulse("imp", "hook-tpl", "s"))

    def test_workloads_materialized(self, rt):
        self._setup(rt)
        rt.pump()
        assert rt.store.get("Impulse", "default", "imp").status["phase"] == "Running"
        dep = rt.store.get("Deployment", "default", "imp-impulse")
        assert dep.spec["image"] == "hook:1"
        assert dep.spec["env"]["BOBRA_TRIGGER_STORY"] == "s"
        assert rt.store.try_get("Service", "default", "imp-impulse-svc") is not None
        assert rt.store.try_get("ServiceAccount", "default", "imp-impulse-sa") is not None

    def test_blocked_when_template_deleted(self, rt):
        self._setup(rt)
        rt.pump()
        rt.store.delete("ImpulseTemplate", "_cluster", "hook-tpl")
        rt.pump()
        assert rt.store.get("Impulse", "default", "imp").status["phase"] == "Blocked"

    def test_blocked_impulse_recovers_when_story_appears(self, rt):
        rt.apply(make_impulse_template("hook-tpl", image="hook:1",
                                       supportedModes=["deployment"]))
        rt.apply(make_impulse("imp", "hook-tpl", "later-story"))
        rt.pump()
        assert rt.store.get("Impulse", "default", "imp").status["phase"] == "Blocked"
        ep = setup_engram(rt)

        @register_engram(ep)
        def impl(ctx):
            return {}

        rt.apply(make_story("later-story",
                            steps=[{"name": "a", "ref": {"name": "worker"}}]))
        rt.pump()
        assert rt.store.get("Impulse", "default", "imp").status["phase"] == "Running"

    def test_max_in_flight_throttle_rejects(self, rt):
        ep = setup_engram(rt)

        @register_engram(ep)
        def impl(ctx):
            return {}

        # a gate step keeps runs in flight until approved
        rt.apply(make_story("s", steps=[
            {"name": "hold", "type": "gate"},
            {"name": "a", "ref": {"name": "worker"}, "needs": ["hold"]},
        ]))
        rt.apply(make_impulse_template("hook-tpl", image="hook:1",
                                       supportedModes=["deployment"]))
        imp = make_impulse("imp", "hook-tpl", "s")
        imp.spec["throttle"] = {"maxInFlight": 1}
        rt.apply(imp)
        rt.pump()
        rt.store.create(make_trigger("t1", "s", key="k1", impulseRef={"name": "imp"}))
        rt.pump(max_virtual_seconds=60)
        assert rt.store.get("StoryTrigger", "default", "t1").status["decision"] == "Created"
        rt.store.create(make_trigger("t2", "s", key="k2", impulseRef={"name": "imp"}))
        rt.pump(max_virtual_seconds=60)
        t2 = rt.store.get("StoryTrigger", "default", "t2").status
        assert t2["decision"] == "Rejected"
        assert t2["reason"] == "Throttled"
        assert rt.store.get("Impulse", "default", "imp").status["triggersThrottled"] == 1

    def test_trigger_stats_token_counted(self, rt):
        self._setup(rt)
        rt.pump()
        trig = make_trigger("t1", "s", key="k1",
                            impulseRef={"name": "imp"})
        rt.store.create(trig)
        rt.pump()
        rt.pump()  # idempotent: second pump must not double-count
        st = rt.store.get("Impulse", "default", "imp").status
        assert st["triggersReceived"] == 1
        assert st["storiesLaunched"] == 1
        assert st["storiesSucceeded"] == 1
        assert st["storiesFailed"] == 0
