"""Traffic harness units: weighted-fair queue, pure autoscaler
decisions, closed-loop load-generator mechanics, traffic.* config keys.

Everything here is engine-free (no jax) — the pure halves of the
subsystem. The closed-loop fairness pin, the drain contract and the
autoscaler e2e live in test_traffic_e2e.py; the preemption chaos soak
in test_traffic_chaos.py.
"""

import random
from collections import deque
from dataclasses import replace

import pytest

from bobrapet_tpu.config.operator import (
    OperatorConfig,
    TrafficConfig,
    parse_config,
)
from bobrapet_tpu.traffic import (
    Autoscaler,
    AutoscalePolicy,
    ClosedLoopLoadGen,
    Decision,
    PoolSignals,
    TenantProfile,
    TrafficPhase,
    WeightedFairQueue,
    decide,
    parse_tenant_weights,
)


class _Req:
    """Duck-typed queue item (matches engine Request / router _Queued)."""

    def __init__(self, tenant, prompt_len=10, max_new=4):
        self.tenant = tenant
        self.prompt = [0] * prompt_len
        self.max_new_tokens = max_new

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"_Req({self.tenant})"


# ---------------------------------------------------------------------------
# parse_tenant_weights
# ---------------------------------------------------------------------------


class TestParseTenantWeights:
    def test_basic(self):
        assert parse_tenant_weights("a:4,b:1") == {"a": 4.0, "b": 1.0}

    def test_empty_is_fifo(self):
        assert parse_tenant_weights("") == {}
        assert parse_tenant_weights("   ") == {}

    def test_default_star(self):
        assert parse_tenant_weights("*:2,a:8") == {"*": 2.0, "a": 8.0}

    def test_colon_in_tenant_name(self):
        # rpartition: the LAST colon splits, so namespaced tenants work
        assert parse_tenant_weights("org:team:3") == {"org:team": 3.0}

    @pytest.mark.parametrize("bad", ["a", "a:", ":3", "a:zero", "a:-1",
                                     "a:0"])
    def test_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_tenant_weights(bad)


# ---------------------------------------------------------------------------
# WeightedFairQueue
# ---------------------------------------------------------------------------


class TestWeightedFairQueue:
    def test_fifo_parity_without_weights(self):
        """No weights = byte-compatible with the deque it replaces."""
        q, d = WeightedFairQueue(), deque()
        rng = random.Random(0)
        for _ in range(300):
            if rng.random() < 0.6 or not d:
                r = _Req(rng.choice("abc"))
                q.append(r)
                d.append(r)
            else:
                assert q.popleft() is d.popleft()
                assert len(q) == len(d)
        while d:
            assert q.popleft() is d.popleft()

    def test_victim_not_starved_by_flood(self):
        """The construction the whole subsystem exists for: a victim
        arriving behind a 100-deep flood is served within ONE flood
        request, not after the backlog."""
        q = WeightedFairQueue({"victim": 1.0, "flood": 1.0})
        for _ in range(100):
            q.append(_Req("flood"))
        q.append(_Req("victim"))
        first_two = [q.popleft().tenant, q.popleft().tenant]
        assert "victim" in first_two

    def test_weight_proportional_share(self):
        q = WeightedFairQueue({"a": 3.0, "b": 1.0})
        for _ in range(60):
            q.append(_Req("a"))
            q.append(_Req("b"))
        served = [q.popleft().tenant for _ in range(40)]
        # 3:1 within one request of exact
        assert 28 <= served.count("a") <= 31

    def test_cost_weighted_not_count_weighted(self):
        """A tenant sending requests 10x the size cannot buy 10x the
        tokens: share is cost-proportional."""
        q = WeightedFairQueue({"big": 1.0, "small": 1.0})
        for _ in range(40):
            q.append(_Req("big", prompt_len=100, max_new=0))
            q.append(_Req("small", prompt_len=10, max_new=0))
        cost = {"big": 0.0, "small": 0.0}
        for _ in range(44):
            r = q.popleft()
            cost[r.tenant] += len(r.prompt)
        ratio = cost["big"] / max(1.0, cost["small"])
        assert 0.7 <= ratio <= 1.4, cost

    def test_head_stability_and_appendleft(self):
        q = WeightedFairQueue({"a": 1.0})
        r1, r2 = _Req("a"), _Req("b")
        q.append(r1)
        q.append(r2)
        head = q[0]
        assert q[0] is head  # repeated peeks stable
        assert q.popleft() is head
        q.appendleft(head)  # engine preemption requeue
        assert q[0] is head and q.popleft() is head

    def test_idle_banks_no_credit(self):
        """A tenant idle while others were served cannot burst through
        banked virtual time on return."""
        q = WeightedFairQueue({"a": 1.0, "b": 1.0})
        for _ in range(20):
            q.append(_Req("a"))
        for _ in range(10):
            q.popleft()  # only a served; clock advanced
        for _ in range(20):
            q.append(_Req("b"))
        served = [q.popleft().tenant for _ in range(10)]
        # b re-enters AT the clock: interleaves, does not monopolize
        assert 3 <= served.count("b") <= 7, served

    def test_iteration_is_arrival_order(self):
        q = WeightedFairQueue({"a": 1.0})
        reqs = [_Req("a"), _Req("b"), _Req("a"), _Req("c")]
        for r in reqs:
            q.append(r)
        assert list(q) == reqs
        assert q[2] is reqs[2]

    def test_len_bool_clear(self):
        q = WeightedFairQueue()
        assert not q and len(q) == 0
        with pytest.raises(IndexError):
            q.popleft()
        q.extend([_Req("a"), _Req("b")])
        assert q and len(q) == 2
        q.clear()
        assert not q

    def test_transfer_preserves_order(self):
        """The live-reload swap path: deque -> fair -> deque keeps
        arrival order exactly."""
        reqs = [_Req(t) for t in "abcabc"]
        d = deque(reqs)
        q = WeightedFairQueue({"a": 2.0})
        q.extend(d)
        back: deque = deque()
        back.extend(q)
        assert list(back) == reqs


# ---------------------------------------------------------------------------
# pure autoscaler decisions (satellite: no engines needed)
# ---------------------------------------------------------------------------


_POL = AutoscalePolicy(
    min_replicas=1, max_replicas=4,
    scale_up_burn=0.30, scale_down_burn=0.05,
    scale_up_queue_wait_s=0.50, scale_down_queue_wait_s=0.05,
    queue_depth_per_replica=8,
    scale_up_cooldown_s=5.0, scale_down_cooldown_s=30.0,
)


class TestDecide:
    def test_decode_scales_up_on_tpot_burn(self):
        d = decide("decode", PoolSignals(burn_rate=0.5, replicas=1),
                   _POL, now=100.0)
        assert (d.direction, d.reason, d.desired) == ("up", "tpot-burn", 2)

    def test_prefill_scales_up_on_queue_wait(self):
        d = decide("prefill", PoolSignals(queue_wait_p95_s=1.0, replicas=1),
                   _POL, now=100.0)
        assert (d.direction, d.reason) == ("up", "queue-wait")

    def test_signal_split_is_strict(self):
        """The PR-11 split: a prefill pool does NOT scale on burn, a
        decode pool does NOT scale on queue wait."""
        d = decide("prefill", PoolSignals(burn_rate=1.0, replicas=1),
                   _POL, now=100.0)
        assert d.direction == "hold"
        d = decide("decode", PoolSignals(queue_wait_p95_s=10.0, replicas=1),
                   _POL, now=100.0)
        assert d.direction == "hold"

    def test_depth_is_shared_leading_indicator(self):
        for pool in ("prefill", "decode"):
            d = decide(pool, PoolSignals(queue_depth=20, replicas=2),
                       _POL, now=100.0)
            assert (d.direction, d.reason) == ("up", "queue-depth"), pool
        # 16 queued on 2 replicas = at the 8/replica bound, not past it
        d = decide("decode", PoolSignals(queue_depth=16, replicas=2),
                   _POL, now=100.0)
        assert d.direction == "hold"

    def test_hysteresis_band_holds(self):
        """Between the down and up thresholds NOTHING happens, in
        either direction — the gap is the anti-flap guarantee."""
        for burn in (0.06, 0.15, 0.29):
            d = decide("decode", PoolSignals(burn_rate=burn, replicas=2),
                       _POL, now=100.0)
            assert d.direction == "hold", burn
        for wait in (0.06, 0.3, 0.49):
            d = decide("prefill",
                       PoolSignals(queue_wait_p95_s=wait, replicas=2),
                       _POL, now=100.0)
            assert d.direction == "hold", wait

    def test_scale_up_cooldown(self):
        sig = PoolSignals(burn_rate=0.9, replicas=2)
        d = decide("decode", sig, _POL, now=103.0, last_up_at=100.0)
        assert d.direction == "hold" and "cooldown" in d.reason
        d = decide("decode", sig, _POL, now=105.1, last_up_at=100.0)
        assert d.direction == "up"

    def test_scale_down_requires_calm_and_cooldown(self):
        calm = PoolSignals(burn_rate=0.0, queue_depth=0, replicas=3)
        d = decide("decode", calm, _POL, now=100.0)
        assert (d.direction, d.desired) == ("down", 2)
        # queued work blocks a scale-down no matter how low the burn
        d = decide("decode", replace(calm, queue_depth=1), _POL, now=100.0)
        assert d.direction == "hold"
        d = decide("decode", calm, _POL, now=110.0, last_down_at=100.0)
        assert d.direction == "hold" and "cooldown" in d.reason
        # a replica added seconds ago must settle before being judged
        d = decide("decode", calm, _POL, now=110.0, last_up_at=100.0)
        assert d.direction == "hold" and "settling" in d.reason

    def test_clamps(self):
        d = decide("decode", PoolSignals(burn_rate=0.9, replicas=4),
                   _POL, now=100.0)
        assert d.direction == "hold" and "max-replicas" in d.reason
        d = decide("decode",
                   PoolSignals(burn_rate=0.0, queue_depth=0, replicas=1),
                   _POL, now=100.0)
        assert d.direction == "hold"  # at min

    def test_draining_counts_against_max(self):
        """A slow drain's chips are still held: 3 routable + 1 draining
        at max 4 means NO room to grow (the double-count trap)."""
        d = decide("decode",
                   PoolSignals(burn_rate=0.9, replicas=3, draining=1),
                   _POL, now=100.0)
        assert d.direction == "hold" and "max-replicas" in d.reason

    def test_one_drain_at_a_time(self):
        d = decide("decode",
                   PoolSignals(burn_rate=0.0, queue_depth=0, replicas=3,
                               draining=1),
                   _POL, now=100.0)
        assert d.direction == "hold" and "drain" in d.reason

    def test_decision_is_pure(self):
        sig = PoolSignals(burn_rate=0.5, replicas=1)
        a = decide("decode", sig, _POL, now=100.0)
        b = decide("decode", sig, _POL, now=100.0)
        assert a == b and isinstance(a, Decision)

    def test_policy_validation(self):
        assert AutoscalePolicy().validate() == []
        assert AutoscalePolicy(min_replicas=0).validate()
        assert AutoscalePolicy(max_replicas=0).validate()
        assert AutoscalePolicy(scale_down_burn=0.5,
                               scale_up_burn=0.3).validate()
        assert AutoscalePolicy(scale_down_queue_wait_s=2.0).validate()
        assert AutoscalePolicy(scale_up_cooldown_s=-1).validate()


# ---------------------------------------------------------------------------
# closed-loop load generator (against an instant fake target)
# ---------------------------------------------------------------------------


class _FakeTarget:
    """Instant-completion serving target: every step finishes every
    pending request (Request-shaped results)."""

    class _Fin:
        def __init__(self, rid, prompt, n):
            self.rid = rid
            self.output = list(range(n))
            self.preemptions = 0
            self.ttft_seconds = 0.01
            self.tpot_seconds = 0.001

    def __init__(self):
        self.finished = []
        self._queue = []
        self._next = 0
        self.submissions = []

    def submit(self, prompt, max_new_tokens, temperature=0.0, tenant="",
               **kw):
        rid = self._next
        self._next += 1
        self.submissions.append((tenant, list(prompt), max_new_tokens))
        self._queue.append((rid, prompt, max_new_tokens))
        return rid

    def step(self):
        for rid, prompt, n in self._queue:
            self.finished.append(self._Fin(rid, prompt, n))
        self._queue.clear()


class TestLoadGen:
    def _profiles(self):
        return [
            TenantProfile("a", users=2, prompt_len=(4, 8),
                          new_tokens=(2, 4), max_requests=10),
            TenantProfile("b", users=1, prompt_len=(16, 16),
                          new_tokens=(8, 8), max_requests=5,
                          shared_prefix_len=8),
        ]

    def test_deterministic_schedule(self):
        """Same seed = identical per-tenant request sequences."""
        subs = []
        for _ in range(2):
            t = _FakeTarget()
            ClosedLoopLoadGen(t, self._profiles(), seed=7).run(
                max_duration_s=10.0)
            subs.append(sorted(t.submissions))
        assert subs[0] == subs[1]
        t = _FakeTarget()
        ClosedLoopLoadGen(t, self._profiles(), seed=8).run(
            max_duration_s=10.0)
        assert sorted(t.submissions) != subs[0]

    def test_budgets_and_report(self):
        t = _FakeTarget()
        rep = ClosedLoopLoadGen(t, self._profiles(), seed=1).run(
            max_duration_s=10.0)
        assert rep.submitted == rep.completed == 15
        assert rep.lost == 0
        assert rep.tenant("a")["completed"] == 10
        assert rep.tenant("b")["completed"] == 5
        assert rep.tenant("b")["ttft_p95_s"] == pytest.approx(0.01)
        assert rep.tenant("b")["tokens"] == 5 * 8

    def test_shared_prefix_rides_every_request(self):
        t = _FakeTarget()
        ClosedLoopLoadGen(t, self._profiles(), seed=1).run(
            max_duration_s=10.0)
        b_prompts = [p for ten, p, _n in t.submissions if ten == "b"]
        prefixes = {tuple(p[:8]) for p in b_prompts}
        assert len(prefixes) == 1
        assert all(len(p) == 24 for p in b_prompts)

    def test_closed_loop_bounds_in_flight(self):
        """In-flight per tenant never exceeds its user count."""
        class SlowTarget(_FakeTarget):
            def __init__(self):
                super().__init__()
                self.max_seen = 0

            def step(self):
                per = {}
                for rid, p, n in self._queue:
                    per.setdefault(len(p) >= 0 and "x", 0)
                self.max_seen = max(self.max_seen, len(self._queue))
                # finish ONE request per step — backlog builds if the
                # generator were open-loop
                if self._queue:
                    rid, prompt, n = self._queue.pop(0)
                    self.finished.append(self._Fin(rid, prompt, n))

        t = SlowTarget()
        ClosedLoopLoadGen(
            t, [TenantProfile("a", users=3, max_requests=30)], seed=2,
        ).run(max_duration_s=20.0)
        assert t.max_seen <= 3

    def test_phases_modulate_rate_and_terminate(self):
        t = _FakeTarget()
        rep = ClosedLoopLoadGen(
            t,
            [TenantProfile("a", users=2, think_time_s=0.002)],
            phases=[TrafficPhase("warm", 0.05, rate=1.0),
                    TrafficPhase("burst", 0.05, rate=50.0),
                    TrafficPhase("ramp-down", 0.05, rate=50.0,
                                 rate_end=0.1)],
            seed=3,
        ).run(max_duration_s=5.0)
        assert [p["phase"] for p in rep.phase_log] == [
            "warm", "burst", "ramp-down"]
        assert rep.lost == 0 and rep.completed == rep.submitted > 0

    def test_phase_rate_shapes_arrivals(self):
        """The multiplier must actually modulate the arrival process —
        not just exist (it was once computed and dropped): the same
        profile through a high-rate phase completes far more requests
        than through a low-rate phase in the same wall budget."""
        def completed(rate):
            t = _FakeTarget()
            rep = ClosedLoopLoadGen(
                t,
                [TenantProfile("a", users=2, think_time_s=0.05)],
                phases=[TrafficPhase("p", 0.4, rate=rate)],
                seed=5,
            ).run(max_duration_s=2.0)
            assert rep.lost == 0
            return rep.completed

        slow, fast = completed(0.1), completed(50.0)
        assert fast > 4 * slow, (slow, fast)

    def test_phase_multiplier_ramp(self):
        ph = TrafficPhase("r", 10.0, rate=1.0, rate_end=11.0)
        assert ph.multiplier(0.0) == pytest.approx(1.0)
        assert ph.multiplier(5.0) == pytest.approx(6.0)
        assert ph.multiplier(10.0) == pytest.approx(11.0)
        assert TrafficPhase("flat", 10.0, rate=2.0).multiplier(7.0) == 2.0

    def test_duplicate_tenants_rejected(self):
        with pytest.raises(ValueError):
            ClosedLoopLoadGen(_FakeTarget(),
                              [TenantProfile("a"), TenantProfile("a")])


# ---------------------------------------------------------------------------
# traffic.* / serving.tenant-weights config plumbing
# ---------------------------------------------------------------------------


class TestTrafficConfigKeys:
    def test_keys_parse(self):
        cfg = parse_config({
            "traffic.autoscale-enabled": "true",
            "traffic.autoscale-interval": "2s",
            "traffic.min-replicas": "2",
            "traffic.max-replicas": "6",
            "traffic.scale-up-burn": "0.4",
            "traffic.scale-down-burn": "0.1",
            "traffic.scale-up-queue-wait": "750ms",
            "traffic.scale-down-queue-wait": "100ms",
            "traffic.queue-depth-per-replica": "16",
            "traffic.scale-up-cooldown": "3s",
            "traffic.scale-down-cooldown": "45s",
            "serving.tenant-weights": "gold:4,free:1",
        })
        t = cfg.traffic
        assert t.autoscale_enabled is True
        assert t.autoscale_interval_seconds == 2.0
        assert (t.min_replicas, t.max_replicas) == (2, 6)
        assert (t.scale_up_burn, t.scale_down_burn) == (0.4, 0.1)
        assert t.scale_up_queue_wait_seconds == pytest.approx(0.75)
        assert t.scale_down_queue_wait_seconds == pytest.approx(0.10)
        assert t.queue_depth_per_replica == 16
        assert t.scale_up_cooldown_seconds == 3.0
        assert t.scale_down_cooldown_seconds == 45.0
        assert cfg.serving.tenant_weights == "gold:4,free:1"
        assert cfg.validate() == []

    def test_validation_rejects(self):
        bad = OperatorConfig()
        bad.serving.tenant_weights = "a:-1"
        assert any("tenant-weights" in e for e in bad.validate())
        bad = OperatorConfig()
        bad.traffic.scale_down_burn = 0.9
        assert any("hysteresis" in e for e in bad.validate())
        bad = OperatorConfig()
        bad.traffic.autoscale_interval_seconds = 0.0
        assert any("autoscale-interval" in e for e in bad.validate())
        bad = OperatorConfig()
        bad.traffic.max_replicas = 0
        assert any("max-replicas" in e for e in bad.validate())

    def test_policy_from_config(self):
        pol = AutoscalePolicy.from_config(TrafficConfig(
            min_replicas=2, max_replicas=8, scale_up_burn=0.5,
        ))
        assert pol.min_replicas == 2 and pol.max_replicas == 8
        assert pol.scale_up_burn == 0.5
        assert pol.validate() == []


class _FakeRouter:
    """Engine-free router double for reload tests."""

    def __init__(self):
        self.engines = {}

    def queue_depths(self):
        return {"prefill": 0, "decode": 0}


class _ZeroSignals:
    def read(self, pool, replicas, draining):
        return PoolSignals(replicas=replicas, draining=draining)


class TestLiveReload:
    def test_runtime_reload_reaches_live_autoscalers(self):
        from bobrapet_tpu.runtime import Runtime
        from bobrapet_tpu.traffic.autoscaler import EngineReplicaSet

        rs = EngineReplicaSet("decode", _FakeRouter(), lambda: None)
        scaler = Autoscaler({"decode": rs}, signals=_ZeroSignals(),
                            interval_s=5.0, enabled=False)
        cfg = parse_config({
            "traffic.autoscale-enabled": "true",
            "traffic.autoscale-interval": "250ms",
            "traffic.max-replicas": "7",
            "traffic.scale-up-burn": "0.6",
        })
        Runtime._apply_traffic_tuning(cfg)
        assert scaler.enabled is True
        assert scaler.interval_s == pytest.approx(0.25)
        assert scaler.policy.max_replicas == 7
        assert scaler.policy.scale_up_burn == 0.6
        # the handoff slot is parked for autoscalers built later
        from bobrapet_tpu.config import operator as opcfg

        assert opcfg.LAST_TRAFFIC_TUNING is cfg.traffic

    def test_multi_router_needs_explicit_signals(self):
        """The default MetricsSignalReader polls ONE router's queues;
        replica sets spanning routers must bring their own reader or
        one pool's depth signal would silently read the wrong router."""
        from bobrapet_tpu.traffic.autoscaler import EngineReplicaSet

        rs_a = EngineReplicaSet("prefill", _FakeRouter(), lambda: None)
        rs_b = EngineReplicaSet("decode", _FakeRouter(), lambda: None)
        with pytest.raises(ValueError, match="multiple routers"):
            Autoscaler({"prefill": rs_a, "decode": rs_b})
        # an explicit reader makes the same shape legal
        Autoscaler({"prefill": rs_a, "decode": rs_b},
                   signals=_ZeroSignals())

    def test_invalid_reload_keeps_prior_policy(self):
        from bobrapet_tpu.traffic import autoscaler as mod

        rs = mod.EngineReplicaSet("decode", _FakeRouter(), lambda: None)
        scaler = Autoscaler({"decode": rs}, signals=_ZeroSignals(),
                            interval_s=1.0)
        prior = scaler.policy
        bad = TrafficConfig(scale_up_burn=0.1, scale_down_burn=0.5)
        mod.apply_tuning(bad)  # logs + skips, never half-applies
        assert scaler.policy is prior
