"""Storage offload: dehydrate/hydrate round-trips, spoofing guards, retention."""

import json

import pytest

from bobrapet_tpu.storage import (
    BlobNotFound,
    FileStore,
    MemoryStore,
    S3Store,
    StorageError,
    StorageManager,
    StorageRef,
)
from bobrapet_tpu.templating import is_storage_ref


@pytest.fixture
def mgr():
    # limit must exceed one storageRef marker (~150B of JSON) or slimmed
    # containers re-offload wholesale
    return StorageManager(MemoryStore(), max_inline_size=256)


BIG = "x" * 500
SMALL = {"a": 1}


class TestDehydrate:
    def test_small_values_stay_inline(self, mgr):
        v = {"a": 1, "b": "short"}
        assert mgr.dehydrate(v, "runs/ns/r1/in") == v

    def test_large_scalar_offloads(self, mgr):
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/in")
        assert is_storage_ref(out["doc"])
        ref = StorageRef.from_marker(out["doc"])
        assert ref.key.startswith("runs/ns/r1/in/doc")
        assert ref.size >= 500

    def test_nested_selective_offload(self, mgr):
        v = {"meta": {"k": 1}, "body": {"text": BIG, "tag": "t"}}
        out = mgr.dehydrate(v, "runs/ns/r1/in")
        assert out["meta"] == {"k": 1}
        assert is_storage_ref(out["body"]["text"]) or is_storage_ref(out["body"])

    def test_dehydrate_inputs_per_key(self, mgr):
        out = mgr.dehydrate_inputs({"q": "small", "ctx": BIG}, "runs/ns/r1/inputs")
        assert out["q"] == "small"
        assert is_storage_ref(out["ctx"])

    def test_already_offloaded_passthrough(self, mgr):
        marker = {"storageRef": {"key": "runs/ns/r1/x", "provider": "memory", "size": 1}}
        assert mgr.dehydrate(marker, "runs/ns/r1/in") == marker

    def test_depth_cap(self):
        mgr = StorageManager(MemoryStore(), max_inline_size=1, max_depth=3)
        deep = {"a": {"b": {"c": {"d": {"e": BIG}}}}}
        with pytest.raises(StorageError):
            mgr.dehydrate(deep, "runs/ns/r1/in")


class TestHydrate:
    def test_roundtrip(self, mgr):
        original = {"doc": BIG, "n": 7, "nested": {"big": BIG + BIG, "small": True}}
        out = mgr.dehydrate(original, "runs/ns/r1/in")
        assert mgr.hydrate(out, allowed_prefixes=["runs/ns/r1"]) == original

    def test_scope_enforcement(self, mgr):
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/in")
        with pytest.raises(StorageError):
            mgr.hydrate(out, allowed_prefixes=["runs/ns/OTHER"])

    def test_spoofed_ref_traversal_rejected(self, mgr):
        evil = {"storageRef": {"key": "../secrets/creds", "provider": "memory", "size": 1}}
        with pytest.raises(StorageError):
            mgr.hydrate(evil, allowed_prefixes=["runs/ns/r1"])

    def test_digest_mismatch_detected(self, mgr):
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/in")
        ref = StorageRef.from_marker(out["doc"])
        mgr.store.put(ref.key, json.dumps("tampered").encode())
        with pytest.raises(StorageError):
            mgr.hydrate(out, allowed_prefixes=["runs/ns/r1"])

    def test_missing_blob(self, mgr):
        marker = {
            "storageRef": {"key": "runs/ns/r1/gone", "provider": "memory", "size": 9}
        }
        with pytest.raises(BlobNotFound):
            mgr.hydrate(marker, allowed_prefixes=["runs/ns/r1"])


class TestRetention:
    def test_delete_prefix(self, mgr):
        mgr.dehydrate({"a": BIG}, "runs/ns/r1/in")
        mgr.dehydrate({"a": BIG}, "runs/ns/r2/in")
        n = mgr.delete_prefix(StorageManager.run_prefix("ns", "r1"))
        assert n == 1
        assert mgr.store.list("runs/ns/r1") == []
        assert len(mgr.store.list("runs/ns/r2")) == 1

    def test_delete_prefix_respects_segment_boundary(self, mgr):
        mgr.dehydrate({"a": BIG}, "runs/ns/r1/in")
        mgr.dehydrate({"a": BIG}, "runs/ns/r10/in")
        mgr.delete_prefix(StorageManager.run_prefix("ns", "r1"))
        # r10's blobs must survive r1's cleanup
        assert len(mgr.store.list("runs/ns/r10")) == 1

    def test_hydrate_tolerates_deep_inline_nesting(self, mgr):
        v = {"leaf": 1}
        for _ in range(40):
            v = {"level": v}
        out = mgr.dehydrate(v, "runs/ns/r1/in")
        assert mgr.hydrate(out, allowed_prefixes=["runs/ns/r1"]) == v


class TestFileStore:
    def test_roundtrip_and_traversal_guard(self, tmp_path):
        fs = FileStore(str(tmp_path))
        fs.put("runs/a/b", b"data")
        assert fs.get("runs/a/b") == b"data"
        assert fs.list("runs/") == ["runs/a/b"]
        # key traversal cannot escape the base dir
        fs.put("../../evil", b"x")
        assert (tmp_path.parent.parent / "evil").exists() is False

    def test_missing(self, tmp_path):
        fs = FileStore(str(tmp_path))
        with pytest.raises(BlobNotFound):
            fs.get("nope")


class TestS3Store:
    def test_requires_client(self):
        s = S3Store(bucket="b")
        with pytest.raises(StorageError, match="no client"):
            s.put("k", b"v")

    def test_fake_client_roundtrip_with_retries(self):
        NoSuchKey = type("NoSuchKey", (Exception,), {})

        class FlakyClient:
            def __init__(self):
                self.objects = {}
                self.failures = 2

            def put_object(self, Bucket, Key, Body):
                if self.failures > 0:
                    self.failures -= 1
                    raise ConnectionError("flake")
                self.objects[Key] = Body

            def get_object(self, Bucket, Key):
                if Key not in self.objects:
                    raise NoSuchKey("missing")
                return {"Body": self.objects[Key]}

            def delete_object(self, Bucket, Key):
                self.objects.pop(Key, None)

            def list_objects(self, Bucket, Prefix):
                return {
                    "Contents": [
                        {"Key": k} for k in self.objects if k.startswith(Prefix)
                    ]
                }

        s = S3Store(bucket="b", client=FlakyClient(), prefix="base", sleep=lambda _: None)
        s.put("runs/r1/x", b"payload")
        assert s.get("runs/r1/x") == b"payload"
        assert s.list("runs/") == ["runs/r1/x"]
        assert not s.exists("runs/r1/gone")
