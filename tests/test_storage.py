"""Storage offload: dehydrate/hydrate round-trips, spoofing guards, retention."""

import json

import pytest

from bobrapet_tpu.storage import (
    BlobNotFound,
    FileStore,
    MemoryStore,
    S3Store,
    StorageError,
    StorageManager,
    StorageRef,
)
from bobrapet_tpu.templating import is_storage_ref


@pytest.fixture
def mgr():
    # limit must exceed one storageRef marker (~150B of JSON) or slimmed
    # containers re-offload wholesale
    return StorageManager(MemoryStore(), max_inline_size=256)


BIG = "x" * 500
SMALL = {"a": 1}


class TestDehydrate:
    def test_small_values_stay_inline(self, mgr):
        v = {"a": 1, "b": "short"}
        assert mgr.dehydrate(v, "runs/ns/r1/in") == v

    def test_large_scalar_offloads(self, mgr):
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/in")
        assert is_storage_ref(out["doc"])
        ref = StorageRef.from_marker(out["doc"])
        assert ref.key.startswith("runs/ns/r1/in/doc")
        assert ref.size >= 500

    def test_nested_selective_offload(self, mgr):
        v = {"meta": {"k": 1}, "body": {"text": BIG, "tag": "t"}}
        out = mgr.dehydrate(v, "runs/ns/r1/in")
        assert out["meta"] == {"k": 1}
        assert is_storage_ref(out["body"]["text"]) or is_storage_ref(out["body"])

    def test_dehydrate_inputs_per_key(self, mgr):
        out = mgr.dehydrate_inputs({"q": "small", "ctx": BIG}, "runs/ns/r1/inputs")
        assert out["q"] == "small"
        assert is_storage_ref(out["ctx"])

    def test_already_offloaded_passthrough(self, mgr):
        marker = {"storageRef": {"key": "runs/ns/r1/x", "provider": "memory", "size": 1}}
        assert mgr.dehydrate(marker, "runs/ns/r1/in") == marker

    def test_depth_cap(self):
        mgr = StorageManager(MemoryStore(), max_inline_size=1, max_depth=3)
        deep = {"a": {"b": {"c": {"d": {"e": BIG}}}}}
        with pytest.raises(StorageError):
            mgr.dehydrate(deep, "runs/ns/r1/in")


class TestHydrate:
    def test_roundtrip(self, mgr):
        original = {"doc": BIG, "n": 7, "nested": {"big": BIG + BIG, "small": True}}
        out = mgr.dehydrate(original, "runs/ns/r1/in")
        assert mgr.hydrate(out, allowed_prefixes=["runs/ns/r1"]) == original

    def test_scope_enforcement(self, mgr):
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/in")
        with pytest.raises(StorageError):
            mgr.hydrate(out, allowed_prefixes=["runs/ns/OTHER"])

    def test_spoofed_ref_traversal_rejected(self, mgr):
        evil = {"storageRef": {"key": "../secrets/creds", "provider": "memory", "size": 1}}
        with pytest.raises(StorageError):
            mgr.hydrate(evil, allowed_prefixes=["runs/ns/r1"])

    def test_digest_mismatch_detected(self, mgr):
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/in")
        ref = StorageRef.from_marker(out["doc"])
        mgr.store.put(ref.key, json.dumps("tampered").encode())
        with pytest.raises(StorageError):
            mgr.hydrate(out, allowed_prefixes=["runs/ns/r1"])

    def test_missing_blob(self, mgr):
        marker = {
            "storageRef": {"key": "runs/ns/r1/gone", "provider": "memory", "size": 9}
        }
        with pytest.raises(BlobNotFound):
            mgr.hydrate(marker, allowed_prefixes=["runs/ns/r1"])


class TestRetention:
    def test_delete_prefix(self, mgr):
        mgr.dehydrate({"a": BIG}, "runs/ns/r1/in")
        mgr.dehydrate({"a": BIG}, "runs/ns/r2/in")
        n = mgr.delete_prefix(StorageManager.run_prefix("ns", "r1"))
        assert n == 1
        assert mgr.store.list("runs/ns/r1") == []
        assert len(mgr.store.list("runs/ns/r2")) == 1

    def test_delete_prefix_respects_segment_boundary(self, mgr):
        mgr.dehydrate({"a": BIG}, "runs/ns/r1/in")
        mgr.dehydrate({"a": BIG}, "runs/ns/r10/in")
        mgr.delete_prefix(StorageManager.run_prefix("ns", "r1"))
        # r10's blobs must survive r1's cleanup
        assert len(mgr.store.list("runs/ns/r10")) == 1

    def test_hydrate_tolerates_deep_inline_nesting(self, mgr):
        v = {"leaf": 1}
        for _ in range(40):
            v = {"level": v}
        out = mgr.dehydrate(v, "runs/ns/r1/in")
        assert mgr.hydrate(out, allowed_prefixes=["runs/ns/r1"]) == v


class TestDedupAndCache:
    """PR 2 fast path: content-addressed dedup on dehydrate, bounded
    hydrate LRU, parallel ref fetch — all behavior-invisible."""

    def test_identical_payloads_write_once(self, mgr):
        from bobrapet_tpu.observability.metrics import metrics

        before = metrics.storage_dedup_hits.value()
        a = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/a/output")
        b = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/b/output")
        ra, rb = StorageRef.from_marker(a["doc"]), StorageRef.from_marker(b["doc"])
        assert ra.sha256 == rb.sha256
        # second write deduplicated onto the first blob
        assert rb.key == ra.key
        assert len(mgr.store.list("runs/ns/r1/")) == 1
        assert metrics.storage_dedup_hits.value() == before + 1
        # both markers hydrate to the same content
        assert mgr.hydrate(b, ["runs/ns/r1"]) == {"doc": BIG}

    def test_dedup_scoped_per_run(self, mgr):
        """Dedup must NOT cross run prefixes: run r1's retention delete
        would otherwise orphan r2's refs (and r2's hydrate scope check
        would reject a key under r1)."""
        a = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/a/output")
        b = mgr.dehydrate({"doc": BIG}, "runs/ns/r2/steps/a/output")
        assert StorageRef.from_marker(a["doc"]).key.startswith("runs/ns/r1/")
        assert StorageRef.from_marker(b["doc"]).key.startswith("runs/ns/r2/")
        mgr.delete_prefix(StorageManager.run_prefix("ns", "r1"))
        # r2 still hydrates after r1's cleanup
        assert mgr.hydrate(b, ["runs/ns/r2"]) == {"doc": BIG}

    def test_dedup_entry_invalidated_when_key_overwritten(self, mgr):
        """Regression: the deterministic key scheme reuses blob paths
        across retries, so overwriting a key with different content
        must invalidate the stale (scope, sha) -> key mapping — a dedup
        hit on it would mint markers whose sha no longer matches the
        stored bytes (hydrate would raise digest-mismatch on valid
        data)."""
        prefix = "runs/ns/r1/steps/a/output"
        a1 = mgr.dehydrate({"doc": BIG}, prefix)          # key .../output-1 = A
        mgr.dehydrate({"doc": "w" * 500}, prefix)         # SAME key, content B
        # content A again at another step: the stale A->output-1 entry
        # must not be trusted (output-1 now holds B)
        a2 = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/b/output")
        assert mgr.hydrate(a2, ["runs/ns/r1"]) == {"doc": BIG}
        ra2 = StorageRef.from_marker(a2["doc"])
        assert ra2.key != StorageRef.from_marker(a1["doc"]).key

    def test_dedup_rewrites_when_prior_blob_deleted(self, mgr):
        a = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/a/output")
        mgr.store.delete(StorageRef.from_marker(a["doc"]).key)
        b = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/b/output")
        # the dedup map entry is stale; a fresh blob must be written
        assert mgr.hydrate(b, ["runs/ns/r1"]) == {"doc": BIG}

    def test_hydrate_cache_hits(self, mgr):
        from bobrapet_tpu.observability.metrics import metrics

        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/c/output")
        h1 = mgr.hydrate(out, ["runs/ns/r1"])
        hits_before = metrics.storage_hydrate_cache.value("hit")
        h2 = mgr.hydrate(out, ["runs/ns/r1"])
        assert h1 == h2 == {"doc": BIG}
        assert metrics.storage_hydrate_cache.value("hit") > hits_before

    def test_cache_does_not_mask_scope_enforcement(self, mgr):
        """A cached payload must still be scope-checked per call: a hit
        with the wrong allowed prefix raises exactly like a miss."""
        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/d/output")
        mgr.hydrate(out, ["runs/ns/r1"])  # warm the cache
        with pytest.raises(StorageError):
            mgr.hydrate(out, ["runs/ns/OTHER"])

    def test_parallel_hydrate_identical_to_serial(self, mgr):
        """The concurrent prefetch + substitution walk must be
        byte-identical to the serial reference walk, nested offloads
        included."""
        value = {
            f"k{i}": {"payload": BIG + str(i), "meta": {"n": i}}
            for i in range(12)
        }
        value["nested"] = {"deep": {"inner": BIG * 2, "more": [BIG, BIG]}}
        out = mgr.dehydrate(value, "runs/ns/r1/steps/p/output")
        parallel = mgr.hydrate(out, ["runs/ns/r1"])
        serial = mgr._hydrate(out, ["runs/ns/r1"], 0)
        assert parallel == serial == value
        assert json.dumps(parallel, sort_keys=True) == json.dumps(
            serial, sort_keys=True
        )

    def test_single_pass_splice_encoding_byte_identical(self, mgr):
        """A slimmed container offloads with bytes spliced from its
        children's encodings — they must equal a from-scratch canonical
        encode (hydrate verifies them against the recorded sha256)."""
        import hashlib

        value = {f"part{i}": BIG + str(i) for i in range(6)}
        out = mgr.dehydrate(value, "runs/ns/r1/steps/sp/output")
        assert is_storage_ref(out)  # slim (6 markers) still > limit
        ref = StorageRef.from_marker(out)
        blob = mgr.store.get(ref.key)
        stored = json.loads(blob.decode())
        canonical = json.dumps(
            stored, sort_keys=True, separators=(",", ":"), default=str
        ).encode()
        assert blob == canonical
        assert hashlib.sha256(blob).hexdigest() == ref.sha256
        assert mgr.hydrate(out, ["runs/ns/r1"]) == value

    def test_prefetch_warms_cache(self, mgr):
        from bobrapet_tpu.observability.metrics import metrics

        out = mgr.dehydrate({"doc": BIG}, "runs/ns/r1/steps/w/output")
        mgr.prefetch(out, ["runs/ns/r1"])
        import time as _time

        deadline = _time.monotonic() + 5
        hits_before = metrics.storage_hydrate_cache.value("hit")
        while _time.monotonic() < deadline:
            mgr.hydrate(out, ["runs/ns/r1"])
            if metrics.storage_hydrate_cache.value("hit") > hits_before:
                break
            _time.sleep(0.02)
        assert metrics.storage_hydrate_cache.value("hit") > hits_before


class TestFileStore:
    def test_roundtrip_and_traversal_guard(self, tmp_path):
        fs = FileStore(str(tmp_path))
        fs.put("runs/a/b", b"data")
        assert fs.get("runs/a/b") == b"data"
        assert fs.list("runs/") == ["runs/a/b"]
        # key traversal cannot escape the base dir
        fs.put("../../evil", b"x")
        assert (tmp_path.parent.parent / "evil").exists() is False

    def test_missing(self, tmp_path):
        fs = FileStore(str(tmp_path))
        with pytest.raises(BlobNotFound):
            fs.get("nope")


class TestS3Store:
    def test_requires_client(self):
        s = S3Store(bucket="b")
        with pytest.raises(StorageError, match="no client"):
            s.put("k", b"v")

    def test_fake_client_roundtrip_with_retries(self):
        NoSuchKey = type("NoSuchKey", (Exception,), {})

        class FlakyClient:
            def __init__(self):
                self.objects = {}
                self.failures = 2

            def put_object(self, Bucket, Key, Body):
                if self.failures > 0:
                    self.failures -= 1
                    raise ConnectionError("flake")
                self.objects[Key] = Body

            def get_object(self, Bucket, Key):
                if Key not in self.objects:
                    raise NoSuchKey("missing")
                return {"Body": self.objects[Key]}

            def delete_object(self, Bucket, Key):
                self.objects.pop(Key, None)

            def list_objects(self, Bucket, Prefix):
                return {
                    "Contents": [
                        {"Key": k} for k in self.objects if k.startswith(Prefix)
                    ]
                }

        s = S3Store(bucket="b", client=FlakyClient(), prefix="base", sleep=lambda _: None)
        s.put("runs/r1/x", b"payload")
        assert s.get("runs/r1/x") == b"payload"
        assert s.list("runs/") == ["runs/r1/x"]
        assert not s.exists("runs/r1/gone")
