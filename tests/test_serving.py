"""Serving engine: paged KV cache + continuous batching.

Correctness bar: the engine's greedy outputs must MATCH the model's
contiguous-cache `greedy_generate` token-for-token — paging, masked
scratch writes, bucketed prefill, admission order, and preemption are
all invisible to the math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bobrapet_tpu.models import llama, quant
from bobrapet_tpu.serving import BlockAllocator, PagedConfig, ServingEngine
from bobrapet_tpu.serving.paged_cache import SCRATCH_BLOCK


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _reference_tokens(params, cfg, prompt, n):
    toks = jax.jit(lambda p, t: llama.greedy_generate(
        p, t, cfg=cfg, max_new_tokens=n,
        cache_capacity=len(prompt) + n))(
        params, jnp.asarray(prompt, jnp.int32)[None, :])
    return np.asarray(toks)[0].tolist()


class TestBlockAllocator:
    def test_scratch_never_allocated(self):
        a = BlockAllocator(8)
        got = a.alloc(7)
        assert got is not None and SCRATCH_BLOCK not in got
        assert a.alloc(1) is None  # pool exhausted (block 0 reserved)
        a.free(got[:3])
        assert a.free_blocks == 3
        with pytest.raises(ValueError):
            a.free([SCRATCH_BLOCK])

    def test_all_or_nothing(self):
        a = BlockAllocator(4)
        assert a.alloc(5) is None
        assert a.free_blocks == 3  # nothing was consumed


class TestEngineCorrectness:
    def test_single_request_matches_greedy_generate(self, model):
        cfg, params = model
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, cfg.vocab_size, 12).tolist()
        want = _reference_tokens(params, cfg, prompt, 6)

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=4, block_size=8, num_blocks=64, max_blocks_per_seq=8))
        rid = eng.submit(prompt, max_new_tokens=6)
        done = eng.run()
        assert [r.rid for r in done] == [rid]
        assert done[0].output == want

    def test_mixed_lengths_all_match_reference(self, model):
        """Requests with different prompt lengths decode fused in one
        batch yet each matches its solo reference run exactly."""
        cfg, params = model
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (5, 17, 9, 26)]
        wants = [_reference_tokens(params, cfg, p, 8) for p in prompts]

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=4, block_size=8, num_blocks=64, max_blocks_per_seq=8))
        rids = [eng.submit(p, max_new_tokens=8) for p in prompts]
        done = {r.rid: r for r in eng.run()}
        for rid, want in zip(rids, wants):
            assert done[rid].output == want

    def test_more_requests_than_slots_stream_through(self, model):
        """Continuous batching: 6 requests over 2 slots; later requests
        are admitted as earlier ones retire, all correct."""
        cfg, params = model
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, cfg.vocab_size, 6 + i).tolist()
                   for i in range(6)]
        wants = [_reference_tokens(params, cfg, p, 5) for p in prompts]

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=4))
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        done = {r.rid: r for r in eng.run()}
        assert len(done) == 6
        for rid, want in zip(rids, wants):
            assert done[rid].output == want
        # every block returned to the pool
        assert eng.allocator.free_blocks == 31

    def test_eos_retires_early_and_frees_blocks(self, model):
        cfg, params = model
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, cfg.vocab_size, 8).tolist()
        want = _reference_tokens(params, cfg, prompt, 8)
        eos = want[2]  # force an early stop at the 3rd token

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        eng.submit(prompt, max_new_tokens=8, eos_token=eos)
        done = eng.run()
        assert done[0].output == want[:3]
        assert eng.allocator.free_blocks == 15

    def test_preemption_recomputes_and_still_matches(self, model):
        """A pool too small for all admitted sequences preempts the
        youngest (recompute strategy); outputs still match reference."""
        cfg, params = model
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, cfg.vocab_size, 14).tolist()
                   for _ in range(3)]
        n_new = 12
        wants = [_reference_tokens(params, cfg, p, n_new) for p in prompts]

        # 3 slots but a pool that cannot hold 3 full sequences:
        # 14+12=26 tokens -> 4 blocks each at block_size=8; 9 usable
        # blocks force at least one preemption
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=3, block_size=8, num_blocks=10, max_blocks_per_seq=4))
        rids = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        done = {r.rid: r for r in eng.run()}
        assert sum(r.preemptions for r in done.values()) >= 1
        for rid, want in zip(rids, wants):
            assert done[rid].output == want
        assert eng.allocator.free_blocks == 9

    def test_int8_params_serve(self, model):
        """The engine consumes an int8 weight-only tree natively (the
        8B single-chip serving shape)."""
        cfg, params = model
        qp = quant.quantize_params(params)
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, cfg.vocab_size, 10).tolist()
        want = _reference_tokens(qp, cfg, prompt, 5)

        eng = ServingEngine(qp, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        eng.submit(prompt, max_new_tokens=5)
        assert eng.run()[0].output == want

    def test_temperature_sampling_is_deterministic_per_engine(self, model):
        cfg, params = model
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, cfg.vocab_size, 8).tolist()

        def run_once():
            eng = ServingEngine(params, cfg, PagedConfig(
                max_slots=2, block_size=8, num_blocks=16,
                max_blocks_per_seq=4))
            eng.submit(prompt, max_new_tokens=6, temperature=0.8)
            return eng.run()[0].output

        a, b = run_once(), run_once()
        assert a == b  # per-request keys + per-step fold = replayable
        assert len(a) == 6


class TestPrefixCaching:
    """Content-addressed prompt-prefix sharing: matched full blocks go
    straight into the new request's block table (zero copy), prefill
    computes only the uncached suffix, and outputs stay exact."""

    def test_shared_prefix_is_reused_and_exact(self, model):
        cfg, params = model
        rng = np.random.default_rng(10)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()  # 2 full blocks
        a = system + rng.integers(0, cfg.vocab_size, 5).tolist()
        b = system + rng.integers(0, cfg.vocab_size, 9).tolist()
        want_a = _reference_tokens(params, cfg, a, 6)
        want_b = _reference_tokens(params, cfg, b, 6)

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        ra = eng.submit(a, max_new_tokens=6)
        done = {r.rid: r for r in eng.run()}
        assert done[ra].output == want_a
        hits_before = eng.blocks.hit_tokens

        rb = eng.submit(b, max_new_tokens=6)
        done = {r.rid: r for r in eng.run()}
        assert done[rb].output == want_b
        # the 16-token system prompt was served from cache
        assert eng.blocks.hit_tokens - hits_before == 16

    def test_concurrent_sharers_protect_blocks(self, model):
        """Two live requests share prefix blocks; the first finishing
        must not free them out from under the second."""
        cfg, params = model
        rng = np.random.default_rng(11)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()
        a = system + rng.integers(0, cfg.vocab_size, 3).tolist()
        b = system + rng.integers(0, cfg.vocab_size, 4).tolist()
        want_a = _reference_tokens(params, cfg, a, 3)
        want_b = _reference_tokens(params, cfg, b, 12)

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        ra = eng.submit(a, max_new_tokens=3)   # finishes early
        rb = eng.submit(b, max_new_tokens=12)  # keeps using the prefix
        done = {r.rid: r for r in eng.run()}
        assert done[ra].output == want_a
        assert done[rb].output == want_b
        assert eng.allocator.free_blocks == 31  # everything reclaimed

    def test_freed_prefix_survives_until_reallocated(self, model):
        """Lazy invalidation: after ALL users finish, the registered
        blocks sit in the free list and are still matchable — until the
        allocator hands them out for new content."""
        cfg, params = model
        rng = np.random.default_rng(12)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        eng.submit(system + [1, 2, 3], max_new_tokens=2)
        eng.run()
        assert eng.allocator.free_blocks == 31

        hits_before = eng.blocks.hit_tokens
        eng.submit(system + [4, 5], max_new_tokens=2)
        eng.run()
        assert eng.blocks.hit_tokens - hits_before == 16
        assert eng.allocator.free_blocks == 31

    def test_disabled_prefix_caching_never_matches(self, model):
        cfg, params = model
        rng = np.random.default_rng(13)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6,
            prefix_caching=False))
        want = _reference_tokens(params, cfg, system + [7], 4)
        eng.submit(system + [7], max_new_tokens=4)
        eng.run()
        eng.submit(system + [7], max_new_tokens=4)
        done = eng.run()
        assert done[-1].output == want
        assert eng.blocks.hit_tokens == 0

    def test_mismatched_prefix_does_not_match(self, model):
        cfg, params = model
        rng = np.random.default_rng(14)
        a = rng.integers(0, cfg.vocab_size, 20).tolist()
        b = list(a)
        b[3] = (b[3] + 1) % cfg.vocab_size  # diverges inside block 0
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        want_b = _reference_tokens(params, cfg, b, 4)
        eng.submit(a, max_new_tokens=4)
        eng.run()
        hits = eng.blocks.hit_tokens
        rb = eng.submit(b, max_new_tokens=4)
        done = {r.rid: r for r in eng.run()}
        assert done[rb].output == want_b
        assert eng.blocks.hit_tokens == hits  # no false sharing


class TestStreamServer:
    """The serving engine behind the real data plane: prompts in on a
    hub stream, completions out downstream, batched continuously."""

    def test_prompts_over_hub_served_exactly(self, model):
        import threading

        from bobrapet_tpu.dataplane import (
            StreamConsumer,
            StreamHub,
            StreamProducer,
        )
        from bobrapet_tpu.serving import StreamServer

        cfg, params = model
        rng = np.random.default_rng(20)
        prompts = [rng.integers(0, cfg.vocab_size, 6 + 3 * i).tolist()
                   for i in range(5)]
        wants = {i: _reference_tokens(params, cfg, p, 5)
                 for i, p in enumerate(prompts)}

        hub = StreamHub()
        hub.start()
        try:
            eng = ServingEngine(params, cfg, PagedConfig(
                max_slots=2, block_size=8, num_blocks=32,
                max_blocks_per_seq=6))
            server = StreamServer(
                eng,
                consumer=StreamConsumer(hub.endpoint, "ns/r/gen",
                                        decode_json=True),
                producer=StreamProducer(hub.endpoint, "ns/r/out"),
            )
            results = []
            out_done = threading.Event()

            def drain():
                c = StreamConsumer(hub.endpoint, "ns/r/out",
                                   decode_json=True)
                for msg in c:
                    results.append(msg)
                out_done.set()

            threading.Thread(target=drain, daemon=True).start()
            serve_thread = threading.Thread(target=server.run, daemon=True)
            serve_thread.start()

            p = StreamProducer(hub.endpoint, "ns/r/gen")
            for i, prompt in enumerate(prompts):
                p.send({"id": i, "prompt": prompt, "maxNewTokens": 5})
            p.close()
            serve_thread.join(120)
            assert not serve_thread.is_alive()
            assert out_done.wait(30)
        finally:
            hub.stop()

        assert server.served == 5
        got = {m["id"]: m["tokens"] for m in results}
        assert got == wants

    def test_malformed_request_answers_in_band(self, model):
        import threading

        from bobrapet_tpu.dataplane import (
            StreamConsumer,
            StreamHub,
            StreamProducer,
        )
        from bobrapet_tpu.serving import StreamServer

        cfg, params = model
        hub = StreamHub()
        hub.start()
        try:
            eng = ServingEngine(params, cfg, PagedConfig(
                max_slots=2, block_size=8, num_blocks=16,
                max_blocks_per_seq=4))
            server = StreamServer(
                eng,
                consumer=StreamConsumer(hub.endpoint, "ns/r/gen2",
                                        decode_json=True),
                producer=StreamProducer(hub.endpoint, "ns/r/out2"),
            )
            results = []
            done = threading.Event()

            def drain():
                c = StreamConsumer(hub.endpoint, "ns/r/out2",
                                   decode_json=True)
                for msg in c:
                    results.append(msg)
                done.set()

            threading.Thread(target=drain, daemon=True).start()
            st = threading.Thread(target=server.run, daemon=True)
            st.start()
            p = StreamProducer(hub.endpoint, "ns/r/gen2")
            p.send({"id": "bad"})  # no prompt
            p.send({"id": "ok", "prompt": [1, 2, 3], "maxNewTokens": 2})
            p.close()
            st.join(60)
            assert done.wait(30)
        finally:
            hub.stop()
        by_id = {m["id"]: m for m in results}
        assert "error" in by_id["bad"]
        assert len(by_id["ok"]["tokens"]) == 2


class TestReviewRegressions:
    def test_budget_one_yields_exactly_one_token(self, model):
        cfg, params = model
        rng = np.random.default_rng(30)
        prompt = rng.integers(0, cfg.vocab_size, 9).tolist()
        want = _reference_tokens(params, cfg, prompt, 1)
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        eng.submit(prompt, max_new_tokens=1)
        done = eng.run()
        assert done[0].output == want  # not one token past the budget

    def test_eos_on_prefill_token_stops_immediately(self, model):
        cfg, params = model
        rng = np.random.default_rng(31)
        prompt = rng.integers(0, cfg.vocab_size, 9).tolist()
        first = _reference_tokens(params, cfg, prompt, 1)[0]
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        eng.submit(prompt, max_new_tokens=8, eos_token=first)
        done = eng.run()
        assert done[0].output == [first]

    def test_zero_budget_rejected(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2, 3], max_new_tokens=0)

    def test_long_shared_prefix_respects_block_table_width(self, model):
        """Shared blocks + bucketed suffix must fit max_blocks_per_seq
        (the suffix bucket is clamped by the remaining capacity)."""
        cfg, params = model
        rng = np.random.default_rng(32)
        base = rng.integers(0, cfg.vocab_size, 47).tolist()  # 5 full blocks
        want_a = _reference_tokens(params, cfg, base, 1)
        b = base[:40] + rng.integers(0, cfg.vocab_size, 7).tolist()
        want_b = _reference_tokens(params, cfg, b, 1)

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        ra = eng.submit(base, max_new_tokens=1)
        done = {r.rid: r for r in eng.run()}
        assert done[ra].output == want_a
        rb = eng.submit(b, max_new_tokens=1)  # shares 5 blocks (40 tokens)
        done = {r.rid: r for r in eng.run()}
        assert done[rb].output == want_b
        assert eng.allocator.free_blocks == 31


class TestServingIntegration:
    def test_tensor_parallel_int8_tree_serves(self, model):
        """The v5e-4 8B serving shape in miniature: an int8 weight-only
        tree sharded over the model axis drives the engine; outputs
        match the unsharded engine exactly."""
        from jax.sharding import Mesh

        from bobrapet_tpu.parallel.sharding import shard_params

        cfg, params = model
        qp = quant.quantize_params(params)
        rng = np.random.default_rng(40)
        prompts = [rng.integers(0, cfg.vocab_size, 7 + i).tolist()
                   for i in range(3)]
        pcfg = PagedConfig(max_slots=2, block_size=8, num_blocks=32,
                           max_blocks_per_seq=6)

        ref_eng = ServingEngine(qp, cfg, pcfg)
        ref_ids = [ref_eng.submit(p, max_new_tokens=4) for p in prompts]
        ref = {r.rid: r.output for r in ref_eng.run()}

        mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("fsdp", "model"))
        sharded = shard_params(qp, mesh)
        eng = ServingEngine(sharded, cfg, pcfg)
        ids = [eng.submit(p, max_new_tokens=4) for p in prompts]
        got = {r.rid: r.output for r in eng.run()}
        for a, b in zip(ref_ids, ids):
            assert got[b] == ref[a]

    def test_restore_checkpoint_then_serve(self, model):
        """train -> sharded checkpoint -> serve: params restored through
        the SDK checkpoint path drive the engine bit-identically."""
        from bobrapet_tpu.sdk.checkpoint import restore_checkpoint, save_checkpoint
        from bobrapet_tpu.storage.store import MemoryStore

        cfg, params = model
        store = MemoryStore()
        save_checkpoint(store, "serve-ckpt", {"params": params}, step=7)
        restored, step = restore_checkpoint(store, "serve-ckpt",
                                            {"params": params})
        assert step == 7

        rng = np.random.default_rng(41)
        prompt = rng.integers(0, cfg.vocab_size, 10).tolist()
        want = _reference_tokens(params, cfg, prompt, 5)
        eng = ServingEngine(restored["params"], cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        eng.submit(prompt, max_new_tokens=5)
        assert eng.run()[0].output == want


class TestServingMetrics:
    def test_engine_emits_serving_series(self, model):
        from bobrapet_tpu.observability.metrics import metrics

        cfg, params = model
        rng = np.random.default_rng(50)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=32, max_blocks_per_seq=6))
        eng.submit(system + [1], max_new_tokens=3)
        eng.submit(system + [2], max_new_tokens=3)
        eng.run()
        assert metrics.serving_requests.value("completed") == 2
        assert metrics.serving_tokens.value() == 6
        assert metrics.serving_prefix_tokens.value("hit") == 16
        assert metrics.serving_active_slots.value() == 0

    def test_null_and_nonobject_messages_dont_kill_the_server(self, model):
        import threading

        from bobrapet_tpu.dataplane import (
            StreamConsumer,
            StreamHub,
            StreamProducer,
        )
        from bobrapet_tpu.serving import StreamServer

        cfg, params = model
        hub = StreamHub()
        hub.start()
        try:
            eng = ServingEngine(params, cfg, PagedConfig(
                max_slots=2, block_size=8, num_blocks=16,
                max_blocks_per_seq=4))
            server = StreamServer(
                eng,
                consumer=StreamConsumer(hub.endpoint, "ns/r/gen3",
                                        decode_json=True),
                producer=StreamProducer(hub.endpoint, "ns/r/out3"),
            )
            results = []
            done = threading.Event()

            def drain():
                c = StreamConsumer(hub.endpoint, "ns/r/out3",
                                   decode_json=True)
                for msg in c:
                    results.append(msg)
                done.set()

            threading.Thread(target=drain, daemon=True).start()
            st = threading.Thread(target=server.run, daemon=True)
            st.start()
            p = StreamProducer(hub.endpoint, "ns/r/gen3")
            p.send(None)          # JSON null must NOT read as input EOS
            p.send([1, 2, 3])     # non-object answers in-band
            p.send({"id": "ok", "prompt": [5, 6], "maxNewTokens": 2})
            p.close()
            st.join(60)
            assert not st.is_alive()
            assert done.wait(30)  # downstream ALWAYS sees a clean EOS
        finally:
            hub.stop()
        errors = [m for m in results if "error" in m]
        assert len(errors) == 2
        ok = [m for m in results if m.get("id") == "ok"]
        assert len(ok) == 1 and len(ok[0]["tokens"]) == 2


class TestChunkedPrefill:
    """Long prompts ingest in block-aligned chunks interleaved with
    decode ticks; outputs stay exact and short requests keep decoding
    while a long one prefills."""

    def test_chunked_matches_unchunked(self, model):
        cfg, params = model
        rng = np.random.default_rng(60)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (40, 7, 33)]
        wants = [_reference_tokens(params, cfg, p, 5) for p in prompts]

        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=3, block_size=8, num_blocks=64, max_blocks_per_seq=8,
            prefill_chunk=16))
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        done = {r.rid: r for r in eng.run()}
        for rid, want in zip(rids, wants):
            assert done[rid].output == want
        assert eng.allocator.free_blocks == 63

    def test_decode_proceeds_while_long_prompt_ingests(self, model):
        """A short request admitted alongside a long one produces
        tokens BEFORE the long one finishes ingesting."""
        cfg, params = model
        rng = np.random.default_rng(61)
        long_p = rng.integers(0, cfg.vocab_size, 48).tolist()
        short_p = rng.integers(0, cfg.vocab_size, 5).tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=64, max_blocks_per_seq=8,
            prefill_chunk=8))
        r_long = eng.submit(long_p, max_new_tokens=3)
        r_short = eng.submit(short_p, max_new_tokens=3)
        eng.step()  # admit both; long starts ingesting, short prefills
        long_slot = next(s for s in eng.slots
                         if s and s.request.rid == r_long)
        short_req = next(s.request for s in eng.slots
                         if s and s.request.rid == r_short)
        assert long_slot.ingest_pos is not None  # still chunking
        eng.step()
        assert len(short_req.output) >= 2  # short decodes meanwhile
        done = {r.rid: r for r in eng.run()}
        assert done[r_long].output == _reference_tokens(
            params, cfg, long_p, 3)
        assert done[r_short].output == _reference_tokens(
            params, cfg, short_p, 3)

    def test_chunked_with_prefix_cache(self, model):
        """Chunked ingest composes with prefix sharing: the matched
        prefix is skipped, remaining chunks ingest, result exact."""
        cfg, params = model
        rng = np.random.default_rng(62)
        system = rng.integers(0, cfg.vocab_size, 24).tolist()  # 3 blocks
        a = system + rng.integers(0, cfg.vocab_size, 30).tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=64, max_blocks_per_seq=8,
            prefill_chunk=16))
        eng.submit(system + [1], max_new_tokens=2)
        eng.run()
        hits = eng.blocks.hit_tokens
        rid = eng.submit(a, max_new_tokens=4)
        done = {r.rid: r for r in eng.run()}
        assert done[rid].output == _reference_tokens(params, cfg, a, 4)
        assert eng.blocks.hit_tokens - hits == 24


class TestServingFuzz:
    """Property check: under random slot/pool/chunk configs and request
    mixes (lengths, budgets, eos, memory pressure forcing preemption),
    every greedy output must equal the reference decode exactly."""

    # bounded shape sets keep the jit cache warm across seeds
    LENS = (5, 12, 23, 40)
    NEWS = (1, 3, 6)

    @pytest.mark.parametrize("seed", range(8))
    def test_random_mix_is_reference_exact(self, model, seed):
        cfg, params = model
        rng = np.random.default_rng(100 + seed)
        pcfg = PagedConfig(
            max_slots=int(rng.integers(1, 4)),
            block_size=int(rng.choice([4, 8])),
            num_blocks=int(rng.integers(12, 40)),
            max_blocks_per_seq=16,
            prefix_caching=bool(rng.integers(0, 2)),
            prefill_chunk=int(rng.choice([8, 16])) if rng.integers(0, 2) else None,
        )
        eng = ServingEngine(params, cfg, pcfg)
        reqs = []
        for _ in range(int(rng.integers(2, 6))):
            prompt = rng.integers(0, cfg.vocab_size,
                                  int(rng.choice(self.LENS))).tolist()
            n = int(rng.choice(self.NEWS))
            if prompt and n and len(prompt) + n <= pcfg.capacity:
                want = _reference_tokens(params, cfg, prompt, n)
                eos = want[0] if rng.integers(0, 4) == 0 else None
                rid = eng.submit(prompt, max_new_tokens=n, eos_token=eos)
                reqs.append((rid, want[:1] if eos is not None else want))
        done = {r.rid: r for r in eng.run(max_steps=5000)}
        assert len(done) == len(reqs), (len(done), len(reqs))
        for rid, want in reqs:
            assert done[rid].output == want, (seed, rid)
        assert eng.allocator.free_blocks == pcfg.num_blocks - 1


class TestMultiLoRA:
    """Many adapters over one resident base model: per-slot LoRA in the
    fused step; every output matches a merged-weights reference."""

    @pytest.fixture(scope="class")
    def lora_setup(self, model):
        from bobrapet_tpu.models import lora as lora_mod

        cfg, params = model
        lcfg = lora_mod.LoRAConfig(rank=4, alpha=8.0, sites=("wq", "wv"))
        adapters = [lora_mod.zero_lora(cfg, lcfg)]
        for seed in (1, 2):
            a = lora_mod.init_lora(jax.random.PRNGKey(seed), cfg, lcfg)
            # give B real content (init is zero so deltas start null)
            a = jax.tree_util.tree_map(
                lambda leaf: leaf + 0.05 * jax.random.normal(
                    jax.random.PRNGKey(seed + 10), leaf.shape, leaf.dtype),
                a,
            )
            adapters.append(a)
        stacked = lora_mod.stack_adapters(adapters)
        merged = [params] + [
            lora_mod.merge_lora(params, a, lcfg.scale) for a in adapters[1:]
        ]
        return cfg, params, lcfg, stacked, merged

    def _engine(self, cfg, params, stacked, lcfg, **pc):
        base = dict(max_slots=3, block_size=8, num_blocks=64,
                    max_blocks_per_seq=6)
        base.update(pc)
        return ServingEngine(params, cfg, PagedConfig(**base),
                             loras=stacked, lora_scale=lcfg.scale)

    def test_each_adapter_matches_merged_reference(self, lora_setup):
        cfg, params, lcfg, stacked, merged = lora_setup
        rng = np.random.default_rng(70)
        prompt = rng.integers(0, cfg.vocab_size, 11).tolist()
        eng = self._engine(cfg, params, stacked, lcfg)
        rids = [eng.submit(prompt, max_new_tokens=5, adapter=i)
                for i in range(3)]
        done = {r.rid: r for r in eng.run()}
        for i, rid in enumerate(rids):
            want = _reference_tokens(merged[i], cfg, prompt, 5)
            assert done[rid].output == want, f"adapter {i}"
        # sanity: the adapters actually change the output
        assert done[rids[1]].output != done[rids[0]].output

    def test_mixed_adapters_decode_fused(self, lora_setup):
        """Different adapters in the SAME decode batch stay independent
        (per-slot gather, no cross-contamination)."""
        cfg, params, lcfg, stacked, merged = lora_setup
        rng = np.random.default_rng(71)
        prompts = [rng.integers(0, cfg.vocab_size, 7 + 3 * i).tolist()
                   for i in range(3)]
        eng = self._engine(cfg, params, stacked, lcfg)
        rids = [eng.submit(p, max_new_tokens=4, adapter=i)
                for i, p in enumerate(prompts)]
        done = {r.rid: r for r in eng.run()}
        for i, (rid, p) in enumerate(zip(rids, prompts)):
            assert done[rid].output == _reference_tokens(
                merged[i], cfg, p, 4), f"adapter {i}"

    def test_prefix_cache_is_adapter_scoped(self, lora_setup):
        """Identical prompts under different adapters must NOT share KV
        blocks (k/v deltas make the cache adapter-specific); the same
        adapter still shares."""
        cfg, params, lcfg, stacked, merged = lora_setup
        rng = np.random.default_rng(72)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()
        eng = self._engine(cfg, params, stacked, lcfg, num_blocks=64)
        # all three admitted together so the first request's registered
        # prefix blocks are still LIVE when the same-adapter request
        # arrives (freed blocks may be lazily recycled by the
        # intervening allocation — by design)
        r1 = eng.submit(system + [1], max_new_tokens=2, adapter=1)
        r2 = eng.submit(system + [2], max_new_tokens=2, adapter=2)
        r3 = eng.submit(system + [3], max_new_tokens=2, adapter=1)
        done = {r.rid: r for r in eng.run()}
        # only the same-adapter pair shared the 16-token system prompt
        assert eng.blocks.hit_tokens == 16
        assert done[r1].output == _reference_tokens(
            merged[1], cfg, system + [1], 2)
        assert done[r2].output == _reference_tokens(
            merged[2], cfg, system + [2], 2)
        assert done[r3].output == _reference_tokens(
            merged[1], cfg, system + [3], 2)

    def test_out_of_range_adapter_rejected(self, lora_setup):
        cfg, params, lcfg, stacked, _ = lora_setup
        eng = self._engine(cfg, params, stacked, lcfg)
        with pytest.raises(ValueError, match="adapter"):
            eng.submit([1, 2, 3], max_new_tokens=2, adapter=7)


class TestLongContextServing:
    def test_near_max_seq_prompt_chunks_through(self, model):
        """A prompt near the model's max_seq_len ingests in chunks and
        decodes exactly (long-context serving path end to end)."""
        cfg, params = model  # llama_tiny: max_seq_len 256
        rng = np.random.default_rng(80)
        prompt = rng.integers(0, cfg.vocab_size, 230).tolist()
        want = _reference_tokens(params, cfg, prompt, 6)
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=16, num_blocks=64,
            max_blocks_per_seq=16, prefill_chunk=64))
        rid = eng.submit(prompt, max_new_tokens=6)
        # a short request rides along while the giant ingests
        short = rng.integers(0, cfg.vocab_size, 5).tolist()
        rs = eng.submit(short, max_new_tokens=6)
        done = {r.rid: r for r in eng.run()}
        assert done[rid].output == want
        assert done[rs].output == _reference_tokens(params, cfg, short, 6)


class TestServingEngram:
    """The packaged serving entrypoint: an EngramContext wired to the
    hub serves prompts end to end (the deployable inference story)."""

    def test_serve_entrypoint_over_hub(self, model):
        import json as _json
        import threading

        from bobrapet_tpu.dataplane import (
            StreamConsumer,
            StreamHub,
            StreamProducer,
        )
        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import serve

        cfg, params = model
        hub = StreamHub()
        hub.start()
        try:
            targets = [{"grpc": {"host": "127.0.0.1", "port": hub.port,
                                 "stepName": "sink"}}]
            env = {
                contract.ENV_NAMESPACE: "default",
                contract.ENV_STORY_RUN: "r1",
                contract.ENV_STEP: "generate",
                contract.ENV_DOWNSTREAM_TARGETS: _json.dumps(targets),
                contract.ENV_CONFIG: _json.dumps({
                    "model": "tiny", "initSeed": 0,
                    "hub": hub.endpoint,
                    "paging": {"maxSlots": 2, "blockSize": 8,
                               "numBlocks": 32, "maxBlocksPerSeq": 6},
                }),
            }
            ctx = EngramContext(env)
            results = []
            done = threading.Event()

            def drain():
                c = StreamConsumer(hub.endpoint, "default/r1/sink",
                                   decode_json=True)
                for m in c:
                    results.append(m)
                done.set()

            threading.Thread(target=drain, daemon=True).start()
            out = {}
            server_thread = threading.Thread(
                target=lambda: out.update(serve(ctx)), daemon=True)
            server_thread.start()

            rng = np.random.default_rng(90)
            prompts = {i: rng.integers(0, cfg.vocab_size, 6 + i).tolist()
                       for i in range(3)}
            p = StreamProducer(hub.endpoint, "default/r1/generate")
            for i, prompt in prompts.items():
                p.send({"id": i, "prompt": prompt, "maxNewTokens": 4})
            p.close()
            server_thread.join(120)
            assert not server_thread.is_alive()
            assert done.wait(30)
        finally:
            hub.stop()
        assert out == {"served": 3}
        got = {m["id"]: m["tokens"] for m in results}
        # the engram's seed-0 init equals the test fixture's params
        for i, prompt in prompts.items():
            assert got[i] == _reference_tokens(params, cfg, prompt, 4)

    def test_build_engine_restores_checkpoint(self, model):
        """checkpoint config -> params restored from the run's blob
        store drive the engine (train -> checkpoint -> serve via the
        engram path)."""
        import json as _json

        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.checkpoint import save_checkpoint
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import build_engine
        from bobrapet_tpu.storage import MemoryStore, StorageManager

        cfg, params = model
        storage = StorageManager(MemoryStore())
        save_checkpoint(storage.store, "runs/d/r1/model", {"params": params},
                        step=3)
        env = {contract.ENV_CONFIG: _json.dumps({
            "model": "tiny", "checkpoint": "runs/d/r1/model",
            "paging": {"maxSlots": 2, "blockSize": 8, "numBlocks": 16,
                       "maxBlocksPerSeq": 4},
        })}
        ctx = EngramContext(env, storage=storage)
        eng = build_engine(ctx)
        rng = np.random.default_rng(91)
        prompt = rng.integers(0, cfg.vocab_size, 8).tolist()
        eng.submit(prompt, max_new_tokens=3)
        assert eng.run()[0].output == _reference_tokens(params, cfg, prompt, 3)

    def test_checkpoint_without_storage_raises(self, model):
        import json as _json

        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import build_engine

        env = {contract.ENV_CONFIG: _json.dumps({
            "model": "tiny", "checkpoint": "runs/prod/llama"})}
        with pytest.raises(ValueError, match="storage"):
            build_engine(EngramContext(env))  # never serve random weights

    def test_lora_config_builds_adapter_stack(self, model):
        import json as _json

        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import build_engine

        env = {contract.ENV_CONFIG: _json.dumps({
            "model": "tiny", "initSeed": 0,
            "lora": {"rank": 4, "alpha": 8, "sites": ["wq", "wv"],
                     "initSeeds": [1, 2]},
            "paging": {"maxSlots": 2, "blockSize": 8, "numBlocks": 16,
                       "maxBlocksPerSeq": 4},
        })}
        eng = build_engine(EngramContext(env))
        assert eng.n_adapters == 3  # zero/base + two configured
        # adapter requests admit (freshly-initialized adapters have
        # B = 0, so outputs equal base — the plumbing is what's tested)
        eng.submit([1, 2, 3], max_new_tokens=2, adapter=2)
        assert len(eng.run()) == 1

    def test_lora_checkpoint_contract(self, model):
        """Adapters trained elsewhere restore through the engram's
        {'lora': tree} checkpoint contract and actually change output."""
        import json as _json

        from bobrapet_tpu.models import lora as lora_mod
        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.checkpoint import save_checkpoint
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import build_engine
        from bobrapet_tpu.storage import MemoryStore, StorageManager

        cfg, params = model
        lcfg = lora_mod.LoRAConfig(rank=4, alpha=8.0, sites=("wq", "wv"))
        trained = jax.tree_util.tree_map(
            lambda leaf: leaf + 0.05 * jax.random.normal(
                jax.random.PRNGKey(5), leaf.shape, leaf.dtype),
            lora_mod.init_lora(jax.random.PRNGKey(4), cfg, lcfg),
        )
        storage = StorageManager(MemoryStore())
        save_checkpoint(storage.store, "runs/d/r2/adapter-a",
                        {"lora": trained}, step=1)
        env = {contract.ENV_CONFIG: _json.dumps({
            "model": "tiny", "initSeed": 0,
            "lora": {"rank": 4, "alpha": 8, "sites": ["wq", "wv"],
                     "checkpoints": ["runs/d/r2/adapter-a"]},
            "paging": {"maxSlots": 2, "blockSize": 8, "numBlocks": 32,
                       "maxBlocksPerSeq": 6},
        })}
        eng = build_engine(EngramContext(env, storage=storage))
        assert eng.n_adapters == 2
        rng = np.random.default_rng(92)
        prompt = rng.integers(0, cfg.vocab_size, 9).tolist()
        r0 = eng.submit(prompt, max_new_tokens=4, adapter=0)
        r1 = eng.submit(prompt, max_new_tokens=4, adapter=1)
        done = {r.rid: r for r in eng.run()}
        merged = lora_mod.merge_lora(params, trained, lcfg.scale)
        assert done[r1].output == _reference_tokens(merged, cfg, prompt, 4)
        assert done[r0].output == _reference_tokens(params, cfg, prompt, 4)
        assert done[r0].output != done[r1].output


class TestMoEServing:
    """The engine serves the sparse-MoE family: routed MLP inside the
    fused step, token-exact vs moe.greedy_generate (no-drop capacity)."""

    @pytest.fixture(scope="class")
    def moe_model(self):
        import dataclasses

        from bobrapet_tpu.models import moe

        cfg = dataclasses.replace(
            moe.moe_tiny(), capacity_factor=float(moe.moe_tiny().n_experts)
        )
        params = moe.init_params(jax.random.PRNGKey(0), cfg)
        return cfg, params

    def _ref(self, params, cfg, prompt, n):
        from bobrapet_tpu.models import moe

        toks = jax.jit(lambda p, t: moe.greedy_generate(
            p, t, cfg=cfg, max_new_tokens=n,
            cache_capacity=len(prompt) + n))(
            params, jnp.asarray(prompt, jnp.int32)[None, :])
        return np.asarray(toks)[0].tolist()

    def test_moe_requests_match_reference(self, moe_model):
        cfg, params = moe_model
        rng = np.random.default_rng(100)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (7, 15, 22)]
        wants = [self._ref(params, cfg, p, 5) for p in prompts]
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=3, block_size=8, num_blocks=64, max_blocks_per_seq=8))
        assert eng.is_moe
        rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
        done = {r.rid: r for r in eng.run()}
        for rid, want in zip(rids, wants):
            assert done[rid].output == want
        assert eng.allocator.free_blocks == 63

    def test_moe_with_prefix_cache_and_chunks(self, moe_model):
        cfg, params = moe_model
        rng = np.random.default_rng(101)
        system = rng.integers(0, cfg.vocab_size, 16).tolist()
        a = system + rng.integers(0, cfg.vocab_size, 20).tolist()
        b = system + rng.integers(0, cfg.vocab_size, 3).tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=64, max_blocks_per_seq=8,
            prefill_chunk=16))
        ra = eng.submit(a, max_new_tokens=4)
        rb = eng.submit(b, max_new_tokens=4)
        done = {r.rid: r for r in eng.run()}
        assert done[ra].output == self._ref(params, cfg, a, 4)
        assert done[rb].output == self._ref(params, cfg, b, 4)

    def test_lora_rejected_for_moe(self, moe_model):
        from bobrapet_tpu.models import lora as lora_mod
        from bobrapet_tpu.models.llama import llama_tiny

        cfg, params = moe_model
        lcfg = lora_mod.LoRAConfig(rank=2)
        stacked = lora_mod.stack_adapters(
            [lora_mod.zero_lora(llama_tiny(), lcfg)] * 2)
        with pytest.raises(ValueError, match="dense-family"):
            ServingEngine(params, cfg, PagedConfig(), loras=stacked)

    def test_droppy_capacity_rejected(self, moe_model):
        from bobrapet_tpu.models import moe

        params = moe.init_params(jax.random.PRNGKey(0), moe.moe_tiny())
        with pytest.raises(ValueError, match="no-drop"):
            ServingEngine(params, moe.moe_tiny(), PagedConfig())

    def test_int8_moe_rejected(self, moe_model):
        from bobrapet_tpu.models import quant

        cfg, params = moe_model
        with pytest.raises(ValueError, match="dense-family"):
            ServingEngine(quant.quantize_params(params), cfg, PagedConfig())

    def test_engram_builds_moe_engine(self, moe_model):
        import json as _json

        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import build_engine

        env = {contract.ENV_CONFIG: _json.dumps({
            "model": "moe-tiny", "initSeed": 0,
            "paging": {"maxSlots": 2, "blockSize": 8, "numBlocks": 32,
                       "maxBlocksPerSeq": 6},
        })}
        eng = build_engine(EngramContext(env))
        assert eng.is_moe
        eng.submit([1, 2, 3, 4], max_new_tokens=3)
        out = eng.run()
        assert len(out) == 1 and len(out[0].output) == 3

    def test_engram_rejects_moe_quant_before_restore(self, moe_model):
        import json as _json

        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import build_engine

        env = {contract.ENV_CONFIG: _json.dumps({
            "model": "moe-tiny", "quant": "int8",
            "checkpoint": "runs/never/restored"})}
        # storage is absent, but the family check must fire FIRST —
        # before any restore attempt (cheap-checks-first)
        with pytest.raises(ValueError, match="dense-family"):
            build_engine(EngramContext(env))


@pytest.fixture(scope="module")
def spec_models():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    dcfg = llama.LlamaConfig(
        vocab_size=cfg.vocab_size, dim=64, n_layers=1, n_heads=2,
        n_kv_heads=2, ffn_hidden=128, max_seq_len=cfg.max_seq_len,
        dtype=jnp.float32,
    )
    dparams = llama.init_params(jax.random.PRNGKey(7), dcfg)
    return cfg, params, dcfg, dparams


class TestSpeculativeServing:
    """Speculative decoding inside the paged engine (spec_decode.py):
    greedy outputs must be token-identical to the non-speculative
    engine, with accept-rate > 0 doing the amortization work."""

    def _run_pair(self, spec_models, prompts, n=12, pcfg=None, **spec_kw):
        cfg, params, dcfg, dparams = spec_models
        pc = pcfg or PagedConfig(max_slots=4, block_size=8, num_blocks=64,
                                 max_blocks_per_seq=8)
        plain = ServingEngine(params, cfg, pc)
        spec = ServingEngine(params, cfg, pc, draft_params=dparams,
                             draft_cfg=dcfg, **spec_kw)
        for pr in prompts:
            plain.submit(list(pr), n)
            spec.submit(list(pr), n)
        plain_out = {r.rid: r.output for r in plain.run()}
        spec_out = {r.rid: r.output for r in spec.run()}
        return plain_out, spec_out, spec

    def test_token_identical_to_plain_engine(self, spec_models):
        prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 12, 13, 14, 15, 16, 17]]
        plain_out, spec_out, eng = self._run_pair(spec_models, prompts)
        assert spec_out == plain_out
        assert eng.spec_drafted > 0  # speculation actually ran

    def test_matches_contiguous_reference(self, spec_models):
        cfg, params, dcfg, dparams = spec_models
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]
        ref = _reference_tokens(params, cfg, prompt, 10)
        eng = ServingEngine(params, cfg,
                            PagedConfig(max_slots=2, block_size=8,
                                        num_blocks=32, max_blocks_per_seq=8),
                            draft_params=dparams, draft_cfg=dcfg)
        eng.submit(prompt, 10)
        (r,) = eng.run()
        assert r.output == ref

    def test_perfect_draft_accepts_everything(self, spec_models):
        """Draft == target: every proposal matches, so each spec tick
        commits spec_k+1 tokens and accept rate is 100%."""
        cfg, params, _, _ = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        # guard off: this test pins the ACCOUNTING property that every
        # spec tick fully accepts; the guard's alternating warmup (and
        # its budget-truncated final tick) is covered in TestSpecGuard
        eng = ServingEngine(params, cfg, pc, draft_params=params,
                            draft_cfg=cfg, spec_k=3, spec_guard=False)
        eng.submit([1, 2, 3, 4], 13)
        (r,) = eng.run()
        ref = ServingEngine(params, cfg, pc)
        ref.submit([1, 2, 3, 4], 13)
        (rr,) = ref.run()
        assert r.output == rr.output
        assert eng.spec_accepted == eng.spec_drafted > 0

    def test_eos_mid_accept_window_truncates(self, spec_models):
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        ref = ServingEngine(params, cfg, pc)
        ref.submit([5, 6, 7], 16)
        (rr,) = ref.run()
        eos = rr.output[4]  # a token the sequence actually produces
        plain = ServingEngine(params, cfg, pc)
        plain.submit([5, 6, 7], 16, eos_token=eos)
        (p,) = plain.run()
        spec = ServingEngine(params, cfg, pc, draft_params=dparams,
                             draft_cfg=dcfg, spec_k=4)
        spec.submit([5, 6, 7], 16, eos_token=eos)
        (s,) = spec.run()
        assert s.output == p.output

    def test_mixed_batch_with_temperature_slots(self, spec_models):
        """Greedy slots speculate; temp>0 slots advance one sampled
        token per tick — greedy outputs stay exact."""
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=4, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        plain = ServingEngine(params, cfg, pc)
        spec = ServingEngine(params, cfg, pc, draft_params=dparams,
                             draft_cfg=dcfg)
        for eng in (plain, spec):
            eng.submit([1, 2, 3], 10)                      # greedy
            eng.submit([4, 5, 6], 6, temperature=0.8)      # sampled
            eng.submit([7, 8, 9, 10], 10)                  # greedy
        plain_out = {r.rid: r.output for r in plain.run()}
        spec_out = {r.rid: r.output for r in spec.run()}
        assert spec_out[0] == plain_out[0]
        assert spec_out[2] == plain_out[2]
        assert len(spec_out[1]) == 6  # sampled slot completed its budget

    def test_chunked_prefill_and_prefix_cache_with_draft(self, spec_models):
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8, prefill_chunk=16)
        long_prompt = list(range(1, 41))
        plain = ServingEngine(params, cfg, pc)
        spec = ServingEngine(params, cfg, pc, draft_params=dparams,
                             draft_cfg=dcfg)
        for eng in (plain, spec):
            eng.submit(list(long_prompt), 6)
            eng.submit(list(long_prompt[:24]) + [49, 50], 6)  # prefix reuse
        plain_out = {r.rid: r.output for r in plain.run()}
        spec_out = {r.rid: r.output for r in spec.run()}
        assert spec_out == plain_out

    def test_block_exhaustion_degrades_to_plain_not_wrong(self, spec_models):
        """Too few free blocks for speculative coverage: slots fall
        back to single-token commits, outputs stay exact."""
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=8,
                         max_blocks_per_seq=4)
        plain = ServingEngine(params, cfg, pc)
        spec = ServingEngine(params, cfg, pc, draft_params=dparams,
                             draft_cfg=dcfg, spec_k=4)
        for eng in (plain, spec):
            eng.submit([1, 2, 3, 4, 5, 6], 8)
            eng.submit([9, 8, 7, 6, 5], 8)
        plain_out = {r.rid: r.output for r in plain.run()}
        spec_out = {r.rid: r.output for r in spec.run()}
        assert spec_out == plain_out

    def test_spec_commit_jump_over_block_boundary_stays_exact(self, spec_models):
        """Multi-token commits can SKIP the block-boundary trigger;
        the next (degraded, last-budget-token) tick must still have a
        real block for its write — not the scratch block."""
        cfg, params, _, _ = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        prompt = list(range(1, 12))  # len 11 -> seq_len 12 after prefill
        plain = ServingEngine(params, cfg, pc)
        plain.submit(list(prompt), 7)
        (p,) = plain.run()
        # perfect draft: tick 1 commits 5 (12 -> 17, skipping the
        # 16-boundary), tick 2 has remaining budget 1 -> spec degraded
        spec = ServingEngine(params, cfg, pc, draft_params=params,
                             draft_cfg=cfg, spec_k=4)
        spec.submit(list(prompt), 7)
        (s,) = spec.run()
        assert s.output == p.output

    def test_all_sampled_batch_takes_plain_step(self, spec_models):
        """A spec engine with nothing to speculate must not pay the
        k+1-wide step (falls back to the plain decode graph)."""
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        eng = ServingEngine(params, cfg, pc, draft_params=dparams,
                            draft_cfg=dcfg)
        eng.submit([1, 2, 3], 5, temperature=0.7)
        (r,) = eng.run()
        assert len(r.output) == 5
        assert eng.spec_drafted == 0  # never speculated

    def test_vocab_mismatch_rejected(self, spec_models):
        cfg, params, _, _ = spec_models
        dcfg = llama.llama_tiny(vocab_size=cfg.vocab_size // 2)
        dparams = llama.init_params(jax.random.PRNGKey(3), dcfg)
        with pytest.raises(ValueError, match="share the tokenizer"):
            ServingEngine(params, cfg, draft_params=dparams,
                          draft_cfg=dcfg)

    def test_moe_target_rejected(self):
        import dataclasses

        from bobrapet_tpu.models import moe

        mcfg = moe.moe_tiny()
        mcfg = dataclasses.replace(mcfg, capacity_factor=float(mcfg.n_experts))
        mparams = moe.init_params(jax.random.PRNGKey(0), mcfg)
        dcfg = llama.llama_tiny(vocab_size=mcfg.vocab_size)
        dparams = llama.init_params(jax.random.PRNGKey(1), dcfg)
        with pytest.raises(ValueError, match="dense-target only"):
            ServingEngine(mparams, mcfg, draft_params=dparams,
                          draft_cfg=dcfg)

    def test_short_draft_context_rejected(self, spec_models):
        cfg, params, _, _ = spec_models
        short = llama.llama_tiny(max_seq_len=cfg.max_seq_len // 2)
        dparams = llama.init_params(jax.random.PRNGKey(2), short)
        with pytest.raises(ValueError, match="draft must cover"):
            ServingEngine(params, cfg, draft_params=dparams,
                          draft_cfg=short)


class TestSpecGuard:
    """The payoff guard (VERDICT r4 #4): speculation must never
    silently run slower than plain decode. The first ticks A/B-measure
    both modes; the decision is one-shot, recorded, and exported as a
    gauge."""

    def _engine(self, spec_models, **kw):
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                        max_blocks_per_seq=8)
        return ServingEngine(params, cfg, pc, draft_params=dparams,
                             draft_cfg=dcfg, **kw)

    def test_decision_logic_unprofitable(self, spec_models):
        """Pinned decision math: spec slower than plain -> disabled,
        with the measured rates in the decision record."""
        eng = self._engine(spec_models)
        eng._guard_samples["spec"] = [-1.0, 50.0, 52.0, 48.0]
        eng._guard_samples["plain"] = [-1.0, 100.0, 104.0, 98.0]
        eng._guard_decide()
        assert eng.spec_active is False
        d = eng.spec_guard_decision
        assert d["active"] is False
        assert d["spec_tok_s"] == 50.0
        assert d["plain_tok_s"] == 100.0

    def test_decision_logic_profitable(self, spec_models):
        eng = self._engine(spec_models)
        eng._guard_samples["spec"] = [-1.0, 300.0, 290.0, 310.0]
        eng._guard_samples["plain"] = [-1.0, 100.0, 110.0, 90.0]
        eng._guard_decide()
        assert eng.spec_active is True
        assert eng.spec_guard_decision["active"] is True

    def test_guard_reaches_decision_and_tokens_stay_exact(self, spec_models):
        """End to end on CPU with guard windows small enough to decide
        mid-run: output must equal the plain engine's regardless of
        which modes the warmup ticks ran in."""
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                        max_blocks_per_seq=8)
        prompt = [5, 4, 3, 2, 1, 6, 7]
        plain = ServingEngine(params, cfg, pc)
        plain.submit(list(prompt), 40)
        want = plain.run()[0].output

        eng = ServingEngine(params, cfg, pc, draft_params=dparams,
                            draft_cfg=dcfg, spec_guard_ticks=2)
        eng.submit(list(prompt), 40)
        got = eng.run()[0].output
        assert got == want
        assert eng.spec_guard_decision is not None
        d = eng.spec_guard_decision
        assert set(d) >= {"active", "spec_tok_s", "plain_tok_s",
                          "accept_rate", "spec_k"}
        assert d["spec_tok_s"] > 0 and d["plain_tok_s"] > 0

    def test_disabled_guard_pins_speculation_on(self, spec_models):
        eng = self._engine(spec_models, spec_guard=False)
        eng.submit([1, 2, 3], 20)
        eng.run()
        assert eng.spec_guard_decision is None
        assert eng.spec_active is True
        assert eng.spec_drafted > 0

    def test_disabled_speculation_stops_draft_work(self, spec_models):
        """After the guard turns speculation off, no further ticks
        draft, and newly admitted requests skip the draft prefill."""
        cfg, params, dcfg, dparams = spec_models
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                        max_blocks_per_seq=8)
        eng = ServingEngine(params, cfg, pc, draft_params=dparams,
                            draft_cfg=dcfg, spec_guard_ticks=2)
        eng.submit([1, 2, 3, 4], 40)
        eng.run()
        assert eng.spec_guard_decision is not None
        if eng.spec_guard_decision["active"]:
            pytest.skip("guard kept speculation on this host; the "
                        "disable path is covered by the pinned "
                        "decision tests")
        drafted_before = eng.spec_drafted
        plain = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=64,
            max_blocks_per_seq=8))
        prompt = [9, 8, 7, 6]
        plain.submit(list(prompt), 10)
        want = plain.run()[-1].output
        rid = eng.submit(list(prompt), 10)
        got = next(r for r in eng.run() if r.rid == rid).output
        assert got == want
        assert eng.spec_drafted == drafted_before

    def test_engram_config_guard_knob(self, spec_models):
        from bobrapet_tpu.serving.engram import _build_draft

        cfg, params, _, _ = spec_models
        _p, _c, k, guard = _build_draft(
            None, {"draft": {"selfInt8": True, "specK": 3}}, cfg, params)
        assert (k, guard) == (3, True)
        _p, _c, k, guard = _build_draft(
            None, {"draft": {"selfInt8": True, "guard": False}}, cfg,
            params)
        assert guard is False
        assert _build_draft(None, {}, cfg, params) == (None, None, 4, True)


class TestPipelinedDecode:
    """Steady-state decode pipelining: tick N+1 dispatched before tick
    N's read-back. Must be invisible to the math."""

    def test_pipelined_equals_synchronous(self, model):
        cfg, params = model
        pc = PagedConfig(max_slots=4, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        sync = ServingEngine(params, cfg, pc, pipeline_decode=False)
        pipe = ServingEngine(params, cfg, pc, pipeline_decode=True)
        prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 12, 13], [4, 4, 4, 4]]
        for eng in (sync, pipe):
            for i, pr in enumerate(prompts):
                # mixed greedy + sampled, mixed budgets
                eng.submit(list(pr), 8 + i,
                           temperature=0.0 if i % 2 == 0 else 0.6)
        sync_out = {r.rid: r.output for r in sync.run()}
        pipe_out = {r.rid: r.output for r in pipe.run()}
        assert pipe_out == sync_out

    def test_eos_lag_does_not_leak_tokens(self, model):
        cfg, params = model
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=32,
                         max_blocks_per_seq=8)
        probe = ServingEngine(params, cfg, pc, pipeline_decode=False)
        probe.submit([5, 6, 7], 16)
        (p,) = probe.run()
        eos = p.output[5]
        for pipeline in (False, True):
            eng = ServingEngine(params, cfg, pc, pipeline_decode=pipeline)
            eng.submit([5, 6, 7], 16, eos_token=eos)
            (r,) = eng.run()
            assert r.output == p.output[:p.output.index(eos) + 1], pipeline

    def test_late_admission_flushes_cleanly(self, model):
        """A request submitted mid-run forces settled ticks; outputs
        stay exact for both the old and new occupants."""
        cfg, params = model
        pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                         max_blocks_per_seq=8)
        ref = ServingEngine(params, cfg, pc, pipeline_decode=False)
        pipe = ServingEngine(params, cfg, pc, pipeline_decode=True)
        outs = {}
        for name, eng in (("ref", ref), ("pipe", pipe)):
            eng.submit([1, 2, 3], 10)
            for _ in range(4):
                eng.step()
            eng.submit([7, 8, 9, 10], 10)  # arrives mid-decode
            eng.run()
            outs[name] = {r.rid: r.output for r in eng.finished}
        assert outs["pipe"] == outs["ref"]

    def test_block_tables_cached_between_structural_changes(self, model):
        cfg, params = model
        eng = ServingEngine(params, cfg,
                            PagedConfig(max_slots=2, block_size=8,
                                        num_blocks=32, max_blocks_per_seq=8))
        eng.submit(list(range(1, 6)), 6)
        eng.step()
        t1 = eng._block_tables()
        t2 = eng._block_tables()
        assert t1 is t2  # same device array, no rebuild


class TestServingEngramDraft:
    """config.draft turns on engine-integrated speculation from the
    Story step's with-config."""

    def _ctx(self, config):
        import json as _json

        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext

        return EngramContext({contract.ENV_CONFIG: _json.dumps(config)})

    def test_self_int8_draft_is_exact_and_speculates(self, model):
        from bobrapet_tpu.serving.engram import build_engine

        cfg, params = model
        paging = {"maxSlots": 2, "blockSize": 8, "numBlocks": 32,
                  "maxBlocksPerSeq": 8}
        plain = build_engine(self._ctx({
            "model": "tiny", "initSeed": 0, "paging": paging}))
        spec = build_engine(self._ctx({
            "model": "tiny", "initSeed": 0, "paging": paging,
            "draft": {"selfInt8": True, "specK": 3}}))
        assert spec.draft_params is not None and spec.spec_k == 3
        prompt = [5, 4, 3, 2, 1]
        for eng in (plain, spec):
            eng.submit(list(prompt), 8)
        assert spec.run()[0].output == plain.run()[0].output
        assert spec.spec_drafted > 0

    def test_named_draft_model(self, model):
        from bobrapet_tpu.serving.engram import build_engine

        eng = build_engine(self._ctx({
            "model": "tiny", "initSeed": 0,
            "paging": {"maxSlots": 2, "blockSize": 8, "numBlocks": 16,
                       "maxBlocksPerSeq": 4},
            "draft": {"model": "tiny", "initSeed": 7, "specK": 2}}))
        assert eng.draft_params is not None and eng.spec_k == 2

    def test_draft_misconfig_fails_fast(self, model):
        from bobrapet_tpu.serving.engram import build_engine

        with pytest.raises(ValueError, match="selfInt8 takes no model"):
            build_engine(self._ctx({
                "model": "tiny",
                "draft": {"selfInt8": True, "model": "tiny"}}))
        with pytest.raises(ValueError, match="unknown"):
            build_engine(self._ctx({
                "model": "tiny", "draft": {"model": "nope"}}))
        with pytest.raises(ValueError, match="dense"):
            build_engine(self._ctx({
                "model": "tiny", "draft": {"model": "moe-tiny"}}))
        # int8 target + selfInt8 draft: the "draft" would BE the target
        with pytest.raises(ValueError, match="target itself"):
            build_engine(self._ctx({
                "model": "tiny", "quant": "int8",
                "draft": {"selfInt8": True}}))
        # MoE target + draft refused BEFORE any checkpoint restore
        with pytest.raises(ValueError, match="dense-family only"):
            build_engine(self._ctx({
                "model": "moe-tiny", "draft": {"selfInt8": True}}))
        # stray initSeed under selfInt8 is a misconfig, not ignored
        with pytest.raises(ValueError, match="initSeed"):
            build_engine(self._ctx({
                "model": "tiny",
                "draft": {"selfInt8": True, "initSeed": 7}}))


class TestRound4Capstone:
    """Everything composes: a serving engram with a speculative draft
    consumes a PARTITIONED + RECORDED + WATERMARKED + fromCheckpoint
    stream through the SDK surface, over a recording hub, and its
    greedy completions are token-identical to the plain engine."""

    def test_full_streaming_stack_into_spec_serving(self, model):
        import json as _json
        import threading

        from bobrapet_tpu.dataplane import StreamHub, StreamRecorder
        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext
        from bobrapet_tpu.serving.engram import serve
        from bobrapet_tpu.storage.store import MemoryStore

        cfg, params = model
        store = MemoryStore()
        rec = StreamRecorder(store)
        hub = StreamHub(recorder=rec)
        hub.start()
        try:
            settings = {
                "flowControl": {"mode": "credits",
                                "initialCredits": {"messages": 16},
                                "ackEvery": {"messages": 1}},
                "delivery": {"semantics": "atLeastOnce",
                             "replay": {"mode": "fromCheckpoint",
                                        "retentionSeconds": 3600,
                                        "checkpointInterval": "5s"}},
                # roundRobin: ONE settings object governs both the
                # prompt edge and the completion edge (the broadcast
                # sends unkeyed completions)
                "partitioning": {"mode": "roundRobin", "partitions": 2},
                "recording": {"mode": "full", "redactFields": ["secret"]},
                "observability": {"watermark": {
                    "enabled": True, "timestampSource": "ts"}},
            }
            serve_config = {
                "model": "tiny", "initSeed": 0,
                "paging": {"maxSlots": 2, "blockSize": 8, "numBlocks": 64,
                           "maxBlocksPerSeq": 8},
                "draft": {"selfInt8": True, "specK": 3},
                "hub": hub.endpoint,
            }
            serve_env = {
                contract.ENV_NAMESPACE: "default",
                contract.ENV_STORY_RUN: "r9",
                contract.ENV_STEP: "generate",
                contract.ENV_CONFIG: _json.dumps(serve_config),
                contract.ENV_BINDING_INFO: _json.dumps(
                    {"settings": settings}),
                contract.ENV_DOWNSTREAM_TARGETS: _json.dumps([{
                    "grpc": {"host": "127.0.0.1", "port": hub.port,
                             "stepName": "sink"}}]),
            }
            result = {}

            def run_server():
                result["served"] = serve(EngramContext(serve_env))["served"]

            server_thread = threading.Thread(target=run_server, daemon=True)
            server_thread.start()

            # downstream consumer of completions
            sink_env = {
                contract.ENV_NAMESPACE: "default",
                contract.ENV_STORY_RUN: "r9",
                contract.ENV_STEP: "sink",
            }
            completions = []
            sink_done = threading.Event()

            def drain():
                for m in EngramContext(sink_env).open_input_stream(
                        hub.endpoint, settings=settings):
                    completions.append(m)
                sink_done.set()

            threading.Thread(target=drain, daemon=True).start()

            # the upstream step streams keyed, watermarked prompts
            prod_env = {
                contract.ENV_NAMESPACE: "default",
                contract.ENV_STORY_RUN: "r9",
                contract.ENV_STEP: "client",
                contract.ENV_DOWNSTREAM_TARGETS: _json.dumps([{
                    "grpc": {"host": "127.0.0.1", "port": hub.port,
                             "stepName": "generate"}}]),
            }
            (out,) = EngramContext(prod_env).open_output_streams(
                settings=settings)
            prompts = {f"u{i}": [1 + i, 2, 3, 4] for i in range(4)}
            for i, (user, prompt) in enumerate(prompts.items()):
                out.send({"id": user, "user": user, "prompt": prompt,
                          "maxNewTokens": 6, "secret": "hunter2",
                          "ts": 1000 * (i + 1)},
                         key=user)
            out.close()

            server_thread.join(timeout=60)
            assert not server_thread.is_alive(), "server never drained"
            assert sink_done.wait(20)
            assert result["served"] == 4

            # token-identical to the plain engine, per prompt
            pc = PagedConfig(max_slots=2, block_size=8, num_blocks=64,
                             max_blocks_per_seq=8)
            ref_eng = ServingEngine(params, cfg, pc)
            rids = {ref_eng.submit(list(p), 6): u
                    for u, p in prompts.items()}
            ref = {rids[r.rid]: r.output for r in ref_eng.run()}
            got = {c["id"]: c["tokens"] for c in completions}
            assert got == ref

            # the recording captured every partitioned prompt, redacted
            recorded = [e for p in range(2)
                        for e in rec.replay(f"default/r9/generate#{p}")]
            assert len(recorded) == 4
            for e in recorded:
                obj = _json.loads(e["payload"])
                assert obj["secret"] == "[REDACTED]"
            # durable checkpoints exist for the serving step's fan-in
            assert len(store.list("checkpoints/default/r9/generate")) == 2
        finally:
            hub.stop()
