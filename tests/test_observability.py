"""Observability layer: metrics families, exposition, tracing, logging.

Mirrors the reference's observability surface (SURVEY §5.5: ~45
bobrapet_* Prometheus series pkg/metrics/controller_metrics.go; §5.1:
OTel spans with status-persisted TraceInfo trace_types.go:20).
"""

import pytest

from bobrapet_tpu.observability import (
    FEATURES,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    StepLogger,
    Tracer,
    TracingConfig,
    metrics,
    trace_info_from_span,
)
from bobrapet_tpu.observability.tracing import InMemorySpanExporter


class TestMetricPrimitives:
    def test_counter_labels(self):
        c = Counter("test_total", "help", ["phase"])
        c.inc("Succeeded")
        c.inc("Succeeded", by=2)
        c.inc("Failed")
        assert c.value("Succeeded") == 3
        assert c.value("Failed") == 1
        assert c.value("Missing") == 0

    def test_counter_rejects_negative(self):
        c = Counter("test_total", "help")
        with pytest.raises(ValueError):
            c.inc(by=-1)

    def test_counter_label_arity_enforced(self):
        c = Counter("test_total", "help", ["a", "b"])
        with pytest.raises(ValueError):
            c.inc("only-one")

    def test_gauge_set_add(self):
        g = Gauge("test_gauge", "help", ["queue"])
        g.set(5, "q1")
        g.add(-2, "q1")
        assert g.value("q1") == 3

    def test_histogram_buckets_and_sum(self):
        h = Histogram("test_seconds", "help", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 4
        assert h.sum() == pytest.approx(55.55)
        text = h.expose()
        assert 'test_seconds_bucket{le="0.1"} 1' in text
        assert 'test_seconds_bucket{le="1.0"} 2' in text
        assert 'test_seconds_bucket{le="+Inf"} 4' in text

    def test_exposition_format(self):
        reg = MetricsRegistry()
        c = reg.counter("x_total", "an x", ["k"])
        c.inc("v")
        page = reg.expose()
        assert "# HELP x_total an x" in page
        assert "# TYPE x_total counter" in page
        assert 'x_total{k="v"} 1.0' in page

    def test_registry_dedupes_by_name(self):
        reg = MetricsRegistry()
        a = reg.counter("same_total", "h")
        b = reg.counter("same_total", "h")
        assert a is b


class TestControlPlaneFamilies:
    def test_reference_series_present(self):
        # the reference inventory parity list (controller_metrics.go:44-442,
        # transport.go:11-35): every capability family must have a series
        for name in [
            "bobrapet_storyrun_duration_seconds",
            "bobrapet_storyrun_queue_depth",
            "bobrapet_storyrun_queue_age_seconds",
            "bobrapet_storyrun_rbac_operations_total",
            "bobrapet_storyrun_dependents_deleted_total",
            "bobrapet_steprun_retries_total",
            "bobrapet_steprun_cache_lookups_total",
            "bobrapet_steprun_duration_seconds",
            "bobrapet_child_stepruns_created_total",
            "bobrapet_dag_iteration_steps",
            "bobrapet_template_evaluation_duration_seconds",
            "bobrapet_template_evaluations_total",
            "bobrapet_template_cache_lookups_total",
            "bobrapet_resolver_stage_duration_seconds",
            "bobrapet_resolver_stage_total",
            "bobrapet_resource_quota_usage",
            "bobrapet_resource_quota_limit",
            "bobrapet_quota_violation_total",
            "bobrapet_resource_cleanup_duration_seconds",
            "bobrapet_cleanup_ops_total",
            "bobrapet_job_executions_total",
            "bobrapet_job_execution_duration_seconds",
            "bobrapet_story_dirty_marks_total",
            "bobrapet_controller_index_fallback_total",
            "bobrapet_mapper_failures_total",
            "bobrapet_downstream_target_mutations_total",
            "bobrapet_impulse_throttled_triggers",
            "bobrapet_transport_binding_ops_total",
            "bobrapet_transport_binding_operation_duration_seconds",
            "bobrapet_transport_bindings",
            "bobravoz_grpc_messages_total",
            "bobravoz_grpc_messages_dropped_total",
            "bobravoz_stream_requests_total",
            "bobravoz_stream_duration_seconds",
            "bobrapet_trigger_decisions_total",
            "bobrapet_trigger_backfills_total",
            "bobrapet_effectclaim_transitions_total",
            "bobrapet_reconcile_duration_seconds",
            "bobrapet_reconcile_total",
            "bobrapet_storage_ops_total",
            "bobrapet_storage_offloaded_bytes_total",
            "bobrapet_gang_chips_in_use",
            "bobrapet_slice_placements_total",
        ]:
            assert REGISTRY.get(name) is not None, name

    def test_new_families_record(self, rt):
        """The round-2 families actually get data from the control
        plane, not just registered names."""
        REGISTRY.reset()
        rt.apply(make_engram_template("nf-tpl", entrypoint="nf-impl"))
        rt.apply(_mk_engram("nf-engram", "nf-tpl"))
        register_engram("nf-impl")(lambda ctx: {"ok": True})
        story = _mk_story(
            "nf-story",
            steps=[{"name": "a", "ref": {"name": "nf-engram"},
                    "if": "{{ inputs.go }}"},
                   {"name": "b", "ref": {"name": "nf-engram"}}],
        )
        story.spec["policy"] = {"concurrency": 1}
        rt.apply(story)
        run = rt.run_story("nf-story", inputs={"go": True})
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        assert metrics.child_stepruns_created.value("engram") >= 1
        assert metrics.job_execution_duration.count("success") >= 1
        hits = metrics.template_cache.value("hit")
        misses = metrics.template_cache.value("miss")
        assert misses >= 1 and hits + misses >= 1
        assert metrics.rbac_ops.value("create") >= 1
        assert metrics.resolver_stages.value("template") >= 1
        # concurrency=1 with two ready steps parked one of them at least
        # once (story-scoped counter, bounded cardinality)...
        assert metrics.quota_violations.value("story:default/nf-story") >= 1
        # ...and the per-run gauge SERIES were deleted when the run
        # finished (value()==0 would also hold for a live zero — assert
        # absence from the scrape page instead)
        run_scope = f"storyrun:default/{run}"
        assert f'scope="{run_scope}"' not in REGISTRY.expose()

    def test_controllers_record_metrics(self, rt):
        REGISTRY.reset()
        rt.apply(make_engram_template("obs-tpl", entrypoint="obs-impl"))
        rt.apply(_mk_engram("obs-engram", "obs-tpl"))
        register_engram("obs-impl")(lambda ctx: {"ok": True})
        rt.apply(
            _mk_story(
                "obs-story",
                steps=[{"name": "only", "ref": {"name": "obs-engram"},
                        "with": {"v": "{{ inputs.v }}"}}],
            )
        )
        run = rt.run_story("obs-story", inputs={"v": 1})
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        assert metrics.storyrun_total.value("Succeeded") >= 1
        assert metrics.steprun_total.value("Succeeded") >= 1
        assert metrics.dag_iterations.count() >= 1
        assert metrics.template_evaluations.value("success") >= 1
        assert metrics.reconcile_total.value("storyrun", "success") >= 1
        page = REGISTRY.expose()
        assert 'bobrapet_storyrun_total{phase="Succeeded"}' in page


class TestTracing:
    def test_disabled_tracer_yields_none(self):
        t = Tracer(TracingConfig(enabled=False))
        with t.start_span("x") as span:
            assert span is None

    def test_span_nesting_same_trace(self):
        exp = InMemorySpanExporter()
        t = Tracer(TracingConfig(enabled=True), exporter=exp)
        with t.start_span("parent") as parent:
            with t.start_span("child") as child:
                assert child.trace_id == parent.trace_id
                assert child.parent_span_id == parent.span_id
        spans = exp.spans
        assert [s.name for s in spans] == ["child", "parent"]
        assert all(s.end_time is not None for s in spans)

    def test_trace_context_resume_across_process_boundary(self):
        # the reference persists TraceInfo into CR status so SDK spans
        # stitch onto the controller trace (trace_types.go:20)
        exp = InMemorySpanExporter()
        t = Tracer(TracingConfig(enabled=True), exporter=exp)
        with t.start_span("controller") as s:
            info = trace_info_from_span(s)
        assert info["traceId"] == s.trace_id and info["sampled"]
        with t.start_span("sdk-side", trace_context=info) as resumed:
            assert resumed.trace_id == info["traceId"]
            assert resumed.parent_span_id == info["spanId"]

    def test_error_recorded(self):
        exp = InMemorySpanExporter()
        t = Tracer(TracingConfig(enabled=True), exporter=exp)
        with pytest.raises(RuntimeError):
            with t.start_span("boom"):
                raise RuntimeError("nope")
        (span,) = exp.spans
        assert span.status == "error"
        assert span.attributes["error.type"] == "RuntimeError"

    def test_propagation_toggle(self):
        t = Tracer(TracingConfig(enabled=True, propagation_enabled=False))
        ctx = {"traceId": "a" * 32, "spanId": "b" * 16}
        with t.start_span("x", trace_context=ctx) as span:
            assert span.trace_id != ctx["traceId"]


class TestLoggingFeatures:
    def test_step_output_gated(self, caplog):
        log = StepLogger("test", step="s1")
        FEATURES.apply(verbosity=0, log_step_output=False)
        with caplog.at_level("INFO", logger="bobrapet_tpu"):
            log.step_output({"big": "payload"})
        assert not caplog.records
        FEATURES.apply(verbosity=0, log_step_output=True)
        with caplog.at_level("INFO", logger="bobrapet_tpu"):
            log.step_output({"big": "payload"})
        assert any("payload" in r.getMessage() for r in caplog.records)
        FEATURES.apply(verbosity=0, log_step_output=False)

    def test_bound_context_in_lines(self, caplog):
        log = StepLogger("test", step="s1").with_values(run="r1")
        with caplog.at_level("INFO", logger="bobrapet_tpu"):
            log.info("hello", extra_key="v")
        line = caplog.records[-1].getMessage()
        assert "step=s1" in line and "run=r1" in line and "extra_key=v" in line


# -- helpers -----------------------------------------------------------------

from bobrapet_tpu.api.catalog import make_engram_template  # noqa: E402
from bobrapet_tpu.api.engram import make_engram as _mk_engram  # noqa: E402
from bobrapet_tpu.api.story import make_story as _mk_story  # noqa: E402
from bobrapet_tpu.sdk.registry import register_engram  # noqa: E402


class TestTracePersistence:
    """VERDICT r1 missing #5: TraceInfo + SchemaReference persisted into
    run/step status; one trace id spans controller -> gang host."""

    def _traced_rt(self, tmp_path):
        from bobrapet_tpu.runtime import Runtime

        exporter = InMemorySpanExporter()
        tracer = Tracer(TracingConfig(enabled=True), exporter=exporter)
        rt = Runtime(tracer=tracer)
        return rt, tracer, exporter

    def test_trace_and_schema_refs_persist(self, tmp_path, monkeypatch):
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.observability import tracing as tracing_mod
        from bobrapet_tpu.sdk import register_engram

        rt, tracer, exporter = self._traced_rt(tmp_path)
        # SDK-side spans go through the global TRACER; point it at the
        # same traced instance for the duration of the test
        monkeypatch.setattr(tracing_mod, "TRACER", tracer)

        engram_trace = {}

        @register_engram("traced-impl")
        def impl(ctx):
            with ctx.start_span("engram.work") as span:
                engram_trace["trace_id"] = span.trace_id
                engram_trace["parent"] = span.parent_span_id
            return {"ok": True}

        rt.apply(make_engram_template(
            "tr-tpl", entrypoint="traced-impl",
            inputSchema={"type": "object"},
            outputSchema={"type": "object"},
        ))
        rt.apply(make_engram("worker", "tr-tpl"))
        rt.apply(make_story("traced", steps=[
            {"name": "s", "ref": {"name": "worker"}},
        ], inputsSchema={"type": "object"},
           outputsSchema={"type": "object"},
           output={"ok": "{{ steps.s.output.ok }}"}))

        run = rt.run_story("traced", inputs={})
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"

        srun = rt.store.get("StoryRun", "default", run)
        trace = srun.status.get("trace")
        assert trace and trace["traceId"] and trace["spanId"]
        assert srun.status["inputSchemaRef"]["ref"] == (
            "bubu://story/default/traced/inputs"
        )
        assert srun.status["outputSchemaRef"]["ref"] == (
            "bubu://story/default/traced/output"
        )

        steps = rt.store.list("StepRun", "default")
        assert steps
        sr = steps[0]
        step_trace = sr.status.get("trace")
        # one trace id spans controller -> steprun -> gang-host SDK span
        assert step_trace["traceId"] == trace["traceId"]
        assert step_trace["spanId"] != trace["spanId"]
        assert sr.status["inputSchemaRef"]["ref"] == (
            "bubu://engram/default/worker/input"
        )
        assert engram_trace["trace_id"] == trace["traceId"]
        # full dispatch chain, still ONE trace: the controller's
        # steprun.dispatch span parents on the StepRun's persisted
        # context; the gang host wraps user code in sdk.step (in sync
        # executor mode the gang runs inside the dispatch span on the
        # same thread); the engram's own span nests inside that
        dispatch_span = next(
            s for s in exporter.spans if s.name == "steprun.dispatch"
        )
        sdk_span = next(s for s in exporter.spans if s.name == "sdk.step")
        assert dispatch_span.trace_id == trace["traceId"]
        assert dispatch_span.parent_span_id == step_trace["spanId"]
        assert sdk_span.trace_id == trace["traceId"]
        assert sdk_span.parent_span_id == dispatch_span.span_id
        assert engram_trace["parent"] == sdk_span.span_id

        names = [s.name for s in exporter.spans]
        assert "storyrun.run" in names
        assert "steprun.launch" in names
        assert "engram.work" in names
        assert "steprun.dispatch" in names
        # controllers + storage emit feature-gated spans too
        # (reference: StartSpan in reconcilers and pkg/storage)
        assert "dag.reconcile" in names
        assert "step.execute" in names
        # the dag span parents on the run's persisted trace
        dag_span = next(s for s in exporter.spans if s.name == "dag.reconcile")
        assert dag_span.trace_id == trace["traceId"]

    def test_no_schemas_no_refs_and_disabled_tracer_no_trace(self, rt):
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.sdk import register_engram

        @register_engram("plain-impl")
        def impl(ctx):
            assert ctx.trace_context is None
            return {}

        rt.apply(make_engram_template("p-tpl", entrypoint="plain-impl"))
        rt.apply(make_engram("worker", "p-tpl"))
        rt.apply(make_story("plain", steps=[
            {"name": "s", "ref": {"name": "worker"}},
        ]))
        run = rt.run_story("plain")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        srun = rt.store.get("StoryRun", "default", run)
        assert "trace" not in srun.status
        assert "inputSchemaRef" not in srun.status


class TestOTLPExport:
    """VERDICT r2 #8: wire-level OTLP/HTTP export behind SpanExporter —
    bounded queue, batch flush, shutdown-with-deadline; spans from a
    story run arrive at a collector stub with parent/child links
    intact across controller -> SDK."""

    @staticmethod
    def _collector():
        import json as _json
        import threading as _t
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        received: list[dict] = []

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):  # noqa: N802
                assert self.path == "/v1/traces"
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.append(_json.loads(body))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        _t.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, f"http://127.0.0.1:{srv.server_address[1]}", received

    @staticmethod
    def _flatten(received):
        out = []
        for post in received:
            for rs in post.get("resourceSpans", []):
                for ss in rs.get("scopeSpans", []):
                    out.extend(ss.get("spans", []))
        return out

    def test_story_spans_reach_collector_with_links(self, monkeypatch):
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.observability import tracing as tracing_mod
        from bobrapet_tpu.observability.tracing import OTLPSpanExporter
        from bobrapet_tpu.runtime import Runtime

        srv, endpoint, received = self._collector()
        exporter = OTLPSpanExporter(endpoint, flush_interval=0.1)
        tracer = Tracer(TracingConfig(enabled=True), exporter=exporter)
        monkeypatch.setattr(tracing_mod, "TRACER", tracer)
        rt = Runtime(tracer=tracer)

        @register_engram("otlp-impl")
        def impl(ctx):
            with ctx.start_span("engram.work"):
                pass
            return {"ok": True}

        rt.apply(make_engram_template("otlp-tpl", entrypoint="otlp-impl"))
        rt.apply(make_engram("otlp-worker", "otlp-tpl"))
        rt.apply(make_story("otlp-story", steps=[
            {"name": "s", "ref": {"name": "otlp-worker"}},
        ]))
        run = rt.run_story("otlp-story")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        exporter.shutdown()
        srv.shutdown()

        spans = self._flatten(received)
        assert spans, "no spans reached the collector"
        by_id = {s["spanId"]: s for s in spans}
        # the SDK-side span parents into a controller-side span IN THE
        # SAME TRACE — the cross-process stitch survived the wire
        work = [s for s in spans if s["name"] == "engram.work"]
        assert work, [s["name"] for s in spans]
        parent = by_id.get(work[0].get("parentSpanId"))
        assert parent is not None, "engram span's parent was not exported"
        assert parent["traceId"] == work[0]["traceId"]
        # OTLP shape: service.name resource attribute present
        res_attrs = received[0]["resourceSpans"][0]["resource"]["attributes"]
        assert {"key": "service.name",
                "value": {"stringValue": "bobrapet-tpu"}} in res_attrs

    def test_bounded_queue_drops_instead_of_blocking(self):
        from bobrapet_tpu.observability.tracing import OTLPSpanExporter, Span

        # endpoint that will never answer: export must stay non-blocking
        exp = OTLPSpanExporter("http://127.0.0.1:1", max_queue=8,
                               flush_interval=30.0, timeout=0.2)
        for i in range(50):
            exp.export(Span(name=f"s{i}", trace_id="t", span_id=str(i),
                            start_time=0.0, end_time=1.0))
        assert exp.dropped > 0
        exp.shutdown(deadline=0.5)
        assert exp.export_errors >= 1

    def test_self_reporting_metrics(self):
        """ISSUE 8 satellite: dropped/export_errors/queue-depth register
        as bobrapet_tracing_* series instead of staying invisible
        attributes."""
        from bobrapet_tpu.observability.tracing import OTLPSpanExporter, Span

        dropped0 = metrics.tracing_dropped.value()
        errors0 = metrics.tracing_export_errors.value()
        exp = OTLPSpanExporter("http://127.0.0.1:1", max_queue=4,
                               flush_interval=30.0, timeout=0.2)
        for i in range(12):
            exp.export(Span(name=f"s{i}", trace_id="t", span_id=str(i),
                            start_time=0.0, end_time=1.0))
        assert metrics.tracing_dropped.value() - dropped0 == exp.dropped > 0
        assert metrics.tracing_queue_depth.value() > 0
        exp.shutdown(deadline=0.5)
        assert metrics.tracing_export_errors.value() - errors0 >= 1
        page = REGISTRY.expose()
        assert "bobrapet_tracing_dropped_total" in page
        assert "bobrapet_tracing_queue_depth" in page


class TestFlightRecorder:
    def _fresh(self, **kw):
        from bobrapet_tpu.observability.timeline import FlightRecorder

        return FlightRecorder(**kw)

    def test_ring_bounded_per_run(self):
        fr = self._fresh(depth=8)
        for i in range(50):
            fr.record("ns", "r", "phase", message=f"m{i}")
        tl = fr.timeline("ns", "r")
        assert len(tl) == 8
        assert tl[-1]["message"] == "m49"  # newest kept, oldest dropped
        assert fr.tail("ns", "r", 3) == tl[-3:]

    def test_run_population_lru_bounded(self):
        fr = self._fresh(depth=8, max_runs=16)
        for i in range(40):
            fr.record("ns", f"r{i}", "phase", trace_id=f"t{i}")
        assert not fr.known("ns", "r0")  # evicted
        assert fr.known("ns", "r39")
        # trace links evicted with their runs
        assert fr.runs_for_trace("t0") == []
        assert fr.runs_for_trace("t39") == [("ns", "r39")]

    def test_forget_drops_ring_and_links(self):
        fr = self._fresh()
        fr.record("ns", "r", "phase", trace_id="tt")
        fr.forget("ns", "r")
        assert fr.timeline("ns", "r") == []
        assert fr.runs_for_trace("tt") == []

    def test_set_depth_live_rebound(self):
        fr = self._fresh(depth=16)
        for i in range(16):
            fr.record("ns", "r", "phase", message=f"m{i}")
        fr.set_depth(8)
        assert fr.depth == 8
        tl = fr.timeline("ns", "r")
        assert len(tl) == 8 and tl[-1]["message"] == "m15"
        fr.record("ns", "r", "phase", message="m16")
        assert len(fr.timeline("ns", "r")) == 8

    def test_span_sink_records_run_scoped_spans_only(self):
        from bobrapet_tpu.observability.tracing import (
            InMemorySpanExporter,
            Tracer,
            TracingConfig,
        )

        tracer = Tracer(TracingConfig(enabled=True), InMemorySpanExporter())
        from bobrapet_tpu.observability.timeline import FLIGHT

        with tracer.start_span("dag.reconcile", run="fr-span-run",
                               namespace="fr-ns"):
            pass
        with tracer.start_span("storage.dehydrate"):
            pass  # no run attr: not run-scoped, not recorded
        tl = FLIGHT.timeline("fr-ns", "fr-span-run")
        assert [r["message"] for r in tl if r["kind"] == "span"] == ["dag.reconcile"]
        FLIGHT.forget("fr-ns", "fr-span-run")

    def test_slo_threshold_live_reload(self):
        from bobrapet_tpu.observability.timeline import (
            SLO_THRESHOLDS,
            set_slo_thresholds,
        )

        before = dict(SLO_THRESHOLDS)
        try:
            set_slo_thresholds(7.5, 0.25)
            assert SLO_THRESHOLDS == {"ttft": 7.5, "tpot": 0.25}
            # invalid values keep the prior thresholds
            set_slo_thresholds(0, -1)
            assert SLO_THRESHOLDS == {"ttft": 7.5, "tpot": 0.25}
        finally:
            set_slo_thresholds(before["ttft"], before["tpot"])


class TestLogTraceCorrelation:
    def test_records_carry_trace_ids_when_span_current(self, caplog, monkeypatch):
        import logging

        from bobrapet_tpu.observability import structured as structured_mod
        from bobrapet_tpu.observability.tracing import (
            InMemorySpanExporter,
            Tracer,
            TracingConfig,
        )

        tracer = Tracer(TracingConfig(enabled=True), InMemorySpanExporter())
        monkeypatch.setattr(structured_mod, "TRACER", tracer)
        logger = StepLogger("corr", namespace="ns", object="x")
        with caplog.at_level(logging.INFO, logger="bobrapet_tpu"):
            with tracer.start_span("steprun.launch", run="corr-run") as span:
                logger.info("inside")
            logger.info("outside")
        inside, outside = caplog.messages[0], caplog.messages[1]
        assert f"trace_id={span.trace_id}" in inside
        assert f"span_id={span.span_id}" in inside
        assert "run_id=corr-run" in inside
        assert "trace_id" not in outside
