"""Prefix-KV persistence through the slice-local disk tier.

A preempted or restarted serving engram loses every in-memory registry
with its process; what survives is the slice-local disk tier. These
tests pin the resume contract: exported prefix blocks spill through the
tier (``kv/<scope>/<chain-hash>``), a FRESH registry in the relaunched
process reads them back, and the new engine adopts its prefix state via
scatter instead of re-running prefill — with BYTE-IDENTICAL decode
output (the same parity bar as the horizon engine, test_serving_horizon).
"""

import jax
import numpy as np
import pytest

from bobrapet_tpu.models import llama
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.serving import PagedConfig, ServingEngine
from bobrapet_tpu.serving.prefix_cache import (
    SharedPrefixRegistry,
    _decode_kv_payload,
    _encode_kv_payload,
)
from bobrapet_tpu.storage.store import SliceLocalSSDStore


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _pcfg(**over):
    kw = dict(max_slots=4, block_size=16, num_blocks=128,
              max_blocks_per_seq=8)
    kw.update(over)
    return PagedConfig(**kw)


def _prompt(cfg, seed=40):
    rng = np.random.default_rng(seed)
    system = rng.integers(0, cfg.vocab_size, 48).tolist()  # 3 full blocks
    tail = rng.integers(0, cfg.vocab_size, 9).tolist()
    return system + tail


def _serve_once(params, cfg, reg, prompt, max_new=8):
    eng = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
    eng.submit(list(prompt), max_new_tokens=max_new)
    out = eng.run()[0].output
    return eng, out


class TestPayloadCodec:
    def test_kv_payload_roundtrip_exact(self):
        payload = {
            "k": np.arange(2 * 3 * 4, dtype=np.float32).reshape(2, 3, 4),
            "v": np.linspace(-1, 1, 24, dtype=np.float32).reshape(2, 3, 4),
        }
        back = _decode_kv_payload(_encode_kv_payload(payload))
        assert set(back) == {"k", "v"}
        for name in ("k", "v"):
            assert back[name].dtype == payload[name].dtype
            assert back[name].shape == payload[name].shape
            np.testing.assert_array_equal(back[name], payload[name])

    def test_jax_arrays_encode_like_numpy(self):
        import jax.numpy as jnp

        arr = jnp.ones((2, 4), dtype=jnp.float32) * 0.5
        back = _decode_kv_payload(_encode_kv_payload({"k": arr}))
        np.testing.assert_array_equal(back["k"], np.asarray(arr))


class TestPreemptionResume:
    def test_restarted_engram_readopts_prefix_state_from_disk(
        self, model, tmp_path
    ):
        """Simulated preemption: engine + registry die; only the disk
        tier survives. The relaunched engine must adopt the persisted
        blocks (scatter, no prefill) and decode byte-identically."""
        cfg, params = model
        tier = SliceLocalSSDStore(str(tmp_path / "tier"))
        prompt = _prompt(cfg)

        reg1 = SharedPrefixRegistry()
        reg1.attach_spill(tier)
        eng1, out_before = _serve_once(params, cfg, reg1, prompt)
        assert len(reg1) >= 3
        assert len(tier.list("kv/")) >= 3  # spilled through the tier
        del eng1, reg1  # the preemption: in-memory state is GONE

        kv_hits0 = metrics.storage_tier.value("kv", "hit")
        reg2 = SharedPrefixRegistry()
        reg2.attach_spill(tier)
        assert len(reg2) == 0  # nothing in memory — disk is the source
        eng2, out_after = _serve_once(params, cfg, reg2, prompt)
        assert eng2.blocks.shared_hits >= 3  # adopted, not re-prefilled
        assert metrics.storage_tier.value("kv", "hit") >= kv_hits0 + 3
        assert out_after == out_before  # byte-identical decode

        # adopted KV must be EXACT: a cold share-less engine agrees
        plain = ServingEngine(params, cfg, _pcfg())
        plain.submit(list(prompt), max_new_tokens=8)
        assert plain.run()[0].output == out_after

    def test_scope_isolation_survives_the_disk_hop(self, model, tmp_path):
        """Different weights fingerprint to a different scope; the scope
        is part of the disk key, so a restarted engine with OTHER
        weights can never adopt the persisted blocks."""
        cfg, params = model
        tier = SliceLocalSSDStore(str(tmp_path / "tier"))
        prompt = _prompt(cfg, seed=41)
        reg1 = SharedPrefixRegistry()
        reg1.attach_spill(tier)
        _eng, _ = _serve_once(params, cfg, reg1, prompt, max_new=6)
        del reg1

        other = llama.init_params(jax.random.PRNGKey(7), cfg)
        reg2 = SharedPrefixRegistry()
        reg2.attach_spill(tier)
        eng2, _ = _serve_once(other, cfg, reg2, prompt, max_new=6)
        assert eng2.blocks.shared_hits == 0

    def test_memory_lru_eviction_recovers_from_disk(self, model, tmp_path):
        """An entry the bounded in-memory LRU evicted stays adoptable:
        the spill read-through repopulates it on demand."""
        cfg, params = model
        tier = SliceLocalSSDStore(str(tmp_path / "tier"))
        prompt = _prompt(cfg, seed=42)
        reg = SharedPrefixRegistry(max_entries=1)  # evicts almost all
        reg.attach_spill(tier)
        _eng, out_a = _serve_once(params, cfg, reg, prompt)
        assert len(reg) == 1
        eng2, out_b = _serve_once(params, cfg, reg, prompt)
        assert eng2.blocks.shared_hits >= 3
        assert out_b == out_a

    def test_detached_spill_is_memory_only(self, model, tmp_path):
        cfg, params = model
        tier = SliceLocalSSDStore(str(tmp_path / "tier"))
        reg = SharedPrefixRegistry()
        reg.attach_spill(tier)
        reg.attach_spill(None)
        _eng, _ = _serve_once(params, cfg, reg, _prompt(cfg, seed=43))
        assert tier.list("kv/") == []  # nothing persisted after detach
