"""Cluster execution backend: apply/watch semantics, the FakeCluster
envtest analog, exit-code extraction, rollout readiness reflection, and
the stdlib Kubernetes REST client against a stub API server.

Reference behaviors under test: workload ensure create-or-update
(pkg/workload/ensure.go:58), handleJobStatus
(steprun_controller.go:1947), extractPodExitCode (:2389).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.cluster import (
    ClusterConflict,
    FakeCluster,
    KubeHttpClient,
    apply_manifest,
    extract_failed_exit_code,
    subset_differs,
)
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram


def job_manifest(name="j1", ns="default", image="img:1", labels=None):
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {
            "backoffLimit": 0,
            "template": {
                "metadata": {"labels": {"x": "y"}},
                "spec": {"containers": [{"name": "engram", "image": image}]},
            },
        },
    }


class TestApplySemantics:
    def test_create_then_unchanged(self):
        c = FakeCluster()
        m = {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "s", "namespace": "default"},
            "spec": {"ports": [{"port": 80}]},
        }
        _, outcome = apply_manifest(c, m)
        assert outcome == "created"
        _, outcome = apply_manifest(c, m)
        assert outcome == "unchanged"

    def test_drift_is_patched_but_server_defaults_are_not_drift(self):
        c = FakeCluster()
        m = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "d", "namespace": "default"},
            "spec": {"replicas": 1},
        }
        apply_manifest(c, m)
        # server-side defaulting: extra live fields are not drift
        c.patch("apps/v1", "Deployment", "default", "d",
                {"spec": {"revisionHistoryLimit": 10}})
        _, outcome = apply_manifest(c, m)
        assert outcome == "unchanged"
        # real drift on a controlled field is patched
        m2 = dict(m, spec={"replicas": 3})
        _, outcome = apply_manifest(c, m2)
        assert outcome == "updated"
        live = c.get("apps/v1", "Deployment", "default", "d")
        assert live["spec"]["replicas"] == 3
        assert live["spec"]["revisionHistoryLimit"] == 10  # merge, not replace

    def test_job_spec_is_immutable_adopt_on_exists(self):
        c = FakeCluster()
        apply_manifest(c, job_manifest(image="img:1"))
        live, outcome = apply_manifest(c, job_manifest(image="img:2"))
        assert outcome == "unchanged"
        assert (
            live["spec"]["template"]["spec"]["containers"][0]["image"] == "img:1"
        )

    def test_create_conflict_raises(self):
        c = FakeCluster()
        c.create(job_manifest())
        with pytest.raises(ClusterConflict):
            c.create(job_manifest())

    def test_subset_differs_lists_and_scalars(self):
        assert not subset_differs({"a": [1, 2]}, {"a": [1, 2], "b": 3})
        assert subset_differs({"a": [1, 2]}, {"a": [1, 2, 3]})
        assert subset_differs({"a": {"b": 1}}, {"a": {}})
        assert not subset_differs({}, {"anything": True})


class TestExitCodeExtraction:
    def test_most_recent_failed_pod_nonzero_code(self):
        pods = [
            {"status": {"phase": "Failed", "containerStatuses": [
                {"state": {"terminated": {"exitCode": 2}}}]}},
            {"status": {"phase": "Succeeded", "containerStatuses": [
                {"state": {"terminated": {"exitCode": 0}}}]}},
            {"status": {"phase": "Failed", "containerStatuses": [
                {"state": {"terminated": {"exitCode": 99}}}]}},
        ]
        assert extract_failed_exit_code(pods) == 99

    def test_unknown_when_no_terminated_state(self):
        # evicted pod: Failed phase but no container terminated record
        pods = [{"status": {"phase": "Failed"}}]
        assert extract_failed_exit_code(pods) == -1
        assert extract_failed_exit_code([]) == -1


class TestFakeClusterControllers:
    def test_indexed_job_creates_pods_with_completion_index(self):
        c = FakeCluster()  # no kubelet: pods stay Pending
        m = job_manifest(name="gang")
        m["spec"].update(completions=4, parallelism=4, completionMode="Indexed")
        c.create(m)
        pods = c.list("v1", "Pod", "default", labels={"job-name": "gang"})
        assert len(pods) == 4
        indexes = sorted(
            p["metadata"]["annotations"]["batch.kubernetes.io/job-completion-index"]
            for p in pods
        )
        assert indexes == ["0", "1", "2", "3"]
        assert all(p["status"]["phase"] == "Pending" for p in pods)

    def test_job_fails_past_backoff_limit_and_succeeds_on_completion(self):
        c = FakeCluster()
        c.create(job_manifest(name="ok"))
        c.patch_status("v1", "Pod", "default", "ok-0", {"status": {
            "phase": "Succeeded",
            "containerStatuses": [{"state": {"terminated": {"exitCode": 0}}}],
        }})
        job = c.get("batch/v1", "Job", "default", "ok")
        assert {c_["type"] for c_ in job["status"]["conditions"]} == {"Complete"}

        c.create(job_manifest(name="bad"))
        c.patch_status("v1", "Pod", "default", "bad-0", {"status": {
            "phase": "Failed",
            "containerStatuses": [{"state": {"terminated": {"exitCode": 7}}}],
        }})
        job = c.get("batch/v1", "Job", "default", "bad")
        assert {c_["type"] for c_ in job["status"]["conditions"]} == {"Failed"}

    def test_deleting_job_cascades_pods(self):
        c = FakeCluster()
        m = job_manifest(name="gone")
        m["spec"].update(completions=2, parallelism=2, completionMode="Indexed")
        c.create(m)
        c.delete("batch/v1", "Job", "default", "gone")
        assert c.list("v1", "Pod", "default", labels={"job-name": "gone"}) == []


class TestClusterBackendEndToEnd:
    def test_unknown_exit_does_not_consume_retry_budget(self):
        """An evicted pod (Failed, no terminated record) classifies as
        unknown (-1) and retries without consuming budget
        (reference: ExitClassUnknown semantics)."""
        rt = Runtime(executor_backend="cluster")
        rt.apply(make_engram_template("w-tpl", entrypoint="w-impl"))
        rt.apply(make_engram("w", "w-tpl"))
        evicted = {"done": False}

        @register_engram("w-impl")
        def impl(ctx):
            return {"ok": True}

        # evict the first pod before the kubelet runs it: hold the
        # kubelet, fail the pod via status patch (the envtest move)
        kubelet = rt.cluster._kubelet
        orig = kubelet.pod_added

        def evict_first(pod):
            if not evicted["done"]:
                evicted["done"] = True
                meta = pod["metadata"]
                rt.cluster.patch_status("v1", "Pod", meta["namespace"], meta["name"], {
                    "status": {"phase": "Failed", "message": "evicted"},
                })
                return
            orig(pod)

        kubelet.pod_added = evict_first
        rt.apply(make_story("s", steps=[
            {"name": "a", "ref": {"name": "w"},
             "execution": {"retry": {"maxRetries": 0}}},
        ]))
        run = rt.run_story("s")
        rt.pump()
        # maxRetries=0 yet the run succeeds: the unknown-class failure
        # was retried for free, the second pod ran normally
        assert rt.run_phase(run) == "Succeeded"
        sr = next(iter(rt.store.list("StepRun")))
        assert sr.status["retries"] == 0
        assert sr.status["attempts"] == 2

    def test_terminal_exit_code_flows_from_watched_pod_status(self):
        rt = Runtime(executor_backend="cluster")
        rt.apply(make_engram_template("f-tpl", entrypoint="f-impl"))
        rt.apply(make_engram("f", "f-tpl"))

        @register_engram("f-impl")
        def impl(ctx):
            from bobrapet_tpu.sdk import EngramExit

            raise EngramExit(126, "bad config")

        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "f"}}]))
        run = rt.run_story("s")
        rt.pump()
        assert rt.run_phase(run) == "Failed"
        sr = next(iter(rt.store.list("StepRun")))
        assert sr.status["exitCode"] == 126
        assert sr.status["exitClass"] == "terminal"
        # the exit code came through the cluster: pod -> job -> bus
        pods = rt.cluster.list("v1", "Pod", "default")
        terms = [
            cs["state"]["terminated"]["exitCode"]
            for p in pods for cs in p["status"].get("containerStatuses", [])
        ]
        assert 126 in terms

    def test_gang_pods_get_distinct_worker_ids(self):
        from bobrapet_tpu.parallel.placement import SlicePool

        rt = Runtime(executor_backend="cluster")
        rt.placer.add_pool(SlicePool("v5e-pool", "2x4", chips_per_host=4))
        rt.apply(make_engram_template("g-tpl", entrypoint="g-impl"))
        rt.apply(make_engram("g", "g-tpl"))
        seen = []

        @register_engram("g-impl")
        def impl(ctx):
            seen.append(ctx.host_id)
            return {"host": ctx.host_id}

        rt.apply(make_story("s", steps=[
            {"name": "a", "ref": {"name": "g"}, "tpu": {"topology": "2x4"}},
        ], policy={"queue": "v5e-pool"}))
        run = rt.run_story("s")
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        # 8 chips / 4 per host = a 2-pod Indexed gang; worker identity
        # flowed from the completion-index annotation (downward API)
        assert sorted(seen) == [0, 1]
        pods = rt.cluster.list("v1", "Pod", "default")
        assert len(pods) == 2
        job = rt.cluster.list("batch/v1", "Job", "default")[0]
        assert job["spec"]["completionMode"] == "Indexed"
        # TPU placement facts are on the pod spec
        tspec = job["spec"]["template"]["spec"]
        assert tspec["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
        limits = tspec["containers"][0]["resources"]["limits"]
        assert limits["google.com/tpu"] == "4"


class TestWorkloadReadinessReflection:
    def _mk_bus_deployment(self, rt, generation=1):
        from bobrapet_tpu.core.object import new_resource

        d = new_resource("Deployment", "rt-step", "default", {
            "replicas": 1,
            "env": {"BOBRA_GRPC_PORT": "50051"},
            "selector": {"bobrapet.io/step-run": "rt-step"},
            "connectorGeneration": generation,
            "serviceName": "rt-step-svc",
        }, labels={"bobrapet.io/step-run": "rt-step"})
        return rt.store.create(d)

    def test_ready_generation_reflects_rollout(self):
        rt = Runtime(executor_backend="cluster")
        self._mk_bus_deployment(rt)
        d = rt.store.get("Deployment", "default", "rt-step")
        assert d.status["readyGeneration"] == 1
        assert d.status["observedConnectorGeneration"] == 1

        # bump the connector generation with readiness held: observed
        # advances, ready does NOT (cutover must keep waiting)
        rt.cluster.hold_readiness = True
        rt.store.mutate("Deployment", "default", "rt-step",
                        lambda r: r.spec.__setitem__("connectorGeneration", 2))
        d = rt.store.get("Deployment", "default", "rt-step")
        assert d.status["observedConnectorGeneration"] == 2
        assert d.status["readyGeneration"] == 1

        # probe passes (model compiled + warm) -> ready advances
        rt.cluster.hold_readiness = False
        rt.cluster.mark_ready("Deployment", "default", "rt-step")
        d = rt.store.get("Deployment", "default", "rt-step")
        assert d.status["readyGeneration"] == 2

    def test_warmup_self_completes_via_timed_reprobe(self):
        """Simulated compile/warmup latency resolves without any manual
        poke: the reconciler's timed re-probe re-derives cluster status
        once the clock passes warm_at."""
        rt = Runtime(executor_backend="cluster")
        rt.cluster.warmup_seconds = 30.0
        self._mk_bus_deployment(rt)
        rt.pump()
        d = rt.store.get("Deployment", "default", "rt-step")
        assert d.status["readyGeneration"] == 1
        assert d.status["readyReplicas"] == 1


# ---------------------------------------------------------------------------
# stub API server for the stdlib REST client
# ---------------------------------------------------------------------------


class _StubAPIHandler(BaseHTTPRequestHandler):
    server_version = "kube-stub"
    store: dict = {}
    requests: list = []

    def log_message(self, *a):  # noqa: D102 - quiet
        pass

    def _reply(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        type(self).requests.append(("GET", self.path, None))
        if "watch=true" in self.path:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            for ev in [
                {"type": "ADDED", "object": {
                    "metadata": {"name": "j1", "resourceVersion": "5"}}},
                {"type": "MODIFIED", "object": {
                    "metadata": {"name": "j1", "resourceVersion": "6"},
                    "status": {"succeeded": 1}}},
            ]:
                self.wfile.write((json.dumps(ev) + "\n").encode())
                self.wfile.flush()
            return
        if self.path.endswith("/jobs"):
            self._reply(200, {"items": [{"metadata": {"name": "j1"}}]})
        elif self.path.endswith("/jobs/missing"):
            self._reply(404, {"kind": "Status", "code": 404})
        else:
            self._reply(200, {"metadata": {"name": "j1"}})

    def do_POST(self):  # noqa: N802
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).requests.append(("POST", self.path, json.loads(body)))
        self._reply(201, json.loads(body))

    def do_PATCH(self):  # noqa: N802
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).requests.append(
            ("PATCH", self.path, self.headers.get("Content-Type")))
        self._reply(200, json.loads(body))

    def do_DELETE(self):  # noqa: N802
        type(self).requests.append(("DELETE", self.path, None))
        self._reply(200, {"kind": "Status", "status": "Success"})


@pytest.fixture
def stub_api():
    _StubAPIHandler.requests = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubAPIHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestKubeHttpClient:
    def test_paths_and_methods(self, stub_api):
        c = KubeHttpClient(base_url=stub_api, token="tok")
        assert c.get("batch/v1", "Job", "ns1", "missing") is None
        c.create(job_manifest(ns="ns1"))
        c.patch("batch/v1", "Job", "ns1", "j1", {"metadata": {"labels": {"a": "b"}}})
        c.patch_status("batch/v1", "Job", "ns1", "j1", {"status": {"succeeded": 1}})
        c.delete("batch/v1", "Job", "ns1", "j1")
        assert c.list("batch/v1", "Job", "ns1")[0]["kind"] == "Job"
        # core-group path has no group segment
        assert c.get("v1", "Pod", "ns1", "p") is not None

        paths = [(m, p) for m, p, _ in _StubAPIHandler.requests]
        assert ("GET", "/apis/batch/v1/namespaces/ns1/jobs/missing") in paths
        assert ("POST", "/apis/batch/v1/namespaces/ns1/jobs") in paths
        assert ("GET", "/api/v1/namespaces/ns1/pods/p") in paths
        patch_types = [x for m, p, x in _StubAPIHandler.requests if m == "PATCH"]
        assert patch_types == ["application/merge-patch+json"] * 2
        status_paths = [p for m, p, _ in _StubAPIHandler.requests
                        if m == "PATCH" and p.endswith("/status")]
        assert status_paths == ["/apis/batch/v1/namespaces/ns1/jobs/j1/status"]

    def test_watch_streams_events(self, stub_api):
        c = KubeHttpClient(base_url=stub_api, token="tok")
        got = []
        done = threading.Event()

        def cb(ev_type, obj):
            got.append((ev_type, obj.get("status", {})))
            if len(got) >= 2:
                done.set()
                c.close()

        c.watch(cb)
        c.start_watch("batch/v1", "Job", "ns1")
        assert done.wait(5.0)
        assert got[0][0] == "ADDED"
        assert got[1] == ("MODIFIED", {"succeeded": 1})


class TestClusterModeStreamingCutover:
    """VERDICT r2 weak #5: readiness-gated cutover driven by WATCHED
    cluster rollout status (FakeCluster Deployment controller), not the
    local workload simulator."""

    def _setup_realtime(self, rt):
        from bobrapet_tpu.api.transport import make_transport

        rt.apply(make_transport("voz", "bobravoz", driver="grpc",
                                supportedAudio=[{"name": "opus",
                                                 "sampleRateHz": 48000}],
                                supportedBinary=["application/json"]))
        rt.apply(make_engram_template("stream-tpl", image="stream:1",
                                      entrypoint="stream-impl",
                                      supportedModes=["deployment"]))
        for e in ("ingest", "emit"):
            rt.apply(make_engram(e, "stream-tpl"))
        rt.apply(make_story("live", steps=[
            {"name": "in", "ref": {"name": "ingest"}, "transport": "voz"},
            {"name": "out", "ref": {"name": "emit"}, "needs": ["in"],
             "transport": "voz"},
        ], transports=[{"name": "voz", "transportRef": "voz"}],
            pattern="realtime"))
        return rt.run_story("live", inputs={"source": "mic"})

    def _renegotiate(self, rt, sr):
        rt.store.mutate(
            "Transport", "_cluster", "voz",
            lambda r: r.spec.__setitem__(
                "supportedAudio", [{"name": "opus", "sampleRateHz": 16000}]),
        )
        rt.manager.enqueue("steprun", "default", sr.meta.name)
        rt.pump()

    def test_realtime_topology_runs_on_cluster_backend(self):
        rt = Runtime(executor_backend="cluster")
        run = self._setup_realtime(rt)
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Running"
        # the cluster holds real applied Deployments + Services
        deps = rt.cluster.list("apps/v1", "Deployment", "default")
        assert len(deps) == 2
        assert all(d["status"]["readyReplicas"] == 1 for d in deps)
        svcs = rt.cluster.list("v1", "Service", "default")
        assert len(svcs) >= 2

    def test_cutover_waits_for_cluster_rollout(self):
        rt = Runtime(executor_backend="cluster")
        self._setup_realtime(rt)
        rt.pump()
        sr = [s for s in rt.store.list("StepRun")
              if s.spec["stepId"] == "in"][0]
        # new generation's pods stay unready (probe not passing yet)
        rt.cluster.hold_readiness = True
        self._renegotiate(rt, sr)

        sr = rt.store.get("StepRun", "default", sr.meta.name)
        handoff = sr.status["handoff"]
        assert handoff["newGeneration"] == 2
        assert handoff["phase"] in ("Draining", "CuttingOver")
        dep = rt.store.get("Deployment", "default", f"{sr.meta.name}-rt")
        assert dep.status["observedConnectorGeneration"] == 2
        assert int(dep.status.get("readyGeneration", 1)) < 2

        # rollout completes on the CLUSTER -> watched status flows back
        # -> handoff completes
        rt.cluster.hold_readiness = False
        rt.cluster.mark_ready("Deployment", "default", f"{sr.meta.name}-rt")
        rt.manager.enqueue("steprun", "default", sr.meta.name)
        rt.pump()
        sr = rt.store.get("StepRun", "default", sr.meta.name)
        assert sr.status["handoff"]["phase"] == "Completed"

    def test_warmup_self_completes_cutover(self):
        """Compile/warmup latency on the cluster resolves the handoff
        without any manual poke (timed re-probe path)."""
        rt = Runtime(executor_backend="cluster")
        self._setup_realtime(rt)
        rt.pump()
        sr = [s for s in rt.store.list("StepRun")
              if s.spec["stepId"] == "in"][0]
        rt.cluster.warmup_seconds = 90.0
        self._renegotiate(rt, sr)  # pump advances through warm_at
        sr = rt.store.get("StepRun", "default", sr.meta.name)
        assert sr.status["handoff"]["phase"] == "Completed"
        dep = rt.store.get("Deployment", "default", f"{sr.meta.name}-rt")
        assert int(dep.status["readyGeneration"]) == 2
