"""Manager HTTP plane: health/readiness, token-gated /metrics, and the
observability debug endpoints (/debug/runs/<id>, /debug/traces/<id>).

These routes had no coverage at all (ISSUE 8 satellite): token auth
accept/reject, /healthz green-while-standby vs /readyz not-ready, the
exposition content, and the flight-recorder dumps for live and failed
runs.
"""

from __future__ import annotations

import http.client
import json
from types import SimpleNamespace

import pytest

from bobrapet_tpu.__main__ import _serve_http
from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.core.object import new_resource
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram


def _get(port: int, path: str, token: str | None = None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    try:
        conn.request("GET", path, headers=headers)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


@pytest.fixture
def server_factory():
    servers = []

    def make(state, token=None):
        server = _serve_http(state, "127.0.0.1:0", token)
        servers.append(server)
        return server.server_address[1]

    yield make
    for s in servers:
        s.shutdown()
        s.server_close()


class TestHealthAndAuth:
    def test_standby_replica_health_vs_ready(self, server_factory):
        # rt=None = waiting on leader election: alive but not ready
        port = server_factory({"rt": None})
        assert _get(port, "/healthz")[0] == 200
        assert _get(port, "/readyz")[0] == 503

    def test_ready_when_manager_running(self, server_factory):
        stub = SimpleNamespace(manager=SimpleNamespace(is_running=lambda: True))
        port = server_factory({"rt": stub})
        status, body = _get(port, "/readyz")
        assert (status, body) == (200, b"ok")

    def test_metrics_token_gate(self, server_factory):
        port = server_factory({"rt": None}, token="sekrit")
        assert _get(port, "/metrics")[0] == 403
        assert _get(port, "/metrics", token="wrong")[0] == 403
        status, body = _get(port, "/metrics", token="sekrit")
        assert status == 200
        # exposition content: HELP/TYPE headers + namespaced families
        assert b"# HELP bobrapet_storyrun_total" in body
        assert b"# TYPE bobrapet_storyrun_total counter" in body
        assert b"bobrapet_tracing_dropped_total" in body

    def test_metrics_open_without_token(self, server_factory):
        port = server_factory({"rt": None})
        assert _get(port, "/metrics")[0] == 200

    def test_debug_routes_share_the_token_gate(self, server_factory):
        port = server_factory({"rt": None}, token="sekrit")
        assert _get(port, "/debug/runs/x")[0] == 403
        # authorized but no runtime yet -> not ready, not a 404
        assert _get(port, "/debug/runs/x", token="sekrit")[0] == 503

    def test_unknown_path_404(self, server_factory):
        port = server_factory({"rt": None})
        assert _get(port, "/nope")[0] == 404


class TestDebugEndpoints:
    @pytest.fixture
    def traced_rt(self):
        rt = Runtime()
        rt.tracer.config.enabled = True
        from bobrapet_tpu.observability.tracing import InMemorySpanExporter

        rt.tracer.exporter = InMemorySpanExporter()
        yield rt
        rt.tracer.config.enabled = False

    def _run_story(self, rt, impl_name, fails=False):
        @register_engram(impl_name)
        def impl(ctx):  # noqa: ARG001
            if fails:
                raise RuntimeError("engram exploded")
            return {"ok": True}

        rt.apply(make_engram_template(f"{impl_name}-tpl", entrypoint=impl_name))
        rt.apply(make_engram(f"{impl_name}-worker", f"{impl_name}-tpl"))
        rt.apply(make_story(f"{impl_name}-story", steps=[
            {"name": "s", "ref": {"name": f"{impl_name}-worker"},
             "execution": {"retry": {"maxRetries": 0}}},
        ]))
        run = rt.run_story(f"{impl_name}-story", inputs={})
        rt.pump()
        return run

    def test_live_run_timeline(self, traced_rt, server_factory):
        run = self._run_story(traced_rt, "dbg-live")
        port = server_factory({"rt": traced_rt})
        status, body = _get(port, f"/debug/runs/default/{run}")
        assert status == 200
        payload = json.loads(body)
        assert payload["phase"] == "Succeeded"
        kinds = {r["kind"] for r in payload["timeline"]}
        # the causal story: phase transitions, launches, dispatch, spans
        assert "phase" in kinds
        assert "launch" in kinds
        assert "dispatch" in kinds
        assert "span" in kinds
        # default-namespace shorthand resolves the same run
        assert _get(port, f"/debug/runs/{run}")[0] == 200

    def test_failed_run_explains_itself(self, traced_rt, server_factory):
        run = self._run_story(traced_rt, "dbg-dead", fails=True)
        srun = traced_rt.store.get("StoryRun", "default", run)
        assert srun.status["phase"] == "Failed"
        # terminal-failure forensics attached to status
        forensics = srun.status.get("forensics")
        assert forensics and any(r["kind"] == "error" for r in forensics)
        port = server_factory({"rt": traced_rt})
        status, body = _get(port, f"/debug/runs/default/{run}")
        assert status == 200
        payload = json.loads(body)
        assert payload["phase"] == "Failed"
        assert any(r["kind"] == "error" for r in payload["timeline"])

    def test_trace_route_joins_spans_and_runs(self, traced_rt, server_factory):
        run = self._run_story(traced_rt, "dbg-trace")
        srun = traced_rt.store.get("StoryRun", "default", run)
        tid = srun.status["trace"]["traceId"]
        port = server_factory({"rt": traced_rt})
        status, body = _get(port, f"/debug/traces/{tid}")
        assert status == 200
        payload = json.loads(body)
        assert payload["traceId"] == tid
        names = {s["name"] for s in payload["spans"]}
        assert {"storyrun.run", "dag.reconcile", "step.execute"} <= names
        assert any(r["run"] == run for r in payload["runs"])

    def test_unknown_run_and_trace_404(self, traced_rt, server_factory):
        port = server_factory({"rt": traced_rt})
        assert _get(port, "/debug/runs/default/no-such-run")[0] == 404
        assert _get(port, "/debug/traces/ffffffffffffffff")[0] == 404
        assert _get(port, "/debug/bogus")[0] == 404

    def test_debug_endpoints_config_gate(self, traced_rt, server_factory):
        run = self._run_story(traced_rt, "dbg-gated")
        port = server_factory({"rt": traced_rt})
        assert _get(port, f"/debug/runs/default/{run}")[0] == 200
        # live reload: telemetry.debug-endpoints=false turns them off
        traced_rt.store.create(new_resource(
            "ConfigMap", "operator-config", "bobrapet-system",
            spec={"data": {"telemetry.debug-endpoints": "false"}},
        ))
        assert not traced_rt.config_manager.config.telemetry.debug_endpoints
        assert _get(port, f"/debug/runs/default/{run}")[0] == 404
        # the ISSUE-13 endpoints ride the same gate
        assert _get(port, "/debug/runs")[0] == 404
        assert _get(port, f"/debug/runs/default/{run}/critical-path")[0] == 404
        assert _get(port, "/debug/fleet/utilization")[0] == 404
        assert _get(port, "/debug/profile")[0] == 404
        # /metrics and health stay up regardless
        assert _get(port, "/metrics")[0] == 200
        assert _get(port, "/healthz")[0] == 200


class TestAnalyticsEndpoints:
    """ISSUE 13: the runs list, critical-path, fleet utilization and
    profiler routes — auth, gate, and payload shape."""

    @pytest.fixture
    def rt_with_run(self):
        rt = Runtime()

        @register_engram("an-ep-impl")
        def impl(ctx):  # noqa: ARG001
            return {"ok": True}

        rt.apply(make_engram_template("an-ep-tpl", entrypoint="an-ep-impl"))
        rt.apply(make_engram("an-ep-worker", "an-ep-tpl"))
        rt.apply(make_story("an-ep-story", steps=[
            {"name": "a", "ref": {"name": "an-ep-worker"}},
            {"name": "b", "ref": {"name": "an-ep-worker"}, "needs": ["a"]},
        ]))
        run = rt.run_story("an-ep-story", inputs={})
        rt.pump()
        return rt, run

    def test_new_routes_share_the_token_gate(self, server_factory):
        port = server_factory({"rt": None}, token="sekrit")
        for path in ("/debug/runs", "/debug/fleet/utilization",
                     "/debug/profile"):
            assert _get(port, path)[0] == 403
            assert _get(port, path, token="wrong")[0] == 403

    def test_runs_list(self, rt_with_run, server_factory):
        rt, run = rt_with_run
        port = server_factory({"rt": rt})
        status, body = _get(port, "/debug/runs")
        assert status == 200
        rows = json.loads(body)["runs"]
        row = next(r for r in rows if r["run"] == run)
        assert row["phase"] == "Succeeded"
        assert row["live"] is True
        assert row["durationSeconds"] is not None
        assert row["steps"] == 2

    def test_critical_path_on_completed_run(self, rt_with_run,
                                            server_factory):
        rt, run = rt_with_run
        port = server_factory({"rt": rt})
        status, body = _get(port, f"/debug/runs/default/{run}/critical-path")
        assert status == 200
        payload = json.loads(body)
        assert payload["phase"] == "Succeeded"
        assert set(payload) >= {"wallClockSeconds", "phases", "coverage",
                                "criticalPath", "segments", "spanBreakdown"}
        # the total state machine covers the terminal wall-clock
        assert payload["coverage"] >= 0.95
        assert {c["step"] for c in payload["criticalPath"]} <= {"a", "b"}
        # default-namespace shorthand + unknown run
        assert _get(port, f"/debug/runs/{run}/critical-path")[0] == 200
        assert _get(port, "/debug/runs/default/nope/critical-path")[0] == 404
        # the suffix belongs to the runs routes only — not traces
        assert _get(port, "/debug/traces/abc/critical-path")[0] == 404
        # the compact analysis also rides the run status + debug payload
        full = json.loads(_get(port, f"/debug/runs/default/{run}")[1])
        assert full["analysis"]["criticalPath"]

    def test_utilization_snapshot_shape(self, rt_with_run, server_factory):
        rt, run = rt_with_run
        del run
        port = server_factory({"rt": rt})
        status, body = _get(port, "/debug/fleet/utilization")
        assert status == 200
        payload = json.loads(body)
        assert set(payload) == {"pools", "occupancy", "snapshots", "ledger"}
        pools = {p["pool"] for p in payload["pools"]}
        assert "local" in pools
        for p in payload["pools"]:
            assert set(p) >= {"totalChips", "occupiedChips",
                              "schedulableChips", "cordonedChips",
                              "largestFreeBlock", "fragmentation"}
        assert set(payload["ledger"]) == {"pools", "goodputChipSeconds",
                                          "openGrants", "closedGrants",
                                          "spans"}

    def test_profile_snapshot(self, rt_with_run, server_factory):
        rt, run = rt_with_run
        del run
        port = server_factory({"rt": rt})
        status, body = _get(port, "/debug/profile")
        assert status == 200
        payload = json.loads(body)
        assert payload["running"] is False  # profiler off by default
        assert set(payload) >= {"intervalSeconds", "samples", "topStacks",
                                "threads", "lockWaits", "overheadRatio"}


class TestTrafficEndpoint:
    """ISSUE 14: /debug/traffic — auth, gate, and payload shape."""

    def test_token_gate(self, server_factory):
        port = server_factory({"rt": None}, token="sekrit")
        assert _get(port, "/debug/traffic")[0] == 403
        assert _get(port, "/debug/traffic", token="wrong")[0] == 403

    def test_payload_shape(self, server_factory):
        from bobrapet_tpu.traffic import Autoscaler, EngineReplicaSet
        from bobrapet_tpu.traffic.autoscaler import PoolSignals

        class _FakeRouter:
            """Engine-free router double: the autoscaler only reads
            engines/queue_depths from it here."""

            def __init__(self):
                self.engines = {}

            def queue_depths(self):
                return {"prefill": 0, "decode": 0}

        class _Signals:
            def read(self, pool, replicas, draining):
                return PoolSignals(replicas=replicas, draining=draining)

        router = _FakeRouter()
        rs = EngineReplicaSet("decode", router, lambda: None)
        scaler = Autoscaler({"decode": rs}, signals=_Signals(),
                            interval_s=0.0)
        scaler.tick(now=1.0)
        rt = Runtime()
        port = server_factory({"rt": rt})
        status, body = _get(port, "/debug/traffic")
        assert status == 200
        payload = json.loads(body)
        ours = [s for s in payload["autoscalers"] if "decode" in s["pools"]]
        assert ours
        s = ours[-1]
        assert set(s) >= {"enabled", "intervalSeconds", "policy", "pools",
                          "decisions"}
        assert set(s["pools"]["decode"]) >= {"actual", "draining",
                                             "members", "grants"}
        # keep the weakset from dropping them before the request landed
        del scaler, rs

    def test_config_gate(self, server_factory):
        rt = Runtime()
        port = server_factory({"rt": rt})
        assert _get(port, "/debug/traffic")[0] == 200
        rt.config_manager.config.telemetry.debug_endpoints = False
        assert _get(port, "/debug/traffic")[0] == 404
