"""Workload installed INSIDE each shard manager process.

Engram entrypoints are process-local callables — they cannot travel
through the store — so the process harness imports this module in every
child (``--workload tests.proc_workload:install``) while the parent
applies the matching templates/engrams/stories through the bus
(:func:`apply_resources`). Keep the two halves in one file so the
entrypoint names cannot drift apart.
"""

from __future__ import annotations

import time

ENTRIES = {
    "proc-fast": 0.0,  # latency-free: tier-1 smoke + correctness legs
    "proc-soak": 0.05,  # latency-bound: churn soak + bench scaling legs
}


def install() -> None:
    from bobrapet_tpu.sdk import register_engram

    for entry, sleep_s in ENTRIES.items():
        def impl(ctx, _sleep=sleep_s):
            if _sleep:
                time.sleep(_sleep)
            return {"i": ctx.inputs.get("i", 0)}

        register_engram(entry)(impl)


def apply_resources(cp, entry: str, steps: int = 1) -> str:
    """Parent-side half: template + engram + a ``steps``-deep chain
    story for ``entry``. Returns the story name."""
    from bobrapet_tpu.api.catalog import make_engram_template
    from bobrapet_tpu.api.engram import make_engram
    from bobrapet_tpu.api.story import make_story

    assert entry in ENTRIES, f"unknown workload entry {entry!r}"
    cp.apply(make_engram_template(f"{entry}-tpl", entrypoint=entry))
    cp.apply(make_engram(f"{entry}-worker", f"{entry}-tpl"))
    defs = [{"name": "s0", "ref": {"name": f"{entry}-worker"},
             "with": {"i": "{{ inputs.i }}"}}]
    for i in range(1, steps):
        defs.append({"name": f"s{i}", "ref": {"name": f"{entry}-worker"},
                     "needs": [f"s{i-1}"],
                     "with": {"i": "{{ steps.s%d.output.i }}" % (i - 1)}})
    cp.apply(make_story(f"{entry}-story", steps=defs))
    return f"{entry}-story"
