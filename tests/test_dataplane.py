"""Streaming data plane: hub, credit backpressure, drop policies, acks.

The enforcement tests for the settings language the webhooks admit
(reference semantics: transport_settings_types.go:207-336; the
reference's own hub is out-of-repo, so this suite is the moral
equivalent of its bobravoz integration coverage). Everything runs over
real localhost TCP.
"""

import json
import socket
import threading
import time

import pytest

from bobrapet_tpu.dataplane import (
    FrameError,
    StreamConsumer,
    StreamHub,
    StreamProducer,
    encode_frame,
)
from bobrapet_tpu.dataplane.frames import read_frame, send_frame


def _native_hub_available() -> bool:
    try:
        from bobrapet_tpu.dataplane.native import load_native

        load_native()
        return True
    except Exception:  # noqa: BLE001 - no toolchain
        return False


@pytest.fixture(params=["python", "native"])
def hub(request):
    """Every data-plane scenario runs against BOTH hub engines: the
    Python broker and the C++ event loop (native/streamhub.cc) — same
    wire protocol, same settings semantics."""
    if request.param == "native":
        if not _native_hub_available():
            pytest.skip("no toolchain for the native hub")
        from bobrapet_tpu.dataplane.native import NativeStreamHub

        h = NativeStreamHub()
    else:
        h = StreamHub()
    h.start()
    yield h
    h.stop()


CREDIT_SETTINGS = {
    "flowControl": {
        "mode": "credits",
        "initialCredits": {"messages": 4},
        "ackEvery": {"messages": 1},
    },
    "backpressure": {"buffer": {"maxMessages": 4, "dropPolicy": "block"}},
}


class TestFrames:
    def test_roundtrip(self):
        left, right = socket.socketpair()
        send_frame(left, {"t": "data", "seq": 7}, b"payload")
        header, payload = read_frame(right)
        assert header == {"t": "data", "seq": 7}
        assert payload == b"payload"
        left.close()
        assert read_frame(right) is None  # clean EOF

    def test_oversized_rejected(self):
        with pytest.raises(FrameError):
            encode_frame({"t": "data"}, b"x" * (65 * 1024 * 1024))


class TestBasicDelivery:
    def test_produce_then_consume(self, hub):
        p = StreamProducer(hub.endpoint, "ns/run/step")
        for i in range(5):
            p.send({"i": i})
        p.close()
        c = StreamConsumer(hub.endpoint, "ns/run/step", decode_json=True)
        got = list(c)
        assert got == [{"i": i} for i in range(5)]

    def test_live_fanout_to_attached_consumer(self, hub):
        c = StreamConsumer(hub.endpoint, "ns/run/live", decode_json=True)
        received = []
        done = threading.Event()

        def drain():
            for msg in c:
                received.append(msg)
            done.set()

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        p = StreamProducer(hub.endpoint, "ns/run/live")
        for i in range(8):
            p.send({"i": i})
        p.close()
        assert done.wait(10)
        assert received == [{"i": i} for i in range(8)]

    def test_binary_payload(self, hub):
        p = StreamProducer(hub.endpoint, "ns/run/bin")
        p.send(b"\x00\x01\xff" * 1000)
        p.close()
        c = StreamConsumer(hub.endpoint, "ns/run/bin")
        assert list(c) == [b"\x00\x01\xff" * 1000]


class TestCreditBackpressure:
    def test_producer_blocks_on_full_buffer(self, hub):
        """BASELINE config 4 shape: with nobody draining, the window
        (4 credits / 4 buffer slots) exhausts and send() blocks — the
        drops/pauses-under-full-buffer half of the backpressure
        contract."""
        p = StreamProducer(hub.endpoint, "ns/run/bp", settings=CREDIT_SETTINGS)
        for i in range(4):
            p.send({"i": i})
        with pytest.raises(TimeoutError, match="backpressured"):
            p.send({"i": 99}, timeout=0.3)
        assert p.credits == 0

    def test_producer_resumes_on_credit(self, hub):
        """...and the resumes-on-credit half: a consumer draining (and
        acking) frees buffer, the hub replenishes, the blocked send
        completes."""
        p = StreamProducer(hub.endpoint, "ns/run/bp2", settings=CREDIT_SETTINGS)
        for i in range(4):
            p.send({"i": i})
        unblocked = threading.Event()
        sent_late = []

        def late_send():
            p.send({"i": "late"}, timeout=15)
            sent_late.append(True)
            unblocked.set()

        t = threading.Thread(target=late_send, daemon=True)
        t.start()
        time.sleep(0.2)
        assert not unblocked.is_set()  # still blocked, nobody drained
        c = StreamConsumer(hub.endpoint, "ns/run/bp2",
                           settings=CREDIT_SETTINGS, decode_json=True)
        got = []
        for msg in c:
            got.append(msg)
            if len(got) == 5:
                break
        assert unblocked.wait(10), "producer never resumed after drain"
        assert {"i": "late"} in got or len(got) == 5

    def test_sending_without_credit_is_rejected(self, hub):
        """A producer that ignores the credit window is a protocol
        violation the hub refuses (not silent data loss)."""
        raw = socket.create_connection(("127.0.0.1", hub.port), timeout=5)
        send_frame(raw, {"t": "hello", "role": "producer", "stream": "ns/r/x",
                         "settings": CREDIT_SETTINGS})
        header, _ = read_frame(raw)
        assert header["t"] == "ok" and header["credits"] == 4
        for _ in range(5):  # one more than granted
            send_frame(raw, {"t": "data"}, b"{}")
        # hub answers the over-budget frame with an error
        deadline = time.monotonic() + 5
        got_err = False
        while time.monotonic() < deadline:
            fr = read_frame(raw)
            if fr is None:
                break
            if fr[0].get("t") == "err":
                got_err = True
                break
        assert got_err
        raw.close()


class TestDropPolicies:
    def _send_n(self, hub, stream, n, policy, buf=4):
        settings = {"backpressure": {"buffer": {
            "maxMessages": buf, "dropPolicy": policy}}}
        p = StreamProducer(hub.endpoint, stream, settings=settings)
        for i in range(n):
            p.send({"i": i})
        time.sleep(0.2)  # let the hub's reader drain the socket
        p.close()
        c = StreamConsumer(hub.endpoint, stream, decode_json=True)
        return [m["i"] for m in c]

    def test_drop_oldest_keeps_tail(self, hub):
        assert self._send_n(hub, "ns/r/do", 10, "dropOldest") == [6, 7, 8, 9]

    def test_drop_newest_keeps_head(self, hub):
        assert self._send_n(hub, "ns/r/dn", 10, "dropNewest") == [0, 1, 2, 3]

    def test_drop_metrics_recorded(self, hub):
        from bobrapet_tpu.dataplane.hub import StreamHub
        from bobrapet_tpu.observability.metrics import metrics

        before = metrics.stream_dropped.value("dropOldest")
        # keep the stream alive past _send_n's consumer so native stats
        # remain queryable
        if isinstance(hub, StreamHub):
            self._send_n(hub, "ns/r/dm", 10, "dropOldest")
            assert metrics.stream_dropped.value("dropOldest") >= before + 6
        else:
            # the native engine counts drops in its own stats (Python
            # metrics live in the Python broker's process space)
            settings = {"backpressure": {"buffer": {
                "maxMessages": 4, "dropPolicy": "dropOldest"}}}
            p = StreamProducer(hub.endpoint, "ns/r/dm", settings=settings)
            for i in range(10):
                p.send({"i": i})
            time.sleep(0.3)
            assert hub.stream_stats("ns/r/dm")["dropped"] >= 6
            p.close()


class TestAtLeastOnce:
    SETTINGS = {
        "flowControl": {"mode": "credits",
                        "initialCredits": {"messages": 64},
                        "ackEvery": {"messages": 1}},
        "delivery": {"semantics": "atLeastOnce"},
        "backpressure": {"buffer": {"maxMessages": 64}},
    }

    def test_unacked_redelivered_on_reconnect(self, hub):
        p = StreamProducer(hub.endpoint, "ns/r/alo", settings=self.SETTINGS)
        for i in range(6):
            p.send({"i": i})

        # consumer 1 reads three, acks them, then dies
        raw = socket.create_connection(("127.0.0.1", hub.port), timeout=5)
        send_frame(raw, {"t": "hello", "role": "consumer", "stream": "ns/r/alo"})
        assert read_frame(raw)[0]["t"] == "ok"
        last = -1
        for _ in range(3):
            header, payload = read_frame(raw)
            assert header["t"] == "data"
            last = header["seq"]
        send_frame(raw, {"t": "ack", "seq": last})
        time.sleep(0.2)
        raw.close()
        p.close()

        # consumer 2 sees only the unacked remainder
        c = StreamConsumer(hub.endpoint, "ns/r/alo",
                           settings=self.SETTINGS, decode_json=True)
        assert [m["i"] for m in c] == [3, 4, 5]

    def test_at_most_once_no_redelivery(self, hub):
        p = StreamProducer(hub.endpoint, "ns/r/amo")
        c1 = StreamConsumer(hub.endpoint, "ns/r/amo", decode_json=True)
        got1 = []
        done = threading.Event()

        def drain():
            for m in c1:
                got1.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        for i in range(4):
            p.send({"i": i})
        p.close()
        assert done.wait(10)
        assert got1 == [{"i": i} for i in range(4)]
        # a second consumer gets nothing: delivery already happened
        c2 = StreamConsumer(hub.endpoint, "ns/r/amo", decode_json=True)
        assert list(c2) == []


class TestHysteresis:
    def test_pause_resume_thresholds(self, hub):
        """Credits stop at pause%, restart only below resume% — the
        grant decision must not flap around one boundary."""
        settings = {
            "flowControl": {
                "mode": "credits",
                "initialCredits": {"messages": 8},
                "ackEvery": {"messages": 1},
                "pauseThreshold": {"bufferPct": 75},
                "resumeThreshold": {"bufferPct": 25},
            },
            "backpressure": {"buffer": {"maxMessages": 8}},
        }
        p = StreamProducer(hub.endpoint, "ns/r/hyst", settings=settings)
        for i in range(8):
            p.send({"i": i})
        # buffer 100% > pause 75% -> no credit; send blocks
        with pytest.raises(TimeoutError):
            p.send({"i": "x"}, timeout=0.3)
        st = hub.stream_stats("ns/r/hyst")
        assert st["paused"] is True

    def test_sdk_context_streams_over_localhost(self, hub):
        """SDK surface end-to-end (BASELINE config 4 shape): producer
        engram ctx streams to the hub via downstream targets, consumer
        engram ctx subscribes, backpressure settings ride along."""
        from bobrapet_tpu.sdk import contract
        from bobrapet_tpu.sdk.context import EngramContext

        targets = [{"grpc": {"host": "127.0.0.1", "port": hub.port,
                             "stepName": "sink"}}]
        prod_env = {
            contract.ENV_NAMESPACE: "default",
            contract.ENV_STORY_RUN: "r1",
            contract.ENV_STEP: "source",
            contract.ENV_DOWNSTREAM_TARGETS: json.dumps(targets),
        }
        cons_env = {
            contract.ENV_NAMESPACE: "default",
            contract.ENV_STORY_RUN: "r1",
            contract.ENV_STEP: "sink",
        }
        producer_ctx = EngramContext(prod_env)
        consumer_ctx = EngramContext(cons_env)

        outs = producer_ctx.open_output_streams(settings=CREDIT_SETTINGS)
        assert len(outs) == 1
        received = []
        done = threading.Event()

        def consume():
            stream = consumer_ctx.open_input_stream(
                hub.endpoint, settings=CREDIT_SETTINGS)
            for msg in stream:
                received.append(msg)
            done.set()

        threading.Thread(target=consume, daemon=True).start()
        for i in range(10):
            outs[0].send({"frame": i}, timeout=10)
        outs[0].close()
        assert done.wait(10)
        assert received == [{"frame": i} for i in range(10)]


class TestFanIn:
    def test_last_producer_eos_ends_stream(self, hub):
        """Fan-in (merge): two producers share the consumer-named
        stream; the first eos must NOT cut off the second producer."""
        pa = StreamProducer(hub.endpoint, "ns/r/fanin")
        pb = StreamProducer(hub.endpoint, "ns/r/fanin")
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/fanin", decode_json=True)
            for m in c:
                received.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        pa.send({"from": "a", "i": 0})
        pa.close()  # A finishes first
        time.sleep(0.2)
        assert not done.is_set(), "stream ended while producer B was live"
        pb.send({"from": "b", "i": 1})
        pb.close()
        assert done.wait(10)
        assert {"from": "a", "i": 0} in received
        assert {"from": "b", "i": 1} in received

    def test_credit_window_is_per_stream(self, hub):
        """Multiple producers may not jointly hold more credits than
        the buffer has slots (lossless backpressure across fan-in)."""
        settings = {
            "flowControl": {"mode": "credits",
                            "initialCredits": {"messages": 8},
                            "ackEvery": {"messages": 1}},
            "backpressure": {"buffer": {"maxMessages": 8,
                                        "dropPolicy": "block"}},
        }
        pa = StreamProducer(hub.endpoint, "ns/r/joint", settings=settings)
        pb = StreamProducer(hub.endpoint, "ns/r/joint", settings=settings)
        assert pa.credits + pb.credits <= 8
        # drain each producer's window in turn: jointly they can send at
        # most 8 (the buffer size) before both block
        sent = 0
        for p in (pa, pb):
            try:
                for _ in range(10):
                    p.send({"i": sent}, timeout=0.3)
                    sent += 1
            except TimeoutError:
                pass
        assert sent <= 8, f"joint window leaked: {sent} sends succeeded"
        assert sent >= 1


class TestReviewRegressions:
    def test_eos_not_deadlocked_by_partial_ack(self, hub):
        """atLeastOnce with ackEvery > 1: a tail shorter than the ack
        cadence must still see eos (the hub doesn't gate eos on a fully
        drained buffer)."""
        settings = {
            "flowControl": {"mode": "credits",
                            "initialCredits": {"messages": 16},
                            "ackEvery": {"messages": 5}},
            "delivery": {"semantics": "atLeastOnce"},
            "backpressure": {"buffer": {"maxMessages": 16}},
        }
        p = StreamProducer(hub.endpoint, "ns/r/partial", settings=settings)
        for i in range(7):  # 7 % 5 != 0 -> tail never hits the cadence
            p.send({"i": i})
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/partial",
                               settings=settings, decode_json=True)
            for m in c:
                received.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        p.close()
        assert done.wait(10), "consumer hung waiting for eos"
        assert [m["i"] for m in received] == list(range(7))

    def test_truncated_stream_raises_not_clean_eof(self, hub):
        """A hub death mid-stream must surface as StreamClosed, never a
        clean end-of-stream (silent partial data)."""
        from bobrapet_tpu.dataplane import StreamClosed

        p = StreamProducer(hub.endpoint, "ns/r/trunc")
        p.send({"i": 0})
        c = StreamConsumer(hub.endpoint, "ns/r/trunc", decode_json=True)
        it = iter(c)
        assert next(it) == {"i": 0}
        hub.stop()  # kills the consumer's socket without an eos frame
        with pytest.raises(StreamClosed):
            next(it)

    def test_ack_rides_behind_consumption(self, hub):
        """atLeastOnce: the ack covering a message goes out only after
        the application consumed it — a crash mid-processing leaves the
        message redeliverable."""
        settings = dict(TestAtLeastOnce.SETTINGS)
        p = StreamProducer(hub.endpoint, "ns/r/lag", settings=settings)
        for i in range(3):
            p.send({"i": i})
        time.sleep(0.2)
        c = StreamConsumer(hub.endpoint, "ns/r/lag",
                           settings=settings, decode_json=True)
        it = iter(c)
        first = next(it)  # delivered but NOT yet acked (ack on resume)
        assert first == {"i": 0}
        time.sleep(0.2)
        assert hub.stream_stats("ns/r/lag")["acked"] == -1
        c.close()  # crash before processing completes
        p.close(eos=False)
        c2 = StreamConsumer(hub.endpoint, "ns/r/lag",
                            settings=settings, decode_json=True)
        redelivered = []
        for m in c2:
            redelivered.append(m)
            if len(redelivered) == 3:
                break
        assert redelivered[0] == {"i": 0}  # message 0 was redelivered

    def test_finished_streams_reclaimed(self, hub):
        """A fully consumed stream disappears from the hub's table
        (long-lived hubs must not leak per-run state)."""
        p = StreamProducer(hub.endpoint, "ns/r/gc")
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/gc", decode_json=True)
            for m in c:
                received.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        p.send({"i": 1})
        p.close()
        assert done.wait(10)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and hub.stream_stats("ns/r/gc"):
            time.sleep(0.05)
        assert hub.stream_stats("ns/r/gc") == {}

    def test_late_consumer_after_gc_gets_clean_eos(self, hub):
        """Re-attaching to a fully-consumed, reclaimed stream must end
        cleanly (tombstone eos), not hang on a fresh empty stream."""
        p = StreamProducer(hub.endpoint, "ns/r/late")
        done = threading.Event()

        def drain():
            list(StreamConsumer(hub.endpoint, "ns/r/late"))
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        p.send(b"x")
        p.close()
        assert done.wait(10)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and hub.stream_stats("ns/r/late"):
            time.sleep(0.05)
        # the stream is gone; a late consumer still terminates
        late = list(StreamConsumer(hub.endpoint, "ns/r/late"))
        assert late == []

    def test_producer_reopens_ended_stream(self, hub):
        """A redriven producer step reuses its stream name: attaching a
        producer clears the ended state so new data flows."""
        p1 = StreamProducer(hub.endpoint, "ns/r/redrive")
        list_done = threading.Event()

        def drain1():
            list(StreamConsumer(hub.endpoint, "ns/r/redrive"))
            list_done.set()

        threading.Thread(target=drain1, daemon=True).start()
        p1.send(b"first")
        p1.close()
        assert list_done.wait(10)
        p2 = StreamProducer(hub.endpoint, "ns/r/redrive")
        p2.send(b"second")
        p2.close()
        got = list(StreamConsumer(hub.endpoint, "ns/r/redrive"))
        assert got == [b"second"]

    def test_non_bmp_key_survives(self, hub):
        """json.dumps ensure_ascii emits non-BMP keys as UTF-16
        surrogate pairs — both engines must round them through without
        corrupting the rebuilt data header."""
        p = StreamProducer(hub.endpoint, "ns/r/emoji")
        p.send({"v": 1}, key="party-\U0001F389")
        p.close()
        got = list(StreamConsumer(hub.endpoint, "ns/r/emoji", decode_json=True))
        assert got == [{"v": 1}]


class TestBatchedWriters:
    """PR 2 fast path: writer threads drain whole queues per wakeup and
    flush vectored/joined batches of ONCE-encoded frames. Batching must
    be invisible to the protocol: per-consumer order, replay semantics,
    and the credit window are unchanged."""

    def test_slow_consumer_preserves_order_under_batching(self, hub):
        """A slow consumer forces deep writer queues (real batches);
        every frame still arrives exactly once, in seq order."""
        n = 400
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/slowb", decode_json=True)
            for i, m in enumerate(c):
                if i % 50 == 0:
                    time.sleep(0.05)  # fall behind; queue builds up
                received.append(m)
            done.set()

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        time.sleep(0.2)
        p = StreamProducer(hub.endpoint, "ns/r/slowb")
        for i in range(n):
            p.send({"i": i})
        p.close()
        assert done.wait(60)
        assert [m["i"] for m in received] == list(range(n))

    def test_two_consumers_one_slow_both_complete_in_order(self, hub):
        """Fan-out shares one encoded frame across queues; a slow
        consumer must not reorder or starve the fast one."""
        n = 300
        results = {"fast": [], "slow": []}
        done = {k: threading.Event() for k in results}

        def drain(name, delay):
            c = StreamConsumer(hub.endpoint, "ns/r/fan2", decode_json=True)
            for i, m in enumerate(c):
                if delay and i % 40 == 0:
                    time.sleep(0.05)
                results[name].append(m["i"])
            done[name].set()

        threading.Thread(target=drain, args=("fast", 0), daemon=True).start()
        threading.Thread(target=drain, args=("slow", 1), daemon=True).start()
        time.sleep(0.2)
        p = StreamProducer(hub.endpoint, "ns/r/fan2")
        for i in range(n):
            p.send({"i": i})
        p.close()
        assert done["fast"].wait(60) and done["slow"].wait(60)
        assert results["fast"] == list(range(n))
        assert results["slow"] == list(range(n))

    def test_consumer_conn_drains_queue_on_close(self):
        """Satellite: close() is drain-then-exit — frames enqueued
        before close are flushed, never silently dropped, and the
        writer thread terminates deterministically."""
        import socket as _socket

        from bobrapet_tpu.dataplane.frames import FrameReader
        from bobrapet_tpu.dataplane.hub import _ConsumerConn

        left, right = _socket.socketpair()
        conn = _ConsumerConn(left, stream=None)
        for i in range(10):
            conn.enqueue(encode_frame({"t": "data", "seq": i}, b"x"), True)
        conn.close()  # BEFORE the writer even started
        w = threading.Thread(target=conn.writer_loop, daemon=True)
        w.start()
        w.join(timeout=5.0)
        assert not w.is_alive(), "writer did not exit after close"
        left.close()
        reader = FrameReader(right)
        seqs = []
        while True:
            fr = reader.read()
            if fr is None:
                break
            seqs.append(fr[0]["seq"])
        right.close()
        assert seqs == list(range(10))
        # post-close enqueue is a (logged) no-op, not a hang or a crash
        conn.enqueue(encode_frame({"t": "data", "seq": 99}, b"x"), True)

    def test_producer_conn_close_uses_notify_all(self):
        """close() must wake the writer even when another waiter exists
        (notify_all, not notify) — and drain queued control frames."""
        import socket as _socket

        from bobrapet_tpu.dataplane.frames import FrameReader
        from bobrapet_tpu.dataplane.hub import _ProducerConn

        left, right = _socket.socketpair()
        conn = _ProducerConn(left, stream=None)
        w = threading.Thread(target=conn.writer_loop, daemon=True)
        w.start()
        conn.enqueue({"t": "credit", "n": 3})
        conn.enqueue({"t": "credit", "n": 4})
        conn.close()
        w.join(timeout=5.0)
        assert not w.is_alive()
        left.close()
        reader = FrameReader(right)
        grants = 0
        while True:
            fr = reader.read()
            if fr is None:
                break
            assert fr[0]["t"] == "credit"
            grants += fr[0]["n"]
        right.close()
        # coalescing may merge the two frames; the TOTAL is invariant
        assert grants == 7

    def test_credit_window_semantics_survive_coalescing(self, hub):
        """With coalesce-acks on (default), a drained producer gets its
        full window back — merged credit frames must sum, not drop."""
        p = StreamProducer(hub.endpoint, "ns/r/ccoal", settings=CREDIT_SETTINGS)
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/ccoal",
                               settings=CREDIT_SETTINGS, decode_json=True)
            for m in c:
                received.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        for i in range(32):  # 8 full windows; replenish rides acks
            p.send({"i": i}, timeout=30)
        p.close()
        assert done.wait(30)
        assert [m["i"] for m in received] == list(range(32))

    def test_batched_replay_from_checkpoint_resumes_exactly(self):
        """Replay-from-checkpoint semantics are batch-invariant: a slow
        consumer that acked through seq N, detached, and reattaches
        with the same consumerId resumes at N+1."""
        from bobrapet_tpu.dataplane import StreamHub, StreamRecorder
        from bobrapet_tpu.storage.store import MemoryStore

        settings = dict(TestFromCheckpointReplay.CKPT)
        store = MemoryStore()
        hub = StreamHub(recorder=StreamRecorder(store))
        hub.start()
        try:
            p = StreamProducer(hub.endpoint, "ns/r/ckb", settings=settings)
            for i in range(20):
                p.send({"i": i})
            c1 = StreamConsumer(hub.endpoint, "ns/r/ckb", settings=settings,
                                decode_json=True, consumer_id="w")
            it = iter(c1)
            got1 = []
            for _ in range(8):
                got1.append(next(it))
                time.sleep(0.01)  # slow consumer: hub queues batch up
            c1.ack()
            time.sleep(0.3)  # checkpoint persists (interval 0s)
            c1.close()
            p.close()
            c2 = StreamConsumer(hub.endpoint, "ns/r/ckb", settings=settings,
                                decode_json=True, consumer_id="w")
            got2 = [m["i"] for m in c2]
            assert [m["i"] for m in got1] == list(range(8))
            assert got2 == list(range(8, 20))
        finally:
            hub.stop()

    def test_tuning_live_reload(self):
        """dataplane.* knobs reload like PR 1's controller keys: the
        parsed config lands in HUB_TUNING, which writers read at drain
        time."""
        from bobrapet_tpu.config.operator import parse_config
        from bobrapet_tpu.dataplane.hub import HUB_TUNING, apply_tuning

        before = (HUB_TUNING.writer_max_batch, HUB_TUNING.coalesce_acks)
        try:
            cfg = parse_config({"dataplane.writer-max-batch": "16",
                                "dataplane.coalesce-acks": "false"})
            assert cfg.dataplane.writer_max_batch == 16
            assert cfg.dataplane.coalesce_acks is False
            apply_tuning(cfg.dataplane)
            assert HUB_TUNING.writer_max_batch == 16
            assert HUB_TUNING.coalesce_acks is False
        finally:
            HUB_TUNING.writer_max_batch, HUB_TUNING.coalesce_acks = before

    def test_watermark_behind_last_frame_does_not_defer_ack_forever(self, hub):
        """Regression: a watermark frame enqueued behind the final data
        frame left the deferred cumulative ack pending forever — the
        producer's credit replenish rides on acks, so a credit-windowed
        producer deadlocked. The flush must run after ANY frame type
        once the local buffer runs dry."""
        settings = {
            "flowControl": {"mode": "credits",
                            "initialCredits": {"messages": 4},
                            "ackEvery": {"messages": 1}},
            "backpressure": {"buffer": {"maxMessages": 4,
                                        "dropPolicy": "block"}},
            "observability": {"watermark": {"enabled": True}},
        }
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/wmack",
                               settings=settings, decode_json=True)
            for m in c:
                received.append(m["i"])
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        time.sleep(0.2)
        p = StreamProducer(hub.endpoint, "ns/r/wmack", settings=settings)
        # 12 sends through a 4-credit window: progress REQUIRES acks to
        # keep flowing even though every data frame is chased by a
        # watermark frame in the consumer's buffer
        for i in range(12):
            p.send({"i": i}, event_time_ms=1000 * (i + 1), timeout=20)
        p.close()
        assert done.wait(30), "credit window starved: ack was deferred forever"
        assert received == list(range(12))

    def test_stream_stats_report_throughput(self, hub):
        from bobrapet_tpu.dataplane.hub import StreamHub

        if not isinstance(hub, StreamHub):
            pytest.skip("per-stream throughput stats are a python-hub field")
        p = StreamProducer(hub.endpoint, "ns/r/tput")
        received = []
        done = threading.Event()

        def drain():
            c = StreamConsumer(hub.endpoint, "ns/r/tput", decode_json=True)
            for m in c:
                received.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        time.sleep(0.2)
        for i in range(25):
            p.send({"i": i})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            st = hub.stream_stats("ns/r/tput")
            if st.get("deliveredFrames", 0) >= 25:
                break
            time.sleep(0.05)
        st = hub.stream_stats("ns/r/tput")
        assert st["deliveredFrames"] == 25
        assert st["deliveredBytes"] > 0
        assert st["framesPerSec"] > 0
        p.close()
        assert done.wait(10)


class TestReplay:
    """delivery.replay.mode=full (VERDICT r2 #7): the hub retains
    history (bounded by retentionSeconds) and a consumer can rejoin at
    ``fromSeq``, re-reading entries that were already acked away."""

    SETTINGS = {
        "flowControl": {"mode": "credits",
                        "initialCredits": {"messages": 64},
                        "ackEvery": {"messages": 1}},
        "delivery": {"semantics": "atLeastOnce",
                     "replay": {"mode": "full", "retentionSeconds": 3600}},
        "backpressure": {"buffer": {"maxMessages": 64}},
    }

    def test_rejoin_at_from_seq_re_reads_acked_history(self, hub):
        p = StreamProducer(hub.endpoint, "ns/r/replay", settings=self.SETTINGS)
        for i in range(8):
            p.send({"i": i})

        # consumer 1 reads and ACKS everything, then the stream ends
        c1 = StreamConsumer(hub.endpoint, "ns/r/replay",
                            settings=self.SETTINGS, decode_json=True)
        got1 = []
        t = threading.Thread(target=lambda: got1.extend(c1), daemon=True)
        t.start()
        time.sleep(0.3)
        p.close()
        t.join(5)
        assert [m["i"] for m in got1] == list(range(8))

        # a replay consumer rejoins at seq 3: acked entries come back
        c2 = StreamConsumer(hub.endpoint, "ns/r/replay",
                            settings=self.SETTINGS, decode_json=True,
                            from_seq=3)
        assert [m["i"] for m in c2] == [3, 4, 5, 6, 7]

    def test_from_seq_zero_replays_everything(self, hub):
        p = StreamProducer(hub.endpoint, "ns/r/replay0", settings=self.SETTINGS)
        for i in range(4):
            p.send({"i": i})
        c1 = StreamConsumer(hub.endpoint, "ns/r/replay0",
                            settings=self.SETTINGS, decode_json=True)
        got = []
        t = threading.Thread(target=lambda: got.extend(c1), daemon=True)
        t.start()
        time.sleep(0.3)
        p.close()
        t.join(5)
        c2 = StreamConsumer(hub.endpoint, "ns/r/replay0",
                            settings=self.SETTINGS, decode_json=True,
                            from_seq=0)
        assert [m["i"] for m in c2] == [0, 1, 2, 3]

    def test_without_replay_from_seq_is_ignored(self, hub):
        """fromSeq on a stream without replay falls back to the normal
        backlog attach (no history exists to serve)."""
        p = StreamProducer(hub.endpoint, "ns/r/noreplay")
        p.send({"i": 0})
        p.close()
        c = StreamConsumer(hub.endpoint, "ns/r/noreplay", decode_json=True,
                           from_seq=0)
        assert [m["i"] for m in c] == [0]


# ---------------------------------------------------------------------------
# TLS (VERDICT r2 #4): shared-CA mutual TLS on the hub data plane
# ---------------------------------------------------------------------------


def _make_ca(tmp_path, name: str):
    """Shared-CA material via the in-tree dev generator (one layout
    for tests, bench, and docs). Needs the cryptography package; on
    images without it the TLS capability cannot run — skip, not fail."""
    pytest.importorskip("cryptography")
    from bobrapet_tpu.dataplane.tls import generate_dev_ca

    return generate_dev_ca(str(tmp_path), name)


@pytest.fixture(params=["python-off", "python-on", "native-off", "native-on"])
def tls_hub(request, tmp_path):
    """Every (engine x TLS) combination; yields (hub, client_tls).
    Native+TLS runs the C++ engine behind the TLS frontend
    (dataplane/tlsfront.py) — mTLS no longer forfeits the native data
    path."""
    from bobrapet_tpu.dataplane import StreamHub
    from bobrapet_tpu.dataplane.native import NativeStreamHub

    engine, mode = request.param.split("-")
    if engine == "native" and not _native_hub_available():
        pytest.skip("native hub unavailable (no toolchain)")
    tls_dir = _make_ca(tmp_path, "shared") if mode == "on" else None
    if engine == "native":
        hub = NativeStreamHub(tls=tls_dir)
    else:
        hub = StreamHub(tls=tls_dir)
    hub.start()
    yield hub, tls_dir
    hub.stop()


class TestTLS:
    def test_roundtrip_with_and_without_tls(self, tls_hub):
        hub, tls = tls_hub
        p = StreamProducer(hub.endpoint, "ns/r/tls", tls=tls)
        for i in range(3):
            p.send({"i": i})
        p.close()
        c = StreamConsumer(hub.endpoint, "ns/r/tls", decode_json=True, tls=tls)
        assert [m["i"] for m in c] == [0, 1, 2]

    def test_wrong_ca_rejected(self, tmp_path):
        import ssl

        from bobrapet_tpu.dataplane import StreamHub, StreamProtocolError

        right = _make_ca(tmp_path, "right")
        wrong = _make_ca(tmp_path, "wrong")
        hub = StreamHub(tls=right)
        hub.start()
        try:
            with pytest.raises((ssl.SSLError, OSError, StreamProtocolError)):
                StreamProducer(hub.endpoint, "ns/r/bad", tls=wrong,
                               connect_timeout=3.0)
        finally:
            hub.stop()

    def test_plaintext_client_rejected_by_tls_hub(self, tmp_path):
        from bobrapet_tpu.dataplane import StreamHub, StreamProtocolError
        from bobrapet_tpu.dataplane.client import StreamClosed

        tls_dir = _make_ca(tmp_path, "shared2")
        hub = StreamHub(tls=tls_dir)
        hub.start()
        try:
            with pytest.raises((StreamProtocolError, StreamClosed, OSError,
                                FrameError)):
                StreamProducer(hub.endpoint, "ns/r/plain", connect_timeout=3.0)
        finally:
            hub.stop()

    def test_make_hub_keeps_native_under_tls(self, tmp_path):
        """mTLS no longer forfeits the native engine: the factory
        returns the C++ hub behind a TLS frontend (falling back to the
        Python hub only when the toolchain is absent)."""
        from bobrapet_tpu.dataplane import StreamHub, make_hub
        from bobrapet_tpu.dataplane.native import NativeStreamHub

        tls_dir = _make_ca(tmp_path, "shared3")
        h = make_hub(tls=tls_dir, prefer_native=True)
        if _native_hub_available():
            assert isinstance(h, NativeStreamHub)
            # round-trip through the frontend proves the splice
            h.start()
            try:
                p = StreamProducer(h.endpoint, "ns/r/nt", tls=tls_dir)
                p.send({"i": 1})
                p.close()
                c = StreamConsumer(h.endpoint, "ns/r/nt", decode_json=True,
                                   tls=tls_dir)
                assert [m["i"] for m in c] == [1]
            finally:
                h.stop()
        else:
            assert isinstance(h, StreamHub)

    def test_native_hub_terminates_tls_in_engine(self, tmp_path):
        """VERDICT r4 weak #3: mTLS terminates INSIDE the C++ poll loop
        (OpenSSL via dlopen), not through the Python frontend — and a
        sustained burst survives the WANT_WRITE retry and per-thread
        error-queue pitfalls that only show up under load."""
        from bobrapet_tpu.dataplane.native import NativeStreamHub

        if not _native_hub_available():
            pytest.skip("native hub unavailable")
        tls_dir = _make_ca(tmp_path, "native-term")
        hub = NativeStreamHub(tls=tls_dir)
        hub.start()
        try:
            if hub.tls_mode != "native":
                pytest.skip("OpenSSL runtime not loadable by the engine")
            assert hub._frontend is None
            got = []
            done = threading.Event()
            c = StreamConsumer(hub.endpoint, "ns/r/ntls", tls=tls_dir)

            def drain():
                for m in c:
                    got.append(m)
                done.set()

            threading.Thread(target=drain, daemon=True).start()
            p = StreamProducer(hub.endpoint, "ns/r/ntls", tls=tls_dir)
            n = 3000
            payload = b"y" * 256
            for _ in range(n):
                p.send(payload)
            p.close()
            assert done.wait(60)
            assert len(got) == n
            assert all(m == payload for m in got[:5])
        finally:
            hub.stop()

    def test_tls_client_works_beyond_fd_setsize(self, tmp_path):
        """The client's TLS wait uses select.poll, not select.select:
        with >1024 fds open, select raises ValueError — and swallowing
        it turned the wait loop into a busy spin (r5 review finding)."""
        import os as _os
        import resource

        from bobrapet_tpu.dataplane.native import NativeStreamHub

        if not _native_hub_available():
            pytest.skip("native hub unavailable")
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < 1400:
            # try raising toward the hard limit; skip (not error) on
            # boxes that cap below what the scenario needs
            try:
                resource.setrlimit(resource.RLIMIT_NOFILE,
                                   (min(4096, hard), hard))
            except (ValueError, OSError):
                pass
            soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
            if soft < 1400:
                pytest.skip(f"RLIMIT_NOFILE soft={soft} too low for the "
                            "beyond-FD_SETSIZE scenario")
        tls_dir = _make_ca(tmp_path, "bigfd")
        hub = NativeStreamHub(tls=tls_dir)
        hub.start()
        pipes = []
        try:
            # push the next fd numbers past FD_SETSIZE
            while True:
                r, w = _os.pipe()
                pipes.append((r, w))
                if w > 1100:
                    break
            p = StreamProducer(hub.endpoint, "ns/r/bigfd", tls=tls_dir)
            assert p._sock.fileno() > 1024
            got = []
            done = threading.Event()
            c = StreamConsumer(hub.endpoint, "ns/r/bigfd", tls=tls_dir)
            assert c._sock.fileno() > 1024

            def drain():
                for m in c:
                    got.append(m)
                done.set()

            threading.Thread(target=drain, daemon=True).start()
            for i in range(50):
                p.send(b"fd-%d" % i)
            p.close()
            assert done.wait(30)
            assert len(got) == 50
        finally:
            for r, w in pipes:
                _os.close(r)
                _os.close(w)
            hub.stop()

    def test_native_tls_rejects_wrong_ca_and_plaintext(self, tmp_path):
        import ssl as _ssl

        from bobrapet_tpu.dataplane import StreamProtocolError
        from bobrapet_tpu.dataplane.client import StreamClosed
        from bobrapet_tpu.dataplane.native import NativeStreamHub

        if not _native_hub_available():
            pytest.skip("native hub unavailable")
        right = _make_ca(tmp_path, "right-n")
        wrong = _make_ca(tmp_path, "wrong-n")
        hub = NativeStreamHub(tls=right)
        hub.start()
        try:
            with pytest.raises((_ssl.SSLError, OSError, StreamProtocolError)):
                StreamProducer(hub.endpoint, "ns/r/nbad", tls=wrong,
                               connect_timeout=3.0)
            with pytest.raises((StreamProtocolError, StreamClosed, OSError,
                                FrameError)):
                StreamProducer(hub.endpoint, "ns/r/nplain",
                               connect_timeout=3.0)
        finally:
            hub.stop()

    def test_tls_paths_from_env_contract(self, tmp_path):
        from bobrapet_tpu.dataplane import TLSPaths
        from bobrapet_tpu.sdk import contract

        paths = TLSPaths.from_env({contract.ENV_TLS_DIR: "/var/run/bobrapet/tls"})
        assert paths.ca_file == "/var/run/bobrapet/tls/ca.crt"
        assert paths.cert_file == "/var/run/bobrapet/tls/tls.crt"
        assert paths.key_file == "/var/run/bobrapet/tls/tls.key"
        assert TLSPaths.from_env({}) is None

    def test_full_duplex_under_credits_over_tls(self, tmp_path):
        """Concurrent SSL read (credit frames) + write (data frames) on
        one connection: the serialized TLS socket must survive a
        credit-paced burst without record corruption."""
        import threading as _t

        from bobrapet_tpu.dataplane import StreamHub

        tls_dir = _make_ca(tmp_path, "duplex")
        hub = StreamHub(tls=tls_dir)
        hub.start()
        try:
            settings = {
                "flowControl": {"mode": "credits",
                                "initialCredits": {"messages": 4},
                                "ackEvery": {"messages": 1}},
                "backpressure": {"buffer": {"maxMessages": 8}},
            }
            received = []
            done = _t.Event()
            c = StreamConsumer(hub.endpoint, "ns/r/duplex",
                               settings=settings, decode_json=True,
                               tls=tls_dir)

            def drain():
                for m in c:
                    received.append(m)
                done.set()

            _t.Thread(target=drain, daemon=True).start()
            p = StreamProducer(hub.endpoint, "ns/r/duplex",
                               settings=settings, tls=tls_dir)
            n = 200
            for i in range(n):
                p.send({"i": i}, timeout=10.0)
            p.close()
            assert done.wait(30)
            assert [m["i"] for m in received] == list(range(n))
        finally:
            hub.stop()


class TestPartitionedDelivery:
    """partitioning.mode=keyHash/roundRobin: N hub streams per logical
    stream, per-partition ordering, key stickiness, consumer fan-in
    (dataplane/partition.py). Runs against BOTH engines — the hub needs
    no partition awareness."""

    KH = {"partitioning": {"mode": "keyHash", "key": "{{ packet.k }}",
                           "partitions": 3}}
    RR = {"partitioning": {"mode": "roundRobin", "partitions": 3}}

    def test_keyhash_per_key_order_and_stickiness(self, hub):
        from bobrapet_tpu.dataplane import open_consumer, open_producer
        from bobrapet_tpu.dataplane.partition import key_partition

        p = open_producer(hub.endpoint, "ns/run/part", settings=self.KH)
        sent: dict[str, list[int]] = {}
        for i in range(30):
            key = f"k{i % 5}"
            p.send({"key": key, "i": i}, key=key)
            sent.setdefault(key, []).append(i)
        p.close()

        c = open_consumer(hub.endpoint, "ns/run/part", settings=self.KH,
                          decode_json=True)
        got: dict[str, list[int]] = {}
        for msg in c:
            got.setdefault(msg["key"], []).append(msg["i"])
        # per-key order survives the parallel partitions
        assert got == sent
        # stickiness: each key landed on exactly its hash partition
        for key in sent:
            assert 0 <= key_partition(key, 3) < 3
        # the hub really carries 3 sub-streams
        seqs = [hub.stream_stats(f"ns/run/part#{i}").get("nextSeq", 0)
                for i in range(3)]
        assert sum(seqs) == 30 and all(s > 0 for s in seqs)

    def test_roundrobin_spreads_messages(self, hub):
        from bobrapet_tpu.dataplane import open_consumer, open_producer

        p = open_producer(hub.endpoint, "ns/run/rr", settings=self.RR)
        for i in range(12):
            p.send({"i": i})
        p.close()
        c = open_consumer(hub.endpoint, "ns/run/rr", settings=self.RR,
                          decode_json=True)
        got = sorted(m["i"] for m in c)
        assert got == list(range(12))
        # exact rotation: every partition carries 4 of the 12
        for i in range(3):
            assert hub.stream_stats(f"ns/run/rr#{i}")["nextSeq"] == 4

    def test_keyhash_requires_key(self, hub):
        from bobrapet_tpu.dataplane import open_producer

        p = open_producer(hub.endpoint, "ns/run/nk", settings=self.KH)
        with pytest.raises(ValueError, match="needs a key"):
            p.send({"x": 1})
        p.close()

    def test_unpartitioned_settings_take_the_plain_path(self, hub):
        from bobrapet_tpu.dataplane import (
            StreamConsumer as SC,
            StreamProducer as SP,
            open_consumer,
            open_producer,
        )

        p = open_producer(hub.endpoint, "ns/run/plain", settings={})
        c = open_consumer(hub.endpoint, "ns/run/plain", settings={})
        assert isinstance(p, SP) and isinstance(c, SC)
        p.send(b"x")
        p.close()
        assert list(c) == [b"x"]


class TestPartitionedAckDiscipline:
    AL = {
        "partitioning": {"mode": "roundRobin", "partitions": 2},
        "flowControl": {"mode": "credits",
                        "initialCredits": {"messages": 32},
                        "ackEvery": {"messages": 1}},
        "delivery": {"semantics": "atLeastOnce"},
    }

    def test_fan_in_does_not_ack_ahead_of_consumption(self, hub):
        """The merge must not ack (nor release producer credit for)
        messages the application has not consumed — atLeastOnce
        through the fan-in."""
        from bobrapet_tpu.dataplane import open_consumer, open_producer

        p = open_producer(hub.endpoint, "ns/run/ackd", settings=self.AL)
        for i in range(10):
            p.send({"i": i})
        p.close()
        c = open_consumer(hub.endpoint, "ns/run/ackd", settings=self.AL,
                          decode_json=True)
        it = iter(c)
        got = [next(it) for _ in range(4)]
        assert len(got) == 4
        time.sleep(0.3)  # let any (wrong) eager acks land
        acked = sum(
            hub.stream_stats(f"ns/run/ackd#{i}").get("acked", -1) + 1
            for i in range(2)
        )
        # consumed 4; each partition may have ONE in-flight handed item
        assert acked <= 4 + 2, acked
        c.close()


class TestRecording:
    """recording.mode=full/sample: data frames tee into the blob store
    with retention + redaction; a recorded stream replays from storage
    (dataplane/recording.py)."""

    def _hub_with_recorder(self, **kw):
        from bobrapet_tpu.dataplane import StreamHub, StreamRecorder
        from bobrapet_tpu.storage.store import MemoryStore

        store = MemoryStore()
        rec = StreamRecorder(store, **kw)
        hub = StreamHub()
        hub._recorder = rec
        hub.start()
        return hub, rec, store

    def test_recorded_stream_replays_from_storage(self):
        hub, rec, store = self._hub_with_recorder(segment_entries=4)
        try:
            settings = {"recording": {"mode": "full"}}
            p = StreamProducer(hub.endpoint, "ns/run/rec", settings=settings)
            for i in range(10):
                p.send({"i": i}, key=f"k{i}")
            p.close()  # eos flushes the tail segment
            # drain so the recording is complete
            list(StreamConsumer(hub.endpoint, "ns/run/rec"))
            entries = list(rec.replay("ns/run/rec"))
            assert [e["seq"] for e in entries] == list(range(10))
            assert [json.loads(e["payload"])["i"] for e in entries] == list(range(10))
            assert entries[3]["key"] == "k3"
            # segments actually persisted (10 entries / 4 per segment)
            assert len(store.list("recordings/ns/run/rec/")) == 3
            # replay from mid-stream
            assert [e["seq"] for e in rec.replay("ns/run/rec", from_seq=7)] == [7, 8, 9]
        finally:
            hub.stop()

    def test_sampled_recording_records_subset_deterministically(self):
        hub, rec, _ = self._hub_with_recorder()
        try:
            settings = {"recording": {"mode": "sample", "sampleRate": 30}}
            p = StreamProducer(hub.endpoint, "ns/run/smp", settings=settings)
            for i in range(50):
                p.send({"i": i})
            p.close()
            got = [e["seq"] for e in rec.replay("ns/run/smp")]
            assert 0 < len(got) < 50
            from bobrapet_tpu.dataplane.recording import _sampled

            assert got == [s for s in range(50) if _sampled(s, 30.0)]
        finally:
            hub.stop()

    def test_redact_fields_scrub_before_storage(self):
        hub, rec, store = self._hub_with_recorder()
        try:
            settings = {"recording": {"mode": "full",
                                      "redactFields": ["secret"]}}
            p = StreamProducer(hub.endpoint, "ns/run/red", settings=settings)
            p.send({"secret": "hunter2", "ok": 1})
            p.close()
            (entry,) = rec.replay("ns/run/red")
            obj = json.loads(entry["payload"])
            assert obj == {"secret": "[REDACTED]", "ok": 1}
            # nothing in the store carries the plaintext
            for key in store.list(""):
                assert b"hunter2" not in store.get(key)
        finally:
            hub.stop()

    def test_retention_sweep_removes_old_segments(self):
        hub, rec, store = self._hub_with_recorder(segment_entries=2)
        try:
            settings = {"recording": {"mode": "full",
                                      "retentionSeconds": 60}}
            p = StreamProducer(hub.endpoint, "ns/run/ret", settings=settings)
            for i in range(4):
                p.send({"i": i})
            p.close()
            assert len(store.list("recordings/ns/run/ret/")) == 2
            assert rec.sweep() == 0  # nothing old yet
            removed = rec.sweep(now=time.time() + 3600)
            assert removed == 2
            assert store.list("recordings/ns/run/ret/") == []
        finally:
            hub.stop()

    def test_reference_vocabulary_payload_and_metadata(self):
        """The reference's off|metadata|payload modes (sampleRate
        orthogonal): payload==full; metadata records seq/key/size with
        NO payload bytes in storage."""
        hub, rec, store = self._hub_with_recorder()
        try:
            p = StreamProducer(hub.endpoint, "ns/run/md",
                               settings={"recording": {"mode": "metadata"}})
            p.send({"token": "hunter2", "i": 0}, key="k0")
            p.send({"token": "hunter2", "i": 1}, key="k1")
            p.close()
            entries = list(rec.replay("ns/run/md"))
            assert [e["seq"] for e in entries] == [0, 1]
            assert all(e["payload"] is None for e in entries)
            assert all(e["bytes"] > 0 for e in entries)
            assert entries[1]["key"] == "k1"
            # the payload bytes never touched storage
            for key in store.list(""):
                assert b"hunter2" not in store.get(key)

            p2 = StreamProducer(hub.endpoint, "ns/run/pl",
                                settings={"recording": {"mode": "payload",
                                                        "sampleRate": 50}})
            for i in range(40):
                p2.send({"i": i})
            p2.close()
            got = [e["seq"] for e in rec.replay("ns/run/pl")]
            assert 0 < len(got) < 40  # orthogonal sampling applied
        finally:
            hub.stop()

    def test_recorderless_hub_refuses_recording_stream(self):
        """Admission accepted a recording contract; a hub with no
        recorder must refuse the producer, not silently record
        nothing."""
        from bobrapet_tpu.dataplane import StreamHub
        from bobrapet_tpu.dataplane.client import StreamProtocolError

        hub = StreamHub()
        hub.start()
        try:
            with pytest.raises(StreamProtocolError, match="no recorder"):
                StreamProducer(hub.endpoint, "ns/run/norec",
                               settings={"recording": {"mode": "full"}})
        finally:
            hub.stop()

    def test_native_engine_refuses_recording_stream(self):
        """The C++ engine has no storage tee: a producer demanding
        recording gets a protocol error, mirroring the recorder-less
        Python hub (fail-loud, not silently unrecorded)."""
        from bobrapet_tpu.dataplane.client import StreamProtocolError
        from bobrapet_tpu.dataplane.native import NativeStreamHub

        if not _native_hub_available():
            pytest.skip("native hub unavailable")
        hub = NativeStreamHub()
        hub.start()
        try:
            with pytest.raises(StreamProtocolError, match="no recorder"):
                StreamProducer(hub.endpoint, "ns/run/nrec",
                               settings={"recording": {"mode": "full"}})
        finally:
            hub.stop()

    def test_native_pin_with_recorder_refuses(self):
        from bobrapet_tpu.dataplane import StreamRecorder, make_hub
        from bobrapet_tpu.dataplane.native import NativeUnavailable
        from bobrapet_tpu.storage.store import MemoryStore

        rec = StreamRecorder(MemoryStore())
        with pytest.raises(NativeUnavailable, match="record"):
            from bobrapet_tpu.dataplane.native import make_hub as native_make

            native_make(native=True, recorder=rec)


class TestWatermarks:
    """observability.watermark: event-time frontier tracking — both
    engines track min-over-live-producers of header-stamped event
    times and push watermark frames; the client extracts event times
    from JSON payloads per timestampSource."""

    WM = {"observability": {"watermark": {
        "enabled": True, "timestampSource": "meta.event_time_ms"}}}

    def test_watermark_advances_and_reaches_consumer(self, hub):
        c = StreamConsumer(hub.endpoint, "ns/r/wm", settings=self.WM,
                           decode_json=True)
        got = []
        done = threading.Event()

        def drain():
            for m in c:
                got.append((m["i"], c.watermark_ms))
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        p = StreamProducer(hub.endpoint, "ns/r/wm", settings=self.WM)
        for i, et in enumerate([1000, 3000, 2000, 5000]):
            p.send({"i": i, "meta": {"event_time_ms": et}})
        p.close()
        assert done.wait(10)
        assert [i for i, _ in got] == [0, 1, 2, 3]
        # frontier is monotone: 1000, 3000, 3000 (2000 can't rewind), 5000
        assert c.watermark_ms == 5000
        stats = hub.stream_stats("ns/r/wm")
        assert stats.get("watermarkMs") == 5000
        assert stats.get("lagMs") is not None and stats["lagMs"] >= 0

    def test_multi_producer_min_over_maxima(self, hub):
        """The stream frontier is the MIN over live producers — a
        laggard holds it back; its departure releases it."""
        settings = {"observability": {"watermark": {"enabled": True}}}
        fast = StreamProducer(hub.endpoint, "ns/r/wm2", settings=settings)
        slow = StreamProducer(hub.endpoint, "ns/r/wm2", settings=settings)
        fast.send({"i": 0}, event_time_ms=9000)
        slow.send({"i": 1}, event_time_ms=2000)
        time.sleep(0.3)
        assert hub.stream_stats("ns/r/wm2")["watermarkMs"] == 2000
        slow.close()  # the laggard leaves; frontier releases to 9000
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if hub.stream_stats("ns/r/wm2").get("watermarkMs") == 9000:
                break
            time.sleep(0.05)
        assert hub.stream_stats("ns/r/wm2")["watermarkMs"] == 9000
        fast.close()

    def test_late_consumer_learns_current_frontier(self, hub):
        settings = {"observability": {"watermark": {"enabled": True}}}
        p = StreamProducer(hub.endpoint, "ns/r/wm3", settings=settings)
        p.send({"i": 0}, event_time_ms=4200)
        time.sleep(0.2)
        c = StreamConsumer(hub.endpoint, "ns/r/wm3", decode_json=True)
        got = []
        done = threading.Event()

        def drain():
            for m in c:
                got.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        p.close()
        assert done.wait(10)
        assert c.watermark_ms == 4200

    def test_partitioned_fan_in_watermark_is_min(self, hub):
        from bobrapet_tpu.dataplane import open_consumer, open_producer

        settings = {
            "partitioning": {"mode": "keyHash", "key": "{{ packet.k }}",
                             "partitions": 2},
            "observability": {"watermark": {"enabled": True}},
        }
        c = open_consumer(hub.endpoint, "ns/r/wmp", settings=settings,
                          decode_json=True)
        got = []
        done = threading.Event()

        def drain():
            for m in c:
                got.append(m)
            done.set()

        threading.Thread(target=drain, daemon=True).start()
        p = open_producer(hub.endpoint, "ns/r/wmp", settings=settings)
        # spread keys so both partitions carry messages
        keys = [f"k{i}" for i in range(6)]
        for i, key in enumerate(keys):
            p.send({"i": i}, key=key, event_time_ms=1000 * (i + 1))
        p.close()
        assert done.wait(10)
        # merged frontier = min over partitions, both > 0
        assert c.watermark_ms is not None and c.watermark_ms >= 1000


class TestFromCheckpointReplay:
    """replay.mode=fromCheckpoint: durable per-consumerId positions in
    the hub's record store; reattaching consumers resume automatically."""

    CKPT = {
        "flowControl": {"mode": "credits",
                        "initialCredits": {"messages": 32},
                        "ackEvery": {"messages": 1}},
        "delivery": {"semantics": "atLeastOnce",
                     "replay": {"mode": "fromCheckpoint",
                                "retentionSeconds": 3600,
                                "checkpointInterval": "0s"}},
    }

    def _hub(self):
        from bobrapet_tpu.dataplane import StreamHub, StreamRecorder
        from bobrapet_tpu.storage.store import MemoryStore

        store = MemoryStore()
        hub = StreamHub(recorder=StreamRecorder(store))
        hub.start()
        return hub, store

    def test_consumer_resumes_after_checkpoint(self):
        hub, store = self._hub()
        try:
            p = StreamProducer(hub.endpoint, "ns/r/ck", settings=self.CKPT)
            for i in range(10):
                p.send({"i": i})

            c1 = StreamConsumer(hub.endpoint, "ns/r/ck", settings=self.CKPT,
                                decode_json=True, consumer_id="worker-a")
            it = iter(c1)
            got1 = [next(it) for _ in range(4)]
            c1.ack()  # flush the cumulative ack for what we consumed
            import time as _t
            _t.sleep(0.2)  # let the hub persist the checkpoint
            c1.close()     # detach mid-stream

            # durable position landed in the store
            keys = store.list("checkpoints/ns/r/ck/")
            assert keys == ["checkpoints/ns/r/ck/worker-a"]

            # same identity reattaches: delivery resumes AFTER the
            # checkpoint — no duplicates of the consumed prefix
            p.close()
            c2 = StreamConsumer(hub.endpoint, "ns/r/ck", settings=self.CKPT,
                                decode_json=True, consumer_id="worker-a")
            got2 = list(c2)
            assert [m["i"] for m in got1] == [0, 1, 2, 3]
            assert [m["i"] for m in got2] == [4, 5, 6, 7, 8, 9]
        finally:
            hub.stop()

    def test_fresh_consumer_id_starts_from_zero(self):
        hub, _ = self._hub()
        try:
            p = StreamProducer(hub.endpoint, "ns/r/ck2", settings=self.CKPT)
            for i in range(5):
                p.send({"i": i})
            p.close()
            c = StreamConsumer(hub.endpoint, "ns/r/ck2", settings=self.CKPT,
                               decode_json=True, consumer_id="newbie")
            assert [m["i"] for m in c] == [0, 1, 2, 3, 4]
        finally:
            hub.stop()

    def test_stale_checkpoint_from_previous_epoch_redelivers(self):
        """Seqs restart when a stream is recreated (hub restart /
        redrive): a durable checkpoint from the previous epoch must
        redeliver-from-0, never skip the new epoch's data."""
        from bobrapet_tpu.dataplane import StreamHub, StreamRecorder

        hub, store = self._hub()
        try:
            p = StreamProducer(hub.endpoint, "ns/r/ep", settings=self.CKPT)
            for i in range(4):
                p.send({"i": i})
            c = StreamConsumer(hub.endpoint, "ns/r/ep", settings=self.CKPT,
                               decode_json=True, consumer_id="w")
            it = iter(c)
            [next(it) for _ in range(4)]
            c.ack()
            import time as _t
            _t.sleep(0.2)
            c.close()
            p.close()
            assert store.list("checkpoints/ns/r/ep/")  # durable position
        finally:
            hub.stop()
        # "restart": a NEW hub sharing the SAME store; the recreated
        # stream has a fresh epoch and a fresh seq space
        hub2 = StreamHub(recorder=StreamRecorder(store))
        hub2.start()
        try:
            p2 = StreamProducer(hub2.endpoint, "ns/r/ep", settings=self.CKPT)
            for i in range(3):
                p2.send({"i": 100 + i})
            p2.close()
            c2 = StreamConsumer(hub2.endpoint, "ns/r/ep", settings=self.CKPT,
                                decode_json=True, consumer_id="w")
            # the stale seq-3 checkpoint must NOT swallow the new data
            assert [m["i"] for m in c2] == [100, 101, 102]
        finally:
            hub2.stop()

    def test_missing_consumer_id_refused(self):
        from bobrapet_tpu.dataplane.client import StreamProtocolError

        hub, _ = self._hub()
        try:
            with pytest.raises(StreamProtocolError, match="consumerId"):
                StreamConsumer(hub.endpoint, "ns/r/ck3", settings=self.CKPT)
        finally:
            hub.stop()

    def test_recorderless_hub_refuses(self):
        from bobrapet_tpu.dataplane import StreamHub
        from bobrapet_tpu.dataplane.client import StreamProtocolError

        hub = StreamHub()
        hub.start()
        try:
            with pytest.raises(StreamProtocolError, match="record store"):
                StreamConsumer(hub.endpoint, "ns/r/ck4", settings=self.CKPT,
                               consumer_id="w")
        finally:
            hub.stop()

    def test_native_engine_refuses(self):
        from bobrapet_tpu.dataplane.client import StreamProtocolError
        from bobrapet_tpu.dataplane.native import NativeStreamHub

        if not _native_hub_available():
            pytest.skip("native hub unavailable")
        hub = NativeStreamHub()
        hub.start()
        try:
            with pytest.raises(StreamProtocolError, match="fromCheckpoint"):
                StreamProducer(hub.endpoint, "ns/r/ck5", settings=self.CKPT)
        finally:
            hub.stop()
