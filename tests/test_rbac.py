"""Per-run RBAC: runner identity, rule sanitization, hijack refusal.

(reference: internal/controller/runs/rbac.go test coverage model)
"""

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.controllers.rbac import sanitize_rules
from bobrapet_tpu.core.object import new_resource
from bobrapet_tpu.sdk import register_engram


class TestSanitize:
    def test_wildcards_rejected(self):
        kept, rejected = sanitize_rules([
            {"resources": ["*"], "verbs": ["get"]},
            {"resources": ["configmaps"], "verbs": ["*"]},
        ])
        assert kept == []
        assert len(rejected) == 2

    def test_allowlist_enforced(self):
        kept, rejected = sanitize_rules([
            {"resources": ["configmaps"], "verbs": ["get", "list"]},
            {"resources": ["nodes"], "verbs": ["get"]},          # cluster kind
            {"resources": ["secrets"], "verbs": ["delete"]},      # verb outside
        ])
        assert kept == [{"resources": ["configmaps"], "verbs": ["get", "list"]}]
        assert len(rejected) == 2

    def test_empty_rule_rejected(self):
        kept, rejected = sanitize_rules([{"resources": [], "verbs": ["get"]}])
        assert not kept and rejected


class TestRunRBAC:
    def _setup(self, rt, rbac_rules=None):
        ep = "w-impl"
        rt.apply(make_engram_template(
            "w-tpl", entrypoint=ep, image="w:1", supportedModes=["job"],
            executionPolicy={"rbacRules": rbac_rules or []},
        ))
        rt.apply(make_engram("worker", "w-tpl"))

        @register_engram(ep)
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("s", steps=[{"name": "a", "ref": {"name": "worker"}}]))

    def test_run_gets_scoped_identity(self, rt):
        self._setup(rt, rbac_rules=[
            {"resources": ["configmaps"], "verbs": ["get"]},
        ])
        run = rt.run_story("s")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Succeeded"
        sa_name = r.status["serviceAccount"]
        assert sa_name == f"{run}-runner"
        sa = rt.store.get("ServiceAccount", "default", sa_name)
        assert sa.has_owner(r)
        role = rt.store.get("Role", "default", sa_name)
        assert role.spec["rules"] == [{"resources": ["configmaps"], "verbs": ["get"]}]
        binding = rt.store.get("RoleBinding", "default", sa_name)
        assert binding.spec["subjects"][0]["name"] == sa_name
        # jobs ran under the run identity
        job = rt.store.list("Job")[0]
        assert job.spec["serviceAccountName"] == sa_name

    def test_unsafe_template_rules_recorded_not_granted(self, rt):
        self._setup(rt, rbac_rules=[
            {"resources": ["*"], "verbs": ["get"]},
            {"resources": ["secrets"], "verbs": ["get"]},
        ])
        run = rt.run_story("s")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        role = rt.store.get("Role", "default", r.status["serviceAccount"])
        assert role.spec["rules"] == [{"resources": ["secrets"], "verbs": ["get"]}]
        assert len(r.status["rejectedRBACRules"]) == 1

    def test_sa_hijack_refused(self, rt):
        self._setup(rt)
        # plant a foreign SA at the name the run will claim
        run_name = "s-run-hijack"
        rt.store.create(new_resource("ServiceAccount", f"{run_name}-runner",
                                     "default", spec={"annotations": {"evil": "1"}}))
        from bobrapet_tpu.api.runs import make_storyrun

        rt.store.create(make_storyrun(run_name, "s", {}, "default"))
        rt.pump()
        r = rt.store.get("StoryRun", "default", run_name)
        assert r.status["phase"] == "Failed"
        assert "refusing to adopt" in r.status["error"]["message"]

    def test_storage_annotations_follow_run(self, rt):
        self._setup(rt)
        rt.store.mutate("Story", "default", "s", lambda r: r.spec.__setitem__(
            "policy", {"storage": {"s3": {
                "bucket": "b",
                "serviceAccountAnnotations": {
                    "iam.gke.io/gcp-service-account": "runner@proj.iam",
                },
            }}},
        ))
        run = rt.run_story("s")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        sa = rt.store.get("ServiceAccount", "default", r.status["serviceAccount"])
        assert sa.spec["annotations"]["iam.gke.io/gcp-service-account"] == "runner@proj.iam"


class TestBranchRules:
    def test_parallel_branch_engram_rules_granted(self, rt):
        """Engrams referenced only inside `parallel` branches contribute
        their template rbacRules to the run Role (regression: all_steps()
        traversal missed branch sub-steps)."""
        rt.apply(make_engram_template(
            "branch-tpl", entrypoint="branch-impl", image="b:1",
            executionPolicy={"rbacRules": [
                {"resources": ["configmaps"], "verbs": ["get"]},
            ]},
        ))
        rt.apply(make_engram("brancher", "branch-tpl"))

        @register_engram("branch-impl")
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("fan", steps=[
            {"name": "fanout", "type": "parallel", "with": {"steps": [
                {"name": "b1", "ref": {"name": "brancher"}},
                {"name": "b2", "ref": {"name": "brancher"}},
            ]}},
        ]))
        run = rt.run_story("fan")
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Succeeded"
        role = rt.store.get("Role", "default", r.status["serviceAccount"])
        assert {"resources": ["configmaps"], "verbs": ["get"]} in role.spec["rules"]

    def test_rejected_rules_cleared_after_fix(self, rt):
        """status.rejectedRBACRules reflects the CURRENT sanitize result —
        fixing the template clears the stale rejection on the next pass."""
        rt.apply(make_engram_template(
            "w-tpl", entrypoint="w-impl", image="w:1",
            executionPolicy={"rbacRules": [
                {"resources": ["*"], "verbs": ["get"]},
            ]},
        ))
        rt.apply(make_engram("worker", "w-tpl"))

        @register_engram("w-impl")
        def impl(ctx):
            return {"ok": True}

        rt.apply(make_story("s2", steps=[{"name": "a", "ref": {"name": "worker"}}]))
        run = rt.run_story("s2")
        rt.storyrun_controller.reconcile("default", run)
        r = rt.store.get("StoryRun", "default", run)
        assert len(r.status["rejectedRBACRules"]) == 1

        rt.store.mutate(
            "EngramTemplate", "_cluster", "w-tpl",
            lambda t: t.spec["executionPolicy"].__setitem__(
                "rbacRules", [{"resources": ["configmaps"], "verbs": ["get"]}]
            ),
        )
        rt.storyrun_controller.reconcile("default", run)
        r = rt.store.get("StoryRun", "default", run)
        assert "rejectedRBACRules" not in r.status
