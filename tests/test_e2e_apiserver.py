"""Real-apiserver e2e: the manager against kube-apiserver + etcd.

The reference gates a Kind-cluster e2e (reference: Makefile:76-97,
test/e2e/e2e_test.go); this is the framework's equivalent, envtest
style (real API server, no kubelet — the test plays the kubelet, like
the reference's suite_test.go pod-status patches). It exercises the
surfaces no stub can: real watch streams (chunked JSON, bookmarks),
CRD installation + Established conditions, structural schema + CEL
validation served by a real apiserver, status subresource patches over
HTTPS with bearer auth.

SKIPS — never silently passes — when kube-apiserver/etcd binaries are
missing (set KUBEBUILDER_ASSETS). Run via ``make test-e2e-apiserver``.
"""

import time

import pytest

from bobrapet_tpu.cluster.envtest import find_assets

ASSETS = find_assets()
pytestmark = pytest.mark.skipif(
    ASSETS is None,
    reason="kube-apiserver+etcd not found (set KUBEBUILDER_ASSETS to an "
           "envtest binaries dir); the real-apiserver e2e cannot run",
)

RUNS_API = "runs.bobrapet.io/v1alpha1"
CORE_API = "bobrapet.io/v1alpha1"


def wait_for(fn, timeout=60.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    return None


@pytest.fixture(scope="module")
def env():
    from bobrapet_tpu.cluster.envtest import EnvTest

    e = EnvTest(ASSETS)
    try:
        e.start()
        e.install_crds()
        yield e
    finally:
        e.stop()


@pytest.fixture
def manager(env):
    from bobrapet_tpu.controllers.manager import Clock
    from bobrapet_tpu.runtime import Runtime

    rt = Runtime(
        clock=Clock(),
        executor_mode="threaded",
        executor_backend="cluster",
        cluster_client=env.client(),
    )
    rt.start()
    yield rt
    rt.stop()


def kubectl_apply(client, resource):
    from bobrapet_tpu.cluster.crsync import resource_to_manifest

    return client.create(resource_to_manifest(resource))


class TestFrontDoorOnRealApiserver:
    def test_primitive_story_with_gate(self, env, manager):
        from bobrapet_tpu.api.runs import make_storyrun
        from bobrapet_tpu.api.story import make_story

        kubectl = env.client()
        kubectl_apply(kubectl, make_story("real-story", steps=[
            {"name": "nap", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "approval", "type": "gate", "with": {"timeout": "1h"},
             "needs": ["nap"]},
        ]))
        kubectl_apply(kubectl, make_storyrun("real-run", "real-story"))

        assert wait_for(lambda: (
            (kubectl.get(RUNS_API, "StoryRun", "default", "real-run") or {})
            .get("status", {}).get("phase") == "Running"
        )), "run never started on the real apiserver"

        # kubectl patch storyrun real-run --subresource status
        kubectl.patch_status(
            RUNS_API, "StoryRun", "default", "real-run",
            {"status": {"gates": {"approval": {"approved": True,
                                               "approver": "e2e"}}}},
        )
        assert wait_for(lambda: (
            (kubectl.get(RUNS_API, "StoryRun", "default", "real-run") or {})
            .get("status", {}).get("phase") == "Succeeded"
        )), "gate approval via real status subresource did not complete run"

    def test_invalid_story_rejected_by_real_schema(self, env, manager):
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.cluster import ClusterError

        kubectl = env.client()
        bad = make_story("real-bad", steps=[
            {"name": "x", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "x", "type": "sleep", "with": {"duration": "1s"}},
        ])
        # duplicate list-map keys: the REAL apiserver rejects this from
        # the exported schema alone (no webhook in the path)
        with pytest.raises(ClusterError):
            kubectl_apply(kubectl, bad)

    def test_synchronous_webhook_admission(self, env, manager, tmp_path):
        """VERDICT r4 #1: with the webhook server registered, an
        invalid-but-schema-valid Story is rejected *synchronously* by
        the apiserver with field errors, and an applied Story reads
        back already defaulted (reference: cmd/main.go:802-924)."""
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.cluster import ClusterError
        from bobrapet_tpu.cluster.admission import (
            AdmissionServer,
            register_webhook_configurations,
        )
        from bobrapet_tpu.cluster.certs import ensure_webhook_certs

        kubectl = env.client()
        certs = ensure_webhook_certs(str(tmp_path / "webhook-certs"))
        server = AdmissionServer(
            manager.store, certs["cert"], certs["key"],
            host="127.0.0.1", port=0,
        ).start()
        try:
            names = register_webhook_configurations(
                kubectl, manager.store, server.base_url, certs["ca_pem"]
            )
            assert names
            # schema-valid but semantically invalid: unknown `needs`
            # target — only the webhook can reject this, and it must do
            # so synchronously at apply time
            bad = make_story("sync-bad", steps=[
                {"name": "a", "type": "condition", "needs": ["ghost"]},
            ])
            with pytest.raises(ClusterError) as exc:
                kubectl_apply(kubectl, bad)
            assert "needs" in str(exc.value)
            assert kubectl.get(CORE_API, "Story", "default", "sync-bad") is None

            # mutating admission: a wait step without onTimeout reads
            # back defaulted on the FIRST get after apply
            kubectl_apply(kubectl, make_story("sync-defaulted", steps=[
                {"name": "w", "type": "wait",
                 "with": {"until": "{{ inputs.ready }}"}},
            ]))
            obj = kubectl.get(CORE_API, "Story", "default", "sync-defaulted")
            assert obj["spec"]["steps"][0]["with"]["onTimeout"] == "fail"
        finally:
            for cfg_kind, name in (
                ("ValidatingWebhookConfiguration",
                 "bobrapet-validating-webhook-configuration"),
                ("MutatingWebhookConfiguration",
                 "bobrapet-mutating-webhook-configuration"),
            ):
                try:
                    kubectl.delete("admissionregistration.k8s.io/v1",
                                   cfg_kind, "", name)
                except Exception:  # noqa: BLE001 - already absent
                    pass
            server.stop()

    def test_configmap_edit_reloads_manager_live(self, env, manager):
        """VERDICT r4 #6: a cluster-side ConfigMap edit (kubectl edit
        configmap) reaches the live config manager without restart."""
        kubectl = env.client()
        assert (manager.config_manager.config.templating
                .offloaded_data_policy.value) == "fail"
        cm = {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "operator-config",
                         "namespace": "bobrapet-system"},
            "data": {"templating.offloaded-data-policy": "inject"},
        }
        ns = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "bobrapet-system", "namespace": ""}}
        if kubectl.get("v1", "Namespace", "", "bobrapet-system") is None:
            kubectl.create(ns)
        try:
            if kubectl.get("v1", "ConfigMap", "bobrapet-system",
                           "operator-config") is None:
                kubectl.create(cm)
            else:
                kubectl.patch("v1", "ConfigMap", "bobrapet-system",
                              "operator-config", {"data": cm["data"]})
            assert wait_for(lambda: (
                manager.config_manager.config.templating
                .offloaded_data_policy.value) == "inject"), (
                "cluster ConfigMap edit never reached the live manager"
            )
        finally:
            # the apiserver outlives this test (module-scoped env):
            # a leftover ConfigMap would leak non-default config into
            # every later Runtime's resync
            try:
                kubectl.delete("v1", "ConfigMap", "bobrapet-system",
                               "operator-config")
            except Exception:  # noqa: BLE001 - never created
                pass

    def test_batch_story_exit_code_from_real_pod_status(self, env, manager):
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.runs import make_storyrun
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.gke.materialize import COMPLETION_INDEX_ANNOTATION

        kubectl = env.client()
        kubectl_apply(kubectl, make_engram_template("real-tpl",
                                                    entrypoint="real-impl"))
        kubectl_apply(kubectl, make_engram("real-worker", "real-tpl"))
        kubectl_apply(kubectl, make_story("real-batch", steps=[
            {"name": "work", "ref": {"name": "real-worker"},
             "execution": {"retry": {"maxRetries": 0}}},
        ]))
        kubectl_apply(kubectl, make_storyrun("real-batch-run", "real-batch"))

        # the manager applies a real batch/v1 Job; no kubelet exists in
        # envtest, so the test plays it (suite_test.go analog). The
        # managed label's VALUE is the job name (materialize.py), so
        # filter on key presence.
        jobs = wait_for(lambda: [
            j for j in kubectl.list("batch/v1", "Job", "default")
            if "bobrapet.io/job" in (j["metadata"].get("labels") or {})
        ])
        assert jobs, "manager never applied a Job to the real apiserver"
        job = jobs[0]
        job_name = job["metadata"]["name"]

        pod = kubectl.create({
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{job_name}-0",
                "namespace": "default",
                "labels": {"job-name": job_name},
                "annotations": {COMPLETION_INDEX_ANNOTATION: "0"},
            },
            "spec": {"containers": [{"name": "engram",
                                     "image": "example/engram:1"}]},
        })
        assert pod["metadata"]["name"] == f"{job_name}-0"
        kubectl.patch_status("v1", "Pod", "default", f"{job_name}-0", {
            "status": {
                "phase": "Failed",
                "message": "bad config",
                "containerStatuses": [{
                    "name": "engram",
                    "state": {"terminated": {"exitCode": 126}},
                }],
            },
        })
        kubectl.patch_status("batch/v1", "Job", "default", job_name, {
            "status": {
                "failed": 1,
                "conditions": [{"type": "Failed", "status": "True",
                                "reason": "BackoffLimitExceeded"}],
            },
        })

        # exit-code classification flows pod -> job -> bus -> mirrored
        # StepRun on the real apiserver
        def steprun_exit():
            for sr in kubectl.list(RUNS_API, "StepRun", "default"):
                if sr.get("status", {}).get("exitCode") == 126:
                    return sr
            return None

        sr = wait_for(steprun_exit)
        assert sr is not None, "exit code 126 never reflected to a StepRun"
        assert sr["status"]["exitClass"] == "terminal"
        assert wait_for(lambda: (
            (kubectl.get(RUNS_API, "StoryRun", "default", "real-batch-run")
             or {}).get("status", {}).get("phase") == "Failed"
        )), "terminal exit did not fail the run on the real apiserver"
