"""bobrarace: the lockset/happens-before data-race sanitizer.

Four layers, mirroring the module split:

1. pure happens-before machinery (analysis/hb.py) driven with
   hand-built clocks — no threads;
2. real-thread HB edges (fork/join, Future, Condition, Event, queue,
   executor submit) and the hybrid lockset rule, via short
   ``sanitize_races`` sessions;
3. the known-bad proof corpus — the PR-6 stale-scope race shape and an
   unlocked-deque mutation — detected AND deterministically replayed
   from a seed (analysis/schedules.py);
4. the contracts around the detector: baseline gating, static/runtime
   registry drift (``discover_guarded`` == ``GUARDED_REGISTRY``), and
   regression pins for the real races fixed alongside this sanitizer
   (ShardRouter.parked vs promote, ControllerManager._failures,
   ResourceStore admission registration).
"""

import json
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

import pytest

# decorated product modules must be imported so GUARDED_REGISTRY is
# populated before the drift test compares it to static discovery
import bobrapet_tpu.controllers.manager  # noqa: F401
import bobrapet_tpu.core.store  # noqa: F401
import bobrapet_tpu.serving.prefix_cache  # noqa: F401
import bobrapet_tpu.serving.router  # noqa: F401
import bobrapet_tpu.shard.coordinator  # noqa: F401
import bobrapet_tpu.shard.procharness  # noqa: F401
import bobrapet_tpu.shard.router  # noqa: F401
import bobrapet_tpu.store_service.client  # noqa: F401
import bobrapet_tpu.store_service.journal  # noqa: F401
import bobrapet_tpu.store_service.service  # noqa: F401
import bobrapet_tpu.traffic.autoscaler  # noqa: F401
import bobrapet_tpu.traffic.fairness  # noqa: F401
import bobrapet_tpu.traffic.loadgen  # noqa: F401
from bobrapet_tpu.analysis.baseline import BaselineError
from bobrapet_tpu.analysis.checkers.shared_state_discipline import (
    discover_guarded,
)
from bobrapet_tpu.analysis.core import load_project
from bobrapet_tpu.analysis.hb import (
    AccessCheck,
    VarState,
    VectorClock,
    epoch_leq,
)
from bobrapet_tpu.analysis.racedetect import (
    GUARDED_REGISTRY,
    RaceViolation,
    render_race_baseline,
    sanitize_races,
    track,
)
from bobrapet_tpu.analysis.schedules import JitterSchedule, SerialSchedule

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# 1. clocks + VarState, no threads
# ---------------------------------------------------------------------------


class TestVectorClock:
    def test_missing_tids_read_zero(self):
        vc = VectorClock()
        assert vc.time_of(7) == 0
        vc.advance(7)
        assert vc.time_of(7) == 1

    def test_join_is_pointwise_max(self):
        a = VectorClock({1: 3, 2: 1})
        a.join({2: 5, 3: 2})
        assert a == {1: 3, 2: 5, 3: 2}
        a.join(None)  # zero clock joins as identity
        assert a == {1: 3, 2: 5, 3: 2}

    def test_leq(self):
        assert VectorClock({1: 1}).leq({1: 2, 9: 9})
        assert not VectorClock({1: 3}).leq({1: 2})

    def test_epoch_leq(self):
        assert epoch_leq(None, {})  # virgin epoch precedes everything
        assert epoch_leq((1, 2), {1: 2})
        assert not epoch_leq((1, 3), {1: 2})
        assert not epoch_leq((1, 1), {2: 5})


class TestVarState:
    def test_unordered_unlocked_writes_race(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset(), True, token="w1")
        chk = vs.on_access(2, {2: 1}, frozenset(), True, token="w2")
        assert chk.is_race and chk.conflicts == ["w1"]

    def test_common_lock_excuses_unordered_writes(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset({"L#1"}), True)
        chk = vs.on_access(2, {2: 1}, frozenset({"L#1"}), True)
        assert chk.conflicts and chk.common_locks == frozenset({"L#1"})
        assert not chk.is_race

    def test_lockset_refines_to_intersection(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset({"A#1", "B#1"}), True)
        chk = vs.on_access(2, {2: 1}, frozenset({"B#1", "C#1"}), True)
        assert chk.common_locks == frozenset({"B#1"})
        # third unordered access without B drains the set: race
        chk = vs.on_access(3, {3: 1}, frozenset({"C#1"}), True)
        assert chk.is_race

    def test_ordered_access_is_clean_and_rearms_lockset(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset(), True, token="w1")
        # tid 2 saw tid 1's write (joined clock): clean handoff, and the
        # drained lockset must NOT leak into the new exclusive phase
        chk = vs.on_access(2, {1: 1, 2: 1}, frozenset({"L#1"}), True)
        assert not chk.conflicts
        chk = vs.on_access(3, {3: 1}, frozenset({"L#1"}), True)
        assert chk.conflicts and not chk.is_race  # excused by L#1

    def test_write_conflicts_with_unordered_read(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset(), False, token="r1")
        chk = vs.on_access(2, {2: 1}, frozenset(), True, token="w2")
        assert chk.is_race and "r1" in chk.conflicts

    def test_reads_never_conflict_with_reads(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset(), False)
        chk = vs.on_access(2, {2: 1}, frozenset(), False)
        assert not chk.conflicts

    def test_write_clears_read_state(self):
        vs = VarState()
        vs.on_access(1, {1: 1}, frozenset(), False, token="r1")
        vs.on_access(1, {1: 2}, frozenset(), True)  # same-thread write
        assert vs.read_epochs == {} and vs.read_tokens == {}

    def test_access_check_shape(self):
        chk = AccessCheck(conflicts=[], common_locks=frozenset())
        assert not chk.is_race


# ---------------------------------------------------------------------------
# 2. real-thread HB edges + the hybrid rule
# ---------------------------------------------------------------------------


def _run_all(*fns):
    ts = [threading.Thread(target=fn) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


class TestThreadedEdges:
    def test_unlocked_writer_pair_races(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.unlocked", {})

            def w(n):
                for i in range(100):
                    d[i % 5] = n

            _run_all(lambda: w(1), lambda: w(2))
        assert det.reports, det.report_text()
        rep = det.reports[0]
        assert rep.var == "t.unlocked"
        assert "NO LOCKS" in rep.render()
        assert rep.fingerprint  # line-number-free identity

    def test_common_lock_is_clean(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.locked", {})
            mu = threading.Lock()

            def w(n):
                for i in range(100):
                    with mu:
                        d[i % 5] = n

            _run_all(lambda: w(1), lambda: w(2))
        assert not det.reports, det.report_text()

    def test_two_different_locks_race(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.twolocks", {})
            mu_a, mu_b = threading.Lock(), threading.Lock()

            def w(mu, n):
                for i in range(100):
                    with mu:
                        d[i % 5] = n

            _run_all(lambda: w(mu_a, 1), lambda: w(mu_b, 2))
        assert det.reports, "disjoint locksets must not excuse the pair"

    def test_fork_join_orders_accesses(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.forkjoin", {})
            d["x"] = 1
            t = threading.Thread(target=lambda: d.update(x=2))
            t.start()
            t.join()
            assert d["x"] == 2  # read after join: ordered
        assert not det.reports, det.report_text()

    def test_future_handoff_orders_accesses(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.future", {})
            fut = Future()

            def worker():
                d["x"] = 41
                fut.set_result(True)

            t = threading.Thread(target=worker)
            t.start()
            assert fut.result(timeout=2.0)
            d["x"] += 1  # ordered by set_result -> result, NOT by join
            t.join()
        assert not det.reports, det.report_text()

    def test_executor_submit_and_result_order_accesses(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.executor", {})
            d["x"] = 1  # visible to the task via the submit edge
            with ThreadPoolExecutor(max_workers=1) as ex:
                fut = ex.submit(lambda: d.update(x=2))
                fut.result(timeout=2.0)
                d["x"] += 1  # ordered by the future edge
        assert not det.reports, det.report_text()

    def test_condition_handoff_orders_accesses(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.cond", {})
            cond = threading.Condition()
            parked = threading.Event()
            out = []

            def consumer():
                with cond:
                    parked.set()
                    ok = cond.wait(timeout=2.0)
                assert ok
                out.append(d["x"])  # read outside any lock

            def producer():
                parked.wait(timeout=2.0)
                d["x"] = 42  # write outside any lock
                with cond:  # consumer holds cond until its wait parks
                    cond.notify()

            _run_all(consumer, producer)
            assert out == [42]
        assert not det.reports, det.report_text()

    def test_event_handoff_orders_accesses(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.event", {})
            ev = threading.Event()

            def producer():
                d["x"] = 7
                ev.set()

            t = threading.Thread(target=producer)
            t.start()
            assert ev.wait(timeout=2.0)
            assert d["x"] == 7  # ordered by set -> wait, not by join
            t.join()
        assert not det.reports, det.report_text()

    def test_queue_handoff_orders_accesses(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.queue", {})
            q = queue.Queue()

            def producer():
                d["x"] = 9
                q.put("token")

            t = threading.Thread(target=producer)
            t.start()
            assert q.get(timeout=2.0) == "token"
            assert d["x"] == 9  # ordered by put -> get
            t.join()
        assert not det.reports, det.report_text()

    def test_hybrid_vs_hb_mode_on_lock_release_ordering(self):
        """A writes under L; B later takes-and-releases L, then writes
        WITHOUT it. mode="hb" treats release->acquire as an HB edge
        (pure FastTrack: clean); default hybrid mode deliberately does
        not, so the unlocked second write is still reported."""

        def scenario():
            d = track("t.relacq", {})
            mu = threading.Lock()
            flag = [False]

            def a():
                with mu:
                    d["x"] = 1
                flag[0] = True

            def b():
                while not flag[0]:
                    time.sleep(0.005)
                with mu:
                    pass
                d["x"] = 2  # unlocked, but after b held-and-released L

            _run_all(a, b)

        with sanitize_races(include_tests=True, mode="hybrid") as det:
            scenario()
        assert det.reports, "hybrid mode must not order through mutexes"

        with sanitize_races(include_tests=True, mode="hb") as det:
            scenario()
        assert not det.reports, det.report_text()

    def test_test_frame_accesses_suppressed_by_default(self):
        with sanitize_races() as det:  # include_tests=False
            d = track("t.observer", {})

            def w(n):
                for i in range(50):
                    d[i % 3] = n

            _run_all(lambda: w(1), lambda: w(2))
        assert not det.reports
        assert det.observer_races, "suppressed races stay visible for triage"

    def test_sessions_do_not_nest(self):
        with sanitize_races():
            with pytest.raises(RuntimeError):
                with sanitize_races():
                    pass

    def test_report_fingerprint_ignores_line_numbers(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.fp", {})

            def w(n):
                for i in range(100):
                    d[i % 5] = n

            _run_all(lambda: w(1), lambda: w(2))
        rep = det.reports[0]
        assert ":w" in rep.a.site_key() or ":w" in rep.b.site_key()
        assert not any(ch.isdigit() for ch in rep.a.site_key().split("@")[0])


# ---------------------------------------------------------------------------
# 3. known-bad corpus + deterministic replay
# ---------------------------------------------------------------------------


def _stale_scope_race(sched=None):
    """The PR-6 stale-scope shape: one worker patches a sibling step's
    outputs into the shared family view while another reads that view
    to decide the next step — no lock, no handoff edge."""
    with sanitize_races(include_tests=True, schedule=sched) as det:
        view = track("corpus.family_status_view",
                     {"phase": "Running", "outputs": None})
        seen = []

        def sibling_patch():
            view["outputs"] = {"tokens": 128}
            view["phase"] = "Succeeded"

        def scope_reader():
            seen.append(view["phase"])
            seen.append(view["outputs"])

        if isinstance(sched, SerialSchedule):
            ts = [sched.spawn(sibling_patch, name="sibling"),
                  sched.spawn(scope_reader, name="reader")]
            for t in ts:
                t.start()
            sched.run(timeout=10.0)
        else:
            _run_all(sibling_patch, scope_reader)
    return det


class TestKnownBadCorpus:
    def test_stale_scope_shape_detected(self):
        det = _stale_scope_race()
        assert det.reports, "stale-scope view race must be detected"
        assert det.reports[0].var == "corpus.family_status_view"

    def test_stale_scope_replays_deterministically(self):
        runs = []
        for _ in range(2):
            sched = SerialSchedule(seed=1337)
            det = _stale_scope_race(sched)
            assert sched.stalls == 0, "determinism degraded (stalled step)"
            assert det.reports, "seeded replay must still detect the race"
            runs.append(tuple(sched.trace))
        assert runs[0] == runs[1], "same seed must give identical traces"
        assert len(runs[0]) >= 4  # both participants actually interleaved

    def test_different_seeds_may_reorder_but_still_detect(self):
        t1 = _stale_scope_race(SerialSchedule(seed=1))
        t2 = _stale_scope_race(SerialSchedule(seed=2))
        assert t1.reports and t2.reports

    def test_unlocked_deque_mutation_detected(self):
        with sanitize_races(include_tests=True) as det:
            dq = track("corpus.worker_deque", deque())

            def pusher():
                for i in range(100):
                    dq.append(i)

            def drainer():
                for _ in range(100):
                    try:
                        dq.popleft()
                    except IndexError:
                        pass

            _run_all(pusher, drainer)
        assert det.reports
        assert det.reports[0].var == "corpus.worker_deque"

    def test_jitter_schedule_decisions_are_seeded(self):
        a, b = JitterSchedule(seed=7), JitterSchedule(seed=7)
        draws_a = [a._rng.random() for _ in range(32)]
        draws_b = [b._rng.random() for _ in range(32)]
        assert draws_a == draws_b
        det = _stale_scope_race(JitterSchedule(seed=7))
        assert det.reports, "jitter must not mask the race"


# ---------------------------------------------------------------------------
# 4. contracts: baseline gating, drift, regression pins
# ---------------------------------------------------------------------------


class TestBaselineContract:
    def _racy_detector(self):
        det = _stale_scope_race()
        assert det.reports
        return det

    def test_assert_clean_raises_on_unsuppressed_race(self, tmp_path):
        det = self._racy_detector()
        with pytest.raises(RaceViolation) as exc:
            det.assert_clean(baseline_path=str(tmp_path / "none.json"))
        assert "DATA RACE" in str(exc.value)

    def test_render_baseline_placeholder_is_rejected(self, tmp_path):
        det = self._racy_detector()
        path = tmp_path / "bobrarace-baseline.json"
        path.write_text(render_race_baseline(det.reports))
        with pytest.raises(BaselineError):
            det.assert_clean(baseline_path=str(path))

    def test_justified_suppression_passes(self, tmp_path):
        det = self._racy_detector()
        doc = json.loads(render_race_baseline(det.reports))
        for entry in doc["suppressions"]:
            entry["justification"] = (
                "known-bad corpus shape, intentionally racy by design"
            )
        path = tmp_path / "bobrarace-baseline.json"
        path.write_text(json.dumps(doc))
        det.assert_clean(baseline_path=str(path))

    def test_stale_suppression_raises_in_strict_mode(self, tmp_path):
        racy = self._racy_detector()
        doc = json.loads(render_race_baseline(racy.reports))
        for entry in doc["suppressions"]:
            entry["justification"] = (
                "entry for a race this clean session never observes"
            )
        path = tmp_path / "bobrarace-baseline.json"
        path.write_text(json.dumps(doc))
        with sanitize_races() as det:
            pass  # clean session: the suppression goes stale
        det.assert_clean(baseline_path=str(path), strict_stale=False)
        with pytest.raises(RaceViolation) as exc:
            det.assert_clean(baseline_path=str(path), strict_stale=True)
        assert "stale" in str(exc.value)

    def test_repo_baseline_loads_and_has_justifications(self):
        from bobrapet_tpu.analysis.baseline import Baseline
        from bobrapet_tpu.analysis.racedetect import default_baseline_path

        Baseline.load(default_baseline_path())  # raises if malformed


class TestRegistryDrift:
    def test_runtime_registry_matches_static_discovery(self):
        ctx, errors = load_project(REPO_ROOT)
        assert not errors, errors
        disc = discover_guarded(
            [pf for pf in ctx.files if pf.rel.startswith("bobrapet_tpu/")]
        )
        assert disc, "no @guarded_state classes discovered statically"
        reg = {
            (cls.__module__.replace(".", "/") + ".py", cls.__name__): fields
            for cls, fields in GUARDED_REGISTRY.items()
        }
        assert set(reg) == set(disc), (
            "runtime registry and static discovery name different classes:\n"
            f"runtime only: {sorted(set(reg) - set(disc))}\n"
            f"static only: {sorted(set(disc) - set(reg))}"
        )
        for key, info in disc.items():
            assert tuple(info.declared) == reg[key], key
            assert set(info.declared) == set(info.containers), (
                f"{key}: declaration drifted from __init__ containers"
            )


class TestRegressionPins:
    """The real races fixed alongside this sanitizer stay fixed: each
    pin drives the pre-fix interleaving under an armed detector."""

    def test_router_gate_parking_vs_promote(self):
        from bobrapet_tpu.core.store import ResourceStore
        from bobrapet_tpu.shard.router import ShardRouter

        with sanitize_races() as det:
            router = ShardRouter(ResourceStore(), "0", shard_count=2)
            stop = threading.Event()

            def gate_worker(n):
                i = 0
                while not stop.is_set() and i < 400:
                    key = ("storyrun", "default", f"r{n}-{i % 7}")
                    router.park(key)
                    router.unpark(key)
                    i += 1

            def promoter():
                for epoch in range(1, 40):
                    router.begin_rebalance(["0", "1"], epoch, 0.0)
                    router.promote()
                stop.set()

            _run_all(lambda: gate_worker(1), lambda: gate_worker(2),
                     promoter)
        parked_races = [r for r in det.reports if "parked" in r.var]
        assert not parked_races, det.report_text()

    def test_manager_failure_counters_under_concurrent_reconciles(self):
        from bobrapet_tpu.controllers.manager import ControllerManager
        from bobrapet_tpu.core.store import ResourceStore

        with sanitize_races() as det:
            mgr = ControllerManager(
                ResourceStore(), requeue_base_delay=0.005,
                requeue_max_delay=0.02, default_max_concurrent=4,
            )
            attempts: dict[str, int] = {}
            attempts_mu = threading.Lock()

            def flaky(ns, name):
                with attempts_mu:
                    n = attempts[name] = attempts.get(name, 0) + 1
                if n == 1:
                    raise RuntimeError("first attempt fails")
                return None

            mgr.register("flaky", flaky, watches={}, max_concurrent=4)
            mgr.start()
            try:
                for i in range(8):
                    mgr.enqueue("flaky", "default", f"r{i}")
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    with attempts_mu:
                        if len(attempts) == 8 and all(
                            v >= 2 for v in attempts.values()
                        ):
                            break
                    time.sleep(0.01)
            finally:
                mgr.stop()
        failure_races = [
            r for r in det.reports
            if "_failures" in r.var or "_controllers" in r.var
        ]
        assert not failure_races, det.report_text()

    def test_store_registration_from_concurrent_threads(self):
        from bobrapet_tpu.core.store import ResourceStore

        with sanitize_races() as det:
            store = ResourceStore()

            def reg(n):
                for i in range(50):
                    store.register_validator(f"Kind{n}", lambda o: None)
                    store.register_defaulter(f"Kind{n}", lambda o: None)
                    store.register_status_validator(
                        f"Kind{n}", lambda o: None
                    )

            _run_all(lambda: reg(1), lambda: reg(2))
        reg_races = [
            r for r in det.reports
            if "validators" in r.var or "_defaulters" in r.var
        ]
        assert not reg_races, det.report_text()


class TestLockorderBridge:
    def test_monitor_held_exposes_current_thread_locks(self):
        from bobrapet_tpu.analysis.lockorder import sanitize_locks

        with sanitize_locks() as mon:
            mu = threading.Lock()
            assert mon.held() == []
            with mu:
                held = mon.held()
                assert len(held) == 1
                assert held[0][0] is mu
            assert mon.held() == []
        mon.assert_clean()

    def test_detector_locksets_name_allocation_sites(self):
        with sanitize_races(include_tests=True) as det:
            d = track("t.lockname", {})
            mu = threading.Lock()

            def w(n):
                for i in range(40):
                    with mu:
                        d[i % 3] = n

            _run_all(lambda: w(1), lambda: w(2))
            # the same lock instance must map to one stable lockset name
            assert len(det._lock_seq) >= 1
        assert not det.reports
