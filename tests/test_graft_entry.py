"""Driver entrypoint regression tests: the multichip dryrun must stay
green on a virtual CPU mesh without ever initializing the default
(possibly TPU) backend, and every mesh axis must be exercised."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from __graft_entry__ import _factorize_axes, dryrun_multichip  # noqa: E402


def test_factorize_axes_exercises_fsdp_at_8():
    axes = _factorize_axes(8)
    assert axes["model"] > 1
    assert axes["seq"] > 1
    assert axes["fsdp"] > 1  # VERDICT r1 weak #7: fsdp must not be vestigial


@pytest.mark.parametrize("n", [1, 2, 4, 8, 16, 32, 6, 12])
def test_factorize_axes_product(n):
    axes = _factorize_axes(n)
    prod = 1
    for v in axes.values():
        prod *= v
    assert prod == n


def test_dryrun_multichip_8():
    # conftest forces the cpu platform with 8 virtual devices; the dryrun
    # must complete one full sharded train step + MoE forward
    dryrun_multichip(8)


def test_remat_guard_fails_on_involuntary_remat_warning():
    """The dryrun must FAIL (not warn) when XLA reports an involuntary
    full rematerialization during compile (VERDICT r3 weak #2)."""
    import os

    import pytest

    import __graft_entry__ as g

    with pytest.raises(RuntimeError, match="involuntary full remat"):
        with g._xla_remat_guard():
            # what XLA's spmd_partitioner.cc:652 writes to fd 2
            os.write(2, b"[SPMD] Involuntary full rematerialization. ...\n")


def test_remat_guard_passes_clean_compiles_and_replays_stderr(capfd):
    import os

    import __graft_entry__ as g

    with g._xla_remat_guard():
        os.write(2, b"benign XLA chatter\n")  # clean compile: no marker
    # forensics guarantee: captured bytes are replayed to real stderr
    assert "benign XLA chatter" in capfd.readouterr().err
