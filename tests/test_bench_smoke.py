"""Standalone-invocation bench smokes (ISSUE 14 satellite).

``config3_fanout_gang`` shipped asking for an 8 x 2x2 = 32-chip gang
from a 16-chip pool; the pre-PR-5 per-branch scheduler served it in
two waves, all-or-nothing gang placement made it permanently
unplaceable, and the run parked in ``Running`` forever — the bench
assert failed on every standalone invocation (and inside the sweep,
recorded as ``config3_failed`` in BENCH_r06) for three releases
without anything in CI noticing. These tests run the config in-process
so it can never silently regress again, and pin the allocator change
that made the failure loud: a gang bigger than a pool's TOTAL capacity
is a permanent ``PlacementError`` (step fails with LaunchFailed), not
an un-clearable ``NoCapacity`` park.
"""

import pytest

from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.parallel.placement import (
    NoCapacity,
    PlacementError,
    SlicePool,
)
from bobrapet_tpu.sdk import register_engram


class TestConfig3Standalone:
    def test_config3_fanout_gang_runs_clean(self):
        import bench

        r = bench.config3_fanout_gang()
        assert r["metric"] == "gang_fanout_branches_per_sec"
        assert r["value"] > 0
        assert r["branches"] == 4  # the docstring's feasible shape
        assert r["fleet"]["ledger_balanced"] is True


class TestImpossibleGangIsPermanent:
    def test_pool_raises_placement_error_not_nocapacity(self):
        pool = SlicePool("p", "4x4", chips_per_host=4)
        with pytest.raises(PlacementError, match="unplaceable") as ei:
            pool.allocate_many([("2x2", None)] * 8)  # 32 > 16 total
        assert not isinstance(ei.value, NoCapacity)
        # the pool is untouched — nothing was partially committed
        assert pool.free_chips() == 16
        # a feasible gang on a BUSY pool still parks as NoCapacity
        # (transient: releases can clear it)
        blocker = pool.allocate(want_topology="4x4")
        with pytest.raises(NoCapacity):
            pool.allocate_many([("2x2", None)] * 4)
        pool.release(blocker.slice_id)
        assert len(pool.allocate_many([("2x2", None)] * 4)) == 4

    def test_run_fails_loudly_instead_of_parking_forever(self):
        """The old config3 shape through the full control plane: the
        run must turn terminal Failed (LaunchFailed), never sit in
        Running with an eternal PlacementQueued park."""
        from bobrapet_tpu.runtime import Runtime

        rt = Runtime()
        rt.placer.add_pool(SlicePool("v5e-16", "4x4", chips_per_host=4))

        @register_engram("smoke-c3-impl")
        def impl(ctx):  # noqa: ARG001
            return {}

        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram

        rt.apply(make_engram_template("smoke-c3-tpl",
                                      entrypoint="smoke-c3-impl"))
        rt.apply(make_engram("smoke-c3-worker", "smoke-c3-tpl"))
        rt.apply(make_story("smoke-c3", steps=[
            {"name": "split", "type": "parallel", "with": {"steps": [
                {"name": f"b{i}", "ref": {"name": "smoke-c3-worker"},
                 "tpu": {"topology": "2x2"}}
                for i in range(8)  # 32 chips vs the 16-chip pool
            ]}},
        ], policy={"queue": "v5e-16"}))
        run = rt.run_story("smoke-c3")
        rt.pump()
        assert rt.run_phase(run) == "Failed"
        status = rt.store.get("StoryRun", "default", run).status
        split = status["stepStates"]["split"]
        assert split["reason"] == "LaunchFailed"
        assert "unplaceable" in split["message"]
