"""Synchronous admission over HTTPS: the AdmissionReview server.

Protocol-level coverage of cluster/admission.py — a real TLS server,
real admission.k8s.io/v1 payloads — so the capability is proven without
kube-apiserver binaries (the gated apiserver e2e exercises the same
server behind a real API server when those exist). Reference
counterpart: the 9 webhook registrations at cmd/main.go:802-924 and the
webhook suites under internal/webhook/.
"""

from __future__ import annotations

import base64
import json
import ssl
import urllib.request

import pytest

# cert minting for the TLS server needs the cryptography package; on
# images without it the capability cannot run at all — skip, don't fail
# (production certs come from the chart's shared CA, not this path)
pytest.importorskip("cryptography")

from bobrapet_tpu.cluster.admission import (
    KIND_PATHS,
    AdmissionServer,
    webhook_configurations,
)
from bobrapet_tpu.cluster.certs import ensure_webhook_certs
from bobrapet_tpu.cluster.crsync import CR_KINDS, resource_to_manifest
from bobrapet_tpu.runtime import Runtime


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return ensure_webhook_certs(str(tmp_path_factory.mktemp("certs")))


@pytest.fixture(scope="module")
def rt_mod():
    return Runtime()


@pytest.fixture(scope="module")
def server(rt_mod, certs):
    srv = AdmissionServer(
        rt_mod.store, certs["cert"], certs["key"], host="127.0.0.1", port=0
    ).start()
    yield srv
    srv.stop()


def post(server, certs, path: str, review: dict) -> dict:
    ctx = ssl.create_default_context(cafile=certs["ca"])
    ctx.check_hostname = False  # leaf SAN covers 127.0.0.1; hostname
    # checking of literal IPs varies by Python build, the CA check is
    # the meaningful assertion here
    req = urllib.request.Request(
        server.base_url + path,
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
        return json.loads(resp.read())


def review_for(obj: dict, operation: str = "CREATE", old: dict | None = None,
               sub_resource: str | None = None) -> dict:
    api_version = obj["apiVersion"]
    group, _, version = api_version.rpartition("/")  # core group: "v1"
    request = {
        "uid": "test-uid-1",
        "kind": {"group": group, "version": version, "kind": obj["kind"]},
        "operation": operation,
        "name": obj["metadata"].get("name", ""),
        "namespace": obj["metadata"].get("namespace", ""),
        "object": obj,
    }
    if old is not None:
        request["oldObject"] = old
    if sub_resource:
        request["subResource"] = sub_resource
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": request,
    }


def story_manifest(name: str, steps: list[dict]) -> dict:
    return {
        "apiVersion": CR_KINDS["Story"][0],
        "kind": "Story",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"steps": steps},
    }


def apply_patch(obj: dict, response: dict) -> dict:
    """Apply the (add/replace-only) JSONPatch our server emits."""
    assert response.get("patchType") == "JSONPatch"
    ops = json.loads(base64.b64decode(response["patch"]))
    out = json.loads(json.dumps(obj))
    for op in ops:
        assert op["op"] in ("add", "replace")
        parts = [p for p in op["path"].split("/") if p]
        target = out
        for p in parts[:-1]:
            target = target.setdefault(p, {})
        target[parts[-1]] = op["value"]
    return out


class TestValidatePath:
    def test_invalid_story_rejected_with_field_errors(self, server, certs):
        obj = story_manifest("bad", [
            {"name": "a", "type": "condition", "needs": ["nope"]},
        ])
        out = post(server, certs, KIND_PATHS["Story"]["validate"],
                   review_for(obj))
        resp = out["response"]
        assert resp["uid"] == "test-uid-1"
        assert resp["allowed"] is False
        assert resp["status"]["code"] == 403
        assert "needs" in resp["status"]["message"]

    def test_valid_story_allowed(self, server, certs):
        obj = story_manifest("ok", [{"name": "a", "type": "condition"}])
        out = post(server, certs, KIND_PATHS["Story"]["validate"],
                   review_for(obj))
        assert out["response"]["allowed"] is True

    def test_execute_story_cycle_rejected(self, server, certs):
        obj = story_manifest("loop", [
            {"name": "again", "type": "executeStory",
             "with": {"storyRef": {"name": "loop"}}},
        ])
        out = post(server, certs, KIND_PATHS["Story"]["validate"],
                   review_for(obj))
        resp = out["response"]
        assert resp["allowed"] is False
        assert "must not reference its own story" in resp["status"]["message"]

    def test_delete_passes_through(self, server, certs):
        obj = story_manifest("bad", [
            {"name": "a", "type": "condition", "needs": ["nope"]},
        ])
        out = post(server, certs, KIND_PATHS["Story"]["validate"],
                   review_for(obj, operation="DELETE"))
        assert out["response"]["allowed"] is True

    def test_unknown_kind_passes_through(self, server, certs):
        obj = {"apiVersion": "v1", "kind": "ConfigMap",
               "metadata": {"name": "cm", "namespace": "default"}}
        out = post(server, certs, "/validate-core-v1-configmap",
                   review_for(obj))
        assert out["response"]["allowed"] is True

    def test_cross_resource_validation_sees_bus_state(self, rt_mod, server,
                                                      certs):
        # an Engram whose templateRef does not exist is rejected; after
        # the template lands on the bus the same review passes — the
        # HTTPS front shares the store the bus chain reads
        engram = {
            "apiVersion": "bobrapet.io/v1alpha1", "kind": "Engram",
            "metadata": {"name": "worker", "namespace": "default"},
            "spec": {"templateRef": {"name": "tool-tpl"}},
        }
        out = post(server, certs, KIND_PATHS["Engram"]["validate"],
                   review_for(engram))
        assert out["response"]["allowed"] is False
        from bobrapet_tpu.api.catalog import make_engram_template

        rt_mod.apply(make_engram_template("tool-tpl", entrypoint="x"))
        out = post(server, certs, KIND_PATHS["Engram"]["validate"],
                   review_for(engram))
        assert out["response"]["allowed"] is True, out["response"]


class TestMutatePath:
    def test_story_defaulting_emits_patch(self, server, certs):
        obj = story_manifest("w", [
            {"name": "w", "type": "wait",
             "with": {"until": "{{ inputs.ready }}"}},
        ])
        out = post(server, certs, KIND_PATHS["Story"]["mutate"],
                   review_for(obj))
        resp = out["response"]
        assert resp["allowed"] is True
        patched = apply_patch(obj, resp)
        assert patched["spec"]["steps"][0]["with"]["onTimeout"] == "fail"

    def test_noop_mutate_has_no_patch(self, server, certs):
        obj = story_manifest("plain", [{"name": "a", "type": "condition"}])
        out = post(server, certs, KIND_PATHS["Story"]["mutate"],
                   review_for(obj))
        resp = out["response"]
        assert resp["allowed"] is True
        # re-applying the defaulters to an already-defaulted object must
        # be a fixed point; any patch here must itself be idempotent
        if "patch" in resp:
            patched = apply_patch(obj, resp)
            out2 = post(server, certs, KIND_PATHS["Story"]["mutate"],
                        review_for(patched))
            assert "patch" not in out2["response"]

    def test_mirror_annotation_survives_mutation(self, server, certs):
        obj = story_manifest("mirrored", [
            {"name": "w", "type": "wait",
             "with": {"until": "{{ inputs.ready }}"}},
        ])
        obj["metadata"]["annotations"] = {"bobrapet.io/mirrored": "true"}
        out = post(server, certs, KIND_PATHS["Story"]["mutate"],
                   review_for(obj))
        patched = apply_patch(obj, out["response"])
        assert patched["metadata"]["annotations"]["bobrapet.io/mirrored"] == "true"


class TestUpdatePath:
    def test_cancel_withdrawal_rejected_with_old_object(self, server, certs):
        """UPDATE reviews carry oldObject; validators that compare
        (new, old) must see it — cancelRequested cannot be withdrawn
        once set (reference: storyrun_webhook.go:175-191)."""
        old = {
            "apiVersion": "runs.bobrapet.io/v1alpha1", "kind": "StoryRun",
            "metadata": {"name": "cr", "namespace": "default"},
            "spec": {"storyRef": {"name": "s"}, "cancelRequested": True},
        }
        new = json.loads(json.dumps(old))
        new["spec"]["cancelRequested"] = False
        out = post(server, certs, KIND_PATHS["StoryRun"]["validate"],
                   review_for(new, operation="UPDATE", old=old))
        resp = out["response"]
        assert resp["allowed"] is False
        assert "cannot be withdrawn" in resp["status"]["message"]

    def test_cancel_set_is_allowed(self, server, certs):
        old = {
            "apiVersion": "runs.bobrapet.io/v1alpha1", "kind": "StoryRun",
            "metadata": {"name": "cr2", "namespace": "default"},
            "spec": {"storyRef": {"name": "s"}},
        }
        new = json.loads(json.dumps(old))
        new["spec"]["cancelRequested"] = True
        out = post(server, certs, KIND_PATHS["StoryRun"]["validate"],
                   review_for(new, operation="UPDATE", old=old))
        assert out["response"]["allowed"] is True, out["response"]


class TestStatusSubresource:
    def test_observed_generation_must_not_regress(self, server, certs):
        new = {
            "apiVersion": "runs.bobrapet.io/v1alpha1", "kind": "StepRun",
            "metadata": {"name": "sr", "namespace": "default",
                         "generation": 10},
            "spec": {"storyRunRef": {"name": "r"}, "stepId": "a",
                     "engramRef": {"name": "e"}},
            "status": {"observedGeneration": 5},
        }
        old = json.loads(json.dumps(new))
        old["status"]["observedGeneration"] = 7
        out = post(server, certs, KIND_PATHS["StepRun"]["validate"],
                   review_for(new, operation="UPDATE", old=old,
                              sub_resource="status"))
        resp = out["response"]
        assert resp["allowed"] is False
        assert "observedGeneration" in resp["status"]["message"]

    def test_status_advance_allowed(self, server, certs):
        new = {
            "apiVersion": "runs.bobrapet.io/v1alpha1", "kind": "StepRun",
            "metadata": {"name": "sr", "namespace": "default",
                         "generation": 10},
            "spec": {"storyRunRef": {"name": "r"}, "stepId": "a",
                     "engramRef": {"name": "e"}},
            "status": {"observedGeneration": 1},
        }
        out = post(server, certs, KIND_PATHS["StepRun"]["validate"],
                   review_for(new, operation="UPDATE",
                              old=json.loads(json.dumps(new)),
                              sub_resource="status"))
        assert out["response"]["allowed"] is True


class TestWebhookConfigurations:
    def test_cover_every_registered_kind(self, rt_mod, certs):
        configs = webhook_configurations(
            rt_mod.store, "https://127.0.0.1:9443", certs["ca_pem"]
        )
        by_kind = {c["kind"]: c for c in configs}
        assert set(by_kind) == {
            "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
        }
        validating = by_kind["ValidatingWebhookConfiguration"]["webhooks"]
        covered = {r for w in validating for rule in w["rules"]
                   for r in rule["resources"]}
        # every kind with a registered validator chain is covered
        # (ReferenceGrant has none — the reference registers 9 webhooks
        # and none for it either, cmd/main.go:832-911); StoryRun/StepRun
        # also guard their status subresource
        from bobrapet_tpu.api.schemas import _registry

        for entry in _registry():
            _d, validators, _s = rt_mod.store.admission_chain(entry.kind)
            if validators:
                assert entry.plural in covered, entry.kind
        assert "stories" in covered and "stepruns" in covered
        assert "storyruns/status" in covered
        assert "stepruns/status" in covered

        mutating = by_kind["MutatingWebhookConfiguration"]["webhooks"]
        mut_resources = {r for w in mutating for rule in w["rules"]
                        for r in rule["resources"]}
        assert {"stories", "engrams"} <= mut_resources

        for w in validating + mutating:
            assert w["sideEffects"] == "None"
            assert w["failurePolicy"] == "Fail"
            assert w["admissionReviewVersions"] == ["v1"]
            ca = base64.b64decode(w["clientConfig"]["caBundle"]).decode()
            assert ca == certs["ca_pem"]
            assert w["clientConfig"]["url"].startswith("https://127.0.0.1:9443/")

    def test_certs_chain_verifies(self, certs):
        import subprocess

        proc = subprocess.run(
            ["openssl", "verify", "-CAfile", certs["ca"], certs["cert"]],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr

    def test_cert_reuse_on_second_call(self, certs, tmp_path):
        import os

        first = ensure_webhook_certs(str(tmp_path / "c"))
        mtime = os.path.getmtime(first["cert"])
        again = ensure_webhook_certs(str(tmp_path / "c"))
        assert os.path.getmtime(again["cert"]) == mtime

    def test_external_mount_served_verbatim(self, certs, tmp_path):
        """A cert-manager mount (tls.crt/tls.key/ca.crt, no ca.key)
        must be served as-is — minting would overwrite the operator's
        issued certs (or crash on a read-only mount)."""
        import os
        import shutil

        mount = tmp_path / "mount"
        mount.mkdir()
        shutil.copy(certs["cert"], mount / "tls.crt")
        shutil.copy(certs["key"], mount / "tls.key")
        shutil.copy(certs["ca"], mount / "ca.crt")
        os.chmod(mount / "tls.crt", 0o444)
        out = ensure_webhook_certs(str(mount), hosts=["only.the.svc"])
        assert out["cert"] == str(mount / "tls.crt")
        assert not os.path.exists(mount / "ca.key")
        with open(certs["ca"]) as f:
            assert out["ca_pem"] == f.read()


class TestBusParity:
    def test_bus_applied_resources_pass_the_http_front(self, rt_mod, server,
                                                       certs):
        """Objects the bus admits round-trip through the HTTPS front:
        the two fronts run the same chain by construction."""
        from bobrapet_tpu.api.story import make_story

        r = rt_mod.apply(make_story("parity", steps=[
            {"name": "a", "type": "condition"},
            {"name": "b", "type": "sleep", "needs": ["a"],
             "with": {"duration": "1s"}},
        ]))
        manifest = resource_to_manifest(r)
        out = post(server, certs, KIND_PATHS["Story"]["validate"],
                   review_for(manifest))
        assert out["response"]["allowed"] is True, out["response"]
