"""Multi-slice hierarchical parallelism (two-level dcn x ICI mesh).

Covers the multi-grant env contract decoders, the hardened build_mesh
axis rules, two-level mesh construction on the CPU-faked 8-device
backend, and the numeric-parity pin: a DCN-data-parallel x
ICI-model-parallel train step must be byte-for-step equivalent (to fp
tolerance) to the single-mesh reference over the same devices.
"""

import json

import numpy as np
import pytest

import jax

from bobrapet_tpu.parallel.mesh import (
    DCN_AXIS,
    build_mesh,
    build_mesh_from_env,
    build_two_level_mesh,
    distributed_init_args,
    span_facts,
)
from bobrapet_tpu.sdk import contract


class TestBuildMeshHardening:
    def test_explicit_multi_axis_honored_verbatim(self):
        mesh = build_mesh({"data": 1, "model": 4})
        assert mesh.shape == {"data": 1, "model": 4}

    def test_non_dividing_multi_axis_fails_loudly(self):
        # 3*2=6 neither equals nor divides 8 — the seed silently scaled
        # the first axis; now the grant mis-size is an error
        with pytest.raises(ValueError, match="does not divide"):
            build_mesh({"data": 3, "model": 2})

    def test_oversized_axes_fail(self):
        with pytest.raises(ValueError, match="need"):
            build_mesh({"data": 4, "model": 4})

    def test_single_axis_fill_kept(self):
        assert build_mesh({"data": 2}).shape == {"data": 8}
        assert build_mesh({"model": 1}).shape == {"model": 8}

    def test_none_axes_one_dim_data(self):
        mesh = build_mesh(None)
        assert mesh.axis_names == ("data",)
        assert mesh.shape["data"] == 8


class TestTwoLevelMesh:
    def test_shape_and_axis_order(self):
        mesh = build_two_level_mesh(2, {"data": 1, "model": 4})
        assert mesh.axis_names == (DCN_AXIS, "data", "model")
        assert mesh.shape == {"dcn": 2, "data": 1, "model": 4}

    def test_each_dcn_row_is_one_contiguous_device_chunk(self):
        devices = list(jax.devices())
        mesh = build_two_level_mesh(2, {"model": 4})
        got = [list(np.asarray(mesh.devices[r]).ravel()) for r in range(2)]
        assert got[0] == devices[:4]
        assert got[1] == devices[4:]

    def test_non_dividing_replicas_fail(self):
        with pytest.raises(ValueError, match="do not divide"):
            build_two_level_mesh(3, None)

    def test_reserved_dcn_axis_rejected(self):
        with pytest.raises(ValueError, match="reserved"):
            build_two_level_mesh(2, {"dcn": 2, "model": 2})

    def test_default_ici_axes(self):
        mesh = build_two_level_mesh(4, None)
        assert mesh.shape == {"dcn": 4, "data": 2}

    def test_single_axis_fill_applies_per_replica(self):
        # {"model": 2} is the single-axis convenience grant: it scales
        # to each replica's full device share, exactly like build_mesh
        mesh = build_two_level_mesh(2, {"model": 2})
        assert mesh.shape == {"dcn": 2, "model": 4}

    def test_smaller_grant_takes_a_prefix_of_each_replica_chunk(self):
        devices = list(jax.devices())
        # explicit multi-axis grant smaller than the per-replica share:
        # honored verbatim over a prefix of each replica's chunk
        mesh = build_two_level_mesh(2, {"data": 1, "model": 2})
        assert mesh.shape == {"dcn": 2, "data": 1, "model": 2}
        got = [list(np.asarray(mesh.devices[r]).ravel()) for r in range(2)]
        assert got[0] == devices[:2]
        assert got[1] == devices[4:6]


class TestEnvContract:
    def _span_env(self):
        return {
            contract.ENV_DCN_REPLICAS: "2",
            contract.ENV_DCN_REPLICA_INDEX: "1",
            contract.ENV_SPAN_ID: "span-7",
            contract.ENV_SPAN_PROCESSES: "4",
            contract.ENV_SPAN_PROCESS_BASE: "2",
            contract.ENV_COORDINATOR_ADDRESS: "pool-a-h0:8476",
            contract.ENV_TPU_HOSTS: "2",
            contract.ENV_MESH_AXES: json.dumps({"data": 1, "model": 4}),
        }

    def test_span_facts_roundtrip(self):
        facts = span_facts(self._span_env())
        assert facts["replicas"] == 2
        assert facts["replica"] == 1
        assert facts["span_id"] == "span-7"
        assert facts["processes"] == 4
        assert facts["process_base"] == 2
        assert facts["coordinator"] == "pool-a-h0:8476"
        assert facts["mesh_axes"] == {"data": 1, "model": 4}

    def test_build_mesh_from_env_two_level(self):
        mesh = build_mesh_from_env(self._span_env())
        assert mesh.shape == {"dcn": 2, "data": 1, "model": 4}

    def test_build_mesh_from_env_flat(self):
        env = {contract.ENV_MESH_AXES: json.dumps({"data": 2, "model": 4})}
        assert build_mesh_from_env(env).shape == {"data": 2, "model": 4}

    def test_distributed_init_args_span_member(self):
        args = distributed_init_args(self._span_env(), host_id=1)
        assert args == {
            "coordinator_address": "pool-a-h0:8476",
            "num_processes": 4,
            "process_id": 3,  # base 2 + host 1
        }

    def test_distributed_init_args_single_host_none(self):
        assert distributed_init_args({}, host_id=0) is None

    def test_distributed_init_args_classic_gang(self):
        # no span: a plain multi-host gang keeps the old semantics
        env = {
            contract.ENV_TPU_HOSTS: "2",
            contract.ENV_COORDINATOR_ADDRESS: "h0:8476",
        }
        args = distributed_init_args(env, host_id=1)
        assert args == {
            "coordinator_address": "h0:8476",
            "num_processes": 2,
            "process_id": 1,
        }

    def test_build_env_emits_span_fields(self):
        env = contract.build_env(
            namespace="ns", story="s", story_run="r", step="t",
            step_run="sr",
            coordinator_address="local-pool-h0:8476",
            span={
                "id": "span-3", "replicas": 2, "replica": 1,
                "processes": 4, "processBase": 2,
                "coordinator": "pool-a-h0:8476",
            },
        )
        assert env[contract.ENV_DCN_REPLICAS] == "2"
        assert env[contract.ENV_DCN_REPLICA_INDEX] == "1"
        assert env[contract.ENV_SPAN_ID] == "span-3"
        assert env[contract.ENV_SPAN_PROCESSES] == "4"
        assert env[contract.ENV_SPAN_PROCESS_BASE] == "2"
        # the span coordinator overrides the per-pool address: every
        # member of the span must dial ONE coordinator
        assert env[contract.ENV_COORDINATOR_ADDRESS] == "pool-a-h0:8476"

    def test_build_env_without_span_unchanged(self):
        env = contract.build_env(
            namespace="ns", story="s", story_run="r", step="t",
            step_run="sr", coordinator_address="h0:8476",
        )
        assert contract.ENV_DCN_REPLICAS not in env
        assert env[contract.ENV_COORDINATOR_ADDRESS] == "h0:8476"


class TestGKESpanEnv:
    def test_gang_job_carries_span_env(self):
        from bobrapet_tpu.gke.materialize import materialize_gang_job

        grant = {
            "sliceId": "pa-s1", "pool": "pa", "topology": "2x4",
            "hosts": 2, "origin": [0, 0],
            "meshAxes": {"data": 1, "model": 8},
            "span": {"id": "span-9", "replicas": 2, "replica": 1,
                     "processes": 4, "processBase": 2,
                     "coordinator": "gang-a-0.gang-a-workers:8476",
                     "pools": ["pa", "pb"]},
        }
        manifests = materialize_gang_job(
            name="gang-b", namespace="ns", image="img", env={},
            grant=grant,
        )
        job = manifests[-1]
        env_list = job["spec"]["template"]["spec"]["containers"][0]["env"]
        env = {e["name"]: e.get("value") for e in env_list}
        assert env[contract.ENV_DCN_REPLICAS] == "2"
        assert env[contract.ENV_DCN_REPLICA_INDEX] == "1"
        assert env[contract.ENV_SPAN_PROCESSES] == "4"
        assert env[contract.ENV_SPAN_PROCESS_BASE] == "2"
        # member 0's address wins over this member's own worker-0
        assert env[contract.ENV_COORDINATOR_ADDRESS] == (
            "gang-a-0.gang-a-workers:8476"
        )

    def _span(self, replica):
        return {"id": "span-abc123", "replicas": 2, "replica": replica,
                "processes": 4, "processBase": 2 * replica,
                "coordinator": None, "pools": ["pa", "pb"]}

    def _grant(self, pool, replica):
        return {
            "sliceId": f"{pool}-s1", "pool": pool, "topology": "2x4",
            "hosts": 2, "origin": [0, 0],
            "meshAxes": {"data": 1, "model": 8},
            "span": self._span(replica),
        }

    def test_coordinatorless_span_derives_one_service(self):
        """Placement on GKE records no coordinator (pool DNS is minted
        by k8s): every member must dial ONE span-scoped Service name —
        each member's own worker-0 would split the span into N
        coordinator groups that all hang — and member 0's manifest
        ships that Service, selecting exactly its worker-0 pod."""
        from bobrapet_tpu.gke.materialize import materialize_gang_job

        def env_of(manifests):
            job = manifests[-1]
            env_list = job["spec"]["template"]["spec"]["containers"][0]["env"]
            return {e["name"]: e.get("value") for e in env_list}

        m0 = materialize_gang_job(
            name="gang-a", namespace="ns", image="img", env={},
            grant=self._grant("pa", 0),
        )
        m1 = materialize_gang_job(
            name="gang-b", namespace="ns", image="img", env={},
            grant=self._grant("pb", 1),
        )
        want = "span-abc123-coord:8476"
        assert env_of(m0)[contract.ENV_COORDINATOR_ADDRESS] == want
        assert env_of(m1)[contract.ENV_COORDINATOR_ADDRESS] == want
        # exactly member 0 ships the coordinator Service, worker-0 only
        svcs0 = [m for m in m0 if m["kind"] == "Service"
                 and m["metadata"]["name"] == "span-abc123-coord"]
        assert len(svcs0) == 1
        sel = svcs0[0]["spec"]["selector"]
        assert sel["bobrapet.io/job"] == "gang-a"
        assert sel["batch.kubernetes.io/job-completion-index"] == "0"
        assert not [m for m in m1 if m["kind"] == "Service"
                    and m["metadata"]["name"] == "span-abc123-coord"]


class TestMultisliceNumericParity:
    """The acceptance pin: DCN-data-parallel x ICI-model-parallel on a
    CPU-faked two-level mesh is numerically parity-locked against the
    single-mesh reference — same init, same tokens, same losses and
    same updated params over several steps. The two meshes partition
    the batch identically (2-way) and the model identically (4-way);
    only WHICH axis carries the gradient psum differs (dcn vs data), so
    any divergence is a sharding bug, not arithmetic noise."""

    def _run(self, mesh, steps=3, same_tokens=False):
        import optax

        from bobrapet_tpu.models.llama import llama_tiny
        from bobrapet_tpu.parallel.train import (
            init_sharded_train_state,
            make_token_batch,
            make_train_step,
        )

        cfg = llama_tiny()
        # deterministic optimizer (no per-run state beyond moments)
        opt = optax.adamw(1e-3, weight_decay=0.1)
        params, opt_state, _ = init_sharded_train_state(
            jax.random.PRNGKey(0), cfg, mesh, optimizer=opt
        )
        step = make_train_step(cfg, mesh, optimizer=opt)
        losses = []
        for i in range(steps):
            tokens = make_token_batch(
                jax.random.PRNGKey(100 if same_tokens else 100 + i),
                cfg, batch=4, seq_len=16, mesh=mesh,
            )
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        return losses, params

    def test_two_level_matches_single_mesh(self):
        from bobrapet_tpu.models.llama import llama_tiny
        from bobrapet_tpu.parallel.train import make_multislice_train_step

        two_level, _ = make_multislice_train_step(
            llama_tiny(), replicas=2, ici_axes={"model": 4}
        )
        assert two_level.shape == {"dcn": 2, "model": 4}
        reference = build_mesh({"data": 2, "model": 4})

        losses_a, params_a = self._run(two_level)
        losses_b, params_b = self._run(reference)
        np.testing.assert_allclose(losses_a, losses_b, rtol=2e-4)
        flat_a = jax.tree_util.tree_leaves(params_a)
        flat_b = jax.tree_util.tree_leaves(params_b)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    def test_loss_decreases_on_two_level_mesh(self):
        mesh = build_two_level_mesh(2, {"model": 2})
        losses, _ = self._run(mesh, steps=4, same_tokens=True)
        assert losses[-1] < losses[0]

    def test_activation_spec_puts_batch_on_dcn(self):
        from jax.sharding import PartitionSpec as P

        from bobrapet_tpu.parallel.sharding import activation_spec

        mesh = build_two_level_mesh(2, {"data": 2, "model": 2})
        spec = activation_spec(mesh)
        assert spec == P(("dcn", "data"))
        # params never shard on dcn: replicated per slice
        from bobrapet_tpu.models.llama import llama_tiny
        from bobrapet_tpu.models.llama import init_params
        from bobrapet_tpu.parallel.sharding import llama_param_specs

        params = init_params(jax.random.PRNGKey(0), llama_tiny())
        specs = llama_param_specs(params, mesh)
        flat = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        for s in flat:
            for part in s:
                parts = part if isinstance(part, tuple) else (part,)
                assert DCN_AXIS not in parts
