"""Indexed sub-mesh allocator (bobrapet_tpu/parallel/placement.py).

Property-based churn equivalence against the retained brute-force
reference (identical grant/no-capacity decisions for single grants),
batched gang semantics (all-or-nothing, ICI-adjacent super-blocks),
fast-negative NoCapacity for parked steps, truthful capacity messages,
ceil-div host counts, fragmentation accounting, and a threaded churn
leg under the runtime lock-order sanitizer.
"""

import random
import threading

import pytest

from bobrapet_tpu.api.shared import TPUPolicy
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.parallel.placement import (
    BruteForceReference,
    NoCapacity,
    PlacementError,
    SlicePlacer,
    SlicePool,
    _cells,
    parse_topology,
)


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    """Lockdep for the whole module: the new allocator core must hold
    its pool lock in a cycle-free order against the metrics locks it
    records into (same harness as the other threaded suites)."""
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


def _grant_cells(grant):
    return set(_cells(tuple(grant.origin), parse_topology(grant.topology)))


class TestHostRounding:
    def test_non_divisible_chip_count_rounds_up(self):
        """Regression: 6 chips at 4 chips/host is 2 hosts — the seed's
        floor-div handed the gang Job a 1-host completions count and
        dropped half the block's workers."""
        pool = SlicePool("p", "2x3", chips_per_host=4)
        g = pool.allocate(want_topology="2x3")
        assert g.hosts == 2

    @pytest.mark.parametrize(
        "topology,cph,want,expected",
        [
            ("4x4", 4, "2x4", 2),   # divisible: unchanged from seed
            ("4x4", 4, "2x2", 1),
            ("8", 4, "6", 2),       # 6/4 -> 2
            ("2x2", 8, "2x2", 1),   # fewer chips than a host
            ("2x4x4", 4, "1x3x3", 3),  # 9/4 -> 3
        ],
    )
    def test_host_counts(self, topology, cph, want, expected):
        pool = SlicePool("p", topology, chips_per_host=cph)
        assert pool.allocate(want_topology=want).hosts == expected


class TestNoCapacityMessage:
    def test_reports_schedulable_not_raw_free(self):
        """The seed reported total-minus-occupied as 'chips free' while
        ignoring cordons — awaitingSlice park logs claimed capacity that
        was quarantined. The message must carry schedulable chips and
        the largest placeable block."""
        pool = SlicePool("p", "4x1")
        pool.set_cordoned({(1, 0), (3, 0)})
        with pytest.raises(NoCapacity) as ei:
            pool.allocate(want_topology="2x1")
        msg = str(ei.value)
        assert "2 schedulable chips" in msg
        assert "2 cordoned" in msg
        assert "largest free block 1 chips" in msg

    def test_full_pool_message(self):
        pool = SlicePool("p", "2x2")
        pool.allocate(want_topology="2x2")
        with pytest.raises(NoCapacity) as ei:
            pool.allocate(want_topology="1x1")
        assert "0 schedulable chips" in str(ei.value)
        assert "largest free block 0 chips" in str(ei.value)


class TestFastNegative:
    def test_repeat_park_probe_skips_the_scan(self):
        pool = SlicePool("fastneg", "4x4")
        pool.allocate(want_topology="4x4")
        with pytest.raises(NoCapacity):
            pool.allocate(want_topology="1x1")
        probes_after_first = metrics.slice_scan_probes.value("fastneg")
        for _ in range(5):  # the awaitingSlice retry loop
            with pytest.raises(NoCapacity):
                pool.allocate(want_topology="1x1")
        assert metrics.slice_scan_probes.value("fastneg") == probes_after_first

    def test_release_reopens_capacity(self):
        pool = SlicePool("p", "2x2")
        g = pool.allocate(want_topology="2x2")
        with pytest.raises(NoCapacity):
            pool.allocate(want_topology="1x1")
        pool.release(g.slice_id)
        assert pool.allocate(want_topology="1x1") is not None

    def test_cordon_change_reopens_capacity(self):
        pool = SlicePool("p", "2x2")
        pool.set_cordoned({(0, 0), (0, 1), (1, 0), (1, 1)})
        for _ in range(2):
            with pytest.raises(NoCapacity):
                pool.allocate(want_topology="1x1")
        pool.set_cordoned(set())
        assert pool.allocate(want_topology="2x2") is not None


class TestAllocateMany:
    def test_gang_grants_are_disjoint(self):
        pool = SlicePool("p", "2x2")
        gs = pool.allocate_many([("1x2", None), ("1x2", None)])
        assert len(gs) == 2
        assert not (_grant_cells(gs[0]) & _grant_cells(gs[1]))
        assert pool.free_chips() == 0

    def test_all_or_nothing_rollback(self):
        pool = SlicePool("p", "2x2")
        # 2 x 1x2 = 4 chips fits the pool's TOTAL but not its current
        # free space: a TRANSIENT NoCapacity that rolls back cleanly
        blocker = pool.allocate(want_topology="1x2")
        with pytest.raises(NoCapacity):
            pool.allocate_many([("1x2", None)] * 2)
        assert pool.free_chips() == 2
        assert pool.schedulable_chips() == 2
        # ...and a release clears it — the rolled-back pool serves the
        # same gang
        pool.release(blocker.slice_id)
        assert len(pool.allocate_many([("1x2", None)] * 2)) == 2

    def test_gang_over_total_capacity_is_permanent(self):
        """A gang bigger than the WHOLE pool can never be cleared by a
        release: permanent PlacementError, never an eternal NoCapacity
        park (the bench-config3 hang, ISSUE 14)."""
        pool = SlicePool("p", "2x2")
        with pytest.raises(PlacementError, match="unplaceable") as ei:
            pool.allocate_many([("1x2", None)] * 3)  # 6 > 4 total
        assert not isinstance(ei.value, NoCapacity)
        assert pool.free_chips() == 4

    def test_siblings_pack_into_a_contiguous_superblock(self):
        """4 x (1x4) siblings on an empty 4x4 pool should land as one
        4x4 super-block: the union of their cells is a contiguous
        bounding box, so branch collectives stay on neighboring ICI."""
        pool = SlicePool("p", "4x4")
        gs = pool.allocate_many([("1x4", None)] * 4)
        cells = set()
        for g in gs:
            cells |= _grant_cells(g)
        assert len(cells) == 16
        xs = [c[0] for c in cells]
        ys = [c[1] for c in cells]
        bbox = (max(xs) - min(xs) + 1) * (max(ys) - min(ys) + 1)
        assert bbox == 16  # contiguous: bounding box == cell count

    def test_mixed_shapes_fall_back_to_individual_blocks(self):
        pool = SlicePool("p", "4x4")
        gs = pool.allocate_many([("2x2", None), (None, 2)])
        assert parse_topology(gs[0].topology) == (2, 2)
        assert len(_grant_cells(gs[1])) == 2
        assert not (_grant_cells(gs[0]) & _grant_cells(gs[1]))

    def test_empty_request_list(self):
        assert SlicePool("p", "2x2").allocate_many([]) == []


class TestPlaceGroup:
    def test_mixed_tpu_and_plain_branches(self):
        placer = SlicePlacer([SlicePool("v5e", "4x4", chips_per_host=4)])
        out = placer.place_group(
            [
                ("train", TPUPolicy(topology="2x2")),
                ("log", None),
                ("eval", TPUPolicy(chips=2)),
            ],
            queue="v5e",
        )
        assert out["log"] is None
        assert parse_topology(out["train"].topology) == (2, 2)
        assert out["train"].mesh_axes == {"data": 1, "model": 4}
        assert len(_grant_cells(out["eval"])) == 2

    def test_group_no_capacity_is_atomic(self):
        pool = SlicePool("tiny", "2x4")
        placer = SlicePlacer([pool])
        blocker = pool.allocate(want_topology="2x2")
        # gang fits the TOTAL pool but not current free space —
        # transient, atomic, pool untouched
        with pytest.raises(NoCapacity):
            placer.place_group(
                [("a", TPUPolicy(topology="2x2")),
                 ("b", TPUPolicy(topology="2x2"))],
                queue="tiny",
            )
        assert pool.free_chips() == 4
        del blocker

    def test_group_over_total_capacity_is_permanent(self):
        pool = SlicePool("tiny", "2x2")
        placer = SlicePlacer([pool])
        with pytest.raises(PlacementError, match="unplaceable"):
            placer.place_group(
                [("a", TPUPolicy(topology="2x2")),
                 ("b", TPUPolicy(topology="2x2"))],
                queue="tiny",
            )
        assert pool.free_chips() == 4

    def test_group_without_tpu_branches_places_nothing(self):
        placer = SlicePlacer()
        out = placer.place_group([("a", None), ("b", TPUPolicy())])
        assert out == {"a": None, "b": None}

    def test_duplicate_branch_names_rejected_before_placing(self):
        """Results key by branch name — a duplicate would shadow its
        sibling's grant and leak the block. Must fail fast with the
        pool untouched."""
        pool = SlicePool("v5e", "4x4")
        placer = SlicePlacer([pool])
        with pytest.raises(ValueError, match="duplicate branch"):
            placer.place_group(
                [("b", TPUPolicy(topology="1x2")),
                 ("b", TPUPolicy(topology="1x2"))],
                queue="v5e",
            )
        assert pool.free_chips() == 16


def _dict_grant_cells(grant):
    return set(_cells(tuple(grant["origin"]), parse_topology(grant["topology"])))


class TestSpanningGrants:
    """One gang across multiple pools (the multi-slice DCN shape):
    balanced round-robin distribution, per-pool ICI-contiguous
    super-blocks, all-or-nothing atomicity across pools, greedy spill,
    and span metadata (replica identity + global process layout)."""

    def _placer(self, *topos, hosts=True):
        pools = []
        for i, topo in enumerate(topos):
            name = f"p{i}"
            pools.append(SlicePool(
                name, topo, chips_per_host=4,
                host_addresses=[f"{name}-h0:8476"] if hosts else None,
            ))
        return SlicePlacer(pools), pools

    def test_balanced_round_robin_with_per_pool_superblocks(self):
        placer, pools = self._placer("4x4", "4x4")
        out = placer.place_group(
            [(f"r{i}", TPUPolicy(topology="2x2")) for i in range(4)],
            pools=["p0", "p1"],
        )
        assert len(out) == 4
        by_pool = {}
        for name, g in out.items():
            by_pool.setdefault(g.pool, []).append(g)
        # balanced: two members per pool
        assert {p: len(gs) for p, gs in by_pool.items()} == {"p0": 2, "p1": 2}
        for gs in by_pool.values():
            cells = set()
            for g in gs:
                c = _grant_cells(g)
                assert not c & cells
                cells |= c
            # same-pool siblings land as one contiguous super-block
            xs = [c[0] for c in cells]
            ys = [c[1] for c in cells]
            assert (max(xs) - min(xs) + 1) * (max(ys) - min(ys) + 1) == len(cells)

    def test_span_metadata_layout(self):
        placer, _ = self._placer("4x4", "4x4")
        out = placer.place_group(
            [(f"r{i}", TPUPolicy(topology="2x4")) for i in range(2)],
            pools=["p0", "p1"],
        )
        g0, g1 = out["r0"], out["r1"]
        assert g0.span["id"] == g1.span["id"]
        assert (g0.span["replica"], g1.span["replica"]) == (0, 1)
        assert g0.span["replicas"] == g1.span["replicas"] == 2
        assert g0.span["pools"] == ["p0", "p1"]
        # 8 chips @ 4/host = 2 hosts each: global process set of 4,
        # member bases 0 and 2, ONE coordinator (member 0's pool)
        assert g0.span["processes"] == g1.span["processes"] == 4
        assert (g0.span["processBase"], g1.span["processBase"]) == (0, 2)
        assert g0.span["coordinator"] == g1.span["coordinator"] == "p0-h0:8476"
        # serialized form carries the span verbatim
        assert g0.to_dict()["span"]["replicas"] == 2

    def test_all_or_nothing_rolls_back_every_pool(self):
        placer, pools = self._placer("4x4", "4x4")
        pools[1].allocate(want_topology="4x4")  # p1 full (transient)
        with pytest.raises(NoCapacity) as ei:
            placer.place_group(
                [(f"r{i}", TPUPolicy(topology="2x2")) for i in range(4)],
                pools=["p0", "p1"], spill=False,
            )
        # truthful per-pool hints in the park message
        assert "p0" in str(ei.value) and "p1" in str(ei.value)
        assert "largest free block" in str(ei.value)
        assert pools[0].free_chips() == 16  # p0 fully rolled back
        assert pools[1].free_chips() == 0

    def test_greedy_spill_packs_unevenly(self):
        placer, pools = self._placer("4x4", "2x2")
        pools[1].allocate(want_topology="2x2")  # p1 full
        out = placer.place_group(
            [("r0", TPUPolicy(topology="2x2")),
             ("r1", TPUPolicy(topology="2x2"))],
            pools=["p0", "p1"], spill=True,
        )
        assert {g.pool for g in out.values()} == {"p0"}
        # span metadata still stamped on the spilled layout
        assert out["r0"].span["replicas"] == 2

    def test_spill_disabled_parks_instead(self):
        placer, pools = self._placer("4x4", "2x2")
        pools[1].allocate(want_topology="2x2")
        with pytest.raises(NoCapacity):
            placer.place_group(
                [("r0", TPUPolicy(topology="2x2")),
                 ("r1", TPUPolicy(topology="2x2"))],
                pools=["p0", "p1"], spill=False,
            )
        assert pools[0].free_chips() == 16

    def test_shape_too_big_for_every_pool_is_permanent(self):
        placer, pools = self._placer("2x2", "2x2")
        with pytest.raises(PlacementError) as ei:
            placer.place_group(
                [("r0", TPUPolicy(topology="4x4"))], pools=["p0", "p1"]
            )
        assert not isinstance(ei.value, NoCapacity)

    def test_oversized_member_spills_to_the_pool_that_fits(self):
        # balanced routing would send member 1 (4x4) to the too-small
        # p1; spill must land it on p0 (largest member packs first) and
        # route the small member to p1
        placer, pools = self._placer("4x4", "2x2")
        out = placer.place_group(
            [("r0", TPUPolicy(topology="2x2")),
             ("r1", TPUPolicy(topology="4x4"))],
            pools=["p0", "p1"],
        )
        assert out["r1"].pool == "p0"
        assert out["r0"].pool == "p1"

    def test_balanced_misfit_with_spill_off_is_permanent(self):
        """Round-robin routes a shape to a pool that can NEVER hold it
        and spill is off: that must be a permanent PlacementError, not
        a NoCapacity park that re-probes forever."""
        placer, pools = self._placer("4x4", "2x2")
        with pytest.raises(PlacementError) as ei:
            placer.place_group(
                [("r0", TPUPolicy(topology="2x4")),
                 ("r1", TPUPolicy(topology="2x4"))],
                pools=["p0", "p1"], spill=False,
            )
        assert not isinstance(ei.value, NoCapacity)
        assert "span-spill" in str(ei.value)
        assert pools[0].free_chips() == 16

    def test_span_coordinator_is_member_zero_only(self):
        """Global process 0 lives on member 0 — when member 0's pool
        declares no addresses, the span coordinator must be None (the
        GKE layer derives one), NEVER another member's address (every
        host would dial a machine where no coordinator listens)."""
        p0 = SlicePool("p0", "4x4", chips_per_host=4)  # no addresses
        p1 = SlicePool("p1", "4x4", chips_per_host=4,
                       host_addresses=["p1-h0:8476"])
        placer = SlicePlacer([p0, p1])
        out = placer.place_group(
            [("r0", TPUPolicy(topology="2x2")),
             ("r1", TPUPolicy(topology="2x2"))],
            pools=["p0", "p1"],
        )
        assert out["r0"].span["coordinator"] is None
        assert out["r1"].span["coordinator"] is None

    def test_unknown_span_pool_fails_loudly(self):
        placer, _ = self._placer("4x4")
        with pytest.raises(PlacementError, match="unknown span pool"):
            placer.place_group(
                [("r0", TPUPolicy(topology="2x2"))], pools=["p0", "ghost"]
            )

    def test_single_pool_span_still_stamps_metadata(self):
        placer, _ = self._placer("4x4")
        out = placer.place_group(
            [("r0", TPUPolicy(topology="2x2")),
             ("r1", TPUPolicy(topology="2x2"))],
            pools=["p0"],
        )
        assert out["r0"].span["replicas"] == 2
        assert out["r0"].pool == out["r1"].pool == "p0"


class TestSpanningChurnOracle:
    """Seeded churn over multiple pools with per-pool brute-force
    mirrors: spanning placement must keep every pool's occupancy exact,
    never overlap grants, and roll back atomically across pools on any
    NoCapacity."""

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_spanning_churn_invariants(self, seed):
        import random

        topos = {"p0": "4x4", "p1": "4x4", "p2": "2x4"}
        pools = {
            n: SlicePool(n, t, chips_per_host=4) for n, t in topos.items()
        }
        placer = SlicePlacer(list(pools.values()))
        refs = {n: BruteForceReference(parse_topology(t))
                for n, t in topos.items()}
        rng = random.Random(seed)
        live = []  # list of grant lists (span gangs)

        def check_counts():
            for n, p in pools.items():
                assert p.free_chips() == (
                    p.total_chips - len(refs[n].occupied)
                ), f"pool {n} drifted"

        for _i in range(150):
            if rng.random() < 0.55 or not live:
                k = rng.randint(2, 4)
                shape = (rng.randint(1, 2), rng.randint(1, 4))
                topo = "x".join(map(str, shape))
                names = [f"r{j}" for j in range(k)]
                before = {n: p.free_chips() for n, p in pools.items()}
                try:
                    out = placer.place_group(
                        [(nm, TPUPolicy(topology=topo)) for nm in names],
                        pools=list(pools),
                        spill=rng.random() < 0.5,
                    )
                except NoCapacity:
                    # atomic: NO pool's occupancy moved
                    after = {n: p.free_chips() for n, p in pools.items()}
                    assert after == before
                else:
                    gang = []
                    for nm in names:
                        g = out[nm]
                        refs[g.pool].occupy(
                            tuple(g.origin), parse_topology(g.topology)
                        )  # raises on any overlap
                        gang.append(g)
                    span_ids = {g.span["id"] for g in gang}
                    assert len(span_ids) == 1
                    assert sorted(g.span["replica"] for g in gang) == list(
                        range(k)
                    )
                    live.append(gang)
            else:
                gang = live.pop(rng.randrange(len(live)))
                for g in gang:
                    pools[g.pool].release(g.slice_id)
                    refs[g.pool].release(
                        tuple(g.origin), parse_topology(g.topology)
                    )
            check_counts()

        while live:
            for g in live.pop():
                pools[g.pool].release(g.slice_id)
                refs[g.pool].release(
                    tuple(g.origin), parse_topology(g.topology)
                )
        for p in pools.values():
            assert p.free_chips() == p.total_chips


class TestChipLedgerChurnBalance:
    """ISSUE 13 acceptance: the chip-seconds ledger balances —
    granted = productive + each waste bucket, EXACTLY, for every grant
    the churn produces. Seeded allocate/release churn with random
    labeled marks drives the ledger the way the controllers do; the
    integer-nanosecond invariant must survive any interleaving of
    marks, zero-length segments, and backwards clock jitter."""

    @pytest.mark.parametrize("seed", [7, 21])
    def test_every_churned_grant_balances_exactly(self, seed):
        from bobrapet_tpu.observability.analytics import ChipLedger

        rng = random.Random(seed)
        pool = SlicePool("churn", "8x8", chips_per_host=4)
        led = ChipLedger()
        outcomes = ["park", "productive", "retry", "preempted", "failed"]
        now = 1000.0
        live = []
        opened = 0
        for _i in range(600):
            # clock advances by messy fractional steps, occasionally
            # stepping BACKWARDS (NTP jitter; the ledger must clamp)
            now += rng.uniform(-0.01, 0.5)
            if rng.random() < 0.55 or not live:
                try:
                    g = pool.allocate(chips=rng.choice([1, 2, 4, 8, 16]))
                except NoCapacity:
                    continue
                led.open_grant(g.to_dict(), now)
                live.append(g)
                opened += 1
            elif rng.random() < 0.5 and live:
                g = rng.choice(live)
                led.account(g.slice_id, rng.choice(outcomes), now)
            else:
                g = live.pop(rng.randrange(len(live)))
                pool.release(g.slice_id)
                led.account(g.slice_id, rng.choice(outcomes), now)
                led.close_grant(g.slice_id, "drain", now)
        for g in live:
            pool.release(g.slice_id)
            led.close_grant(g.slice_id, "drain", now + 1.0)
        assert pool.free_chips() == pool.total_chips

        entries = led.entries()
        assert len(entries) >= opened  # closed-entry ring kept them all
        assert all(e["closed"] for e in entries)
        # THE invariant: zero unbalanced grants, exactly
        assert led.unbalanced() == []
        # and the per-pool totals reconcile with the per-grant sums
        summary = led.summary()["pools"]["churn"]
        total = sum(summary["chipSeconds"].values())
        assert total == pytest.approx(summary["grantedChipSeconds"])


class TestFleetBatchedReplacement:
    def _runtime_with_pool(self):
        from bobrapet_tpu.runtime import Runtime

        rt = Runtime()
        rt.placer.add_pool(SlicePool("v5e", "4x4", chips_per_host=4))
        return rt, rt.placer.pool("v5e")

    def test_replace_grants_re_places_siblings_around_quarantine(self):
        rt, pool = self._runtime_with_pool()
        sib = [g.to_dict() for g in pool.allocate_many([("1x4", None)] * 2)]
        rt.fleet.on_preemption(sib[0], host=0, key="ns/j1")
        news = rt.fleet.replace_grants(sib)
        assert news is not None and len(news) == 2
        quarantined = set(map(tuple, rt.fleet.registry.quarantined_cells("v5e")))
        assert quarantined
        c0, c1 = _dict_grant_cells(news[0]), _dict_grant_cells(news[1])
        assert not c0 & c1
        assert not (c0 | c1) & quarantined

    def test_replace_grants_rejects_cross_pool_siblings(self):
        """Non-SPAN siblings on different pools are a caller bug — only
        grants carrying span metadata may legitimately cross pools."""
        rt, pool = self._runtime_with_pool()
        rt.placer.add_pool(SlicePool("other", "2x2"))
        a = pool.allocate(want_topology="1x2").to_dict()
        b = rt.placer.pool("other").allocate(want_topology="1x2").to_dict()
        with pytest.raises(ValueError, match="span pools"):
            rt.fleet.replace_grants([a, b])

    def _runtime_with_span(self):
        from bobrapet_tpu.runtime import Runtime

        rt = Runtime()
        rt.placer.add_pool(SlicePool("pa", "4x4", chips_per_host=4))
        rt.placer.add_pool(SlicePool("pb", "4x4", chips_per_host=4))
        out = rt.placer.place_group(
            [("r0", TPUPolicy(topology="2x4")),
             ("r1", TPUPolicy(topology="2x4"))],
            pools=["pa", "pb"],
        )
        return rt, [out["r0"].to_dict(), out["r1"].to_dict()]

    def test_replace_grants_spanning_re_places_per_pool(self):
        rt, grants = self._runtime_with_span()
        rt.fleet.on_preemption(grants[0], host=0, key="ns/span-j1")
        news = rt.fleet.replace_grants(grants)
        assert news is not None and len(news) == 2
        # each replacement stays on its member's pool and keeps its
        # logical span identity (replica index, process base, id)
        for old, new in zip(grants, news):
            assert new["pool"] == old["pool"]
            assert new["span"] == old["span"]
        quarantined = set(map(tuple, rt.fleet.registry.quarantined_cells("pa")))
        assert quarantined
        assert not _dict_grant_cells(news[0]) & quarantined

    def test_replace_grants_spanning_rolls_back_on_partial_fit(self):
        """One pool cannot re-place its member: the OTHER pool's fresh
        allocation is handed back and the dead grants stay released —
        no chips leak in either pool, callers park."""
        rt, grants = self._runtime_with_span()
        # quarantine all of pb so its member can never re-place
        rt.fleet.registry.report_preemption(
            "pb", [(x, y) for x in range(4) for y in range(4)], key="k"
        )
        assert rt.fleet.replace_grants(grants) is None
        assert rt.placer.pool("pa").free_chips() == 16  # rolled back
        assert rt.placer.pool("pb").free_chips() == 16  # dead grant freed
        assert rt.placer.pool("pb").schedulable_chips() == 0

    def test_capacity_hint_covers_every_span_pool(self):
        rt, grants = self._runtime_with_span()
        hint = rt.fleet.capacity_hint(grants[0])
        assert "pool pa" in hint and "pool pb" in hint
        # per-pool figures are the exact brute-force largest blocks
        for name in ("pa", "pb"):
            ref = BruteForceReference(parse_topology("4x4"))
            for g in grants:
                if g["pool"] == name:
                    ref.occupy(tuple(g["origin"]), parse_topology(g["topology"]))
            assert (
                f"largest free block {ref.largest_free_block()} chips"
                in hint.split(f"pool {name}:")[1].split(";")[0]
            )

    def test_replace_grants_releases_dead_blocks_even_when_parking(self):
        """Fail fast: the dead gang's chips return to the pool even
        when no replacement fits (callers park on awaitingSlice)."""
        rt, pool = self._runtime_with_pool()
        sib = [g.to_dict() for g in pool.allocate_many([("2x4", None)] * 2)]
        # quarantine everything so nothing can re-place
        rt.fleet.registry.report_preemption(
            "v5e", [(x, y) for x in range(4) for y in range(4)], key="k"
        )
        assert rt.fleet.replace_grants(sib) is None
        assert pool.free_chips() == 16  # released, not leaked
        assert pool.schedulable_chips() == 0  # but all cordoned


class TestLargestFreeAndFragmentation:
    def test_split_free_space(self):
        pool = SlicePool("frag", "4x1")
        pool.set_cordoned({(1, 0), (3, 0)})
        assert pool.schedulable_chips() == 2
        assert pool.largest_free_block() == 1
        assert pool.fragmentation() == pytest.approx(0.5)
        assert metrics.slice_fragmentation.value("frag") == pytest.approx(0.5)

    def test_empty_and_full(self):
        pool = SlicePool("p", "4x4")
        assert pool.largest_free_block() == 16
        assert pool.fragmentation() == pytest.approx(1.0)
        pool.allocate(want_topology="4x4")
        assert pool.largest_free_block() == 0


class TestPropertyChurnEquivalence:
    """Random allocate/release/cordon sequences replayed against the
    retained brute-force reference: the indexed allocator must never
    overlap grants, must restore free counts on release, and must agree
    with the brute-force scan on every single-grant grant/no-capacity
    decision."""

    @pytest.mark.parametrize(
        "topology,seed",
        [
            ("8x8", 1), ("8x8", 2), ("8x8", 3),
            ("4x4x4", 4), ("4x4x4", 5),
            ("16", 6),
            ("2x3", 7),
            ("3x5x2", 8),
        ],
    )
    def test_churn_matches_brute_force(self, topology, seed):
        dims = parse_topology(topology)
        pool = SlicePool(f"pb-{topology}-{seed}", topology, chips_per_host=4)
        ref = BruteForceReference(dims)
        rng = random.Random(seed)
        total = pool.total_chips
        all_cells = [()]
        for d in dims:
            all_cells = [c + (i,) for c in all_cells for i in range(d)]
        live = []  # (slice_id, origin, shape)

        def check_counts():
            assert pool.free_chips() == total - len(ref.occupied)
            assert pool.schedulable_chips() == total - len(
                ref.occupied | ref.cordoned
            )

        for i in range(250):
            op = rng.random()
            if op < 0.08:
                cord = set(
                    rng.sample(all_cells, rng.randrange(0, max(2, total // 6)))
                )
                pool.set_cordoned(cord)
                ref.cordoned = set(cord)
            elif op < 0.62 or not live:
                if rng.random() < 0.5:
                    shape = tuple(rng.randint(1, d) for d in dims)
                    kwargs = {"want_topology": "x".join(map(str, shape))}
                else:
                    chips = rng.randint(1, total)
                    shape = ref.fit_shape(chips)
                    kwargs = {"chips": chips}
                try:
                    g = pool.allocate(**kwargs)
                except NoCapacity:
                    # decision agreement: brute force finds nothing either
                    assert ref.find_block(shape) is None, (
                        f"op {i}: indexed said NoCapacity for {shape} but "
                        f"brute force finds {ref.find_block(shape)}"
                    )
                else:
                    origin = tuple(g.origin)
                    granted = parse_topology(g.topology)
                    assert granted == shape
                    # decision agreement: brute force also finds a block
                    assert ref.find_block(shape) is not None
                    cells = set(_cells(origin, granted))
                    assert all(
                        all(0 <= c < d for c, d in zip(cell, dims))
                        for cell in cells
                    )
                    assert not cells & ref.cordoned, "grant on cordoned cells"
                    ref.occupy(origin, granted)  # raises on overlap
                    live.append((g.slice_id, origin, granted))
            else:
                sid, origin, shape = live.pop(rng.randrange(len(live)))
                pool.release(sid)
                ref.release(origin, shape)
            check_counts()
            if i % 50 == 25 and total <= 64:
                assert pool.largest_free_block() == ref.largest_free_block()

        while live:
            sid, origin, shape = live.pop()
            pool.release(sid)
            ref.release(origin, shape)
        check_counts()
        pool.set_cordoned(set())
        assert pool.free_chips() == total
        assert pool.largest_free_block() == total

    def test_gang_churn_invariants(self):
        """allocate_many under churn: grants stay disjoint (the
        reference's occupy() raises on overlap) and rollback restores
        counts exactly."""
        dims = (4, 4)
        pool = SlicePool("gang-churn", "4x4")
        ref = BruteForceReference(dims)
        rng = random.Random(99)
        live = []
        for _i in range(200):
            if rng.random() < 0.55 or not live:
                k = rng.randint(2, 4)
                shape = (1, rng.randint(1, 4))
                topo = "x".join(map(str, shape))
                try:
                    gs = pool.allocate_many([(topo, None)] * k)
                except NoCapacity:
                    pass
                else:
                    for g in gs:
                        origin = tuple(g.origin)
                        ref.occupy(origin, parse_topology(g.topology))
                        live.append((g.slice_id, origin,
                                     parse_topology(g.topology)))
            else:
                sid, origin, shape = live.pop(rng.randrange(len(live)))
                pool.release(sid)
                ref.release(origin, shape)
            assert pool.free_chips() == 16 - len(ref.occupied)
        while live:
            sid, origin, shape = live.pop()
            pool.release(sid)
        assert pool.free_chips() == 16


class TestOversizeRequests:
    def test_oversize_topology_is_placement_error_not_no_capacity(self):
        pool = SlicePool("p", "2x2")
        with pytest.raises(PlacementError) as ei:
            pool.allocate(want_topology="4x4")
        assert not isinstance(ei.value, NoCapacity)

    def test_oversize_chips(self):
        with pytest.raises(PlacementError):
            SlicePool("p", "2x2").allocate(chips=32)


class TestThreadedChurn:
    def test_concurrent_allocate_release(self):
        """4 workers churning one pool: no overlap (the allocator's
        internal commit guard raises PlacementError on any), no lost
        cells, and the module-level lockdep sees a cycle-free order."""
        pool = SlicePool("threaded", "8x8")
        errors = []
        barrier = threading.Barrier(4)

        def worker(worker_seed):
            rng = random.Random(worker_seed)
            mine = []
            barrier.wait()
            try:
                for _ in range(150):
                    if rng.random() < 0.6 or not mine:
                        try:
                            mine.append(pool.allocate(
                                chips=rng.choice([1, 2, 4, 8, 16])
                            ))
                        except NoCapacity:
                            pass
                    else:
                        pool.release(mine.pop(rng.randrange(len(mine))).slice_id)
                for g in mine:
                    pool.release(g.slice_id)
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(s,)) for s in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors
        assert pool.free_chips() == 64
        assert pool.largest_free_block() == 64
