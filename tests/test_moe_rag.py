"""MoE family, embedding model, and the BASELINE config-5 RAG pipeline.

Compute half: expert-parallel sharding on the virtual 8-device mesh
(expert axis + model TP), routing invariants, gradient flow.
Workflow half: nested executeStory RAG story (embed -> retrieve ->
generate) through the full control plane with real tiny models.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bobrapet_tpu.models import embedder, llama, moe
from bobrapet_tpu.parallel.sharding import moe_param_specs, shard_params


class TestRouting:
    def test_dispatch_combine_shapes_and_mass(self):
        cfg = moe.moe_tiny()
        t = 32
        logits = jax.random.normal(jax.random.PRNGKey(0), (t, cfg.n_experts))
        dispatch, combine, aux = moe.route_topk(logits, cfg)
        c = cfg.capacity(t)
        assert dispatch.shape == (t, cfg.n_experts, c)
        assert combine.shape == (t, cfg.n_experts, c)
        # every expert slot holds at most one token
        assert float(dispatch.sum(axis=(0,))[0].max()) <= 1.0
        # each token is dispatched at most k times, and combine mass per
        # token is <= 1 (== 1 when nothing was capacity-dropped)
        per_token = dispatch.sum(axis=(1, 2))
        assert float(per_token.max()) <= cfg.experts_per_token
        assert float(combine.sum(axis=(1, 2)).max()) <= 1.0 + 1e-5
        assert float(aux) > 0.0

    def test_capacity_drops_overflow(self):
        cfg = moe.moe_tiny()
        t = 16
        # all tokens want expert 0 -> only `capacity` of them may land
        logits = jnp.zeros((t, cfg.n_experts)).at[:, 0].set(100.0)
        dispatch, _, _ = moe.route_topk(logits, cfg)
        c = cfg.capacity(t)
        assert float(dispatch[:, 0].sum()) <= c

    def test_forward_and_grad(self):
        cfg = moe.moe_tiny()
        p = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits, _, aux = jax.jit(lambda p, t: moe.forward(p, t, cfg))(p, toks)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert jnp.isfinite(logits).all()
        g = jax.grad(lambda p: moe.loss_fn(p, toks[:, :-1], toks[:, 1:], cfg))(p)
        norms = jax.tree_util.tree_map(lambda x: float(jnp.abs(x).sum()), g)
        router_grad = norms["layers"][0]["moe"]["w_router"]
        assert router_grad > 0.0  # routing is differentiable via gates


class TestExpertParallel:
    def test_expert_sharded_forward_matches_replicated(self):
        cfg = moe.moe_tiny()  # 4 experts
        p = moe.init_params(jax.random.PRNGKey(0), cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        ref, _, _ = jax.jit(lambda p, t: moe.forward(p, t, cfg))(p, toks)

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("data", "expert"))
        sharded = shard_params(p, mesh, specs=moe_param_specs(p, mesh))
        tok_sharded = jax.device_put(toks, NamedSharding(mesh, P("data")))

        @jax.jit
        def run(params, tokens):
            logits, _, _ = moe.forward(params, tokens, cfg)
            return logits

        out = run(sharded, tok_sharded)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def test_moe_specs_cover_tree(self):
        cfg = moe.moe_tiny()
        p = moe.init_params(jax.random.PRNGKey(0), cfg)
        devs = np.array(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devs, ("expert",))
        specs = moe_param_specs(p, mesh)
        jax.tree_util.tree_map(
            lambda x, s: None, p, specs,
            is_leaf=lambda x: hasattr(x, "shape") and not isinstance(x, dict),
        )  # mismatched structure would raise
        assert specs["layers"][0]["moe"]["w_gate"] == P("expert")


class TestEmbedder:
    def test_encode_normalized_and_deterministic(self):
        cfg = embedder.embed_tiny()
        p = embedder.init_params(jax.random.PRNGKey(0), cfg)
        docs = jax.random.randint(jax.random.PRNGKey(1), (3, 12), 0, cfg.vocab_size)
        e1 = embedder.encode(p, docs, cfg)
        e2 = embedder.encode(p, docs, cfg)
        np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))
        np.testing.assert_allclose(
            np.asarray(jnp.linalg.norm(e1, axis=-1)), np.ones(3), atol=1e-5
        )

    def test_mask_changes_pooling(self):
        cfg = embedder.embed_tiny()
        p = embedder.init_params(jax.random.PRNGKey(0), cfg)
        docs = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, cfg.vocab_size)
        full = embedder.encode(p, docs, cfg)
        half = embedder.encode(
            p, docs, cfg, mask=jnp.arange(12)[None, :] < 6
        )
        assert float(jnp.abs(full - half).max()) > 1e-6

    def test_padding_length_invariance(self):
        """Embeddings must not depend on how much padding follows the
        real tokens: the mask gates attention keys, not just pooling
        (ADVICE: embedder.py:51)."""
        cfg = embedder.embed_tiny()
        p = embedder.init_params(jax.random.PRNGKey(0), cfg)
        real = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 1, cfg.vocab_size)
        short = jnp.concatenate([real, jnp.zeros((1, 2), real.dtype)], axis=1)
        long = jnp.concatenate([real, jnp.full((1, 10), 7, real.dtype)], axis=1)
        m_short = jnp.arange(8)[None, :] < 6
        m_long = jnp.arange(16)[None, :] < 6
        e_short = embedder.encode(p, short, cfg, mask=m_short)
        e_long = embedder.encode(p, long, cfg, mask=m_long)
        np.testing.assert_allclose(
            np.asarray(e_short), np.asarray(e_long), atol=1e-5
        )

    def test_retrieval_selfmatch(self):
        cfg = embedder.embed_tiny()
        p = embedder.init_params(jax.random.PRNGKey(0), cfg)
        docs = jax.random.randint(jax.random.PRNGKey(1), (6, 12), 0, cfg.vocab_size)
        emb = embedder.encode(p, docs, cfg)
        _, idx = embedder.cosine_topk(emb, emb, k=1)
        assert [int(i) for i in idx[:, 0]] == list(range(6))


class TestRAGPipeline:
    def test_nested_executestory_rag(self, rt):
        """BASELINE config 5 shape: an outer story whose retrieve stage is
        a nested executeStory (embed -> retrieve), feeding generation."""
        from bobrapet_tpu.api.catalog import make_engram_template
        from bobrapet_tpu.api.engram import make_engram
        from bobrapet_tpu.api.story import make_story
        from bobrapet_tpu.sdk import register_engram

        ecfg = embedder.embed_tiny()
        eparams = embedder.init_params(jax.random.PRNGKey(0), ecfg)
        corpus_tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 12), 0, ecfg.vocab_size
        )
        corpus_emb = embedder.encode(eparams, corpus_tokens, ecfg)

        gcfg = llama.llama_tiny()
        gparams = llama.init_params(jax.random.PRNGKey(2), gcfg)

        for n, ep in (("embedder", "rag-embed"), ("retriever", "rag-retrieve"),
                      ("generator", "rag-generate")):
            rt.apply(make_engram_template(f"{n}-tpl", entrypoint=ep))
            rt.apply(make_engram(n, f"{n}-tpl"))

        @register_engram("rag-embed")
        def embed_impl(ctx):
            # embed the "query" (deterministic token ids from its hash)
            seed = abs(hash(ctx.inputs["query"])) % (2**31)
            q = jax.random.randint(
                jax.random.PRNGKey(seed), (1, 12), 0, ecfg.vocab_size
            )
            vec = embedder.encode(eparams, q, ecfg)
            return {"vector": np.asarray(vec[0]).tolist()}

        @register_engram("rag-retrieve")
        def retrieve_impl(ctx):
            q = jnp.asarray([ctx.inputs["vector"]], jnp.float32)
            _, idx = embedder.cosine_topk(q, corpus_emb, k=3)
            return {"docIds": [int(i) for i in idx[0]]}

        @register_engram("rag-generate")
        def generate_impl(ctx):
            ids = ctx.inputs["docIds"]
            prompt = jnp.asarray(
                [[i % gcfg.vocab_size for i in ids] + [1, 2]], jnp.int32
            )
            toks = llama.greedy_generate(gparams, prompt, gcfg, max_new_tokens=4)
            return {"tokens": np.asarray(toks[0]).tolist(), "nDocs": len(ids)}

        # inner story: embed -> retrieve
        rt.apply(make_story("retrieve-docs", steps=[
            {"name": "embed", "ref": {"name": "embedder"},
             "with": {"query": "{{ inputs.query }}"}},
            {"name": "retrieve", "ref": {"name": "retriever"},
             "with": {"vector": "{{ steps.embed.output.vector }}"}},
        ], output={"docIds": "{{ steps.retrieve.output.docIds }}"}))

        # outer story: executeStory(retrieve-docs) -> generate
        rt.apply(make_story("rag", steps=[
            {"name": "lookup", "type": "executeStory",
             "with": {"storyRef": {"name": "retrieve-docs"},
                      "with": {"query": "{{ inputs.question }}"}}},
            {"name": "answer", "ref": {"name": "generator"},
             "with": {"docIds": "{{ steps.lookup.output.docIds }}"}},
        ], output={"tokens": "{{ steps.answer.output.tokens }}",
                   "nDocs": "{{ steps.answer.output.nDocs }}"}))

        run = rt.run_story("rag", inputs={"question": "what is a bobrapet?"})
        rt.pump()
        assert rt.run_phase(run) == "Succeeded"
        out = rt.run_output(run)
        assert out["nDocs"] == 3
        assert len(out["tokens"]) == 4
        # the nested run exists and completed
        subruns = [
            r for r in rt.store.list("StoryRun")
            if (r.spec.get("storyRef") or {}).get("name") == "retrieve-docs"
        ]
        assert len(subruns) == 1
        assert subruns[0].status["phase"] == "Succeeded"
