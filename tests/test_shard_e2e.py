"""Sharded control plane — end-to-end over N in-process managers.

Every test here runs N full Runtimes (dispatcher pools, threaded gang
executor, shard coordinator each) against ONE shared ResourceStore —
the in-process model of N manager replicas behind one API server — with
the PR 4 lock-order sanitizer armed and the double-reconcile detector
installed on every shard. The invariant under test everywhere: **no run
family is ever reconciled by two shards at once**, across steady state,
cross-shard ``executeStory`` handoff, join/leave rebalances, and crash
recovery.

The scaling soak (``TestShardedSoak``) is the acceptance measurement:
4 shards must sustain >= 3x the single-shard steps/s on the same
workload. The workload is latency-bound (sleeping engrams under a
per-manager ``scheduling.global-max-concurrent-steps`` budget) because
in-process shards share the GIL — production runs one process per
shard, so coordination overhead, not compute parallelism, is what this
harness can honestly measure (see docs/SCALING.md). The fast leg runs
in tier-1; the long churn leg is ``slow``-marked.
"""

from __future__ import annotations

import gc
import time

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.enums import Phase
from bobrapet_tpu.api.runs import STORY_RUN_KIND
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.sdk import register_engram
from bobrapet_tpu.shard import HashRing, ShardedControlPlane
from bobrapet_tpu.utils.naming import compose_unique


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    """Lockdep for the sharded suites (see test_concurrency.py): N
    managers over one bus is the widest lock surface in the repo —
    store RLock x N dispatcher pools x coordinator barriers."""
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace over the sharded e2e suite: router parked sets, store
    indexes and dispatcher pools across N shard managers are tracked
    (see test_concurrency.py for the contract). The churn soak arms a
    seeded JitterSchedule on top (BOBRA_RACE_SEED replays a failure)."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


def _install_workload(cp: ShardedControlPlane, entry: str,
                      sleep_s: float = 0.0, steps: int = 1) -> None:
    """A ``steps``-deep chain story backed by a sleeping engram."""

    @register_engram(entry)
    def impl(ctx):
        if sleep_s:
            time.sleep(sleep_s)
        return {"i": ctx.inputs.get("i", 0)}

    cp.apply(make_engram_template(f"{entry}-tpl", entrypoint=entry))
    cp.apply(make_engram(f"{entry}-worker", f"{entry}-tpl"))
    from bobrapet_tpu.api.story import make_story

    defs = [{"name": "s0", "ref": {"name": f"{entry}-worker"},
             "with": {"i": "{{ inputs.i }}"}}]
    for i in range(1, steps):
        defs.append({"name": f"s{i}", "ref": {"name": f"{entry}-worker"},
                     "needs": [f"s{i-1}"],
                     "with": {"i": "{{ steps.s%d.output.i }}" % (i - 1)}})
    cp.apply(make_story(f"{entry}-story", steps=defs))


def _wait_for_leader(cp: ShardedControlPlane, timeout: float = 15.0) -> str:
    """Condition-wait until SOME shard holds the leader lease and
    return its sid, captured inside the predicate — leadership can
    lapse between lease renewals, so a separate probe after the wait
    reintroduces the StopIteration flake this exists to kill."""
    found: list[str] = []

    def probe() -> bool:
        found[:] = [sid for sid, rt in cp.runtimes.items()
                    if rt.shard_coordinator.elector.is_leader]
        return bool(found)

    cp.wait_until(probe, timeout, "no shard ever took the leader lease")
    return found[0]


def _assert_all_succeeded(cp: ShardedControlPlane, runs) -> None:
    """Terminal + succeeded + nothing orphaned (every run accounted).
    On failure, dump the family's StepRuns and recorded events — churn
    flakes are rare enough that the forensics must ride the assert."""
    from bobrapet_tpu.api.runs import STEP_RUN_KIND

    for r in runs:
        phase = cp.run_phase(r)
        if phase == Phase.SUCCEEDED:
            continue
        run = cp.store.try_get(STORY_RUN_KIND, "default", r)
        detail = [f"run {r}: phase={phase} status={run and run.status}"]
        for sr in cp.store.list(STEP_RUN_KIND, "default"):
            if (sr.spec.get("storyRunRef") or {}).get("name") == r:
                detail.append(f"  step {sr.meta.name}: {sr.status}")
        for ev in cp.recorder.all():
            if r in (getattr(ev, "name", "") or "") or r in (ev.message or ""):
                detail.append(f"  event {ev.reason}: {ev.message}")
        raise AssertionError("\n".join(detail))


class TestCrossShardHandoff:
    def test_execute_story_spans_two_shards(self):
        """An ``executeStory`` parent on shard A whose child StoryRun
        hashes to shard B: creation through the shared store IS the
        handoff; the child must run on B while A's waiting step
        observes completion — with zero double-reconciles."""
        cp = ShardedControlPlane(shards=2, heartbeat_interval=0.25,
                                 member_ttl=3.0, lease_duration=4.0)
        ring = HashRing(["0", "1"])
        # pick a parent run name owned by shard 0 whose child
        # (compose_unique is deterministic) is owned by shard 1
        parent = child = None
        for i in range(2000):
            cand = f"handoff-{i}"
            sub = compose_unique(cand, "sub", "sub")
            if (ring.owner(f"default/{cand}") == "0"
                    and ring.owner(f"default/{sub}") == "1"):
                parent, child = cand, sub
                break
        assert parent is not None, "no cross-shard name pair found"

        with cp:
            cp.wait_members({"0", "1"})
            _install_workload(cp, "shard-handoff-leaf")
            from bobrapet_tpu.api.story import make_story

            cp.apply(make_story("handoff-parent", steps=[
                {"name": "sub", "type": "executeStory",
                 "with": {"storyRef": {"name": "shard-handoff-leaf-story"},
                          "with": {"i": 7}}},
            ]))
            before = metrics.shard_handoffs.value("1")
            run = cp.run_story("handoff-parent", inputs={}, name=parent)
            cp.wait_runs([run], timeout=30.0)
            # the child ran to completion on the other shard
            cp.wait_runs([child], timeout=10.0)

        _assert_all_succeeded(cp, [run, child])
        child_r = cp.store.get(STORY_RUN_KIND, "default", child)
        assert child_r.meta.labels["bobrapet.io/story-run"] == parent
        # the accepting shard recorded the handoff
        assert metrics.shard_handoffs.value("1") == before + 1
        assert any(
            ev.reason == "CrossShardHandoff" and ev.labels.get("shard") == "1"
            for ev in cp.recorder.all()
        )
        cp.detector.assert_clean()


class TestRebalance:
    def test_join_and_leave_churn_mid_soak(self, _race_sanitizer):
        """Shard join + graceful leave while runs are in flight: the
        drain/ack/promote barrier must hand families over with zero
        double-owned and zero orphaned runs.

        The historical "cannot index NoneType with .i" flake (a
        dependent StepRun resolving ``steps.<sib>.output`` from a
        StoryRun view lagging the sibling's output patch during a
        drain) is FIXED: the StepRun controller now heals the scope
        from authoritative StepRun state and requeues on view lag
        (steprun.StaleRunScope; pinned by tests/test_stale_scope.py).
        The all-succeeded assert below stays armed as the detector —
        if it ever fires again, a NEW lost-work path exists; do not
        de-assert it."""
        import os as _os

        from bobrapet_tpu.analysis.schedules import JitterSchedule

        # seeded perturbation at every tracked shared-state access: a
        # race this soak exposes replays from the printed seed via
        # BOBRA_RACE_SEED=<seed> (see docs/ANALYSIS.md, bobrarace)
        seed = int(_os.environ.get("BOBRA_RACE_SEED", "1337"))
        print(f"bobrarace churn soak: JitterSchedule seed={seed}")
        cp = ShardedControlPlane(shards=2, heartbeat_interval=0.25,
                                 member_ttl=3.0, lease_duration=4.0)
        with _race_sanitizer.scoped_schedule(JitterSchedule(seed)), cp:
            cp.wait_members({"0", "1"})
            _install_workload(cp, "shard-churn", sleep_s=0.05, steps=2)
            runs = []

            def submit(n):
                for _ in range(n):
                    runs.append(cp.run_story(
                        "shard-churn-story", inputs={"i": len(runs)}))

            submit(12)
            joined = cp.add_shard()  # live join mid-flight
            cp.wait_members({"0", "1", joined}, timeout=30.0)
            submit(12)
            cp.leave_shard("1")  # graceful leave mid-flight
            cp.wait_members({"0", joined}, timeout=30.0)
            submit(8)
            cp.wait_runs(runs, timeout=90.0)

        _assert_all_succeeded(cp, runs)
        cp.detector.assert_clean()
        # both original shards AND the joiner actually processed work
        assert set(cp.detector.processed) >= {"0", "1", joined}
        # at least two rebalance barriers cleared (join + leave)
        epochs = [rt.shard_router.active_epoch
                  for rt in cp.runtimes.values()]
        assert min(epochs) >= 2, epochs

    def test_crash_detection_republishes_and_recovers(self):
        """A killed shard (no drain, no ack): the leader detects the
        stale member heartbeat, republishes without it, and the
        survivors resync the orphaned families to completion."""
        cp = ShardedControlPlane(shards=2, heartbeat_interval=0.2,
                                 member_ttl=1.2, lease_duration=2.0)
        with cp:
            cp.wait_members({"0", "1"})
            _install_workload(cp, "shard-crash", sleep_s=0.02)
            runs = [cp.run_story("shard-crash-story", inputs={"i": i})
                    for i in range(16)]
            # kill the NON-leader so map publication survives the crash
            # (leader crash also recovers, but through lease expiry —
            # that path is the slow churn leg's job). The leader is
            # captured INSIDE the wait predicate: leadership is an
            # event, not an invariant of any instant, and a second
            # probe after the wait could land in a between-renewals gap
            leader = _wait_for_leader(cp)
            victim = next(sid for sid in cp.runtimes if sid != leader)
            cp.kill_shard(victim)
            survivor = next(iter(cp.runtimes))
            cp.wait_members({survivor}, timeout=30.0)
            cp.wait_runs(runs, timeout=90.0)

        _assert_all_succeeded(cp, runs)
        cp.detector.assert_clean()

    def test_leader_crash_takeover_via_lease_expiry(self):
        """A killed LEADER releases nothing (kill_shard crashes the
        coordinator first): the survivor must take the shard-leader
        lease by OUTLIVING its TTL — the expiry + fencing-epoch-bump
        path a graceful release never exercises — then republish and
        resync the orphaned families to completion."""
        cp = ShardedControlPlane(shards=2, heartbeat_interval=0.2,
                                 member_ttl=1.2, lease_duration=2.0)
        with cp:
            cp.wait_members({"0", "1"})
            _install_workload(cp, "shard-leadercrash", sleep_s=0.02)
            runs = [cp.run_story("shard-leadercrash-story",
                                 inputs={"i": i}) for i in range(12)]
            # leadership is an EVENT, not an invariant of any instant:
            # between lease renewals on a loaded box an instantaneous
            # probe can see nobody leading (observed StopIteration ~1
            # in 10 tier-1 runs) — the wait predicate CAPTURES the
            # leader in the same observation that proves one exists
            victim = _wait_for_leader(cp)
            old_fence = cp.runtimes[victim].shard_coordinator.elector.fence_token
            cp.kill_shard(victim)
            survivor = next(iter(cp.runtimes))
            cp.wait_members({survivor}, timeout=30.0)
            cp.wait_runs(runs, timeout=90.0)

            elector = cp.runtimes[survivor].shard_coordinator.elector
            assert elector.is_leader
            # takeover was a steal past the dead leader's epoch, not a
            # renewal of a released lease
            assert elector.fence_token > old_fence

        _assert_all_succeeded(cp, runs)
        cp.detector.assert_clean()


class TestShardedSoak:
    #: soak shape (calibrated on the 2-core CI box, see docs/SCALING.md):
    #: one sleeping step per run under a per-manager
    #: ``scheduling.global-max-concurrent-steps`` budget. The workload
    #: is deliberately latency-dominated — in-process shards share one
    #: GIL, so reconcile CPU must stay well under a core for the
    #: coordination scaling (the thing this harness can honestly
    #: measure) to show through. Ideal steps/s = shards x CAP / SLEEP.
    SLEEP_S = 0.6
    CAP_PER_SHARD = 2
    WINDOW_PER_SHARD = 6  # closed-loop outstanding runs per shard

    @pytest.fixture(autouse=True)
    def _gc_posture(self):
        """The manager's long-lived-server GC posture (see
        test_scale_soak.py): late in tier-1 the process heap is large
        and default gen0 thresholds tax the GIL-bound 4-shard leg
        disproportionately — production shards are fresh processes."""
        saved = gc.get_threshold()
        gc.set_threshold(100_000, 50, 50)
        yield
        gc.set_threshold(*saved)

    def _steady_state_soak(self, shards: int, measure_s: float = 6.0,
                           warmup_s: float = 2.5):
        """Closed-loop steady-state measurement: keep WINDOW_PER_SHARD x
        shards runs outstanding, count completions inside the timed
        window only (warmup fills the pipeline; the drain tail is
        excluded). Returns (steps_per_sec, control_plane)."""
        def configure(cfg):
            cfg.scheduling.global_max_concurrent_steps = self.CAP_PER_SHARD
            # liveness backstop only: slot refill is event-driven
            # (Runtime._wake_capacity_parked), so the probe timer no
            # longer sets the refill latency
            cfg.scheduling.queue_probe_interval = 1.0

        cp = ShardedControlPlane(
            shards=shards, heartbeat_interval=0.25, member_ttl=3.0,
            lease_duration=4.0, configure=configure,
        )
        with cp:
            cp.wait_members({str(i) for i in range(shards)})
            _install_workload(cp, f"shard-soak-{shards}",
                              sleep_s=self.SLEEP_S)
            sps = cp.steady_state_steps_per_sec(
                f"shard-soak-{shards}-story",
                window=self.WINDOW_PER_SHARD * shards,
                measure_s=measure_s, warmup_s=warmup_s,
            )
        return sps, cp

    def test_four_shards_share_steady_state_work(self):
        """The tier-1 leg of the old 3x acceptance test, made
        DETERMINISTIC: the wall-clock throughput ratio flaked ~5/10 on
        a loaded 1-core CI box (steps/s is a property of the box, not
        the architecture), so tier-1 now pins only event/condition
        facts — a closed-loop 4-shard soak completes every run (the
        wait_runs condition wait replaces the timed window), EVERY
        shard processed work, ownership was disjoint (detector), and
        nothing was lost or double-finished. The throughput claim
        itself lives where wall-clock belongs: the slow-marked ratio
        leg below and the bench's gated `sharded_steps_per_sec`
        lineage (scaling_x recorded per run)."""
        def configure(cfg):
            cfg.scheduling.global_max_concurrent_steps = self.CAP_PER_SHARD
            cfg.scheduling.queue_probe_interval = 1.0

        cp = ShardedControlPlane(
            shards=4, heartbeat_interval=0.25, member_ttl=3.0,
            lease_duration=4.0, configure=configure,
        )
        n_runs = 32
        with cp:
            cp.wait_members({str(i) for i in range(4)})
            _install_workload(cp, "shard-soak-fast", sleep_s=0.05)
            runs, done = [], 0
            # closed loop: keep a bounded window outstanding so all
            # four shards stay busy without depending on timing
            while done < n_runs:
                while (len(runs) < n_runs
                       and len(runs) - done < 4 * self.CAP_PER_SHARD):
                    runs.append(cp.run_story("shard-soak-fast-story",
                                             inputs={"i": len(runs)}))
                done = sum(
                    cp.run_phase(r) in (Phase.SUCCEEDED, Phase.FAILED)
                    for r in runs)
                time.sleep(0.02)
            cp.wait_runs(runs, timeout=60.0)

        _assert_all_succeeded(cp, runs)
        cp.detector.assert_clean()
        # all four shards genuinely shared the work (hash-ring spread
        # over 32 run families makes an idle shard an ownership bug,
        # not a scheduling accident)
        assert len(cp.detector.processed) == 4, cp.detector.processed

    @pytest.mark.slow
    def test_four_shards_sustain_3x_single_shard(self):
        """The wall-clock acceptance measurement (4 cooperating
        managers >= 3x one manager's steps/s on the same per-manager
        budget), slow-marked out of tier-1: the ratio is real on an
        idle box (4.1-4.4x measured) but a loaded single-core CI
        runner fails it ~5/10 through scheduler noise alone. The bench
        regression gate (`sharded_steps_per_sec` + recorded scaling_x)
        guards the trend on every bench run."""
        single_sps, cp1 = self._steady_state_soak(shards=1)
        cp1.detector.assert_clean()
        ratio = 0.0
        for attempt in range(3):
            if attempt:
                # a retry means something (CI neighbor, scheduler
                # hiccup) stole CPU — RE-measure the single-shard leg
                # back-to-back with the 4-shard one so the thief taxes
                # both sides of the ratio, and escalate the window to
                # amortize a transient it can't hide from
                single_sps, cp1 = self._steady_state_soak(
                    shards=1, measure_s=6.0 + 3.0 * attempt)
                cp1.detector.assert_clean()
            quad_sps, cp4 = self._steady_state_soak(
                shards=4, measure_s=6.0 + 3.0 * attempt)
            cp4.detector.assert_clean()
            # all four shards genuinely shared the work
            assert len(cp4.detector.processed) == 4
            ratio = max(ratio, quad_sps / single_sps)
            if ratio >= 3.0:
                break
        assert ratio >= 3.0, (
            f"4-shard soak only {ratio:.2f}x single shard "
            f"({quad_sps:.1f} vs {single_sps:.1f} steps/s)"
        )

    def test_soak_with_rebalance_event_stays_clean(self):
        """A shard joins mid-soak: the barrier rebalance must complete
        under load with zero double-reconciles and zero lost runs."""
        def configure(cfg):
            cfg.scheduling.global_max_concurrent_steps = self.CAP_PER_SHARD
            cfg.scheduling.queue_probe_interval = 1.0

        cp = ShardedControlPlane(
            shards=2, heartbeat_interval=0.25, member_ttl=3.0,
            lease_duration=4.0, configure=configure,
        )
        n_runs = 40
        with cp:
            cp.wait_members({"0", "1"})
            _install_workload(cp, "shard-soak-reb", sleep_s=0.1)
            runs, done, joined = [], 0, None
            while done < n_runs:
                while len(runs) < n_runs and len(runs) - done < 12:
                    runs.append(cp.run_story("shard-soak-reb-story",
                                             inputs={"i": len(runs)}))
                if joined is None and done >= n_runs // 3:
                    joined = cp.add_shard()  # live join mid-soak
                done = sum(
                    cp.run_phase(r) in (Phase.SUCCEEDED, Phase.FAILED)
                    for r in runs)
                time.sleep(0.02)
            cp.wait_members({"0", "1", joined}, timeout=30.0)
            cp.wait_runs(runs, timeout=60.0)

        _assert_all_succeeded(cp, runs)
        cp.detector.assert_clean()
        epochs = [rt.shard_router.active_epoch
                  for rt in cp.runtimes.values()]
        assert min(epochs) >= 2, f"join never promoted: {epochs}"

    @pytest.mark.slow
    def test_long_churn_soak(self, _race_sanitizer):
        """The long leg: repeated join/leave cycles under sustained
        load — minutes of wall clock, excluded from tier-1."""
        import os as _os

        from bobrapet_tpu.analysis.schedules import JitterSchedule

        def configure(cfg):
            cfg.scheduling.global_max_concurrent_steps = self.CAP_PER_SHARD
            cfg.scheduling.queue_probe_interval = 0.05

        seed = int(_os.environ.get("BOBRA_RACE_SEED", "20260807"))
        print(f"bobrarace long churn soak: JitterSchedule seed={seed}")
        cp = ShardedControlPlane(
            shards=2, heartbeat_interval=0.25, member_ttl=3.0,
            lease_duration=4.0, configure=configure,
        )
        with _race_sanitizer.scoped_schedule(JitterSchedule(seed)), cp:
            cp.wait_members({"0", "1"})
            _install_workload(cp, "shard-churn-long", sleep_s=0.05,
                              steps=2)
            runs = []
            alive = {"0", "1"}
            for cycle in range(3):
                for _ in range(20):
                    runs.append(cp.run_story(
                        "shard-churn-long-story",
                        inputs={"i": len(runs)}))
                sid = cp.add_shard()
                alive.add(sid)
                cp.wait_members(alive, timeout=30.0)
                for _ in range(20):
                    runs.append(cp.run_story(
                        "shard-churn-long-story",
                        inputs={"i": len(runs)}))
                victim = sorted(alive)[cycle % len(alive)]
                cp.leave_shard(victim)
                alive.discard(victim)
                cp.wait_members(alive, timeout=30.0)
            cp.wait_runs(runs, timeout=300.0)

        _assert_all_succeeded(cp, runs)
        cp.detector.assert_clean()
