"""HF Llama checkpoint conversion: our model math pinned to the
canonical transformers implementation at the LOGIT level."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from bobrapet_tpu.models import llama  # noqa: E402
from bobrapet_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    load_hf,
    params_from_hf_state_dict,
)


@pytest.fixture(scope="module")
def hf_model():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=160,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10_000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    return model


class TestHFConversion:
    def test_config_mapping(self, hf_model):
        cfg = config_from_hf(hf_model.config)
        assert (cfg.vocab_size, cfg.dim, cfg.n_layers) == (160, 64, 2)
        assert (cfg.n_heads, cfg.n_kv_heads, cfg.ffn_hidden) == (4, 2, 128)
        assert cfg.rope_theta == 10_000.0

    def test_logits_match_transformers(self, hf_model):
        """The whole model — embeddings, RMSNorm, GQA attention, RoPE
        convention, SwiGLU, head — agrees with transformers' forward."""
        params, cfg = load_hf(hf_model, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (2, 24))
        with torch.no_grad():
            want = hf_model(torch.tensor(ids)).logits.numpy()
        got, _ = llama.forward(params, jnp.asarray(ids, jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)

    def test_greedy_continuations_agree(self, hf_model):
        params, cfg = load_hf(hf_model, dtype=jnp.float32)
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (1, 10))
        with torch.no_grad():
            want = hf_model.generate(
                torch.tensor(ids), max_new_tokens=6, do_sample=False,
                pad_token_id=0,
            ).numpy()[0, 10:]
        got = llama.greedy_generate(
            params, jnp.asarray(ids, jnp.int32), cfg,
            max_new_tokens=6, cache_capacity=32,
        )
        np.testing.assert_array_equal(np.asarray(got)[0], want)

    def test_converted_tree_serves_and_quantizes(self, hf_model):
        """Converted weights drop into the serving engine and int8
        path unchanged."""
        from bobrapet_tpu.models import quant
        from bobrapet_tpu.serving import PagedConfig, ServingEngine

        params, cfg = load_hf(hf_model, dtype=jnp.float32)
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, cfg.vocab_size, 9).tolist()
        want = np.asarray(llama.greedy_generate(
            params, jnp.asarray(prompt, jnp.int32)[None], cfg,
            max_new_tokens=4, cache_capacity=32))[0].tolist()
        eng = ServingEngine(params, cfg, PagedConfig(
            max_slots=2, block_size=8, num_blocks=16, max_blocks_per_seq=4))
        eng.submit(prompt, max_new_tokens=4)
        assert eng.run()[0].output == want
        qp = quant.quantize_params(params)  # int8 path accepts the tree
        assert qp["layers"][0]["attn"]["wq"]["q"].dtype == jnp.int8

    def test_missing_weight_named(self, hf_model):
        cfg = config_from_hf(hf_model.config)
        sd = dict(hf_model.state_dict())
        sd.pop("model.layers.1.mlp.up_proj.weight")
        with pytest.raises(KeyError, match="up_proj"):
            params_from_hf_state_dict(sd, cfg)


class TestRopeScaling:
    def test_llama3_rope_scaling_matches_transformers(self):
        """Llama-3.1-style long-context checkpoints (rope_type=llama3)
        convert AND agree with transformers' scaled-RoPE forward."""
        hf_cfg = transformers.LlamaConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=96,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=256,
            rope_theta=10_000.0,
            rope_scaling={
                "rope_type": "llama3",
                "factor": 8.0,
                "low_freq_factor": 1.0,
                "high_freq_factor": 4.0,
                "original_max_position_embeddings": 64,
            },
            tie_word_embeddings=False,
            attention_bias=False,
            mlp_bias=False,
        )
        torch.manual_seed(2)
        model = transformers.LlamaForCausalLM(hf_cfg).eval()
        params, cfg = load_hf(model, dtype=jnp.float32)
        assert cfg.rope_scaling == (8.0, 1.0, 4.0, 64)
        rng = np.random.default_rng(3)
        # positions BEYOND the original 64-token context exercise the
        # scaled band for real
        ids = rng.integers(0, cfg.vocab_size, (1, 150))
        with torch.no_grad():
            want = model(torch.tensor(ids)).logits.numpy()
        got, _ = llama.forward(params, jnp.asarray(ids, jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)

    def test_unsupported_scaling_types_rejected(self, hf_model):
        from bobrapet_tpu.models.convert import config_from_hf

        cfg_dict = {
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "num_hidden_layers": 1, "num_attention_heads": 4,
            "num_key_value_heads": 2, "max_position_embeddings": 64,
            "rope_scaling": {"rope_type": "yarn", "factor": 4.0},
        }
        with pytest.raises(ValueError, match="yarn"):
            config_from_hf(cfg_dict)


class TestMixtralConversion:
    """The MoE family pinned to transformers' MixtralForCausalLM."""

    @pytest.fixture(scope="class")
    def hf_mixtral(self):
        hf_cfg = transformers.MixtralConfig(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=96,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            num_local_experts=4,
            num_experts_per_tok=2,
            max_position_embeddings=128,
            rope_theta=10_000.0,
            sliding_window=None,
            tie_word_embeddings=False,
            attention_bias=False,
        )
        torch.manual_seed(3)
        model = transformers.MixtralForCausalLM(hf_cfg)
        model.eval()
        return model

    def test_logits_match_transformers(self, hf_mixtral):
        """Routing (softmax -> top-2 -> renormalize), expert SwiGLU,
        dispatch/combine, and attention all agree with the canonical
        implementation (no-drop capacity)."""
        from bobrapet_tpu.models import moe
        from bobrapet_tpu.models.convert import load_hf_mixtral

        params, cfg = load_hf_mixtral(hf_mixtral, dtype=jnp.float32)
        assert cfg.n_experts == 4 and cfg.experts_per_token == 2
        rng = np.random.default_rng(4)
        ids = rng.integers(0, cfg.vocab_size, (2, 20))
        with torch.no_grad():
            want = hf_mixtral(torch.tensor(ids)).logits.numpy()
        got, _, _ = moe.forward(params, jnp.asarray(ids, jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)

    def test_expert_weight_mapping(self, hf_mixtral):
        from bobrapet_tpu.models.convert import load_hf_mixtral

        params, cfg = load_hf_mixtral(hf_mixtral, dtype=jnp.float32)
        moe_layer = params["layers"][0]["moe"]
        assert moe_layer["w_gate"].shape == (4, 64, 96)   # [E, D, F]
        assert moe_layer["w_down"].shape == (4, 96, 64)   # [E, F, D]
        assert moe_layer["w_router"].shape == (64, 4)     # [D, E]
        sd = hf_mixtral.state_dict()
        np.testing.assert_allclose(
            np.asarray(moe_layer["w_gate"][1]),
            sd["model.layers.0.block_sparse_moe.experts.1.w1.weight"
               ].numpy().T,
            rtol=1e-6,
        )
