"""Chart <-> code webhook drift (ADVICE r5).

`cluster/admission.webhook_configurations()` builds the Mutating/
Validating WebhookConfiguration manifests from what is actually
registered on the store — the chart's `webhooks.yaml` is the
hand-maintained Service-based mirror of the same list. Like the
schema<->webhook parity suite (test_admission_parity.py), this renders
the chart template and diffs webhook names, paths, and rules against
the code-built configurations, so adding a webhook chain without
updating the chart (or vice versa) fails here instead of shipping a
cluster that silently skips admission for a kind.
"""

from __future__ import annotations

import os

import pytest
import yaml

from bobrapet_tpu.cluster.admission import webhook_configurations
from bobrapet_tpu.runtime import Runtime

CHART = os.path.join(
    os.path.dirname(__file__), "..",
    "deploy", "chart", "bobrapet-tpu", "templates", "webhooks.yaml",
)
PORT = "9443"


def render_chart() -> dict[str, dict]:
    """Poor-man's helm template: drop control directives, substitute the
    few values the webhook template consumes, parse the YAML stream."""
    with open(CHART) as f:
        text = f.read()
    text = "\n".join(
        line for line in text.splitlines()
        if not line.strip().startswith("{{-")
    )
    text = (
        text.replace("{{ .Release.Name }}", "rel")
        .replace("{{ .Release.Namespace }}", "ns")
        .replace("{{ .Values.webhooks.port }}", PORT)
    )
    docs = [d for d in yaml.safe_load_all(text) if d]
    return {
        d["kind"]: d
        for d in docs
        if d["kind"].endswith("WebhookConfiguration")
    }


@pytest.fixture(scope="module")
def chart_configs():
    return render_chart()


@pytest.fixture(scope="module")
def code_configs():
    rt = Runtime()
    return {
        c["kind"]: c
        for c in webhook_configurations(
            rt.store, f"https://host:{PORT}", "test-ca"
        )
    }


CONFIG_KINDS = ["MutatingWebhookConfiguration", "ValidatingWebhookConfiguration"]


class TestChartWebhookDrift:
    def test_both_configuration_kinds_exist_in_both(self, chart_configs, code_configs):
        assert set(chart_configs) == set(CONFIG_KINDS)
        assert set(code_configs) == set(CONFIG_KINDS)

    @pytest.mark.parametrize("kind", CONFIG_KINDS)
    def test_webhook_names_match(self, chart_configs, code_configs, kind):
        chart = {w["name"] for w in chart_configs[kind]["webhooks"]}
        code = {w["name"] for w in code_configs[kind]["webhooks"]}
        assert chart == code, (
            f"{kind} drifted: chart-only={sorted(chart - code)}, "
            f"code-only={sorted(code - chart)} — update "
            f"deploy/chart/bobrapet-tpu/templates/webhooks.yaml or the "
            f"registered admission chain"
        )

    @pytest.mark.parametrize("kind", CONFIG_KINDS)
    def test_paths_and_rules_match(self, chart_configs, code_configs, kind):
        chart = {w["name"]: w for w in chart_configs[kind]["webhooks"]}
        code = {w["name"]: w for w in code_configs[kind]["webhooks"]}
        for name in sorted(set(chart) & set(code)):
            # chart uses Service client config, code uses URL mode: the
            # request path must be identical either way
            chart_path = chart[name]["clientConfig"]["service"]["path"]
            code_path = code[name]["clientConfig"]["url"].split(PORT, 1)[1]
            assert chart_path == code_path, (
                f"{name}: chart serves {chart_path}, code expects {code_path}"
            )
            chart_rule = chart[name]["rules"][0]
            code_rule = code[name]["rules"][0]
            for field in ("apiGroups", "apiVersions", "operations", "resources"):
                assert sorted(chart_rule[field]) == sorted(code_rule[field]), (
                    f"{name}: rule field {field} drifted "
                    f"({chart_rule[field]} vs {code_rule[field]})"
                )

    @pytest.mark.parametrize("kind", CONFIG_KINDS)
    def test_chart_webhooks_fail_closed(self, chart_configs, kind):
        """Every chart webhook keeps failurePolicy: Fail and sideEffects:
        None — the posture the code-built configurations pin."""
        for w in chart_configs[kind]["webhooks"]:
            assert w["failurePolicy"] == "Fail", w["name"]
            assert w["sideEffects"] == "None", w["name"]
            assert w["admissionReviewVersions"] == ["v1"], w["name"]
