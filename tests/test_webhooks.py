"""Admission webhook layer: the validation matrix per kind.

Mirrors the reference's webhook tests (SURVEY §2.3 —
internal/webhook/v1alpha1/story_webhook.go validations,
internal/webhook/runs/v1alpha1/{storyrun,steprun}_webhook.go,
transport_webhook.go). Each test drives admission through the store the
way the reference's envtest suites drive the real API server.
"""

import pytest

from bobrapet_tpu.api.catalog import make_engram_template, make_impulse_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.impulse import make_impulse
from bobrapet_tpu.api.policy import make_reference_grant
from bobrapet_tpu.api.runs import make_storyrun
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.api.transport import make_transport
from bobrapet_tpu.core.object import new_resource
from bobrapet_tpu.core.store import AdmissionDenied
from bobrapet_tpu.runtime import Runtime


def denied(fn, match=None):
    with pytest.raises(AdmissionDenied, match=match):
        fn()


class TestStoryWebhook:
    def test_step_requires_exactly_one_of_ref_or_type(self, rt):
        denied(lambda: rt.apply(make_story("s1", steps=[{"name": "x"}])),
               "exactly one of")
        denied(lambda: rt.apply(make_story("s2", steps=[
            {"name": "x", "ref": {"name": "e"}, "type": "sleep"}])),
            "exactly one of")

    def test_duplicate_step_names_rejected(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition"},
            {"name": "a", "type": "condition"},
        ])), "duplicate step name")

    def test_unknown_needs_rejected(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition", "needs": ["ghost"]},
        ])), "unknown step")

    def test_self_dependency_rejected(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition", "needs": ["a"]},
        ])), "cannot depend on itself")

    def test_needs_cycle_rejected(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition", "needs": ["b"]},
            {"name": "b", "type": "condition", "needs": ["a"]},
        ])), "cycle")

    def test_batch_only_primitives_rejected_in_realtime(self, rt):
        for prim in ("wait", "gate"):
            denied(lambda p=prim: rt.apply(make_story(
                f"rt-{p}", pattern="realtime",
                steps=[{"name": "x", "type": p,
                        **({"with": {"until": "{{ inputs.go }}"}} if p == "wait" else {})}],
            )), "batch-only")

    def test_sleep_requires_duration(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "z", "type": "sleep"}])), "duration")
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "z", "type": "sleep", "with": {"duration": "not-a-time"}}])),
            "invalid duration")

    def test_wait_shape(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "w", "type": "wait"}])), "until")
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "w", "type": "wait",
             "with": {"until": "{{ inputs.x }}", "onTimeout": "explode"}}])),
            "fail.*or.*skip")

    def test_wait_ontimeout_defaulted(self, rt):
        rt.apply(make_story("s", steps=[
            {"name": "w", "type": "wait", "with": {"until": "{{ inputs.x }}"}}]))
        stored = rt.store.get("Story", "default", "s")
        assert stored.spec["steps"][0]["with"]["onTimeout"] == "fail"

    def test_execute_story_requires_ref(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "sub", "type": "executeStory"}])), "storyRef")

    def test_execute_story_self_cycle_rejected(self, rt):
        denied(lambda: rt.apply(make_story("loop", steps=[
            {"name": "sub", "type": "executeStory",
             "with": {"storyRef": {"name": "loop"}}}])), "own story")

    def test_execute_story_transitive_cycle_rejected(self, rt):
        rt.apply(make_story("a", steps=[{"name": "c", "type": "condition"}]))
        rt.apply(make_story("b", steps=[
            {"name": "sub", "type": "executeStory",
             "with": {"storyRef": {"name": "a"}}}]))
        # now updating `a` to call `b` would close the cycle b -> a -> b
        denied(lambda: rt.apply(make_story("a", steps=[
            {"name": "sub", "type": "executeStory",
             "with": {"storyRef": {"name": "b"}}}])), "cycle")

    def test_parallel_requires_branches(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "p", "type": "parallel"}])), "non-empty")
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "p", "type": "parallel",
             "with": {"steps": [
                 {"name": "inner", "type": "parallel",
                  "with": {"steps": [{"name": "x", "type": "condition"}]}},
             ]}}])), "nest")

    def test_parallel_replicas_spelling_validated(self, rt):
        # replicas + steps together is ambiguous
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "p", "type": "parallel",
             "with": {"replicas": 2,
                      "step": {"name": "r", "ref": {"name": "w"}},
                      "steps": [{"name": "b", "ref": {"name": "w"}}]}}])),
               "not both")
        # replicas must be a positive integer with a step template
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "p", "type": "parallel",
             "with": {"replicas": 0,
                      "step": {"name": "r", "ref": {"name": "w"}}}}])),
               "replicas")
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "p", "type": "parallel",
             "with": {"replicas": 2, "step": {"name": "r",
                                              "ref": {"name": "w"}},
                      "pools": []}}])), "pools")
        # a replicated fan-out nested inside another parallel is
        # rejected at admission like the explicit spelling
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "p", "type": "parallel",
             "with": {"steps": [
                 {"name": "inner", "type": "parallel",
                  "with": {"replicas": 2,
                           "step": {"name": "r", "ref": {"name": "w"}}}},
             ]}}])), "nest")

    def test_template_scope_validation(self, rt):
        # `steps` root is not available in realtime static config scope
        denied(lambda: rt.apply(make_story(
            "rts", pattern="realtime",
            steps=[{"name": "a", "type": "condition",
                    "with": {"v": "{{ steps.other.output.x }}"}}],
        )), "steps")
        # packet root is invalid in batch scope
        denied(lambda: rt.apply(make_story(
            "bat", steps=[{"name": "a", "type": "condition",
                           "with": {"v": "{{ packet.data }}"}}],
        )), "packet")

    def test_template_syntax_error_rejected(self, rt):
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition", "if": "{{ inputs. }}"}])))

    def test_with_size_cap(self, rt):
        big = {"blob": "x" * (300 * 1024)}  # default cap is 256KiB
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition", "with": big}])), "exceeds cap")
        # the cap is live config (max-story-with-block-size-bytes)
        rt.config_manager.config.max_story_with_block_size_bytes = 16
        denied(lambda: rt.apply(make_story("s", steps=[
            {"name": "a", "type": "condition", "with": {"k": "0123456789abcdef"}}])),
            "exceeds cap")

    def test_execute_story_cycle_through_finally_rejected(self, rt):
        rt.apply(make_story("fa", steps=[{"name": "c", "type": "condition"}]))
        rt.apply(make_story("fb", steps=[{"name": "c", "type": "condition"}],
                            **{"finally": [
                                {"name": "sub", "type": "executeStory",
                                 "with": {"storyRef": {"name": "fa"}}}]}))
        denied(lambda: rt.apply(make_story("fa", steps=[
            {"name": "sub", "type": "executeStory",
             "with": {"storyRef": {"name": "fb"}}}])), "cycle")

    def test_policy_timeouts_parsed(self, rt):
        denied(lambda: rt.apply(make_story(
            "s", steps=[{"name": "a", "type": "condition"}],
            policy={"timeouts": {"story": "eleventy"}})), "invalid duration")
        denied(lambda: rt.apply(make_story(
            "s", steps=[{"name": "a", "type": "condition"}],
            policy={"concurrency": 0})), "concurrency")

    def test_valid_story_admitted(self, rt):
        rt.apply(make_story("good", steps=[
            {"name": "a", "type": "sleep", "with": {"duration": "1s"}},
            {"name": "b", "needs": ["a"], "type": "stop",
             "with": {"phase": "success"}},
        ], policy={"timeouts": {"story": "5m"}}))
        assert rt.store.get("Story", "default", "good")


class TestEngramImpulseWebhooks:
    def test_engram_requires_existing_template(self, rt):
        denied(lambda: rt.apply(make_engram("e", "ghost-tpl")), "not found")

    def test_engram_mode_must_be_supported(self, rt):
        rt.apply(make_engram_template("tpl", entrypoint="x",
                                      supportedModes=["job"]))
        denied(lambda: rt.apply(make_engram("e", "tpl", mode="deployment")),
               "supportedModes")

    def test_engram_secret_schema_conformance(self, rt):
        rt.apply(make_engram_template(
            "tpl", entrypoint="x",
            secretSchema=[{"name": "api-key", "required": True}]))
        denied(lambda: rt.apply(make_engram("e", "tpl")), "required secret")
        denied(lambda: rt.apply(make_engram(
            "e", "tpl", secrets={"api-key": "s1", "rogue": "s2"})),
            "not declared")
        rt.apply(make_engram("e", "tpl", secrets={"api-key": "s1"}))

    def test_impulse_requires_template_and_story(self, rt):
        denied(lambda: rt.apply(make_impulse("i", "ghost", "story")), "not found")
        rt.apply(make_impulse_template("itpl", image="img"))
        denied(lambda: rt.apply(make_impulse("i", "itpl", "")), "storyRef")

    def test_impulse_cross_namespace_denied_by_default(self, rt):
        rt.apply(make_impulse_template("itpl", image="img"))
        rt.apply(make_story("target", steps=[{"name": "a", "type": "condition"}],
                            namespace="other"))
        denied(lambda: rt.apply(make_impulse(
            "i", "itpl", "target",
            storyRef={"name": "target", "namespace": "other"})),
            "denied by policy")

    def test_impulse_cross_namespace_with_grant(self, rt):
        rt.config_manager.config.reference_cross_namespace_policy = "grant"
        rt.apply(make_impulse_template("itpl", image="img"))
        rt.apply(make_story("target", steps=[{"name": "a", "type": "condition"}],
                            namespace="other"))
        rt.apply(make_reference_grant(
            "allow-impulses", "other",
            from_=[{"kind": "Impulse", "namespace": "default"}],
            to=[{"kind": "Story"}],
        ))
        rt.apply(make_impulse("i", "itpl", "target",
                              storyRef={"name": "target", "namespace": "other"}))


class TestStoryRunWebhook:
    def test_story_ref_required(self, rt):
        denied(lambda: rt.store.create(
            new_resource("StoryRun", "r", "default", {})), "storyRef")

    def test_inputs_schema_validated(self, rt):
        rt.apply(make_story(
            "s", steps=[{"name": "a", "type": "condition"}],
            inputsSchema={"type": "object", "required": ["msg"],
                          "properties": {"msg": {"type": "string"}}}))
        denied(lambda: rt.store.create(make_storyrun("r1", "s", inputs={})),
               "required property")
        denied(lambda: rt.store.create(
            make_storyrun("r2", "s", inputs={"msg": 42})), "expected string")
        rt.store.create(make_storyrun("r3", "s", inputs={"msg": "ok"}))

    def test_inputs_schema_integer_rejects_bool(self, rt):
        rt.apply(make_story(
            "si", steps=[{"name": "a", "type": "condition"}],
            inputsSchema={"type": "object",
                          "properties": {"count": {"type": "integer"}}}))
        denied(lambda: rt.store.create(
            make_storyrun("rb", "si", inputs={"count": True})),
            "expected integer")

    def test_status_invariants_hold_on_create_and_full_update(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))
        # create with bogus caller-supplied status
        bad = make_storyrun("rc", "s")
        bad.status = {"observedGeneration": 7}
        denied(lambda: rt.store.create(bad), "ahead of")
        # full update carrying a status regression
        rt.store.create(make_storyrun("ru", "s"))
        rt.store.patch_status("StoryRun", "default", "ru",
                              lambda s: s.__setitem__("observedGeneration", 1))

        def regress(r):
            r.status["observedGeneration"] = 0

        denied(lambda: rt.store.mutate("StoryRun", "default", "ru", regress),
               "regress")

    def test_inputs_size_cap(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))
        denied(lambda: rt.store.create(
            make_storyrun("r", "s", inputs={"blob": "x" * (1100 * 1024)})),
            "exceeds")

    def test_storage_ref_spoofing_rejected(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))
        denied(lambda: rt.store.create(make_storyrun(
            "r", "s",
            inputs={"stolen": {"storageRef": {"key": "runs/victim-ns/run/x",
                                              "provider": "memory"}}})),
            "outside namespace")
        # a marker buried beside other keys is still a marker at runtime
        # (is_storage_ref semantics) — admission must see it too
        denied(lambda: rt.store.create(make_storyrun(
            "rb", "s",
            inputs={"d": {"storageRef": {"key": "runs/victim-ns/run/x"},
                          "pad": 1}})),
            "outside namespace")
        # refs under the caller's own canonical scope are legitimate
        rt.store.create(make_storyrun(
            "r2", "s",
            inputs={"mine": {"storageRef": {"key": "runs/default/run/x",
                                            "provider": "memory"}}}))

    def test_oversized_inputs_offload_then_readmit(self, rt):
        # the controller's own dehydrated writes (runs/<ns>/... keys) must
        # pass admission or oversized-input runs wedge in a retry loop
        from bobrapet_tpu.api.catalog import make_engram_template as mk_tpl
        from bobrapet_tpu.api.engram import make_engram as mk_eng
        from bobrapet_tpu.sdk.registry import register_engram

        rt.apply(mk_tpl("t", entrypoint="impl"))
        rt.apply(mk_eng("w", "t"))
        register_engram("impl")(lambda ctx: {"n": len(ctx.inputs.get("blob", ""))})
        rt.apply(make_story("big", steps=[
            {"name": "a", "ref": {"name": "w"},
             "with": {"blob": "{{ inputs.blob }}"}}]))
        run = rt.run_story("big", inputs={"blob": "x" * (80 * 1024)})
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Succeeded", r.status

    def test_cancel_cannot_be_withdrawn(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))
        rt.store.create(make_storyrun("r", "s"))
        rt.store.mutate("StoryRun", "default", "r",
                        lambda r: r.spec.__setitem__("cancelRequested", True))
        denied(lambda: rt.store.mutate(
            "StoryRun", "default", "r",
            lambda r: r.spec.__setitem__("cancelRequested", False)),
            "withdrawn")

    def test_observed_generation_monotonic(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))
        rt.store.create(make_storyrun("r", "s"))
        rt.store.patch_status("StoryRun", "default", "r",
                              lambda s: s.__setitem__("observedGeneration", 1))
        denied(lambda: rt.store.patch_status(
            "StoryRun", "default", "r",
            lambda s: s.__setitem__("observedGeneration", 0)), "regress")
        denied(lambda: rt.store.patch_status(
            "StoryRun", "default", "r",
            lambda s: s.__setitem__("observedGeneration", 99)), "ahead of")


class TestStepRunWebhook:
    def _mk(self, rt, name="sr", **spec):
        base = {"storyRunRef": {"name": "run"}, "engramRef": {"name": "e"},
                "stepId": "s"}
        base.update(spec)
        return new_resource("StepRun", name, "default", base)

    def test_required_refs(self, rt):
        denied(lambda: rt.store.create(
            new_resource("StepRun", "sr", "default", {})), "storyRunRef")

    def test_downstream_target_shape(self, rt):
        denied(lambda: rt.store.create(self._mk(
            rt, downstreamTargets=[{}])), "exactly one")
        denied(lambda: rt.store.create(self._mk(
            rt, downstreamTargets=[{"grpc": {"host": "", "port": 9000}}])),
            "host is required")
        denied(lambda: rt.store.create(self._mk(
            rt, downstreamTargets=[{"grpc": {"host": "h", "port": 99999}}])),
            "port")
        rt.store.create(self._mk(
            rt, downstreamTargets=[{"grpc": {"host": "h", "port": 9000}},
                                   {"terminate": True}]))

    def test_structured_error_contract_on_status(self, rt):
        rt.store.create(self._mk(rt))
        denied(lambda: rt.store.patch_status(
            "StepRun", "default", "sr",
            lambda s: s.__setitem__("error", {"type": "martian"})),
            "unknown error type")
        denied(lambda: rt.store.patch_status(
            "StepRun", "default", "sr",
            lambda s: s.__setitem__("error", "exploded")),
            "StructuredError")
        rt.store.patch_status(
            "StepRun", "default", "sr",
            lambda s: s.__setitem__(
                "error", {"type": "execution", "message": "boom",
                          "exitClass": "terminal", "retryable": False}))

    def test_oversized_status_output_rejected(self, rt):
        rt.store.create(self._mk(rt))
        denied(lambda: rt.store.patch_status(
            "StepRun", "default", "sr",
            lambda s: s.__setitem__("output", {"x": "y" * (1100 * 1024)})),
            "offload")


class TestTriggerClaimWebhooks:
    def test_trigger_identity_requirements(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))

        def trig(identity):
            return new_resource("StoryTrigger", "t", "default",
                                {"storyRef": {"name": "s"}, "identity": identity})

        denied(lambda: rt.store.create(
            new_resource("StoryTrigger", "t", "default",
                         {"storyRef": {"name": "s"}})), "identity is required")
        denied(lambda: rt.store.create(trig({"mode": "key"})), "key")
        denied(lambda: rt.store.create(trig(
            {"mode": "keyAndInputHash", "key": "k"})), "inputHash")
        denied(lambda: rt.store.create(trig(
            {"mode": "keyAndInputHash", "key": "k", "inputHash": "zz"})),
            "sha256")
        denied(lambda: rt.store.create(trig({"mode": "none"})), "submissionId")
        rt.store.create(trig({"mode": "key", "key": "order-123"}))

    def test_trigger_identity_immutable(self, rt):
        rt.apply(make_story("s", steps=[{"name": "a", "type": "condition"}]))
        rt.store.create(new_resource(
            "StoryTrigger", "t", "default",
            {"storyRef": {"name": "s"},
             "identity": {"mode": "key", "key": "k1"}}))
        denied(lambda: rt.store.mutate(
            "StoryTrigger", "default", "t",
            lambda r: r.spec["identity"].__setitem__("key", "k2")),
            "immutable")

    def test_effect_claim_shape(self, rt):
        denied(lambda: rt.store.create(
            new_resource("EffectClaim", "c", "default", {})), "effectId")
        denied(lambda: rt.store.create(new_resource(
            "EffectClaim", "c", "default",
            {"effectId": "charge-1", "stepRunRef": {"name": "sr"},
             "holderIdentity": "sdk-1", "leaseDurationSeconds": 0})),
            ">= 1")
        rt.store.create(new_resource(
            "EffectClaim", "c", "default",
            {"effectId": "charge-1", "stepRunRef": {"name": "sr"},
             "holderIdentity": "sdk-1", "leaseDurationSeconds": 30}))


class TestTransportWebhooks:
    def test_transport_driver_and_provider(self, rt):
        denied(lambda: rt.store.create(
            new_resource("Transport", "t", "default", {"driver": "carrier-pigeon"})),
            "driver")
        denied(lambda: rt.apply(make_transport("t", "", driver="grpc")), "provider")

    def test_ici_driver_requires_topology(self, rt):
        denied(lambda: rt.apply(make_transport("t", "tpu", driver="ici")),
               "meshTopology")
        rt.apply(make_transport("t", "tpu", driver="ici", meshTopology="4x4"))

    def test_streaming_settings_validated(self, rt):
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"delivery": {"semantics": "exactlyOnceHonest"}})),
            "semantics")
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"fanIn": {"mode": "quorum"}})), "quorum")
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"lanes": [{"name": "a"}, {"name": "a"}]})),
            "duplicate lane")

    def test_binding_shape(self, rt):
        denied(lambda: rt.store.create(
            new_resource("TransportBinding", "b", "default", {})), "transportRef")

    def test_inert_settings_rejected(self, rt):
        """Settings the data plane cannot honor are rejected at
        admission, not silently ignored (VERDICT: 'inert config')."""
        # credit knobs without credit mode
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"flowControl": {
                "mode": "none", "initialCredits": {"messages": 8}}})),
            "flowControl.mode=credits")
        # credits mode without any credits
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"flowControl": {"mode": "credits"}})),
            "initialCredits")
        # atLeastOnce without the ack protocol
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"delivery": {"semantics": "atLeastOnce"}})),
            "ack")
        # total ordering across partitions
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={
                "delivery": {"ordering": "total"},
                "partitioning": {"mode": "keyHash", "key": "{{ packet.id }}"}})),
            "partitions")
        # hysteresis inversion
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"flowControl": {
                "mode": "credits", "initialCredits": {"messages": 8},
                "pauseThreshold": {"bufferPct": 50},
                "resumeThreshold": {"bufferPct": 80}}})),
            "hysteresis")
        # fromCheckpoint replay became ENFORCED in round 4 (durable
        # consumer checkpoints in the hub's record store); it now needs
        # the ack protocol + a retention bound
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"delivery": {
                "replay": {"mode": "fromCheckpoint"}}})),
            "ack")
        rt.apply(make_transport(
            "t-ckpt", "p", streaming={
                "flowControl": {"mode": "credits",
                                "initialCredits": {"messages": 8},
                                "ackEvery": {"messages": 1}},
                "delivery": {"semantics": "atLeastOnce",
                             "replay": {"mode": "fromCheckpoint",
                                        "retentionSeconds": 3600,
                                        "checkpointInterval": "5s"}}}))
        # cutover with a drain timeout
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"lifecycle": {
                "strategy": "cutover", "drainTimeoutSeconds": 10}})),
            "strategy=drain")
        # sampling without a rate
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"recording": {"mode": "sample"}})),
            "sampleRate")
        # partitioning and recording became ENFORCED in round 4
        # (dataplane/partition.py, dataplane/recording.py) — valid
        # configs are now admitted
        rt.apply(make_transport(
            "t-part", "p", streaming={
                "partitioning": {"mode": "keyHash", "key": "{{ packet.id }}",
                                 "partitions": 4}}))
        rt.apply(make_transport(
            "t-rec", "p", streaming={
                "recording": {"mode": "sample", "sampleRate": 10}}))
        # ...but partitions without a mode still make no sense
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"partitioning": {"partitions": 4}})),
            "requires mode")
        # watermarks became ENFORCED in round 4 (hub event-time
        # frontier tracking); valid configs are admitted
        rt.apply(make_transport(
            "t-wm", "p", streaming={
                "observability": {"watermark": {
                    "enabled": True,
                    "timestampSource": "metadata.event_time_ms"}}}))
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={
                "observability": {"watermark": {
                    "enabled": True,
                    "timestampSource": "not a path!"}}})),
            "dotted field path")
        denied(lambda: rt.apply(make_transport(
            "t", "p", streaming={"delivery": {
                "replay": {"mode": "fromCheckpoint",
                           "checkpointInterval": "30s"}}})),
            "ack protocol")
        # a coherent credit + ack + replay config is admitted — with the
        # ENFORCED replay mode (hub retained history + fromSeq rejoin)
        rt.apply(make_transport("t-ok", "p", streaming={
            "backpressure": {"buffer": {"maxMessages": 64,
                                        "dropPolicy": "dropOldest"}},
            "flowControl": {"mode": "credits",
                            "initialCredits": {"messages": 16},
                            "ackEvery": {"messages": 4},
                            "pauseThreshold": {"bufferPct": 80},
                            "resumeThreshold": {"bufferPct": 40}},
            "delivery": {"semantics": "atLeastOnce", "ordering": "perKey",
                         "replay": {"mode": "full",
                                    "retentionSeconds": 3600}},
        }))


class TestWebhookToggle:
    def test_disabled_webhooks_admit_anything(self):
        rt = Runtime(enable_webhooks=False)
        rt.apply(make_story("junk", steps=[{"name": "x"}]))  # no ref/type
        assert rt.store.get("Story", "default", "junk")
