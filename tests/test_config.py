"""Operator config parsing/live-reload + hierarchical resolver tests."""

from bobrapet_tpu.api.catalog import EngramTemplateSpec
from bobrapet_tpu.api.engram import EngramSpec
from bobrapet_tpu.api.enums import OffloadedDataPolicy
from bobrapet_tpu.api.shared import ExecutionOverrides
from bobrapet_tpu.api.story import Step, StoryPolicy
from bobrapet_tpu.config import (
    OperatorConfig,
    OperatorConfigManager,
    Resolver,
    parse_config,
)
from bobrapet_tpu.core import ResourceStore, new_resource


class TestParseConfig:
    def test_dotted_keys(self):
        cfg = parse_config(
            {
                "controllers.max-concurrent-reconciles": "8",
                "templating.offloaded-data-policy": "inject",
                "templating.deterministic": "false",
                "engram.max-inline-size": "4096",
                "scheduling.global-max-concurrent-steps": "50",
                "scheduling.queue.v5e-pool.max-concurrent": "4",
                "scheduling.queue.v5e-pool.accelerator": "tpu-v5-lite-podslice",
                "scheduling.queue.v5e-pool.chip-budget": "16",
                "reference-cross-namespace-policy": "grant",
                "retention.children-ttl": "30m",
                "timeouts.approval": "2h",
            }
        )
        assert cfg.controllers.max_concurrent_reconciles == 8
        assert cfg.templating.offloaded_data_policy is OffloadedDataPolicy.INJECT
        assert not cfg.templating.deterministic
        assert cfg.engram.max_inline_size == 4096
        assert cfg.scheduling.global_max_concurrent_steps == 50
        q = cfg.scheduling.queue("v5e-pool")
        assert q.max_concurrent == 4 and q.chip_budget == 16
        assert cfg.reference_cross_namespace_policy == "grant"
        assert cfg.retention.children_ttl_seconds == 1800
        assert cfg.timeouts.approval_seconds == 7200

    def test_invalid_values_keep_defaults(self):
        cfg = parse_config({"engram.grpc-port": "not-a-port", "unknown.key": "x"})
        assert cfg.engram.grpc_port == 50051

    def test_per_controller_max_concurrent_reconciles(self):
        cfg = parse_config({
            "controllers.max-concurrent-reconciles": "2",
            "controllers.steprun.max-concurrent-reconciles": "16",
            "controllers.storyrun.max-concurrent-reconciles": "8",
        })
        assert cfg.controllers.max_concurrent_reconciles == 2
        assert cfg.controllers.per_controller == {"steprun": 16, "storyrun": 8}

    def test_per_controller_invalid_value_ignored(self):
        cfg = parse_config({
            "controllers.steprun.max-concurrent-reconciles": "lots",
        })
        assert cfg.controllers.per_controller == {}

    def test_validation(self):
        cfg = OperatorConfig()
        cfg.reference_cross_namespace_policy = "maybe"
        assert any("referenceCrossNamespacePolicy" in e for e in cfg.validate())

    def test_validation_rejects_nonpositive_pool_width(self):
        cfg = OperatorConfig()
        cfg.controllers.per_controller = {"steprun": 0}
        assert any(
            "controllers.steprun.max-concurrent-reconciles" in e
            for e in cfg.validate()
        )


class TestLiveReload:
    def test_manager_watches_configmap(self):
        store = ResourceStore()
        mgr = OperatorConfigManager(store, namespace="sys", name="op")
        assert mgr.config.engram.max_inline_size == 16 * 1024
        seen = []
        mgr.subscribe(lambda c: seen.append(c.engram.max_inline_size))
        store.create(
            new_resource("ConfigMap", "op", "sys", spec={"data": {"engram.max-inline-size": "1234"}})
        )
        assert mgr.config.engram.max_inline_size == 1234
        assert seen == [1234]
        store.mutate(
            "ConfigMap", "sys", "op",
            lambda r: r.spec.update(data={"engram.max-inline-size": "99"}),
        )
        assert mgr.config.engram.max_inline_size == 99

    def test_initial_load_from_existing(self):
        store = ResourceStore()
        store.create(
            new_resource("ConfigMap", "op", "sys", spec={"data": {"logging.verbosity": "3"}})
        )
        mgr = OperatorConfigManager(store, namespace="sys", name="op")
        assert mgr.config.verbosity == 3

    def test_invalid_reload_keeps_last_good(self):
        store = ResourceStore()
        mgr = OperatorConfigManager(store, namespace="sys", name="op")
        store.create(
            new_resource(
                "ConfigMap", "op", "sys",
                spec={"data": {"reference-cross-namespace-policy": "chaos"}},
            )
        )
        assert mgr.config.reference_cross_namespace_policy == "deny"


class TestResolver:
    def test_layering_order(self):
        cfg = OperatorConfig()
        r = Resolver(cfg)
        template = EngramTemplateSpec.from_dict(
            {
                "image": "gcr.io/x/llama:1",
                "entrypoint": "engrams.llama:run",
                "executionPolicy": {
                    "timeout": "20m",
                    "retry": {"maxRetries": 5},
                    "resources": {"requests": {"cpu": "4"}},
                },
            }
        )
        engram = EngramSpec.from_dict(
            {"templateRef": {"name": "t"}, "execution": {"retry": {"maxRetries": 7}}}
        )
        policy = StoryPolicy.from_dict(
            {"execution": {"timeout": "10m"}, "storage": {"maxInlineSize": 2048}}
        )
        step = Step.from_dict(
            {
                "name": "gen",
                "ref": {"name": "llama"},
                "execution": {"timeout": "5m"},
                "tpu": {"topology": "2x4", "accelerator": "tpu-v5-lite-podslice"},
            }
        )
        overrides = ExecutionOverrides.from_dict({"retry": {"maxRetries": 1}})

        out = r.resolve(template, engram, policy, step, overrides)
        assert out.image == "gcr.io/x/llama:1"
        assert out.entrypoint == "engrams.llama:run"
        assert out.timeout_seconds == 300  # step wins over story over template
        assert out.retry.max_retries == 1  # steprun override wins
        assert out.resources.requests.cpu == "4"  # template survives
        assert out.max_inline_size == 2048  # story storage policy
        assert out.tpu.chip_count() == 8

    def test_defaults_only(self):
        out = Resolver(OperatorConfig()).resolve()
        assert out.retry.max_retries == 3
        assert out.max_inline_size == 16 * 1024
        assert out.timeout_seconds == 3600

    def test_partial_nested_merge(self):
        r = Resolver(OperatorConfig())
        template = EngramTemplateSpec.from_dict(
            {"executionPolicy": {"retry": {"maxRetries": 5, "delay": "9s"}}}
        )
        step = Step.from_dict(
            {"name": "s", "ref": {"name": "e"}, "execution": {"retry": {"maxRetries": 2}}}
        )
        out = r.resolve(template_spec=template, step=step)
        assert out.retry.max_retries == 2
        assert out.retry.delay == "9s"  # inherited from template layer
