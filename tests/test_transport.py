"""Transport layer: negotiation, topology, routing, bindings, handoff.

Coverage model: the reference's pkg/transport unit tests + the
steprun realtime-path envtest scenarios (SURVEY §2.4, §3.5).
"""

import json

import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import StorySpec, make_story
from bobrapet_tpu.api.transport import (
    MediaBinding,
    MediaCodec,
    TransportSpec,
    make_transport,
)
from bobrapet_tpu.transport import (
    CodecError,
    aggregate_bindings,
    analyze_topology,
    merge_streaming_settings,
    negotiate_binding,
)
from bobrapet_tpu.transport.codecs import negotiate_media, validate_transport_spec


# ---------------------------------------------------------------------------
# unit: codecs
# ---------------------------------------------------------------------------

class TestCodecs:
    def test_negotiate_defaults_when_no_offer(self):
        supported = [MediaCodec(name="opus"), MediaCodec(name="pcm")]
        assert [c.name for c in negotiate_media(None, supported, "audio")] == ["opus", "pcm"]

    def test_negotiate_intersection(self):
        supported = [MediaCodec(name="opus", sample_rate_hz=48000), MediaCodec(name="pcm")]
        offered = MediaBinding(codecs=[MediaCodec(name="opus")])
        agreed = negotiate_media(offered, supported, "audio")
        assert [c.name for c in agreed] == ["opus"]
        assert agreed[0].sample_rate_hz == 48000  # supported params fill in

    def test_negotiate_failure(self):
        with pytest.raises(CodecError):
            negotiate_media(
                MediaBinding(codecs=[MediaCodec(name="flac")]),
                [MediaCodec(name="opus")], "audio",
            )

    def test_ici_negotiation_returns_mesh(self):
        spec = TransportSpec(provider="tpu", driver="ici", mesh_topology="4x4")
        neg = negotiate_binding(spec)
        assert neg == {"driver": "ici", "mesh": {"topology": "4x4", "sliceId": None}}

    def test_ici_negotiation_narrows_to_slice_grant(self):
        spec = TransportSpec(provider="tpu", driver="ici", mesh_topology="4x4")
        neg = negotiate_binding(spec, slice_grant={"topology": "2x2", "sliceId": "s0"})
        assert neg["mesh"] == {"topology": "2x2", "sliceId": "s0"}

    def test_validate_transport_spec(self):
        bad = TransportSpec(
            provider="", driver="smoke",
            supported_audio=[MediaCodec(name="a"), MediaCodec(name="a")],
            supported_binary=["not-a-mime"],
        )
        errs = validate_transport_spec(bad)
        assert len(errs) == 4  # provider, driver, duplicate codec, bad mime


# ---------------------------------------------------------------------------
# unit: topology + settings + aggregation
# ---------------------------------------------------------------------------

def _story(steps):
    return StorySpec.from_dict({"steps": steps})


class TestTopology:
    def test_pure_chain_is_p2p(self):
        s = _story([
            {"name": "a", "ref": {"name": "x"}},
            {"name": "b", "ref": {"name": "x"}, "needs": ["a"]},
        ])
        topo = analyze_topology(s, lambda step: step.ref is not None)
        assert topo.downstream["a"] == ["b"]
        assert topo.upstream["b"] == ["a"]
        assert not topo.needs_hub("a") and not topo.needs_hub("b")

    def test_primitive_between_streams_forces_hub(self):
        s = _story([
            {"name": "a", "ref": {"name": "x"}},
            {"name": "gate", "type": "condition", "needs": ["a"]},
            {"name": "b", "ref": {"name": "x"}, "needs": ["gate"]},
        ])
        topo = analyze_topology(s, lambda step: step.ref is not None)
        assert topo.downstream["a"] == ["b"]
        assert topo.needs_hub("a") and topo.needs_hub("b")

    def test_terminal_steps(self):
        s = _story([
            {"name": "a", "ref": {"name": "x"}},
            {"name": "b", "ref": {"name": "x"}, "needs": ["a"]},
        ])
        topo = analyze_topology(s, lambda step: step.ref is not None)
        assert topo.terminal_steps() == ["b"]


class TestSettingsMerge:
    def test_later_layers_win_per_field(self):
        from bobrapet_tpu.api.transport import TransportStreamingSettings

        base = TransportStreamingSettings.from_dict({
            "backpressure": {"buffer": {"dropPolicy": "block", "maxMessages": 10}},
            "delivery": {"semantics": "atMostOnce"},
        })
        merged = merge_streaming_settings(
            base,
            {"delivery": {"semantics": "atLeastOnce"}},
            {"backpressure": {"buffer": {"dropPolicy": "dropOldest"}}},
        )
        assert merged.backpressure.buffer.drop_policy == "dropOldest"
        assert merged.backpressure.buffer.max_messages == 10  # base preserved
        assert merged.delivery.semantics == "atLeastOnce"


class TestAggregation:
    def _binding(self, phase, beat, negotiated=None):
        from bobrapet_tpu.core.object import new_resource

        b = new_resource("TransportBinding", f"b{id(object())}", "default",
                         spec={"transportRef": "t"})
        b.status = {"phase": phase, "heartbeatAt": beat,
                    "negotiated": negotiated or {"audio": [{"name": "opus"}]}}
        return b

    def test_stale_bindings_excluded(self):
        live = self._binding("Ready", 100.0)
        stale = self._binding("Ready", 0.0)
        caps = aggregate_bindings([live, stale], now=110.0, heartbeat_timeout=60.0)
        assert caps["liveBindings"] == 1
        assert caps["staleBindings"] == 1
        assert caps["audio"] == [{"name": "opus"}]

    def test_failed_and_pending_counted(self):
        caps = aggregate_bindings(
            [self._binding("Failed", 0), self._binding("Pending", 0)],
            now=0.0,
        )
        assert caps["failedBindings"] == 1
        assert caps["pendingBindings"] == 1
        assert caps["liveBindings"] == 0


# ---------------------------------------------------------------------------
# integration: realtime story through the control plane
# ---------------------------------------------------------------------------

def _setup_realtime(rt, transport_kwargs=None, step_extra=None):
    rt.apply(make_transport("voz", "bobravoz", driver="grpc", **(transport_kwargs or {
        "supportedAudio": [{"name": "opus", "sampleRateHz": 48000}],
        "supportedBinary": ["application/json"],
    })))
    rt.apply(make_engram_template("stream-tpl", image="stream:1",
                                  entrypoint="stream-impl",
                                  supportedModes=["deployment"]))
    for e in ("ingest", "transform", "emit"):
        rt.apply(make_engram(e, "stream-tpl"))
    steps = [
        {"name": "in", "ref": {"name": "ingest"}, "transport": "voz"},
        {"name": "mid", "ref": {"name": "transform"}, "needs": ["in"], "transport": "voz"},
        {"name": "out", "ref": {"name": "emit"}, "needs": ["mid"], "transport": "voz"},
    ]
    if step_extra:
        for s in steps:
            s.update(step_extra.get(s["name"], {}))
    rt.apply(make_story("live", steps=steps,
                        transports=[{"name": "voz", "transportRef": "voz"}],
                        pattern="realtime"))
    return rt.run_story("live", inputs={"source": "mic"})


class TestRealtimeStory:
    def test_full_pipeline_materializes(self, rt):
        run = _setup_realtime(rt)
        rt.pump()
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Running"  # live topology stays up
        by_step = {sr.spec["stepId"]: sr for sr in rt.store.list("StepRun")}
        assert set(by_step) == {"in", "mid", "out"}
        for sr in by_step.values():
            assert sr.status["phase"] == "Running"
        # P2P chain: in -> mid -> out -> terminate
        assert by_step["in"].spec["downstreamTargets"][0]["grpc"]["stepName"] == "mid"
        assert by_step["mid"].spec["downstreamTargets"][0]["grpc"]["stepName"] == "out"
        assert by_step["out"].spec["downstreamTargets"] == [{"terminate": True}]
        # bindings negotiated
        for sr in by_step.values():
            b = rt.store.get("TransportBinding", "default",
                             f"{sr.meta.name}-binding")
            assert b.status["phase"] == "Ready"
            assert b.status["negotiated"]["audio"][0]["name"] == "opus"
        # deployments carry the env contract
        deps = rt.store.list("Deployment")
        assert len(deps) == 3
        env = deps[0].spec["env"]
        assert "BOBRA_BINDING_INFO" in env
        assert env["BOBRA_EXECUTION_MODE"] == "deployment"

    def test_transport_aggregates_capabilities(self, rt):
        _setup_realtime(rt)
        rt.pump()
        t = rt.store.get("Transport", "_cluster", "voz")
        assert t.status["liveBindings"] == 3
        assert t.status["capabilities"]["audio"] == [
            {"name": "opus", "sampleRateHz": 48000}
        ]
        assert t.status["usageCount"] == 1

    def test_codec_mismatch_fails_step(self, rt):
        run = _setup_realtime(
            rt,
            step_extra={"in": {"runtime": {
                "audio": {"codecs": [{"name": "flac"}]},
            }}},
        )
        rt.pump()
        by_step = {sr.spec["stepId"]: sr for sr in rt.store.list("StepRun")}
        assert by_step["in"].status["phase"] == "Failed"
        assert "no codec in common" in by_step["in"].status["message"]

    def test_cancel_terminates_topology(self, rt):
        run = _setup_realtime(rt)
        rt.pump()
        rt.store.mutate("StoryRun", "default", run,
                        lambda r: r.spec.__setitem__("cancelRequested", True))
        rt.pump(max_virtual_seconds=600)
        r = rt.store.get("StoryRun", "default", run)
        assert r.status["phase"] == "Finished"
        assert r.status["reason"] == "Canceled"
        for b in rt.store.list("TransportBinding"):
            assert b.status["phase"] == "Terminated"

    def test_connector_generation_bumps_on_settings_change(self, rt):
        run = _setup_realtime(rt)
        rt.pump()
        sr = [s for s in rt.store.list("StepRun") if s.spec["stepId"] == "in"][0]
        b0 = rt.store.get("TransportBinding", "default", f"{sr.meta.name}-binding")
        assert b0.status["connectorGeneration"] == 1
        # narrow the transport's supported codecs -> renegotiation
        rt.store.mutate(
            "Transport", "_cluster", "voz",
            lambda r: r.spec.__setitem__("supportedAudio",
                                         [{"name": "opus", "sampleRateHz": 16000}]),
        )
        # nudge the steprun (transport watch -> story; steprun re-reconcile
        # happens via binding/deployment events after the next touch)
        rt.manager.enqueue("steprun", "default", sr.meta.name)
        rt.pump()
        b1 = rt.store.get("TransportBinding", "default", f"{sr.meta.name}-binding")
        assert b1.status["connectorGeneration"] == 2
        assert b1.status["negotiated"]["audio"][0]["sampleRateHz"] == 16000

    def test_ici_transport_binds_mesh_descriptor(self, rt):
        rt.apply(make_transport("ici", "tpu", driver="ici", meshTopology="2x4"))
        rt.apply(make_engram_template("stream-tpl", image="s:1",
                                      entrypoint="impl",
                                      supportedModes=["deployment"]))
        rt.apply(make_engram("worker", "stream-tpl"))
        rt.apply(make_story("mesh-story", steps=[
            {"name": "a", "ref": {"name": "worker"}, "transport": "ici"},
        ], transports=[{"name": "ici", "transportRef": "ici"}],
            pattern="realtime"))
        rt.run_story("mesh-story")
        rt.pump()
        b = rt.store.list("TransportBinding")[0]
        assert b.status["negotiated"]["mesh"]["topology"] == "2x4"
        t = rt.store.get("Transport", "_cluster", "ici")
        assert t.status["capabilities"]["meshes"] == ["2x4"]


class TestHeartbeatStaleness:
    def test_default_runtime_sweeps_stale_bindings(self, rt):
        """The staleness sweep runs in the default runtime (finite
        heartbeat window): bindings heartbeat while their workers are
        up, then go stale when the clock outruns the last beat."""
        _setup_realtime(rt)
        rt.pump()
        t = rt.store.get("Transport", "_cluster", "voz")
        assert t.status["liveBindings"] == 3
        assert t.status["staleBindings"] == 0
        for b in rt.store.list("TransportBinding"):
            assert b.status.get("heartbeatAt") is not None

        # a healthy quiet topology keeps beating through the periodic
        # refresh requeue — advancing past the window does NOT stale it
        rt.clock.advance(2 * 3600.0)
        rt.pump(max_virtual_seconds=0.0)
        rt.manager.enqueue("transport", "_cluster", "voz")
        rt.pump(max_virtual_seconds=0.0)
        t = rt.store.get("Transport", "_cluster", "voz")
        assert t.status["liveBindings"] == 3, t.status

        # workers go down -> heartbeats stop -> the sweep marks stale
        rt.workload_simulator.auto_ready = False
        for dep in rt.store.list("Deployment"):
            rt.workload_simulator.mark_ready("Deployment", "default", dep.meta.name,
                                       ready=False)
        rt.pump(max_virtual_seconds=0.0)
        rt.clock.advance(2 * 3600.0)
        rt.pump(max_virtual_seconds=0.0)
        rt.manager.enqueue("transport", "_cluster", "voz")
        rt.pump(max_virtual_seconds=0.0)
        t = rt.store.get("Transport", "_cluster", "voz")
        assert t.status["staleBindings"] == 3, t.status
        assert t.status["liveBindings"] == 0


class TestReadinessGatedCutover:
    """SURVEY §7 hard parts: 'cutover must wait for compiled-model
    readiness' — a handoff completes only when the NEW connector
    generation's workers pass their readiness probe, not merely when
    the new spec is observed."""

    def _renegotiate(self, rt, sr):
        rt.store.mutate(
            "Transport", "_cluster", "voz",
            lambda r: r.spec.__setitem__(
                "supportedAudio", [{"name": "opus", "sampleRateHz": 16000}]),
        )
        rt.manager.enqueue("steprun", "default", sr.meta.name)
        rt.pump()

    def test_cutover_waits_for_compiled_model_readiness(self, rt):
        run = _setup_realtime(rt)
        rt.pump()
        sr = [s for s in rt.store.list("StepRun") if s.spec["stepId"] == "in"][0]
        # new generations observe immediately but stay "compiling"
        # until released manually
        rt.workload_simulator.hold_readiness = True
        self._renegotiate(rt, sr)

        sr = rt.store.get("StepRun", "default", sr.meta.name)
        handoff = sr.status["handoff"]
        assert handoff["newGeneration"] == 2
        assert handoff["phase"] in ("Draining", "CuttingOver")
        dep = rt.store.get("Deployment", "default", f"{sr.meta.name}-rt")
        assert dep.status["observedConnectorGeneration"] == 2  # spec seen
        assert int(dep.status.get("readyGeneration", 1)) < 2   # not warm yet

        # model finishes compiling -> probe passes -> handoff completes
        rt.workload_simulator.mark_generation_ready(
            "Deployment", "default", f"{sr.meta.name}-rt", 2)
        rt.manager.enqueue("steprun", "default", sr.meta.name)
        rt.pump()
        sr = rt.store.get("StepRun", "default", sr.meta.name)
        assert sr.status["handoff"]["phase"] == "Completed"

    def test_warmup_latency_delays_cutover(self, rt):
        """The simulator's warmup models jit-compile time: the handoff
        stays open for warmup_seconds of virtual time, then completes
        ON ITS OWN (the simulator re-probes itself at warm_at — no
        external nudge required)."""
        run = _setup_realtime(rt)
        rt.pump()
        sr = [s for s in rt.store.list("StepRun") if s.spec["stepId"] == "in"][0]
        rt.workload_simulator.warmup_seconds = 120.0
        # bounded pump: renegotiate without letting virtual time advance
        # through the warmup timer
        rt.store.mutate(
            "Transport", "_cluster", "voz",
            lambda r: r.spec.__setitem__(
                "supportedAudio", [{"name": "opus", "sampleRateHz": 16000}]),
        )
        rt.manager.enqueue("steprun", "default", sr.meta.name)
        rt.pump(max_virtual_seconds=0.0)
        sr1 = rt.store.get("StepRun", "default", sr.meta.name)
        assert sr1.status["handoff"]["phase"] in ("Draining", "CuttingOver")
        dep = rt.store.get("Deployment", "default", f"{sr.meta.name}-rt")
        assert int(dep.status.get("readyGeneration", 1)) < 2  # still compiling

        # full pump: virtual time flows through the self-scheduled
        # reprobe at warm_at; readiness flips and the handoff completes
        rt.pump()
        sr2 = rt.store.get("StepRun", "default", sr.meta.name)
        assert sr2.status["handoff"]["phase"] == "Completed"
