"""Device-resident decode horizon: parity + cross-engine prefix sharing.

The horizon engine (`decode_horizon > 1`) must emit BYTE-IDENTICAL
output streams to the retained single-step reference engine
(`decode_horizon=1`) for every scheduling shape: greedy, sampled with
fixed seeds, mixed-temperature batches, speculation on/off, an EOS
firing inside a horizon, and a preemption landing mid-drain. That is
the contract that lets the fused multi-step scan replace the per-token
host round-trip without a correctness asterisk.

Sampling parity is not luck: sampled streams are a pure function of
(engine seed, rid, token index) — `engine._fold_keys` — so slot
assignment, co-tenancy, recompute, and horizon size cannot move them.
"""

import jax
import numpy as np
import pytest

from bobrapet_tpu.models import llama, quant
from bobrapet_tpu.serving import PagedConfig, ServingEngine
from bobrapet_tpu.serving.prefix_cache import SharedPrefixRegistry


@pytest.fixture(scope="module")
def model():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def draft(model):
    _cfg, params = model
    return quant.quantize_params(params)


def _pcfg(**over):
    kw = dict(max_slots=4, block_size=16, num_blocks=128,
              max_blocks_per_seq=8)
    kw.update(over)
    return PagedConfig(**kw)


def _prompts(cfg, n=8, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, 8 + (i % 5) * 7).tolist()
            for i in range(n)]


def _drain(engine, prompts, *, max_new=12, temps=None, eos=None):
    for i, p in enumerate(prompts):
        engine.submit(list(p), max_new_tokens=max_new,
                      temperature=(temps[i] if temps else 0.0),
                      eos_token=eos)
    done = engine.run()
    return {r.rid: r.output for r in done}


class TestHorizonParity:
    """Every case: horizon engine vs the decode_horizon=1 reference."""

    def _pair(self, model, horizon=8, pc=None, **kw):
        cfg, params = model
        ref = ServingEngine(params, cfg, pc or _pcfg(),
                            decode_horizon=1, **kw)
        hz = ServingEngine(params, cfg, pc or _pcfg(),
                           decode_horizon=horizon, **kw)
        return ref, hz

    def test_greedy_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg)
        ref, hz = self._pair(model)
        assert _drain(ref, prompts) == _drain(hz, prompts)
        assert hz.phase_counts["horizons"] > 0
        # the whole point: horizon syncs ~1/H as often as the
        # reference commits tokens
        assert hz.phase_counts["host_syncs"] < 8 * 12

    def test_sampled_fixed_seed_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, seed=3)
        temps = [0.7, 1.1, 0.9, 1.3, 0.8, 1.0, 0.6, 1.2]
        ref, hz = self._pair(model)
        a = _drain(ref, prompts, temps=temps)
        b = _drain(hz, prompts, temps=temps)
        assert a == b

    def test_mixed_temperature_batch_byte_identical(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, seed=4)
        temps = [0.0, 0.8, 0.0, 1.2, 0.0, 0.0, 0.9, 0.0]
        ref, hz = self._pair(model)
        assert _drain(ref, prompts, temps=temps) == _drain(
            hz, prompts, temps=temps)

    def test_eos_fires_inside_horizon(self, model):
        cfg, _ = model
        prompts = _prompts(cfg, seed=5)
        ref, hz = self._pair(model)
        base = _drain(ref, prompts, max_new=16)
        # an eos token observed MID-stream: the horizon loop must stop
        # the request on device at the same position the single-step
        # reference stops it on host
        eos = next(t for out in base.values() for t in out[3:10])
        ref2, hz2 = self._pair(model)
        a = _drain(ref2, prompts, max_new=16, eos=eos)
        b = _drain(hz2, prompts, max_new=16, eos=eos)
        assert a == b
        assert any(len(v) < 16 for v in a.values())

    def test_spec_on_off_byte_identical(self, model, draft):
        cfg, _ = model
        prompts = _prompts(cfg, seed=6)
        ref, _unused = self._pair(model)
        base = _drain(ref, prompts, max_new=14)
        for horizon in (1, 8):
            spec = ServingEngine(
                model[1], cfg, _pcfg(), decode_horizon=horizon,
                draft_params=draft, draft_cfg=cfg, spec_k=4,
                spec_guard=False)
            assert _drain(spec, prompts, max_new=14) == base
            assert spec.spec_drafted > 0

    def test_spec_horizon_mixed_temps_byte_identical(self, model, draft):
        cfg, _ = model
        prompts = _prompts(cfg, seed=7)
        temps = [0.0, 0.9, 0.0, 1.1, 0.0, 0.7, 0.0, 0.0]
        ref, _unused = self._pair(model)
        base = _drain(ref, prompts, temps=temps)
        spec = ServingEngine(model[1], cfg, _pcfg(), decode_horizon=8,
                             draft_params=draft, draft_cfg=cfg, spec_k=4,
                             spec_guard=False)
        assert _drain(spec, prompts, temps=temps) == base

    def test_preemption_mid_drain_byte_identical(self, model, draft):
        """Tight block pool: growth preempts the youngest slot while
        horizons are in flight; recompute + the request-identity key
        scheme keep every stream byte-identical anyway."""
        cfg, params = model
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, cfg.vocab_size, 10 + (i % 3) * 9).tolist()
                   for i in range(6)]
        pc = dict(max_slots=4, block_size=8, num_blocks=18,
                  max_blocks_per_seq=8, prefix_caching=False)

        def run(horizon, spec=False):
            kw = dict(draft_params=draft, draft_cfg=cfg, spec_k=4,
                      spec_guard=False) if spec else {}
            eng = ServingEngine(params, cfg, PagedConfig(**pc),
                                decode_horizon=horizon, **kw)
            for p in prompts:
                eng.submit(list(p), max_new_tokens=24)
            done = eng.run()
            return ({r.rid: r.output for r in done},
                    sum(r.preemptions for r in done))

        base, pre_ref = run(1)
        hz, pre_hz = run(8)
        spec_hz, _pre_spec = run(8, spec=True)
        assert pre_ref > 0 and pre_hz > 0
        assert base == hz == spec_hz

    def test_horizon_live_reload_mid_stream(self, model):
        """set_decode_horizon between ticks (the serving.decode-horizon
        reload path) must not change a single output byte."""
        cfg, params = model
        prompts = _prompts(cfg, seed=9)
        ref, _unused = self._pair(model)
        base = _drain(ref, prompts, max_new=16)
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=4)
        for p in prompts:
            eng.submit(list(p), max_new_tokens=16)
        for hz in (4, 1, 8, 2):
            eng.set_decode_horizon(hz)
            eng.step()
        done = eng.run()
        assert {r.rid: r.output for r in done} == base

    def test_invalid_horizon_rejected(self, model):
        cfg, params = model
        with pytest.raises(ValueError):
            ServingEngine(params, cfg, _pcfg(), decode_horizon=0)
        eng = ServingEngine(params, cfg, _pcfg())
        with pytest.raises(ValueError):
            eng.set_decode_horizon(0)
        with pytest.raises(ValueError):
            eng.set_spec_k(0)


class TestHorizonMetrics:
    def test_horizon_series_emitted(self, model):
        from bobrapet_tpu.observability.metrics import metrics

        cfg, params = model
        before = metrics.serving_host_syncs.value("decode")
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8)
        for p in _prompts(cfg, n=4, seed=10):
            eng.submit(list(p), max_new_tokens=10)
        eng.run()
        assert metrics.serving_host_syncs.value("decode") > before
        assert metrics.serving_horizon.value() == 8.0
        assert eng.phase_counts["device_steps"] >= 10
        # breakdown populated where the work happened
        assert eng.phase_seconds["decode_device"] > 0
        assert eng.phase_seconds["host_sync"] > 0
        eng.reset_phase_stats()
        assert eng.phase_seconds["decode_device"] == 0.0
        assert eng.phase_counts["horizons"] == 0

    def test_spec_round_series_emitted(self, model, draft):
        from bobrapet_tpu.observability.metrics import metrics

        cfg, params = model
        before = metrics.serving_spec_rounds.value()
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=8,
                            draft_params=draft, draft_cfg=cfg, spec_k=4,
                            spec_guard=False)
        for p in _prompts(cfg, n=4, seed=11):
            eng.submit(list(p), max_new_tokens=10)
        eng.run()
        assert metrics.serving_spec_rounds.value() > before
        assert eng.phase_seconds["draft"] > 0
        assert eng.phase_seconds["verify"] > 0


class TestPrefixSharing:
    """Two engines with identical weights share prefix KV by content
    hash through a SharedPrefixRegistry; different weights, draft
    identity, or adapter stacks must never cross-hit."""

    def _workload(self, cfg, seed=20):
        rng = np.random.default_rng(seed)
        system = rng.integers(0, cfg.vocab_size, 48).tolist()  # 3 blocks
        tail = rng.integers(0, cfg.vocab_size, 9).tolist()
        return system + tail

    def test_same_weights_cross_hit_and_exact(self, model):
        from bobrapet_tpu.observability.metrics import metrics

        cfg, params = model
        reg = SharedPrefixRegistry()
        prompt = self._workload(cfg)
        hits0 = metrics.serving_prefix_shared.value("hit")

        a = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        a.submit(list(prompt), max_new_tokens=8)
        out_a = a.run()[0].output
        assert len(reg) >= 3  # full prompt blocks exported

        b = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        b.submit(list(prompt), max_new_tokens=8)
        out_b = b.run()[0].output
        assert b.blocks.shared_hits >= 3
        assert metrics.serving_prefix_shared.value("hit") >= hits0 + 3
        assert out_b == out_a

        # adopted KV must be EXACT: a share-less engine agrees
        plain = ServingEngine(params, cfg, _pcfg())
        plain.submit(list(prompt), max_new_tokens=8)
        assert plain.run()[0].output == out_b

    def test_different_weights_isolated(self, model):
        from bobrapet_tpu.observability.metrics import metrics

        cfg, params = model
        other = llama.init_params(jax.random.PRNGKey(7), cfg)
        reg = SharedPrefixRegistry()
        prompt = self._workload(cfg, seed=21)
        a = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        a.submit(list(prompt), max_new_tokens=6)
        a.run()
        miss0 = metrics.serving_prefix_shared.value("miss")
        c = ServingEngine(other, cfg, _pcfg(), prefix_shared=reg)
        c.submit(list(prompt), max_new_tokens=6)
        c.run()
        assert c.blocks.shared_hits == 0
        assert metrics.serving_prefix_shared.value("miss") > miss0

    def test_draft_identity_isolated(self, model, draft):
        """A spec engine's scope includes its draft: it must not adopt
        a draft-less export (the hole would collapse the accept rate),
        and vice versa."""
        cfg, params = model
        reg = SharedPrefixRegistry()
        prompt = self._workload(cfg, seed=22)
        a = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        a.submit(list(prompt), max_new_tokens=6)
        a.run()
        s = ServingEngine(params, cfg, _pcfg(), draft_params=draft,
                          draft_cfg=cfg, spec_k=4, spec_guard=False,
                          prefix_shared=reg)
        s.submit(list(prompt), max_new_tokens=6)
        s.run()
        assert s.blocks.shared_hits == 0

    def test_adapter_stacks_isolated(self, model):
        """Engines whose LoRA stacks differ hash to different scopes;
        within one engine the per-adapter salt still separates chains
        exactly as the local cache always did."""
        from bobrapet_tpu.models.lora import (
            LoRAConfig, init_lora, stack_adapters, zero_lora,
        )

        cfg, params = model
        lcfg = LoRAConfig(rank=4, alpha=8.0, sites=("wq", "wv"))
        stack1 = stack_adapters([
            zero_lora(cfg, lcfg),
            init_lora(jax.random.PRNGKey(1), cfg, lcfg),
        ])
        stack2 = stack_adapters([
            zero_lora(cfg, lcfg),
            init_lora(jax.random.PRNGKey(2), cfg, lcfg),
        ])
        reg = SharedPrefixRegistry()
        prompt = self._workload(cfg, seed=23)
        a = ServingEngine(params, cfg, _pcfg(), loras=stack1,
                          prefix_shared=reg)
        a.submit(list(prompt), max_new_tokens=6)
        a.run()
        b = ServingEngine(params, cfg, _pcfg(), loras=stack2,
                          prefix_shared=reg)
        b.submit(list(prompt), max_new_tokens=6)
        b.run()
        assert b.blocks.shared_hits == 0

    def test_registry_lru_bound(self):
        reg = SharedPrefixRegistry(max_entries=2)
        reg.put("s", b"a", {"k": 1})
        reg.put("s", b"b", {"k": 2})
        reg.put("s", b"c", {"k": 3})
        assert len(reg) == 2
        assert reg.get("s", b"a") is None
        assert reg.get("s", b"c") == {"k": 3}

    def test_sharing_requires_prefix_caching(self, model):
        cfg, params = model
        with pytest.raises(ValueError):
            ServingEngine(params, cfg, _pcfg(prefix_caching=False),
                          prefix_shared=SharedPrefixRegistry())


class TestServingConfigKnobs:
    """`serving.*` operator keys: registration, validation, and the
    live-reload path through serving/engram.apply_tuning."""

    def test_keys_parse_and_validate(self):
        from bobrapet_tpu.config.operator import parse_config

        cfg = parse_config({
            "serving.decode-horizon": "16",
            "serving.spec-k": "6",
            "serving.prefix-cache-shared": "true",
        })
        assert cfg.serving.decode_horizon == 16
        assert cfg.serving.spec_k == 6
        assert cfg.serving.prefix_cache_shared is True
        assert cfg.validate() == []

    def test_horizon_validation_floor(self):
        from bobrapet_tpu.config.operator import OperatorConfig

        cfg = OperatorConfig()
        cfg.serving.decode_horizon = 0
        assert any("serving.decode-horizon" in e for e in cfg.validate())
        cfg.serving.decode_horizon = 8
        cfg.serving.spec_k = 0
        assert any("serving.spec-k" in e for e in cfg.validate())

    def test_apply_tuning_retunes_live_engine(self, model):
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram

        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=4)
        engram._LIVE_ENGINES.add(eng)
        try:
            engram.apply_tuning(ServingConfig(
                decode_horizon=16, spec_k=5, prefix_cache_shared=False))
            assert eng.decode_horizon == 16
            assert eng.spec_k == 5
            # prefix sharing toggles on live through the global registry
            engram.apply_tuning(ServingConfig(prefix_cache_shared=True))
            assert eng.blocks._shared is not None
            engram.apply_tuning(ServingConfig(prefix_cache_shared=False))
            assert eng.blocks._shared is None
        finally:
            engram._LIVE_ENGINES.discard(eng)
            engram._TUNING = None

    def test_apply_tuning_respects_step_pinned_knobs(self, model):
        """A reload of UNRELATED keys must not clobber step-pinned
        values or swap a custom tenant registry for the global one."""
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram

        cfg, params = model
        reg = SharedPrefixRegistry()
        eng = ServingEngine(params, cfg, _pcfg(), decode_horizon=1,
                            prefix_shared=reg)
        eng._engram_pinned = frozenset({"decode_horizon", "prefix_shared"})
        engram._LIVE_ENGINES.add(eng)
        try:
            engram.apply_tuning(ServingConfig(
                decode_horizon=8, prefix_cache_shared=False))
            assert eng.decode_horizon == 1  # pinned parity reference
            assert eng.blocks._shared is reg  # custom registry kept
            # unpinned engine with a CUSTOM registry: never detached by
            # the operator default nor swapped onto the global registry
            eng._engram_pinned = frozenset()
            engram.apply_tuning(ServingConfig(prefix_cache_shared=False))
            assert eng.blocks._shared is reg
            engram.apply_tuning(ServingConfig(prefix_cache_shared=True))
            assert eng.blocks._shared is reg
        finally:
            engram._LIVE_ENGINES.discard(eng)
            engram._TUNING = None

    def test_guard_retired_draft_rescopes_to_plain(self, model, draft):
        """A spec engine whose payoff guard retires the draft must
        export/import in the PLAIN engine's namespace — its dk-less
        exports would otherwise squat the draft scope's publish-once
        keys and poison every live spec engine's imports."""
        cfg, params = model
        reg = SharedPrefixRegistry()
        rng = np.random.default_rng(30)
        system = rng.integers(0, cfg.vocab_size, 48).tolist()
        spec = ServingEngine(params, cfg, _pcfg(), draft_params=draft,
                             draft_cfg=cfg, spec_k=4, spec_guard=True,
                             spec_guard_ticks=2, decode_horizon=8,
                             prefix_shared=reg)
        for i in range(8):
            spec.submit(system + [i], max_new_tokens=24)
        spec.run()
        assert spec.spec_guard_decision is not None
        if spec.spec_active:
            pytest.skip("guard kept speculation on this box")
        # pre-decision registrations exported under the draft scope; a
        # POST-retirement prefill re-registers the chain and publishes
        # it under the engine's new (plain) scope
        spec.submit(system + [50], max_new_tokens=4)
        spec.run()
        # after retirement the scope equals a plain engine's: a plain
        # engine adopts this engine's exports
        plain = ServingEngine(params, cfg, _pcfg(), prefix_shared=reg)
        plain.submit(system + [99], max_new_tokens=8)
        out_p = plain.run()[0].output
        assert plain.blocks.shared_hits >= 3
        ref = ServingEngine(params, cfg, _pcfg())
        ref.submit(system + [99], max_new_tokens=8)
        assert ref.run()[0].output == out_p

    def test_horizon_reload_rearms_spec_guard(self, model, draft):
        """serving.decode-horizon reload changes the guard's
        measurement shape: a kept/retired decision (and the watchdog's
        plain-rate floor) from the old horizon must be re-measured, not
        compared across cadences (a stale floor spuriously demotes a
        profitable draft one-way)."""
        cfg, params = model
        rng = np.random.default_rng(31)
        eng = ServingEngine(params, cfg, _pcfg(), draft_params=draft,
                            draft_cfg=cfg, spec_k=4, spec_guard=True,
                            spec_guard_ticks=2, decode_horizon=8)
        for i in range(8):
            eng.submit(rng.integers(0, cfg.vocab_size, 12).tolist(),
                       max_new_tokens=32)
        eng.run()
        assert eng.spec_guard_decision is not None
        eng.set_decode_horizon(2)
        assert eng.spec_guard_decision is None
        assert eng.spec_active  # the draft gets a fresh A/B at H=2
        # same horizon again: no spurious re-arm
        eng.submit(rng.integers(0, cfg.vocab_size, 12).tolist(),
                   max_new_tokens=32)
        eng.run()
        decided = eng.spec_guard_decision
        eng.set_decode_horizon(2)
        assert eng.spec_guard_decision is decided

    def test_startup_configmap_seeds_serving_tuning(self, model):
        """A ConfigMap that EXISTS at manager startup must reach
        engines built later in the process — subscribers only fire on
        reloads, so Runtime seeds the engram tuning at construction."""
        from bobrapet_tpu.core.object import new_resource
        from bobrapet_tpu.core.store import ResourceStore
        from bobrapet_tpu.runtime import Runtime
        from bobrapet_tpu.serving import engram

        store = ResourceStore()
        store.create(new_resource(
            "ConfigMap", "operator-config", "bobrapet-system",
            spec={"data": {"serving.decode-horizon": "16"}}))
        prev = engram._TUNING
        try:
            Runtime(store=store)
            assert engram._TUNING is not None
            assert engram._TUNING.decode_horizon == 16
        finally:
            engram._TUNING = prev

    def test_apply_tuning_survives_misfit_engine(self, model):
        """prefix-cache-shared on an engine built without prefix
        caching is a per-engine skip, not a fleet-wide reload crash."""
        from bobrapet_tpu.config.operator import ServingConfig
        from bobrapet_tpu.serving import engram

        cfg, params = model
        eng = ServingEngine(params, cfg, _pcfg(prefix_caching=False))
        engram._LIVE_ENGINES.add(eng)
        try:
            engram.apply_tuning(ServingConfig(prefix_cache_shared=True))
            assert eng.decode_horizon == 8  # the rest still applied
        finally:
            engram._LIVE_ENGINES.discard(eng)
            engram._TUNING = None
