"""Fleet chaos suite: fault-injected preemption recovery end to end.

Runs in tier-1 (not marked slow); select explicitly with ``-m chaos``.
The injector (controllers/workload_sim.PreemptionInjector) plays the GKE
spot reclaimer: it kills gang hosts mid-step with SIGTERM + a preemption
notice, which must drive quarantine, cordon-aware re-placement, and
checkpoint-resuming redrive — with zero lost runs and zero user retry
budget consumed.
"""

from __future__ import annotations

import numpy as np
import pytest

from bobrapet_tpu.api.catalog import make_engram_template
from bobrapet_tpu.api.engram import make_engram
from bobrapet_tpu.api.story import make_story
from bobrapet_tpu.controllers.workload_sim import PreemptionInjector
from bobrapet_tpu.fleet import grant_cells
from bobrapet_tpu.observability.metrics import metrics
from bobrapet_tpu.parallel.placement import SlicePool
from bobrapet_tpu.runtime import Runtime
from bobrapet_tpu.sdk import register_engram

pytestmark = pytest.mark.chaos

TRAIN_STEPS = 6
#: params after an uninterrupted run: zeros + sum(1..TRAIN_STEPS)
REFERENCE_PARAMS = [float(sum(range(1, TRAIN_STEPS + 1)))] * 4


@pytest.fixture(autouse=True, scope="module")
def _lock_order_sanitizer():
    """Lockdep for the chaos suite (see test_concurrency.py): the
    preemption storm is the highest-entropy lock interleaving in
    tier-1, exactly where an ordering inversion would surface."""
    from bobrapet_tpu.analysis.lockorder import sanitize_locks

    with sanitize_locks() as monitor:
        yield monitor
    monitor.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _race_sanitizer(_lock_order_sanitizer):
    """bobrarace over the preemption storm (see test_concurrency.py
    for the contract): chaos interleavings are exactly where an
    unlocked shared-container access would finally collide."""
    from bobrapet_tpu.analysis.racedetect import sanitize_races

    with sanitize_races(monitor=_lock_order_sanitizer) as det:
        yield det
    det.assert_clean()


class ScriptedInjector(PreemptionInjector):
    """Deterministic plan list instead of a seeded rate."""

    def __init__(self, plans):
        super().__init__(rate=0.0)
        self._plans = list(plans)

    def plan(self, job):
        if not self._plans:
            return None
        if int(job.spec.get("hosts") or 1) < self.min_hosts:
            return None
        if not job.spec.get("sliceGrant"):
            return None
        self.planned += 1
        return self._plans.pop(0)


def _training_rt(injector, pool_topology="4x4", chips_per_host=2):
    rt = Runtime(preemption_injector=injector)
    # assertions read StepRuns after the drain: park retention far past
    # the virtual-time horizon (same pattern as test_scale_soak)
    rt.config_manager.config.retention.children_ttl_seconds = 7 * 86400.0
    rt.config_manager.config.retention.storyrun_retention_seconds = 14 * 86400.0
    rt.placer.add_pool(
        SlicePool("v5e", pool_topology, chips_per_host=chips_per_host)
    )

    @register_engram("chaos-train")
    def train(ctx):
        steps_total = int(ctx.inputs.get("steps", TRAIN_STEPS))
        if ctx.host_id != 0:
            # worker hosts: cooperative SIGTERM points once per step
            for _ in range(steps_total):
                ctx.check_deadline()
            return None
        state = {"params": np.zeros(4), "step": 0}
        restored = ctx.restore_model_checkpoint(state)
        start = 0
        if restored is not None:
            state, start = restored
            start = int(start)
        params = np.asarray(state["params"]).copy()
        for s in range(start, steps_total):
            ctx.check_deadline()  # preemption lands between checkpoints
            params = params + (s + 1)  # deterministic update rule
            ctx.save_model_checkpoint(
                {"params": params, "step": s + 1}, step=s + 1
            )
        return {"params": params.tolist(), "resumedFrom": start}

    rt.apply(make_engram_template("chaos-tpl", entrypoint="chaos-train"))
    rt.apply(make_engram("chaos-trainer", "chaos-tpl"))
    rt.apply(make_story("chaos-train", steps=[
        {"name": "fit", "ref": {"name": "chaos-trainer"},
         "with": {"steps": TRAIN_STEPS},
         "tpu": {"topology": "2x2", "meshAxes": {"data": 2, "model": 2}}},
    ], policy={"queue": "v5e"}))
    return rt


def drain(rt, max_virtual_seconds=43_200.0):
    while rt.pump(max_virtual_seconds=max_virtual_seconds) > 0:
        pass


def _steprun(rt, run_name):
    srs = [
        sr for sr in rt.store.list("StepRun")
        if (sr.spec.get("storyRunRef") or {}).get("name") == run_name
    ]
    assert len(srs) == 1
    return srs[0]


def _condition(obj, ctype):
    for c in obj.status.get("conditions") or []:
        if c.get("type") == ctype:
            return c
    return None


class TestSinglePreemptionRecovery:
    def test_redrive_resumes_from_checkpoint(self, rt):
        del rt  # fixture unused; chaos runtimes carry injectors
        inj = ScriptedInjector([{"host": 0, "afterPolls": 3}])
        rt = _training_rt(inj)
        run = rt.run_story("chaos-train")
        drain(rt)

        assert rt.run_phase(run) == "Succeeded"
        sr = _steprun(rt, run)
        # param delta vs the uninterrupted run is exactly 0.0
        assert sr.status["output"]["params"] == REFERENCE_PARAMS
        # ...and it actually resumed mid-stream, not from step zero
        assert sr.status["output"]["resumedFrom"] > 0
        assert sr.status.get("preemptions") == 1
        # the user retry budget was NOT consumed
        assert int(sr.status.get("retries") or 0) == 0

        cond = _condition(sr, "PreemptionRecovered")
        assert cond and cond["status"] == "True"
        srun = rt.store.get("StoryRun", "default", run)
        assert srun.status.get("preemptions") == 1
        rcond = _condition(srun, "PreemptionRecovered")
        assert rcond and rcond["status"] == "True"

        assert metrics.fleet_preemptions.value("v5e") == 1
        assert metrics.fleet_resumed_steps.value() == 1
        assert metrics.fleet_recovery_seconds.count("v5e") == 1
        assert metrics.fleet_quarantined_cells.value("v5e") == 2

    def test_replacement_grant_avoids_quarantined_cells(self, rt):
        del rt
        inj = ScriptedInjector([{"host": 1, "afterPolls": 2}])
        rt = _training_rt(inj)
        run = rt.run_story("chaos-train")
        drain(rt)

        assert rt.run_phase(run) == "Succeeded"
        sr = _steprun(rt, run)
        new_grant = sr.spec["sliceGrant"]
        quarantined = rt.fleet.registry.quarantined_cells("v5e")
        assert quarantined  # the dead host's cells are booked
        assert not set(grant_cells(new_grant)) & quarantined

    def test_worker_host_preemption_also_recovers(self, rt):
        """Victim host 1 (not the trainer): the gang fail-fast kills
        host 0 too; redrive resumes whatever host 0 checkpointed."""
        del rt
        inj = ScriptedInjector([{"host": 1, "afterPolls": 1}])
        rt = _training_rt(inj)
        run = rt.run_story("chaos-train")
        drain(rt)
        assert rt.run_phase(run) == "Succeeded"
        sr = _steprun(rt, run)
        assert sr.status["output"]["params"] == REFERENCE_PARAMS
        assert sr.status.get("preemptions") == 1


class TestPreemptionBudget:
    def test_cap_exhaustion_turns_terminal(self, rt):
        del rt
        # every attempt dies after one training step
        inj = ScriptedInjector([{"host": 0, "afterPolls": 1}] * 10)
        rt = _training_rt(inj)
        rt.config_manager.config.fleet.preemption_retry_cap = 2
        run = rt.run_story("chaos-train")
        drain(rt)

        assert rt.run_phase(run) == "Failed"
        sr = _steprun(rt, run)
        assert sr.status["phase"] == "Failed"
        assert sr.status["exitClass"] == "preempted"
        assert sr.status["preemptions"] == 3  # cap 2 + the terminal one
        assert "preemption-retry-cap" in sr.status["error"]["message"]
        # even a terminal preemption never touched the user budget
        assert int(sr.status.get("retries") or 0) == 0
        cond = _condition(sr, "PreemptionRecovered")
        assert cond and cond["status"] == "False"
        assert cond["reason"] == "PreemptionBudgetExhausted"

    def test_user_retry_budget_still_independent(self, rt):
        """An application failure AFTER a preemption recovery consumes
        the user budget; the preemption tally stays separate."""
        del rt
        inj = ScriptedInjector([{"host": 0, "afterPolls": 2}])
        rt = Runtime(preemption_injector=inj)
        rt.config_manager.config.retention.children_ttl_seconds = 7 * 86400.0
        rt.config_manager.config.retention.storyrun_retention_seconds = 14 * 86400.0
        rt.placer.add_pool(SlicePool("v5e", "4x4", chips_per_host=2))
        calls = {"n": 0}

        @register_engram("flaky-train")
        def train(ctx):
            if ctx.host_id != 0:
                for _ in range(4):
                    ctx.check_deadline()
                return None
            for _ in range(4):
                ctx.check_deadline()
            calls["n"] += 1
            if calls["n"] == 2:  # first post-preemption attempt fails
                raise RuntimeError("app bug")
            return {"ok": calls["n"]}

        rt.apply(make_engram_template("flaky-tpl", entrypoint="flaky-train"))
        rt.apply(make_engram("flaky", "flaky-tpl"))
        rt.apply(make_story("flaky-story", steps=[
            {"name": "fit", "ref": {"name": "flaky"},
             "tpu": {"topology": "2x2"},
             "execution": {"retry": {"maxRetries": 2, "delay": "1s"}}},
        ], policy={"queue": "v5e"}))
        run = rt.run_story("flaky-story")
        drain(rt)

        sr = _steprun(rt, run)
        # exit 1 is TERMINAL class (application error): the run fails,
        # but the two ledgers stayed independent
        assert sr.status.get("preemptions") == 1
        assert int(sr.status.get("retries") or 0) == 0


class TestHeartbeatStaleness:
    def test_stale_gang_host_reported_suspect(self, rt):
        from bobrapet_tpu.core.object import new_resource

        grant = {"sliceId": "v5e-s1", "pool": "v5e", "topology": "2x2",
                 "hosts": 2, "origin": [0, 0], "meshAxes": {}}
        rt.placer.add_pool(SlicePool("v5e", "4x4", chips_per_host=2))
        rt.store.create(new_resource(
            "StepRun", "hb-test", "default",
            {"stepId": "fit", "sliceGrant": grant,
             "storyRunRef": {"name": "hb-run"},
             "engramRef": {"name": "hb-engram"}},
        ))
        rt.store.patch_status(
            "StepRun", "default", "hb-test",
            lambda st: st.update(
                {"phase": "Running",
                 "hostHeartbeats": {"0": rt.clock.now(), "1": rt.clock.now()}}
            ),
        )
        # host 1 goes silent past fleet.heartbeat-timeout (60s default)
        rt.clock.advance(45.0)
        rt.store.patch_status(
            "StepRun", "default", "hb-test",
            lambda st: st["hostHeartbeats"].__setitem__("0", rt.clock.now()),
        )
        rt.clock.advance(45.0)
        rt.preemption_watcher.sweep("default", "hb-test")
        reg = rt.fleet.registry
        assert reg.suspicion("v5e", (1, 0)) > 0  # host 1's cells
        assert reg.suspicion("v5e", (0, 0)) == 0  # host 0 kept beating

    def test_redrive_cleared_beats_are_not_judged_stale(self, rt):
        """A preemption redrive pops status.hostHeartbeats; the dead
        attempt's beats must not book suspicion against the REPLACEMENT
        grant's cells."""
        from bobrapet_tpu.core.object import new_resource

        grant = {"sliceId": "v5e-s1", "pool": "v5e", "topology": "2x2",
                 "hosts": 2, "origin": [0, 0], "meshAxes": {}}
        rt.placer.add_pool(SlicePool("v5e", "4x4", chips_per_host=2))
        rt.store.create(new_resource(
            "StepRun", "hb-redrive", "default",
            {"stepId": "fit", "sliceGrant": grant,
             "storyRunRef": {"name": "hb-run"},
             "engramRef": {"name": "hb-engram"}},
        ))
        rt.store.patch_status(
            "StepRun", "default", "hb-redrive",
            lambda st: st.update(
                {"phase": "Running",
                 "hostHeartbeats": {"0": rt.clock.now(), "1": rt.clock.now()}}
            ),
        )
        # the redrive patch clears the dead attempt's beats
        rt.store.patch_status(
            "StepRun", "default", "hb-redrive",
            lambda st: (st.pop("hostHeartbeats", None),
                        st.__setitem__("phase", "Pending")),
        )
        rt.clock.advance(120.0)
        rt.preemption_watcher.sweep("default", "hb-redrive")
        reg = rt.fleet.registry
        assert reg.suspicion("v5e", (0, 0)) == 0
        assert reg.suspicion("v5e", (1, 0)) == 0


class TestChaosSoak:
    def test_200_run_soak_zero_lost_runs(self, rt):
        """Acceptance: >=10% of multi-host steps killed mid-run across a
        200-run soak; every StoryRun completes, preempted steps resume
        from the latest checkpoint with zero parameter delta, user retry
        budgets stay untouched, and the fleet metrics are populated."""
        del rt
        inj = PreemptionInjector(rate=0.2, seed=1234, min_hosts=2)
        rt = _training_rt(inj)
        # short quarantine so the 16-chip pool never starves the soak
        rt.config_manager.config.fleet.quarantine_seconds = 60.0

        # 200 runs in waves of 25: the priority gate is O(queue peers)
        # per launch attempt, so a single 200-run dump measures the
        # scheduler's worst case instead of the fleet machinery
        n, wave = 200, 25
        runs = []
        for i in range(0, n, wave):
            runs.extend(rt.run_story("chaos-train") for _ in range(wave))
            drain(rt)

        phases = [rt.run_phase(r) for r in runs]
        assert phases.count("Succeeded") == n, (
            f"lost {n - phases.count('Succeeded')} runs: "
            f"{[p for p in phases if p != 'Succeeded'][:5]}"
        )

        preempted_runs = 0
        resumed_runs = 0
        for r in runs:
            sr = _steprun(rt, r)
            out = sr.status["output"]
            # post-resume parameter delta vs uninterrupted run == 0.0
            assert out["params"] == REFERENCE_PARAMS, (r, out)
            p = int(sr.status.get("preemptions") or 0)
            if p:
                preempted_runs += 1
                # preemption redrives never consume the user budget
                assert int(sr.status.get("retries") or 0) == 0
            if out["resumedFrom"] > 0:
                resumed_runs += 1
                assert p > 0  # only recovered gangs resume mid-stream

        # injection level: >=10% of the multi-host steps were killed
        assert preempted_runs >= n // 10, (
            f"only {preempted_runs}/{n} runs preempted — injector too quiet"
        )
        assert resumed_runs > 0

        total_preemptions = metrics.fleet_preemptions.value("v5e")
        assert total_preemptions >= preempted_runs
        # a run preempted k times relaunches k times, each resuming from
        # its newest checkpoint (first-attempt-before-any-checkpoint
        # kills redrive without resume env, hence <= total)
        assert resumed_runs <= metrics.fleet_resumed_steps.value() <= total_preemptions
        # every preemption's recovery latency was observed
        assert metrics.fleet_recovery_seconds.count("v5e") == total_preemptions
        # the quarantine gauge series exists on the scrape page
        page = metrics.fleet_quarantined_cells.expose()
        assert 'bobrapet_fleet_quarantined_cells{pool="v5e"}' in page
